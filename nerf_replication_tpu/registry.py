"""Config-driven plugin registry.

The reference's load-bearing architectural idea (SURVEY.md §1) is that every
layer boundary is crossed through a string-module plugin registry: the YAML
names a module per role (``*_module`` keys) and a ``make_*`` factory loads the
implementation at runtime (reference `src/datasets/make_dataset.py:16-29`,
`src/models/make_network.py:4-8`, etc. — via ``imp.load_source`` on derived
file paths).

We keep the idea and modernize the mechanism: modules are resolved with
``importlib`` by dotted name, and reference-style names (``src.models.nerf.
network``) are transparently aliased to our packages so the reference's YAML
configs work unchanged. Third-party task plugins can register themselves with
:func:`register_alias` or simply use their own importable dotted path in YAML.
"""

from __future__ import annotations

import importlib
from types import ModuleType
from typing import Any

_PKG = "nerf_replication_tpu"

# Aliases for the reference repo's module names (capability parity: its YAML
# configs select implementations by these exact strings).
_ALIASES: dict[str, str] = {
    "src.datasets.nerf.blender": f"{_PKG}.datasets.blender",
    "src.datasets.img_fit.synthetic": f"{_PKG}.datasets.img_fit",
    "src.datasets.latent": f"{_PKG}.datasets.latent",
    "src.datasets.light_stage": f"{_PKG}.datasets.light_stage",
    "src.models.nerf.network": f"{_PKG}.models.nerf.network",
    "src.models.img_fit.network": f"{_PKG}.models.img_fit.network",
    "src.models.nerf.renderer.volume_renderer": f"{_PKG}.renderer.volume",
    "src.models.nerf.renderer.make_renderer": f"{_PKG}.renderer",
    "src.train.trainers.nerf": f"{_PKG}.train.loss",
    "src.train.losses.img_fit": f"{_PKG}.train.loss_img_fit",
    "src.evaluators.nerf": f"{_PKG}.evaluators.nerf",
    "src.evaluators.img_fit": f"{_PKG}.evaluators.img_fit",
}


def register_alias(name: str, target: str) -> None:
    """Register (or override) a module-name alias."""
    _ALIASES[name] = target


def resolve_module(name: str) -> ModuleType:
    """Resolve a ``*_module`` config string to an imported module.

    A value ending in ``.py`` is loaded from that FILE PATH — the seat of
    the reference's ``imp.load_source`` (make_dataset.py:16-29), which lets
    a task plugin live OUTSIDE the package tree and still be selected from
    YAML. Loaded path-modules are cached by absolute path."""
    if name.endswith(".py"):
        return _load_from_path(name)
    target = _ALIASES.get(name, name)
    try:
        return importlib.import_module(target)
    except ImportError as e:
        if name.startswith("src."):
            # Heuristic fallback for unaliased reference-style names.
            guess = _PKG + name[len("src") :]
            try:
                return importlib.import_module(guess)
            except ImportError:
                pass
        raise ImportError(
            f"Cannot resolve plugin module {name!r} (tried {target!r})"
        ) from e


_PATH_MODULES: dict[str, ModuleType] = {}


def _load_from_path(path: str) -> ModuleType:
    import importlib.util
    import os

    key = os.path.abspath(path)
    mod = _PATH_MODULES.get(key)
    if mod is not None:
        return mod
    if not os.path.isfile(key):
        raise ImportError(f"Plugin file {path!r} does not exist")
    # key the module name by the FULL path, not the basename: two plugin
    # files named e.g. network.py in different directories must not
    # overwrite each other's sys.modules entry (round-4 advisor finding —
    # re-import/pickle of the first would silently resolve to the second)
    import hashlib

    digest = hashlib.sha1(key.encode()).hexdigest()[:12]
    modname = (
        "_nerf_plugin_"
        + os.path.splitext(os.path.basename(key))[0]
        + "_"
        + digest
    )
    spec = importlib.util.spec_from_file_location(modname, key)
    mod = importlib.util.module_from_spec(spec)
    # register BEFORE exec so plugin-defined classes are re-importable by
    # name (pickle, dataclass machinery) — the standard importlib recipe
    import sys

    sys.modules[modname] = mod
    spec.loader.exec_module(mod)
    _PATH_MODULES[key] = mod
    return mod


def load_attr(module_name: str, attr: str, *fallbacks: str) -> Any:
    """Load ``attr`` (or the first present fallback) from a plugin module."""
    mod = resolve_module(module_name)
    for candidate in (attr, *fallbacks):
        if hasattr(mod, candidate):
            return getattr(mod, candidate)
    raise AttributeError(
        f"Plugin module {module_name!r} defines none of {(attr, *fallbacks)}"
    )
