"""Shared scanning of append-only sweep records (BENCH_SWEEP*.jsonl).

Sweep files are append-only (a crash must never destroy prior records), so
a point may appear many times across runs. The repo-wide recency rule: the
LAST record per (config, n_rays, dtype, remat, scan_steps, grad_accum,
opts) key wins, ordered by the record's ``ts`` (absent on pre-round-3
records ⇒ oldest), ties by file/line order. Used by scripts/promote_bench_defaults.py (writing BENCH_DEFAULTS.
json) and bench.py's failure diagnostics — one implementation, one rule.
"""

from __future__ import annotations

import json


def latest_points(paths) -> dict:
    """{(config, n_rays, dtype, remat, scan_steps, grad_accum, opts):
    record} after recency resolution.

    Malformed lines are skipped; error/null records are kept here (the
    caller decides) so a re-measured failure correctly supersedes an old
    success for its point.
    """
    latest: dict = {}
    for path in paths:
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            key = (
                rec.get("config", "lego.yaml"),
                rec.get("n_rays"),
                rec.get("dtype"),
                rec.get("remat"),
                rec.get("scan_steps", 1),
                rec.get("grad_accum", 1),
                rec.get("opts", ""),
            )
            if key not in latest or rec.get("ts", 0) >= latest[key].get("ts", 0):
                latest[key] = rec
    return latest


def best_point(paths, config: str | None = None):
    """The highest-value current (post-recency) record, or None.

    ``config`` filters to one config; None considers every config.
    """
    valid = [
        r for (cfg_name, *_), r in latest_points(paths).items()
        if isinstance(r.get("value"), (int, float))
        and (config is None or cfg_name == config)
    ]
    return max(valid, key=lambda r: r["value"], default=None)
