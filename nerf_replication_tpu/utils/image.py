"""Image metrics and I/O helpers (PSNR, SSIM, PNG writing).

The reference uses skimage for SSIM (src/evaluators/nerf.py:43); that
dependency is replaced by a native implementation of Wang et al. SSIM
(gaussian 11×11 window, sigma 1.5, K1=0.01, K2=0.03) over float images with
``data_range=1`` — fixing the reference's nonstandard
``data_range=pred.max()-pred.min()`` quirk (SURVEY.md §2.5).
"""

from __future__ import annotations

import os

import numpy as np


def psnr(pred: np.ndarray, gt: np.ndarray) -> float:
    """-10·log10(mse) on float images in [0, 1] (src/evaluators/nerf.py:23-26)."""
    mse = float(np.mean((pred.astype(np.float64) - gt.astype(np.float64)) ** 2))
    if mse == 0:
        return float("inf")
    return float(-10.0 * np.log10(mse))


def _gaussian_kernel(size: int = 11, sigma: float = 1.5) -> np.ndarray:
    x = np.arange(size, dtype=np.float64) - (size - 1) / 2
    g = np.exp(-(x**2) / (2 * sigma**2))
    return g / g.sum()


def _filter2d_sep(img: np.ndarray, k: np.ndarray) -> np.ndarray:
    """Separable 'valid' gaussian filtering over the two leading axes."""
    from numpy.lib.stride_tricks import sliding_window_view

    w = k.size
    out = sliding_window_view(img, w, axis=0) @ k
    out = sliding_window_view(out, w, axis=1) @ k
    return out


def ssim(pred: np.ndarray, gt: np.ndarray, data_range: float = 1.0) -> float:
    """Mean SSIM; channels averaged. Inputs [H, W] or [H, W, C] floats."""
    pred = np.asarray(pred, np.float64)
    gt = np.asarray(gt, np.float64)
    if pred.ndim == 2:
        pred, gt = pred[..., None], gt[..., None]
    k = _gaussian_kernel()
    c1 = (0.01 * data_range) ** 2
    c2 = (0.03 * data_range) ** 2
    vals = []
    for c in range(pred.shape[-1]):
        x, y = pred[..., c], gt[..., c]
        mu_x = _filter2d_sep(x, k)
        mu_y = _filter2d_sep(y, k)
        xx = _filter2d_sep(x * x, k) - mu_x**2
        yy = _filter2d_sep(y * y, k) - mu_y**2
        xy = _filter2d_sep(x * y, k) - mu_x * mu_y
        s = ((2 * mu_x * mu_y + c1) * (2 * xy + c2)) / (
            (mu_x**2 + mu_y**2 + c1) * (xx + yy + c2)
        )
        vals.append(s.mean())
    return float(np.mean(vals))


def write_png(path: str, img: np.ndarray):
    """Write a float [0,1] or uint8 image as PNG."""
    import imageio.v2 as imageio

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if img.dtype != np.uint8:
        img = (np.clip(img, 0.0, 1.0) * 255).astype(np.uint8)
    imageio.imwrite(path, img)
