"""COLMAP sparse-model I/O: read AND write, binary AND text.

The reference vendors COLMAP's own scripts for this
(src/utils/colmap/read_write_model.py:1-503, with self-tests that are
not wired to any runner — test_read_write_model.py). This is an
independent implementation of the public COLMAP model format, sized to
what a capture workflow actually touches: cameras/images/points3D in
both encodings, round-trippable, with the quaternion helpers. The
vestigial remainder of that vendored package (flickr crawler, windows
app builder, bundler/VisualSFM exporters) is deliberately not carried —
see docs/parity.md.

Format (public spec, reimplemented from scratch):

* ``cameras.bin``   — u64 count, then per camera: i32 id, i32 model_id,
  u64 width, u64 height, f64 params[n_params(model)].
* ``images.bin``    — u64 count, then per image: i32 id, f64 qvec[4]
  (w, x, y, z), f64 tvec[3], i32 camera_id, NUL-terminated name,
  u64 n_points2D, then f64 x, f64 y, i64 point3D_id per observation.
* ``points3D.bin``  — u64 count, then per point: i64 id, f64 xyz[3],
  u8 rgb[3], f64 error, u64 track_len, then i32 image_id,
  i32 point2D_idx per track element.
* ``*.txt``         — same fields, ``#`` comments; images.txt uses two
  lines per image (header, then the observation triplets).

Poses are world→camera (COLMAP convention); ``qvec2rotmat`` /
``rotmat2qvec`` convert to/from rotation matrices.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field

import numpy as np

# model_id -> (name, n_params); public COLMAP camera-model table
CAMERA_MODELS = {
    0: ("SIMPLE_PINHOLE", 3),
    1: ("PINHOLE", 4),
    2: ("SIMPLE_RADIAL", 4),
    3: ("RADIAL", 5),
    4: ("OPENCV", 8),
    5: ("OPENCV_FISHEYE", 8),
    6: ("FULL_OPENCV", 12),
    7: ("FOV", 5),
    8: ("SIMPLE_RADIAL_FISHEYE", 4),
    9: ("RADIAL_FISHEYE", 5),
    10: ("THIN_PRISM_FISHEYE", 12),
}
CAMERA_MODEL_IDS = {name: mid for mid, (name, _) in CAMERA_MODELS.items()}


@dataclass
class Camera:
    id: int
    model: str  # name, e.g. "PINHOLE"
    width: int
    height: int
    params: np.ndarray  # [n_params] f64


@dataclass
class Image:
    id: int
    qvec: np.ndarray  # [4] f64, (w, x, y, z), world->camera
    tvec: np.ndarray  # [3] f64
    camera_id: int
    name: str
    xys: np.ndarray = field(
        default_factory=lambda: np.zeros((0, 2), np.float64)
    )
    point3D_ids: np.ndarray = field(
        default_factory=lambda: np.zeros((0,), np.int64)
    )


@dataclass
class Point3D:
    id: int
    xyz: np.ndarray  # [3] f64
    rgb: np.ndarray  # [3] u8
    error: float
    image_ids: np.ndarray  # [track] i32
    point2D_idxs: np.ndarray  # [track] i32


def qvec2rotmat(q) -> np.ndarray:
    w, x, y, z = (float(v) for v in q)
    return np.array(
        [
            [
                1 - 2 * (y * y + z * z),
                2 * (x * y - w * z),
                2 * (x * z + w * y),
            ],
            [
                2 * (x * y + w * z),
                1 - 2 * (x * x + z * z),
                2 * (y * z - w * x),
            ],
            [
                2 * (x * z - w * y),
                2 * (y * z + w * x),
                1 - 2 * (x * x + y * y),
            ],
        ]
    )


def rotmat2qvec(R) -> np.ndarray:
    """Rotation matrix -> (w, x, y, z), w >= 0 (Shepperd's branch pick)."""
    R = np.asarray(R, np.float64)
    t = np.trace(R)
    if t > 0:
        s = np.sqrt(t + 1.0) * 2
        q = np.array(
            [0.25 * s, (R[2, 1] - R[1, 2]) / s, (R[0, 2] - R[2, 0]) / s,
             (R[1, 0] - R[0, 1]) / s]
        )
    elif R[0, 0] >= R[1, 1] and R[0, 0] >= R[2, 2]:
        s = np.sqrt(1.0 + R[0, 0] - R[1, 1] - R[2, 2]) * 2
        q = np.array(
            [(R[2, 1] - R[1, 2]) / s, 0.25 * s,
             (R[0, 1] + R[1, 0]) / s, (R[0, 2] + R[2, 0]) / s]
        )
    elif R[1, 1] >= R[2, 2]:
        s = np.sqrt(1.0 - R[0, 0] + R[1, 1] - R[2, 2]) * 2
        q = np.array(
            [(R[0, 2] - R[2, 0]) / s, (R[0, 1] + R[1, 0]) / s,
             0.25 * s, (R[1, 2] + R[2, 1]) / s]
        )
    else:
        s = np.sqrt(1.0 - R[0, 0] - R[1, 1] + R[2, 2]) * 2
        q = np.array(
            [(R[1, 0] - R[0, 1]) / s, (R[0, 2] + R[2, 0]) / s,
             (R[1, 2] + R[2, 1]) / s, 0.25 * s]
        )
    if q[0] < 0:
        q = -q
    return q


# ---------------------------------------------------------------- binary

def _read(f, fmt):
    return struct.unpack(fmt, f.read(struct.calcsize(fmt)))


def read_cameras_bin(path) -> dict[int, Camera]:
    out = {}
    with open(path, "rb") as f:
        (n,) = _read(f, "<Q")
        for _ in range(n):
            cid, mid, w, h = _read(f, "<iiQQ")
            if mid not in CAMERA_MODELS:
                raise ValueError(f"{path}: unknown camera model id {mid}")
            name, n_p = CAMERA_MODELS[mid]
            params = np.array(_read(f, f"<{n_p}d"))
            out[cid] = Camera(cid, name, int(w), int(h), params)
    return out


def write_cameras_bin(cameras: dict[int, Camera], path) -> None:
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(cameras)))
        for cam in cameras.values():
            mid = CAMERA_MODEL_IDS[cam.model]
            n_p = CAMERA_MODELS[mid][1]
            if len(cam.params) != n_p:
                raise ValueError(
                    f"camera {cam.id}: {cam.model} wants {n_p} params, "
                    f"got {len(cam.params)}"
                )
            f.write(
                struct.pack("<iiQQ", cam.id, mid, cam.width, cam.height)
            )
            f.write(struct.pack(f"<{n_p}d", *map(float, cam.params)))


def read_images_bin(path, skip_points2D: bool = False) -> dict[int, Image]:
    """``skip_points2D`` seeks past the observation records (a pose-only
    consumer like colmap2nerf avoids materializing ~24 B × n_obs per
    image); the Images then carry empty xys/point3D_ids."""
    out = {}
    empty_xy = np.zeros((0, 2), np.float64)
    empty_id = np.zeros((0,), np.int64)
    with open(path, "rb") as f:
        (n,) = _read(f, "<Q")
        for _ in range(n):
            iid = _read(f, "<i")[0]
            vals = _read(f, "<7d")
            cam_id = _read(f, "<i")[0]
            name = bytearray()
            while True:
                c = f.read(1)
                if c == b"\x00":
                    break
                if c == b"":
                    raise ValueError(
                        f"{path}: truncated (EOF inside image name)"
                    )
                name += c
            (m,) = _read(f, "<Q")
            if skip_points2D:
                f.seek(24 * m, os.SEEK_CUR)
                xys, p3d = empty_xy, empty_id
            else:
                # each observation is (f64 x, f64 y, i64 point3D_id): read
                # the 24-byte records raw, reinterpret the column groups
                trip = np.frombuffer(
                    f.read(24 * m), np.uint8
                ).reshape(m, 24)
                xys = trip[:, :16].copy().view(np.float64).reshape(m, 2)
                p3d = trip[:, 16:].copy().view(np.int64).reshape(m)
            out[iid] = Image(
                iid, np.array(vals[:4]), np.array(vals[4:]), cam_id,
                name.decode("utf-8"), xys, p3d,
            )
    return out


def write_images_bin(images: dict[int, Image], path) -> None:
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(images)))
        for im in images.values():
            f.write(struct.pack("<i", im.id))
            f.write(struct.pack("<7d", *im.qvec, *im.tvec))
            f.write(struct.pack("<i", im.camera_id))
            f.write(im.name.encode("utf-8") + b"\x00")
            m = len(im.point3D_ids)
            f.write(struct.pack("<Q", m))
            for k in range(m):
                f.write(
                    struct.pack(
                        "<ddq",
                        float(im.xys[k, 0]),
                        float(im.xys[k, 1]),
                        int(im.point3D_ids[k]),
                    )
                )


def read_points3D_bin(path) -> dict[int, Point3D]:
    out = {}
    with open(path, "rb") as f:
        (n,) = _read(f, "<Q")
        for _ in range(n):
            pid = _read(f, "<q")[0]
            xyz = np.array(_read(f, "<3d"))
            rgb = np.array(_read(f, "<3B"), np.uint8)
            (err,) = _read(f, "<d")
            (t,) = _read(f, "<Q")
            track = np.array(_read(f, f"<{2 * t}i"), np.int32).reshape(t, 2)
            out[pid] = Point3D(
                pid, xyz, rgb, float(err), track[:, 0].copy(),
                track[:, 1].copy(),
            )
    return out


def write_points3D_bin(points: dict[int, Point3D], path) -> None:
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(points)))
        for p in points.values():
            f.write(struct.pack("<q3d3Bd", p.id, *map(float, p.xyz),
                                *map(int, p.rgb), float(p.error)))
            t = len(p.image_ids)
            f.write(struct.pack("<Q", t))
            for k in range(t):
                f.write(struct.pack("<ii", int(p.image_ids[k]),
                                    int(p.point2D_idxs[k])))


# ------------------------------------------------------------------ text

def _data_lines(path):
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                yield line


def read_cameras_txt(path) -> dict[int, Camera]:
    out = {}
    for line in _data_lines(path):
        parts = line.split()
        cid, model, w, h = (
            int(parts[0]), parts[1], int(parts[2]), int(parts[3])
        )
        out[cid] = Camera(cid, model, w, h,
                          np.array([float(x) for x in parts[4:]]))
    return out


def write_cameras_txt(cameras: dict[int, Camera], path) -> None:
    with open(path, "w") as f:
        f.write("# Camera list: CAMERA_ID MODEL WIDTH HEIGHT PARAMS[]\n")
        for cam in cameras.values():
            ps = " ".join(repr(float(p)) for p in cam.params)
            f.write(f"{cam.id} {cam.model} {cam.width} {cam.height} {ps}\n")


def read_images_txt(path, skip_points2D: bool = False) -> dict[int, Image]:
    # an image's observation line may be legitimately EMPTY, so blank
    # lines can't be skipped wholesale (that desyncs the 2-line pairing):
    # skip blanks/comments only while LOOKING FOR a header, then consume
    # the immediately following line — whatever it holds — as the
    # observations (same discipline as scripts/colmap2nerf.py)
    out = {}
    with open(path) as f:
        lines = f.read().splitlines()
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        i += 1
        if not line or line.startswith("#"):
            continue
        header = line.split(maxsplit=9)
        if len(header) < 10:
            # junk/partial line — not an image header; do NOT consume a
            # partner line (matches COLMAP's own reader tolerance)
            continue
        parts = (
            [] if skip_points2D or i >= len(lines) else lines[i].split()
        )
        i += 1
        iid = int(header[0])
        q = np.array([float(v) for v in header[1:5]])
        t = np.array([float(v) for v in header[5:8]])
        cam_id = int(header[8])
        name = header[9]
        m = len(parts) // 3
        xys = np.array(
            [[float(parts[3 * k]), float(parts[3 * k + 1])]
             for k in range(m)]
        ).reshape(m, 2)
        p3d = np.array([int(parts[3 * k + 2]) for k in range(m)], np.int64)
        out[iid] = Image(iid, q, t, cam_id, name, xys, p3d)
    return out


def write_images_txt(images: dict[int, Image], path) -> None:
    with open(path, "w") as f:
        f.write(
            "# Image list, two lines per image:\n"
            "#   IMAGE_ID QW QX QY QZ TX TY TZ CAMERA_ID NAME\n"
            "#   POINTS2D[] as (X, Y, POINT3D_ID)\n"
        )
        for im in images.values():
            pose = " ".join(repr(float(v)) for v in (*im.qvec, *im.tvec))
            f.write(f"{im.id} {pose} {im.camera_id} {im.name}\n")
            f.write(
                " ".join(
                    f"{float(im.xys[k, 0])!r} {float(im.xys[k, 1])!r} "
                    f"{int(im.point3D_ids[k])}"
                    for k in range(len(im.point3D_ids))
                )
                + "\n"
            )


def read_points3D_txt(path) -> dict[int, Point3D]:
    out = {}
    for line in _data_lines(path):
        parts = line.split()
        pid = int(parts[0])
        xyz = np.array([float(v) for v in parts[1:4]])
        rgb = np.array([int(v) for v in parts[4:7]], np.uint8)
        err = float(parts[7])
        track = parts[8:]
        t = len(track) // 2
        out[pid] = Point3D(
            pid, xyz, rgb, err,
            np.array([int(track[2 * k]) for k in range(t)], np.int32),
            np.array([int(track[2 * k + 1]) for k in range(t)], np.int32),
        )
    return out


def write_points3D_txt(points: dict[int, Point3D], path) -> None:
    with open(path, "w") as f:
        f.write(
            "# 3D point list: POINT3D_ID X Y Z R G B ERROR "
            "TRACK[] as (IMAGE_ID, POINT2D_IDX)\n"
        )
        for p in points.values():
            xyz = " ".join(repr(float(v)) for v in p.xyz)
            rgb = " ".join(str(int(v)) for v in p.rgb)
            tr = " ".join(
                f"{int(p.image_ids[k])} {int(p.point2D_idxs[k])}"
                for k in range(len(p.image_ids))
            )
            f.write(
                f"{p.id} {xyz} {rgb} {float(p.error)!r} {tr}\n".rstrip()
                + "\n"
            )


# ------------------------------------------------------------- model dir

def detect_model_format(model_dir: str) -> str:
    if os.path.exists(os.path.join(model_dir, "cameras.bin")):
        return ".bin"
    if os.path.exists(os.path.join(model_dir, "cameras.txt")):
        return ".txt"
    raise FileNotFoundError(
        f"{model_dir}: neither cameras.bin nor cameras.txt"
    )


def read_model(model_dir: str, ext: str = "auto"):
    """(cameras, images, points3D) dicts from a model dir.

    ``ext``: ".bin", ".txt" or "auto". points3D is optional on disk
    (capture pipelines often prune it) — missing file reads as {}.
    """
    if ext == "auto":
        ext = detect_model_format(model_dir)
    rd = {
        ".bin": (read_cameras_bin, read_images_bin, read_points3D_bin),
        ".txt": (read_cameras_txt, read_images_txt, read_points3D_txt),
    }[ext]
    cams = rd[0](os.path.join(model_dir, "cameras" + ext))
    ims = rd[1](os.path.join(model_dir, "images" + ext))
    p3_path = os.path.join(model_dir, "points3D" + ext)
    pts = rd[2](p3_path) if os.path.exists(p3_path) else {}
    return cams, ims, pts


def write_model(cameras, images, points3D, model_dir: str,
                ext: str = ".bin") -> None:
    os.makedirs(model_dir, exist_ok=True)
    wr = {
        ".bin": (write_cameras_bin, write_images_bin, write_points3D_bin),
        ".txt": (write_cameras_txt, write_images_txt, write_points3D_txt),
    }[ext]
    wr[0](cameras, os.path.join(model_dir, "cameras" + ext))
    wr[1](images, os.path.join(model_dir, "images" + ext))
    wr[2](points3D, os.path.join(model_dir, "points3D" + ext))
