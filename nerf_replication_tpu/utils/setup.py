"""Shared eval-time bootstrap: build the network and load trained weights.

One implementation of the make_network → init_params → load_network sequence
every inference entry point needs (parity: the reference repeats this in
run.py:54-58, occupancy_grid.py:16-18, render_video.py:24-27).
"""

from __future__ import annotations

import jax


def load_trained_network(cfg, verbose: bool = True):
    """Returns ``(network, params, epoch)`` with params from the trained
    checkpoint (epoch selected by ``cfg.test.epoch``; -1 → latest)."""
    from ..models import init_params_for, make_network
    from ..train.checkpoint import load_network

    network = make_network(cfg)
    params = init_params_for(cfg)(network, jax.random.PRNGKey(0))
    params, epoch = load_network(
        cfg.trained_model_dir, params, epoch=int(cfg.test.get("epoch", -1))
    )
    if verbose:
        print(f"loaded network from {cfg.trained_model_dir} (epoch {epoch})")
    return network, params, epoch
