"""Shared eval-time bootstrap: build the network and load trained weights.

One implementation of the make_network → init_params → load_network sequence
every inference entry point needs (parity: the reference repeats this in
run.py:54-58, occupancy_grid.py:16-18, render_video.py:24-27).
"""

from __future__ import annotations

import random

import jax
import numpy as np


def configure_runtime(cfg) -> None:
    """Apply the config's debug/determinism switches to the JAX runtime.

    Parity with the reference's train.py:23-28: ``debug_nans`` is the
    NaN-anomaly detector (``set_detect_anomaly``, always-on there, opt-in
    here — it re-checks every primitive's output and costs throughput);
    ``fix_random`` pins the host-side RNGs the way cudnn.deterministic +
    global seeding does there. The device path needs no switch: explicit
    key threading already makes it deterministic and resumable.
    """
    import os

    # explicit platform pin for the CLIs (NERF_PLATFORM=cpu): plain
    # JAX_PLATFORMS is beaten by this machine's sitecustomize (see
    # utils/platform.py), which would silently send a CPU-intended run to a
    # possibly-wedged TPU tunnel
    # "cpu:8" pins the platform AND a virtual device count (the CLI route
    # to the multi-device emulation the tests/dryrun use)
    platform = os.environ.get("NERF_PLATFORM", "")
    if platform:
        from .platform import force_platform, parse_platform_pin

        force_platform(*parse_platform_pin(platform))
    # persistent executable cache: battery stages / sweep points are fresh
    # processes that would otherwise re-pay identical compiles (no-op if a
    # caller — e.g. the test harness — already configured a cache dir)
    from .platform import enable_compilation_cache

    enable_compilation_cache()
    if cfg.get("debug_nans", False):
        jax.config.update("jax_debug_nans", True)
    if cfg.get("fix_random", False):
        seed = int(cfg.get("seed", 0))
        random.seed(seed)
        np.random.seed(seed)


def load_trained_network(cfg, verbose: bool = True):
    """Returns ``(network, params, epoch)`` with params from the trained
    checkpoint (epoch selected by ``cfg.test.epoch``; -1 → latest).

    The init key threads ``cfg.seed`` (the values are overwritten by the
    checkpoint load, but the param-tree STRUCTURE must come from the same
    stream the trainer used — a hardcoded key here would silently diverge
    from a seed-varied training run for any init whose shapes depend on
    the draw)."""
    from ..models import init_params_for, make_network
    from ..train.checkpoint import load_network

    network = make_network(cfg)
    init_key = jax.random.PRNGKey(int(cfg.get("seed", 0)))
    params = init_params_for(cfg)(network, init_key)
    params, epoch = load_network(
        cfg.trained_model_dir, params, epoch=int(cfg.test.get("epoch", -1))
    )
    if verbose:
        print(f"loaded network from {cfg.trained_model_dir} (epoch {epoch})")
    return network, params, epoch
