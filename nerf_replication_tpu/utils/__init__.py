from . import image  # noqa: F401
