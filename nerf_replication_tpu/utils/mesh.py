"""Mesh extraction: dense density sweep → iso-surface → PLY export.

Capability parity with the reference's `extract_mesh`
(src/utils/mesh_utils.py:8-46: 256³ density query → marching_cubes_lewiner →
trimesh PLY, driven by ``cfg.level`` / ``cfg.resolution``). This image has no
skimage/trimesh, so both halves are native here:

* the density sweep is a jitted `lax.map` over voxel batches (same pattern as
  the occupancy bake);
* the iso-surface comes from **marching tetrahedra** (each cube split into 6
  tets; 2^4 sign cases each yield 0/1/2 triangles with edge-interpolated
  vertices) — topologically watertight per tet and far less table machinery
  than full marching cubes;
* PLY export is a ~30-line binary little-endian writer.
"""

from __future__ import annotations

import os
import struct

import jax
import jax.numpy as jnp
import numpy as np

# 6-tetrahedra decomposition of the unit cube (indices into its 8 corners,
# corner c ↔ offset bits (x=c&1, y=c>>1&1, z=c>>2&1)); all share diagonal 0-7
_TETS = (
    (0, 5, 1, 7), (0, 1, 3, 7), (0, 3, 2, 7),
    (0, 2, 6, 7), (0, 6, 4, 7), (0, 4, 5, 7),
)
_CORNER_OFFSETS = np.array(
    [[(c >> 0) & 1, (c >> 1) & 1, (c >> 2) & 1] for c in range(8)], np.float32
)


def sample_density_grid(params, network, bbox, resolution: int,
                        batch: int = 65536) -> np.ndarray:
    """[R, R, R] float σ of the COARSE head at voxel-corner grid points."""
    lo = np.asarray(bbox[0], np.float32)
    hi = np.asarray(bbox[1], np.float32)
    axes = [np.linspace(lo[d], hi[d], resolution, dtype=np.float32)
            for d in range(3)]
    pts = np.stack(np.meshgrid(*axes, indexing="ij"), -1).reshape(-1, 3)

    n = pts.shape[0]
    n_batches = -(-n // batch)
    pad = n_batches * batch - n
    pts_p = np.pad(pts, ((0, pad), (0, 0))).reshape(n_batches, batch, 3)

    # one-shot offline mesh export: the sweep runs exactly once per
    # invocation, so routing it through the AOT registry would only move
    # the same single compile somewhere less obvious
    @jax.jit  # graftlint: ok(aot: one-shot mesh-export sweep, no steady-state dispatch)
    def sweep(params, pts_p):
        def body(p):
            dirs = jnp.zeros((p.shape[0], 3), jnp.float32)
            raw = network.apply(params, p[:, None, :], dirs, model="coarse")
            return jax.nn.relu(raw[:, 0, 3])

        return jax.lax.map(body, pts_p)

    sigma = np.asarray(sweep(params, jnp.asarray(pts_p))).reshape(-1)[:n]
    return sigma.reshape(resolution, resolution, resolution)


def marching_tetrahedra(grid: np.ndarray, level: float, bbox) -> tuple:
    """(vertices [V, 3] world coords, faces [F, 3]) of the iso-surface."""
    R = grid.shape[0]
    lo = np.asarray(bbox[0], np.float64)
    hi = np.asarray(bbox[1], np.float64)
    spacing = (hi - lo) / (R - 1)

    # cube-corner values for every cell, vectorized: [nc, 8]
    idx = np.arange(R - 1)
    ci, cj, ck = np.meshgrid(idx, idx, idx, indexing="ij")
    base = np.stack([ci, cj, ck], -1).reshape(-1, 3)  # [nc, 3]
    corner_vals = np.empty((base.shape[0], 8), grid.dtype)
    for c in range(8):
        o = _CORNER_OFFSETS[c].astype(int)
        corner_vals[:, c] = grid[
            base[:, 0] + o[0], base[:, 1] + o[1], base[:, 2] + o[2]
        ]

    # single-corner cases: the separated corner's 3 edges → one triangle
    SINGLES = {1: 0, 2: 1, 4: 2, 8: 3, 14: 0, 13: 1, 11: 2, 7: 3}
    # two-two splits: 4 crossed edges → a quad → two triangles
    PAIRS = {
        3: ((0, 2), (0, 3), (1, 3), (1, 2)),
        12: ((0, 2), (1, 2), (1, 3), (0, 3)),
        5: ((0, 1), (0, 3), (2, 3), (2, 1)),
        10: ((0, 1), (2, 1), (2, 3), (0, 3)),
        6: ((1, 0), (1, 3), (2, 3), (2, 0)),
        9: ((1, 0), (2, 0), (2, 3), (1, 3)),
    }

    verts, faces = [], []
    for tet in _TETS:
        vals = corner_vals[:, tet]  # [nc, 4]
        inside = vals > level
        case = (
            inside[:, 0] * 1 + inside[:, 1] * 2
            + inside[:, 2] * 4 + inside[:, 3] * 8
        )
        tet_offsets = _CORNER_OFFSETS[list(tet)]

        def edge_point(cells, a, b):
            """Iso-crossing on tet edge (a, b) for the selected cells."""
            va, vb = vals[cells, a], vals[cells, b]
            t = (level - va) / np.where(vb - va == 0, 1e-12, vb - va)
            pa = base[cells] + tet_offsets[a]
            pb = base[cells] + tet_offsets[b]
            return pa + t[:, None] * (pb - pa)

        for code, corner in SINGLES.items():
            cells = np.nonzero(case == code)[0]
            if cells.size == 0:
                continue
            others = [c for c in range(4) if c != corner]
            tri = [edge_point(cells, corner, o) for o in others]
            _append_tris(verts, faces, tri)

        for code, quad in PAIRS.items():
            cells = np.nonzero(case == code)[0]
            if cells.size == 0:
                continue
            p = [edge_point(cells, *e) for e in quad]
            _append_tris(verts, faces, [p[0], p[1], p[2]])
            _append_tris(verts, faces, [p[0], p[2], p[3]])

    if not faces:
        return np.zeros((0, 3), np.float32), np.zeros((0, 3), np.int64)
    v = np.concatenate(verts, 0)
    f = np.concatenate(faces, 0)

    # weld: identical edge-crossings emitted by neighboring tets/cells merge
    # into shared vertices, so triangles connect into a manifold surface
    # (and the PLY shrinks ~6x). Quantize in index space; crossings of the
    # same grid edge agree to float rounding, so a fine grid snap is safe.
    quant = np.round(v * 1048576.0).astype(np.int64)
    _, first_idx, inverse = np.unique(
        quant, axis=0, return_index=True, return_inverse=True
    )
    v = v[first_idx]
    f = inverse[f]
    # drop triangles degenerated by the weld (two corners on one vertex)
    keep = (f[:, 0] != f[:, 1]) & (f[:, 1] != f[:, 2]) & (f[:, 0] != f[:, 2])
    f = f[keep]

    world = lo + v * spacing
    return world.astype(np.float32), f


def _append_tris(verts, faces, tri_pts):
    """Append one triangle per cell: tri_pts = [p0, p1, p2] each [nc, 3]."""
    nc = tri_pts[0].shape[0]
    v0 = sum(v.shape[0] for v in verts)
    verts.extend(tri_pts)
    idx = np.arange(nc)
    faces.append(np.stack([v0 + idx, v0 + nc + idx, v0 + 2 * nc + idx], -1))
    return v0


def write_ply(path: str, vertices: np.ndarray, faces: np.ndarray) -> str:
    """Binary little-endian PLY (the role trimesh.export plays in the
    reference, mesh_utils.py:44-46)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        header = (
            "ply\nformat binary_little_endian 1.0\n"
            f"element vertex {len(vertices)}\n"
            "property float x\nproperty float y\nproperty float z\n"
            f"element face {len(faces)}\n"
            "property list uchar int vertex_indices\nend_header\n"
        )
        f.write(header.encode("ascii"))
        f.write(np.ascontiguousarray(vertices, "<f4").tobytes())
        for tri in np.asarray(faces, np.int32):
            f.write(struct.pack("<B3i", 3, *tri))
    return path


def extract_mesh(params, network, cfg, out_path: str | None = None) -> str:
    """Full pipeline (mesh_utils.py:8-46): density sweep at cfg.resolution,
    iso-surface at cfg.level, PLY into the result dir."""
    bbox = np.asarray(cfg.train_dataset.scene_bbox, np.float32)
    grid = sample_density_grid(
        params, network, bbox, int(cfg.get("resolution", 256))
    )
    verts, faces = marching_tetrahedra(grid, float(cfg.get("level", 32.0)), bbox)
    if out_path is None:
        out_path = os.path.join(cfg.result_dir, "mesh.ply")
    return write_ply(out_path, verts, faces)
