"""Force the JAX platform on machines whose sitecustomize pins it at boot.

This machine's axon sitecustomize calls ``jax.config.update("jax_platforms",
...)`` at interpreter start, which BEATS the ``JAX_PLATFORMS`` env var — so
selecting the virtual-CPU platform (for tests, the driver's multi-chip
dryrun, or CI smoke runs) requires updating the config AFTER importing jax,
before any backend touch. One shared implementation; tests/conftest.py,
__graft_entry__.py, and bench.py all route through it.
"""

from __future__ import annotations

import os
import re


def force_platform(platform: str = "cpu", device_count: int | None = None) -> None:
    """Pin the JAX platform (and, for cpu, the virtual device count).

    Must run before the process touches any JAX backend; the XLA flag is
    read once at backend init. An existing
    ``--xla_force_host_platform_device_count`` flag is REWRITTEN, not kept:
    a stale count from the environment (or an earlier caller) would
    silently validate a different topology than requested.
    """
    os.environ["JAX_PLATFORMS"] = platform
    if device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        new = f"--xla_force_host_platform_device_count={device_count}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", new, flags
            )
        else:
            flags = (flags + " " + new).strip()
        os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", platform)
