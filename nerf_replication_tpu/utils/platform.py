"""Force the JAX platform on machines whose sitecustomize pins it at boot.

This machine's axon sitecustomize calls ``jax.config.update("jax_platforms",
...)`` at interpreter start, which BEATS the ``JAX_PLATFORMS`` env var — so
selecting the virtual-CPU platform (for tests, the driver's multi-chip
dryrun, or CI smoke runs) requires updating the config AFTER importing jax,
before any backend touch. One shared implementation; tests/conftest.py,
__graft_entry__.py, and bench.py all route through it.
"""

from __future__ import annotations

import os
import re


def force_platform(platform: str = "cpu", device_count: int | None = None) -> None:
    """Pin the JAX platform (and, for cpu, the virtual device count).

    Must run before the process touches any JAX backend; the XLA flag is
    read once at backend init. An existing
    ``--xla_force_host_platform_device_count`` flag is REWRITTEN, not kept:
    a stale count from the environment (or an earlier caller) would
    silently validate a different topology than requested.
    """
    os.environ["JAX_PLATFORMS"] = platform
    if device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        new = f"--xla_force_host_platform_device_count={device_count}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", new, flags
            )
        else:
            flags = (flags + " " + new).strip()
        os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", platform)


def enable_compilation_cache(path: str | None = None) -> None:
    """Persistent XLA executable cache shared across processes.

    Every battery stage / sweep point is a fresh Python process that would
    otherwise re-pay 20-40 s TPU compiles for shapes an earlier stage
    already built, and the CPU test suite re-compiles identical tiny
    executables on every run. Safe everywhere: a cache miss is just the
    normal compile path, and failures (read-only FS, unsupported backend)
    degrade to no caching.

    The default location anchors to the REPO root (this package's parent),
    not the process cwd — battery stages launched from different
    directories must resolve the same cache. A second call without an
    explicit ``path`` is a no-op when a cache dir is already configured,
    so an earlier caller's choice (e.g. the test harness's dedicated
    cache) is never clobbered.
    """
    import jax

    try:
        if path is None:
            if jax.config.jax_compilation_cache_dir:
                return  # respect an earlier caller's cache choice
            repo_root = os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            )
            path = os.path.join(repo_root, "data", "jax_cache")
        jax.config.update("jax_compilation_cache_dir", os.path.abspath(path))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        # -1 = no size floor (0 would filter every entry out)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass
