"""Force the JAX platform on machines whose sitecustomize pins it at boot.

This machine's axon sitecustomize calls ``jax.config.update("jax_platforms",
...)`` at interpreter start, which BEATS the ``JAX_PLATFORMS`` env var — so
selecting the virtual-CPU platform (for tests, the driver's multi-chip
dryrun, or CI smoke runs) requires updating the config AFTER importing jax,
before any backend touch. One shared implementation; tests/conftest.py,
__graft_entry__.py, and bench.py all route through it.
"""

from __future__ import annotations

import os
import re
import sys


def parse_platform_pin(value: str) -> tuple[str, int | None]:
    """Parse the documented pin syntax: ``"cpu"`` or ``"cpu:8"``.

    The single parser for every consumer of ``NERF_PLATFORM`` /
    ``--force_platform`` (utils/setup.configure_runtime, setup_backend,
    __graft_entry__) — a malformed value fails loudly, naming the value,
    instead of an int() traceback deep in backend setup."""
    name, _, count = value.partition(":")
    if not name:
        raise ValueError(f"malformed platform pin {value!r}: empty name")
    if not count:
        return name, None
    try:
        n = int(count)
    except ValueError:
        raise ValueError(
            f"malformed platform pin {value!r}: count {count!r} is not an "
            f"integer (expected e.g. 'cpu' or 'cpu:8')"
        ) from None
    if n <= 0:
        raise ValueError(
            f"malformed platform pin {value!r}: device count must be >= 1"
        )
    return name, n


def force_platform(platform: str = "cpu", device_count: int | None = None) -> None:
    """Pin the JAX platform (and, for cpu, the virtual device count).

    Must run before the process touches any JAX backend; the XLA flag is
    read once at backend init. An existing
    ``--xla_force_host_platform_device_count`` flag is REWRITTEN, not kept:
    a stale count from the environment (or an earlier caller) would
    silently validate a different topology than requested.
    """
    os.environ["JAX_PLATFORMS"] = platform
    if device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        new = f"--xla_force_host_platform_device_count={device_count}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", new, flags
            )
        else:
            flags = (flags + " " + new).strip()
        os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", platform)


def donation_argnums(*argnums: int) -> tuple[int, ...]:
    """``donate_argnums`` value honoring the active backend.

    Donating the input state halves parameter+optimizer HBM on
    accelerators, but XLA:CPU's input-output aliasing under the
    ``--xla_force_host_platform_device_count`` emulation (the test
    topology) is unsound: a donated buffer can be freed while the aliased
    output still references it, leaving stable pointer-pattern garbage in
    the output leaves — most reliably when the donated state was just
    restored from a checkpoint (numpy-backed leaves), and intermittently
    as the corrupted step counters tests/test_ngp.py triaged with retries.
    Host RAM is not the scarce resource donation exists for, so on the
    cpu backend every step executable keeps plain copy semantics.
    """
    import jax

    if jax.default_backend() == "cpu":
        return ()
    return tuple(argnums)


def enable_compilation_cache(path: str | None = None) -> None:
    """Persistent XLA executable cache shared across processes.

    Every battery stage / sweep point is a fresh Python process that would
    otherwise re-pay 20-40 s TPU compiles for shapes an earlier stage
    already built, and the CPU test suite re-compiles identical tiny
    executables on every run. Safe everywhere: a cache miss is just the
    normal compile path, and failures (read-only FS, unsupported backend)
    degrade to no caching.

    The default location anchors to the REPO root (this package's parent),
    not the process cwd — battery stages launched from different
    directories must resolve the same cache. A second call without an
    explicit ``path`` is a no-op when a cache dir is already configured,
    so an earlier caller's choice (e.g. the test harness's dedicated
    cache) is never clobbered.
    """
    import jax

    try:
        if path is None:
            if jax.config.jax_compilation_cache_dir:
                return  # respect an earlier caller's cache choice
            repo_root = os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            )
            path = os.path.join(repo_root, "data", "jax_cache")
        jax.config.update("jax_compilation_cache_dir", os.path.abspath(path))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        # -1 = no size floor (0 would filter every entry out)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as exc:
        # cache is an optimization: runs proceed uncached, but say so once
        print(
            f"warning: persistent compilation cache disabled: "
            f"{type(exc).__name__}: {exc}",
            file=sys.stderr,
        )


def init_backend_with_retry(
    retries: int | None = None,
    delay_s: float | None = None,
    hang_timeout_s: float | None = None,
    total_budget_s: float | None = None,
    delay_cap_s: float | None = None,
    trail: list | None = None,
):
    """Touch the device backend, retrying on transient init failures.

    The axon TPU tunnel on this machine is monoclient and can WEDGE (init
    hangs forever) or flap (UNAVAILABLE) — measured behavior: after an
    HBM-OOM compile storm the terminal restarts itself and answers again
    minutes later (its port increments on each restart). Every chip-facing
    entry point must bound its first backend touch or a wedged tunnel
    silently eats its whole time budget (round-3 failure mode: quality_run
    hung 20 min at 0% CPU on init).

    Two failure modes, two handlings:

    * init RAISES (UNAVAILABLE): transient — bounded retry.
    * init HANGS: probe in a SUBPROCESS (killable, doesn't poison this
      process's backend state, releases the monoclient tunnel on exit),
      then attach in-process under a watchdog thread. Each probe is a
      fresh interpreter that re-imports the axon sitecustomize, so the
      tunnel's post-restart port is re-resolved on every attempt — no
      stale-port state survives in this process until the attach, which
      only happens after a probe has already succeeded.

    The default budget is shaped to what wedges actually last on this
    machine (docs/operations.md: "minutes to hours"; the round-4 bench
    died because 3×120 s was too short): 6 probes with exponential
    backoff between them (delay_s, 2·delay_s, … capped at 320 s) —
    worst case ≈ 6×120 s probing + ~10 min sleeping ≈ 20 min, bounded
    by ``total_budget_s`` (a probe never starts with less than one
    probe-timeout of budget left, so the bound is hard to within one
    attach watchdog). Defaults come from ``BENCH_INIT_RETRIES`` /
    ``BENCH_INIT_DELAY_S`` / ``BENCH_INIT_DELAY_CAP_S`` /
    ``BENCH_INIT_TIMEOUT_S`` / ``BENCH_INIT_TOTAL_S`` so sweep drivers
    can narrow or widen it.

    ``trail``: optional list; every attempt appends a dict
    ``{attempt, t, outcome}`` so callers (bench.py) can emit the partial
    probe history in their failure record instead of an opaque error.

    Returns the device list; raises RuntimeError when the budget is spent.
    """
    import subprocess
    import sys
    import threading
    import time

    import jax

    if retries is None:
        retries = int(os.environ.get("BENCH_INIT_RETRIES", 6))
    if delay_s is None:
        delay_s = float(os.environ.get("BENCH_INIT_DELAY_S", 20))
    if hang_timeout_s is None:
        hang_timeout_s = float(os.environ.get("BENCH_INIT_TIMEOUT_S", 120))
    if total_budget_s is None:
        total_budget_s = float(os.environ.get("BENCH_INIT_TOTAL_S", 1500))
    if delay_cap_s is None:
        delay_cap_s = float(os.environ.get("BENCH_INIT_DELAY_CAP_S", 320))
    if trail is None:
        trail = []
    t_start = time.monotonic()

    def _attach_in_process():
        result: dict = {}

        def attach():
            try:
                result["devices"] = jax.devices()
            # graftlint: ok(swallow: error is returned to the retry loop, which logs it)
            except Exception as exc:
                result["error"] = exc

        t = threading.Thread(target=attach, daemon=True)
        t.start()
        t.join(hang_timeout_s)
        if t.is_alive():
            return None, RuntimeError(
                f"in-process backend init hung >{hang_timeout_s:.0f}s"
            )
        return result.get("devices"), result.get("error")

    def _note(outcome: str) -> None:
        trail.append(
            {
                "attempt": attempt,
                "t": round(time.monotonic() - t_start, 1),
                "outcome": outcome,
            }
        )

    last = "unknown"
    attempt = 0
    while attempt < retries:
        attempt += 1
        # every probe (including the first) is clamped to the remaining
        # budget: the documented bound must hold even when a caller sets
        # BENCH_INIT_TOTAL_S below one probe timeout — an overshooting
        # probe risks the caller's outer timeout killing bench.py before
        # its JSON failure record is printed.
        probe_timeout = min(
            hang_timeout_s,
            max(0.1, total_budget_s - (time.monotonic() - t_start)),
        )
        try:
            p = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True,
                text=True,
                timeout=probe_timeout,
            )
            if p.returncode == 0:
                devices, err = _attach_in_process()
                if devices is not None:
                    print(
                        f"backend '{jax.default_backend()}' up, "
                        f"{len(devices)} device(s): {devices[0].device_kind}",
                        file=sys.stderr,
                    )
                    _note("ok")
                    return devices
                if isinstance(err, RuntimeError) and "hung" in str(err):
                    # a thread stuck in backend init holds the init lock:
                    # further in-process attempts block on it — fail fast
                    _note(f"attach hung: {err}")
                    err.trail = trail
                    raise err
                last = str(err)
            else:
                tail = (p.stderr or p.stdout).strip().splitlines()
                last = tail[-1] if tail else "probe exited nonzero"
        except subprocess.TimeoutExpired:
            last = (
                f"backend init hung >{probe_timeout:.0f}s (tunnel wedged?)"
            )
        _note(last)
        print(
            f"backend probe {attempt}/{retries} failed: {last}",
            file=sys.stderr,
        )
        elapsed = time.monotonic() - t_start
        if attempt >= retries:
            break
        # exponential backoff: wedges resolve on the tunnel's schedule
        # (minutes), so later waits should be long, and every probe
        # re-resolves the post-restart port in its own interpreter.
        sleep = min(delay_s * (2 ** (attempt - 1)), delay_cap_s)
        # hard budget: never launch a probe that cannot finish inside it —
        # an overshooting probe risks the CALLER's outer timeout killing
        # bench.py before it can emit its JSON failure record.
        if elapsed + sleep + hang_timeout_s > total_budget_s:
            break
        print(
            f"next probe in {sleep:.0f}s "
            f"(budget {elapsed:.0f}/{total_budget_s:.0f}s)",
            file=sys.stderr,
        )
        time.sleep(sleep)
    # name WHICH budget stopped the loop — a retry-count message on a
    # wall-budget cut sends the operator chasing a phantom retry bug
    reason = (
        f"retry budget ({retries}) spent"
        if attempt >= retries
        else (
            f"total budget ({total_budget_s:.0f}s) spent with "
            f"{retries - attempt} retries remaining"
        )
    )
    exc = RuntimeError(
        f"backend unavailable after {attempt} attempts / "
        f"{time.monotonic() - t_start:.0f}s ({reason}): {last}"
    )
    exc.trail = trail
    raise exc


def setup_backend(force_platform_name: str | None = None) -> None:
    """One-call backend setup for chip-facing entry points.

    ``force_platform_name`` set (e.g. "cpu"): pin that platform — CI /
    smoke / driver-dryrun path, no tunnel touched. Unset: guarded init of
    the real backend (``init_backend_with_retry``) so a wedged axon tunnel
    fails the entry point loudly instead of hanging it. Every script that
    can run on the chip routes through this — the round-3 20-minute silent
    hang was one entry point missing the guard.
    """
    # the documented escape hatch (docs/operations.md) must work on every
    # chip-facing CLI: an explicit --force_platform wins, else the
    # NERF_PLATFORM env pin ("cpu" / "cpu:8"), else guarded real init
    if not force_platform_name:
        force_platform_name = os.environ.get("NERF_PLATFORM", "")
    if force_platform_name:
        force_platform(*parse_platform_pin(force_platform_name))
        return
    try:
        init_backend_with_retry()
    except RuntimeError as exc:
        import sys

        print(f"backend init failed: {exc}", file=sys.stderr)
        sys.stderr.flush()
        # hard exit: a watchdogged attach thread may be wedged in C++
        # backend code and would block normal interpreter shutdown —
        # the stage must die NOW so its outer timeout budget survives.
        # (bench.py deliberately does NOT route through here: it must
        # catch the error itself to emit its JSON failure record first.)
        os._exit(1)
