"""Profiling & timing hooks.

Parity with the reference's ad-hoc instrumentation (SURVEY.md §5 "Tracing /
profiling": `perf_timer` in base_utils.py:11-59, CUDA-event timing in
volume_renderer.py:273-275, `torch.cuda.synchronize` wall-clocks in
run.py:35-39), plus the TPU-native additions: `jax.profiler` trace capture
(viewable in TensorBoard/XProf) and named trace annotations.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

_records: dict[str, list[float]] = defaultdict(list)


def _sync_devices():
    """Flush every local device's execution stream: a sentinel computation
    enqueued per device completes only after all previously dispatched
    programs on that device (the role of torch.cuda.synchronize,
    run.py:35-39). jax.effects_barrier is NOT enough — it only waits for
    effectful computations, not pure jitted work."""
    import jax.numpy as jnp

    jax.block_until_ready(
        [jax.device_put(jnp.zeros(()), d) + 0 for d in jax.local_devices()]
    )


@contextlib.contextmanager
def perf_timer(name: str, sync: bool = True, log=None):
    """Wall-clock a block; with ``sync``, drains all in-flight device work
    before and after so the block's device time is actually measured."""
    if sync:
        _sync_devices()
    t0 = time.perf_counter()
    yield
    if sync:
        _sync_devices()
    dt = time.perf_counter() - t0
    _records[name].append(dt)
    if log is not None:
        log(f"[perf] {name}: {dt:.4f}s")


def timings(name: str | None = None):
    """Recorded durations: one list, or all of them."""
    if name is not None:
        return list(_records[name])
    return {k: list(v) for k, v in _records.items()}


def reset_timings():
    _records.clear()


@contextlib.contextmanager
def device_trace(log_dir: str):
    """Capture a jax.profiler trace for the block (open with TensorBoard's
    profile plugin / XProf)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up on the device timeline."""
    return jax.profiler.TraceAnnotation(name)


def time_fn(fn, *args, iters: int = 10, warmup: int = 2, **kwargs) -> float:
    """Mean seconds per call, compile excluded, device-synced
    (run.py:15-40's `--type network` timing contract)."""
    for _ in range(warmup):
        out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters
