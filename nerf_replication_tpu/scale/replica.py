"""Replica runtime: one serving stack (engine + micro-batcher) behind
the front-door router.

Two concrete shapes share one duck-typed surface (``replica_id``,
``state``, ``submit``, ``load``, ``heartbeat``, ``drain``, ``kill``):

* :class:`InProcessReplica` — an engine + MicroBatcher in this process.
  What serve_bench's ``--replicas`` mode and chaos_run's kill-a-replica
  scenario spawn: real executables, real warm-start economics (a fresh
  replica built against the shared ``.aot`` artifact dir reports
  ``warm_source == "disk"`` with zero compiles), without process
  plumbing in the way of measurement.
* :class:`ProcessReplica` — a ``serve.py`` child process reached over
  HTTP. The production shape: heartbeats are ``GET /healthz`` (which
  carries the replica block — warm source, compile count, resident
  scenes), drain is ``POST /drain``.

Lifecycle: ``starting -> ready -> draining -> retired``, with ``dead``
reachable from anywhere (missed heartbeats or a crash). Draining stops
NEW admissions at the router while everything already queued renders to
completion — retirement never fails an in-flight request.
"""

from __future__ import annotations

import time

from ..obs import get_emitter
from ..obs.metrics import get_metrics
from ..obs.trace import trace_headers


class ReplicaState:
    STARTING = "starting"
    READY = "ready"
    DRAINING = "draining"
    RETIRED = "retired"
    DEAD = "dead"


class ReplicaUnavailableError(RuntimeError):
    """The replica cannot accept this request (draining/retired/dead);
    the router fails over to another replica."""


def _emit_lifecycle(replica_id: str, event: str, **fields) -> None:
    get_emitter().emit("replica", replica=replica_id, event=event, **fields)
    get_metrics().counter("scale_replica_events_total", event=event)


class InProcessReplica:
    """One engine + batcher wearing the replica surface."""

    def __init__(self, replica_id: str, engine, batcher,
                 clock=time.monotonic, capacity=None):
        self.replica_id = str(replica_id)
        self.engine = engine
        self.batcher = batcher
        self.clock = clock
        # optional obs.capacity.CapacityLedger: per-scene heat accounting
        # on the submit path (serve_bench snapshots one per replica)
        self.capacity = capacity
        self.state = ReplicaState.READY
        self.n_submitted = 0
        self.spawned_t = clock()
        stats = engine.stats()
        self.warm_source = stats.get("warm_source")
        self.warm_compiles = int(stats.get("total_compiles", 0))
        _emit_lifecycle(
            self.replica_id, "ready",
            state=self.state,
            warm_source=self.warm_source or "",
            total_compiles=self.warm_compiles,
        )

    # -- serving --------------------------------------------------------------

    def accepting(self) -> bool:
        return self.state == ReplicaState.READY

    # the router passes the routed request's SpanContext explicitly
    # (InProcessReplica shares the router's process, so "propagation" is
    # an argument, not a header) — see Router.submit
    accepts_ctx = True

    # request shapes this replica serves: the router filters candidates
    # on this (a capability mismatch is not a failover — the replica is
    # healthy, it just doesn't speak that protocol)
    capabilities = ("rays",)

    def submit(self, rays, near, far, scene=None, tenant=None, ctx=None):
        """Enqueue on this replica's batcher (router-facing). Raises
        :class:`ReplicaUnavailableError` when not accepting, so the
        router's failover loop moves on without losing the request."""
        if not self.accepting():
            raise ReplicaUnavailableError(
                f"replica {self.replica_id} is {self.state}"
            )
        self.n_submitted += 1
        if self.capacity is not None:
            self.capacity.note_request(scene or "default", len(rays))
        return self.batcher.submit(rays, near, far, scene=scene,
                                   tenant=tenant, ctx=ctx)

    def load(self) -> int:
        """Routing load signal: requests queued and not yet completed."""
        return self.batcher.queue_depth()

    def resident_scenes(self) -> list[str]:
        return self.engine.resident_scenes()

    # -- lifecycle ------------------------------------------------------------

    def heartbeat(self) -> dict:
        """The registration payload the router sweeps (pull model: one
        code path for in-process and HTTP replicas)."""
        if self.state == ReplicaState.DEAD:
            raise ReplicaUnavailableError(
                f"replica {self.replica_id} is dead"
            )
        health = self.batcher.health()
        beat = {
            "replica": self.replica_id,
            "state": self.state,
            "ok": bool(health.get("ok")),
            "load": self.load(),
            "scenes": self.resident_scenes(),
            "warm_source": self.warm_source,
            "total_compiles": int(self.engine.tracker.total_compiles()),
        }
        # full residency state for the placement planner: staging-tier
        # ids plus byte watermarks/budgets straight off the ladder
        fleet = getattr(self.engine, "fleet", None)
        if fleet is not None:
            fs = fleet.stats()
            beat.update(
                staging=list(fs.get("staging", [])),
                hbm_bytes=int(fs.get("resident_bytes", 0)),
                staging_bytes=int(fs.get("staging_bytes", 0)),
                hbm_budget_bytes=int(fs.get("budget_bytes", 0)),
                staging_budget_bytes=int(fs.get("staging_budget_bytes", 0)),
                # model-parallel serving (scale.mesh_shape): the planner
                # packs 1/shards of each scene's bytes onto this replica
                param_shards=int(fs.get("param_shards", 1)),
            )
        return beat

    def drain(self, timeout_s: float = 60.0) -> int:
        """Render everything queued, then retire. Returns the number of
        in-flight requests that FAILED during the drain — the
        drain-before-retire contract wants exactly zero."""
        if self.state in (ReplicaState.RETIRED, ReplicaState.DEAD):
            return 0
        self.state = ReplicaState.DRAINING
        _emit_lifecycle(self.replica_id, "drain", state=self.state,
                        load=self.load())
        failures_before = (self.batcher.n_timeouts
                          + self.batcher.n_dispatch_errors
                          + self.batcher.n_scene_errors)
        if self.batcher._started:
            self.batcher.close(drain=True)
        else:
            # test/manual-drive batchers (start=False) drain synchronously
            deadline = self.clock() + timeout_s
            while self.batcher.queue_depth() and self.clock() < deadline:
                self.batcher.pump()
        failed = (self.batcher.n_timeouts
                  + self.batcher.n_dispatch_errors
                  + self.batcher.n_scene_errors) - failures_before
        self.state = ReplicaState.RETIRED
        _emit_lifecycle(self.replica_id, "retire", state=self.state,
                        n_ready=0, detail=f"drain_failed={failed}")
        return failed

    def kill(self) -> None:
        """Simulated process death (the chaos path): queued futures fail
        immediately, heartbeats start raising."""
        self.state = ReplicaState.DEAD
        _emit_lifecycle(self.replica_id, "dead", state=self.state)
        # close(drain=False) fails every queued future immediately —
        # with no worker thread it just never joins one
        self.batcher.close(drain=False)

    # -- fleet metrics --------------------------------------------------------

    def metrics_source_id(self) -> str:
        """In-process replicas all write the PROCESS registry — the fleet
        aggregator dedups scrapes on this id so N in-process replicas
        contribute one copy, not N."""
        return "process"

    def scrape_metrics(self) -> str:
        return get_metrics().render_prometheus()

    def stats(self) -> dict:
        return {
            "replica": self.replica_id,
            "state": self.state,
            "n_submitted": self.n_submitted,
            "warm_source": self.warm_source,
            "warm_compiles": self.warm_compiles,
            "total_compiles": int(self.engine.tracker.total_compiles()),
            "batcher": self.batcher.stats(),
        }


class ProcessReplica:
    """A ``serve.py`` child process behind the same replica surface.

    Spawn-side only needs argv + environment: the child warms from the
    SHARED artifact dir (``compile.dir``), so its start-to-serving time
    is the BENCH_COLDSTART warm number, not a compile. ``submit`` is not
    implemented at the ray level — HTTP replicas serve whole poses via
    ``POST /render``; the router treats them as opaque capacity and
    routes pose requests. Used by operators/scripts, not tier-1 (no
    subprocess spawns in the test budget)."""

    # pose-only over HTTP: ray-level submit is the in-process surface
    capabilities = ("pose",)

    def __init__(self, replica_id: str, cfg_file: str, host: str,
                 port: int, python: str = "python",
                 clock=time.monotonic, healthz_ttl_s: float = 0.5):
        self.replica_id = str(replica_id)
        self.cfg_file = cfg_file
        self.host = host
        self.port = int(port)
        self.python = python
        self.clock = clock
        # one /healthz snapshot serves every probe inside the TTL: the
        # router calls load() AND resident_scenes() per candidate per
        # dispatch, and two HTTP round trips per routing decision is
        # the probe tax this cache removes
        self.healthz_ttl_s = float(healthz_ttl_s)
        self._beat_t = -float("inf")
        self._beat_health: dict | None = None
        self.state = ReplicaState.STARTING
        self.proc = None
        self.n_submitted = 0

    def argv(self) -> list[str]:
        return [self.python, "serve.py", "--cfg_file", self.cfg_file,
                "--host", self.host, "--port", str(self.port)]

    def spawn(self, env=None, cwd=None) -> None:
        import os
        import subprocess

        _emit_lifecycle(self.replica_id, "spawn", state=self.state)
        self.proc = subprocess.Popen(
            self.argv(), env={**os.environ, **(env or {}),
                              "SCALE_REPLICA_ID": self.replica_id},
            cwd=cwd,
        )

    def _get(self, path: str, timeout: float = 2.0) -> dict:
        import json
        import urllib.request

        # every fleet HTTP call carries the caller's span ctx (no-op
        # headers outside a traced request) — the child parents under it
        req = urllib.request.Request(
            f"http://{self.host}:{self.port}{path}",
            headers=trace_headers(),
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode())

    def accepting(self) -> bool:
        return self.state == ReplicaState.READY

    def _healthz(self, force: bool = False) -> dict:
        """The shared heartbeat snapshot. A fetch inside the TTL is
        free (cache hit); failures are never cached, so the
        unreachable→sentinel behavior of the probes is unchanged."""
        now = self.clock()
        if (not force and self._beat_health is not None
                and now - self._beat_t < self.healthz_ttl_s):
            return self._beat_health
        health = self._get("/healthz")
        self._beat_health = health
        self._beat_t = now
        return health

    def load(self) -> int:
        try:
            return int(self._healthz().get("queue_depth", 0))
        # graftlint: ok(swallow: routing probe; unreachable -> sentinel load, sweep owns the dead-marking)
        except Exception:
            return 1 << 30  # unreachable sorts last for routing

    def resident_scenes(self) -> list[str]:
        try:
            return list(self._healthz()
                        .get("replica", {}).get("scenes", []))
        # graftlint: ok(swallow: affinity hint only; empty set just loses the routing preference)
        except Exception:
            return []

    def heartbeat(self) -> dict:
        if self.proc is not None and self.proc.poll() is not None:
            self.state = ReplicaState.DEAD
            self._beat_health = None  # a dead child has no fresh beat
            raise ReplicaUnavailableError(
                f"replica {self.replica_id} exited "
                f"(code {self.proc.returncode})"
            )
        try:
            health = self._healthz()
        except Exception as exc:
            raise ReplicaUnavailableError(
                f"replica {self.replica_id} unreachable: {exc}"
            ) from exc
        if self.state == ReplicaState.STARTING:
            self.state = ReplicaState.READY
            _emit_lifecycle(self.replica_id, "ready", state=self.state)
        rep = health.get("replica", {})
        return {
            "replica": self.replica_id,
            "state": self.state,
            "ok": bool(health.get("ok")),
            "load": int(health.get("queue_depth", 0)),
            "scenes": list(rep.get("scenes", [])),
            "warm_source": rep.get("warm_source"),
            "total_compiles": int(rep.get("total_compiles", 0)),
            # full residency state for the placement planner (serve.py
            # /healthz carries the child's ladder tiers + watermarks)
            "staging": list(rep.get("staging", [])),
            "hbm_bytes": int(rep.get("hbm_bytes", 0)),
            "staging_bytes": int(rep.get("staging_bytes", 0)),
            "hbm_budget_bytes": int(rep.get("hbm_budget_bytes", 0)),
            "staging_budget_bytes": int(rep.get("staging_budget_bytes", 0)),
            "param_shards": int(rep.get("param_shards", 1)),
            # tracing health rides the heartbeat for free (spans emitted,
            # sink drops, remote-parented count) — serve.py /healthz
            "trace": dict(rep.get("trace", {})),
        }

    def submit(self, rays, near, far, scene=None, tenant=None):
        raise ReplicaUnavailableError(
            "ProcessReplica serves poses over HTTP (POST /render); "
            "ray-level submit is the in-process surface"
        )

    def render(self, body: dict, timeout_s: float = 30.0) -> dict:
        """``POST /render`` one pose request, stamping the caller's span
        ctx as the :data:`~..obs.trace.TRACE_HEADER` — the child's
        ``serve.request`` span parents under the router's dispatch span,
        which is what makes one routed request ONE trace."""
        import json
        import urllib.request

        if not self.accepting():
            raise ReplicaUnavailableError(
                f"replica {self.replica_id} is {self.state}"
            )
        self.n_submitted += 1
        data = json.dumps(body).encode()
        req = urllib.request.Request(
            f"http://{self.host}:{self.port}/render",
            data=data, method="POST",
            headers={"Content-Type": "application/json", **trace_headers()},
        )
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return json.loads(r.read().decode())

    # -- fleet metrics --------------------------------------------------------

    def metrics_source_id(self) -> str:
        """Each child process owns its registry — scrape every one."""
        return self.replica_id

    def scrape_metrics(self, timeout: float = 2.0) -> str:
        """Raw ``GET /metrics`` text from the child (Prometheus
        exposition; exemplar suffixes included) for the fleet merge."""
        import urllib.request

        req = urllib.request.Request(
            f"http://{self.host}:{self.port}/metrics",
            headers=trace_headers(),
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.read().decode()

    def drain(self, timeout_s: float = 60.0) -> int:
        self.state = ReplicaState.DRAINING
        _emit_lifecycle(self.replica_id, "drain", state=self.state)
        try:
            import urllib.request

            req = urllib.request.Request(
                f"http://{self.host}:{self.port}/drain", method="POST",
                headers=trace_headers(),
            )
            with urllib.request.urlopen(req, timeout=timeout_s):
                pass  # response body unused; the with closes the socket
        # graftlint: ok(swallow: best-effort drain request; the wait-loop below is the authority)
        except Exception:
            pass  # the wait-loop below is the authority
        deadline = self.clock() + timeout_s
        while self.clock() < deadline:
            try:
                if int(self._get("/healthz").get("queue_depth", 0)) == 0:
                    break
            # graftlint: ok(swallow: unreachable mid-drain means the queue is gone; terminate below)
            except Exception:
                break
            time.sleep(0.2)
        if self.proc is not None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10.0)
            # graftlint: ok(swallow: terminate timed out; the kill() IS the handling)
            except Exception:
                self.proc.kill()
        self.state = ReplicaState.RETIRED
        _emit_lifecycle(self.replica_id, "retire", state=self.state)
        return 0

    def kill(self) -> None:
        self.state = ReplicaState.DEAD
        _emit_lifecycle(self.replica_id, "dead", state=self.state)
        if self.proc is not None:
            self.proc.kill()
