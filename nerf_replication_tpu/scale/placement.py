"""Scene placement planner: which replica holds which scene.

The fleet pieces exist — replicas scale (scale/supervisor.py), scenes
page through a two-tier residency ladder (fleet/ladder.py), and the
PR 16 :class:`~..obs.capacity.CapacityLedger` measures per-scene heat
and byte watermarks — but until now nothing DECIDED placement: the
router only reacted to residency it observed passively, so a hot scene
stayed one-replica-wide until traffic happened to spill. The
:class:`PlacementPlanner` closes that loop:

* **inputs** — the scene catalog (a :class:`~..fleet.store.SceneStore`
  or any registry duck), per-replica residency state off router
  heartbeats (:meth:`~.router.Router.residency_view`: HBM + staging
  scene sets, byte watermarks, ladder budgets), and windowed scene heat
  (requests/s, rays/s) from one or more capacity ledgers;
* **policy** — a scene at/above ``hot_rps`` is hot and is replicated
  ``hot_width``-wide, plus one replica per ``width_rps`` of additional
  heat (capped at ``max_width``); every other observed scene gets one
  planned holder, bin-packed greedily (hottest first, prefer replicas
  that already hold the scene, then least-packed) under each replica's
  HBM+staging byte budget — the two ladder tiers are one byte pool for
  planning, the ladder itself decides tiering. A scene nothing can fit
  stays unassigned: the router falls back to passive dispatch for it;
* **output** — a versioned :class:`PlacementPlan`. The version bumps
  only when the scene→replicas assignment changes, so identical inputs
  produce identical plans (the determinism tier-1 asserts). Rebalance
  deltas come out as an ORDERED move list — publishes, then prefetches
  (hottest scene first), then demotes — so a planned scene is never
  globally unresident mid-rebalance, and a demote is always the
  ladder's tier transition (``evict`` refuses pinned leases and the
  refusal is counted as a failed move), never a raw drop.

The :class:`PlacementExecutor` applies moves against per-replica
primitives (``TieredResidencyManager.prefetch``/``evict``,
``ScenePublisher.publish``); a replica without a local backend (a
``serve.py`` child) realizes prefetches lazily — the router's plan
consult steers traffic there and the engine's on-demand load makes the
scene resident — and leaves demotes to its own ladder TTL sweep.

Every replan emits a ``placement_plan`` telemetry row (version, moves
by kind, convergence wall time once the move list drains to empty,
evidence scene-heat snapshot) and every applied move a
``placement_move`` row; tlm_report.py summarizes both and ``--diff``
gates on grown unplanned-dispatch share and failed moves.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..obs import get_emitter
from ..obs.metrics import get_metrics
from .options import PlacementOptions

MOVE_KINDS = ("publish", "prefetch", "demote")


@dataclass(frozen=True)
class PlacementMove:
    """One ordered rebalance step: ``kind`` applied to ``scene`` on
    ``replica``."""

    kind: str
    scene: str
    replica: str


@dataclass(frozen=True)
class PlacementPlan:
    """A versioned scene→replicas assignment plus the ordered moves
    that take the fleet from its observed residency to it."""

    version: int
    assignments: dict = field(default_factory=dict)  # scene -> (rid, ...)
    moves: tuple = ()                                # ordered PlacementMoves
    reason: str = ""
    scene_heat: dict = field(default_factory=dict)   # evidence snapshot

    @property
    def converged(self) -> bool:
        return not self.moves

    def replicas_for(self, scene) -> tuple:
        return self.assignments.get(scene, ())

    def moves_by_kind(self) -> dict:
        out = {k: 0 for k in MOVE_KINDS}
        for m in self.moves:
            out[m.kind] = out.get(m.kind, 0) + 1
        return out


def merge_heat(*views) -> dict:
    """Fold capacity views into one ``scene -> heat`` dict.

    Accepts full :meth:`~..obs.capacity.CapacityLedger.view` dicts
    (their ``scenes`` block) or already-flat scene->heat dicts; rates
    sum across replicas (each ledger sees its replica's share)."""
    out: dict[str, dict] = {}
    for view in views:
        if view is None:
            continue
        scenes = view.get("scenes", view)
        for sid, h in scenes.items():
            if not isinstance(h, dict):
                continue
            agg = out.setdefault(str(sid), {"requests_per_s": 0.0,
                                            "rays_per_s": 0.0})
            agg["requests_per_s"] += float(h.get("requests_per_s", 0.0))
            agg["rays_per_s"] += float(h.get("rays_per_s", 0.0))
    return out


class PlacementPlanner:
    """Computes :class:`PlacementPlan` s from catalog + residency + heat.

    ``heat_fn`` (optional) returns the merged heat view
    :meth:`replan_from_router` uses; ``scene_bytes_fn`` (optional) maps
    a scene id to its device-byte estimate — without one the planner
    uses the fleet-wide mean bytes-per-resident-scene it can observe
    (and no budget pressure at all before any residency is observed).
    """

    def __init__(self, catalog=None, *,
                 options: PlacementOptions | None = None,
                 heat_fn=None, scene_bytes_fn=None, clock=time.monotonic):
        self.catalog = catalog
        self.options = options or PlacementOptions(enabled=True)
        self.heat_fn = heat_fn
        self.scene_bytes_fn = scene_bytes_fn
        self.clock = clock
        self._lock = threading.Lock()
        self.current: PlacementPlan | None = None
        self.pending: list[PlacementMove] = []
        self._pending_publishes: set[str] = set()
        self._unconverged_t: float | None = None
        self.n_plans = 0
        self.n_version_bumps = 0
        self.n_moves_planned = 0
        self.n_moves_applied = {k: 0 for k in MOVE_KINDS}
        self.n_failed_moves = 0
        self.n_skipped_moves = 0
        self.convergence_s: list[float] = []

    # -- plan consult (the router's read path) --------------------------------

    def active(self) -> bool:
        plan = self.current
        return bool(self.options.enabled and plan is not None
                    and plan.assignments)

    def planned_replicas(self, scene) -> tuple:
        """The replicas the current plan wants ``scene`` on (empty when
        disabled, unplanned, or no plan yet — the router then behaves
        exactly as before this module existed)."""
        if scene is None or not self.active():
            return ()
        return self.current.replicas_for(str(scene))

    # -- replan triggers ------------------------------------------------------

    def note_publish(self, scene_id: str) -> None:
        """A scene version was published: the next plan carries publish
        moves pushing it to every assigned replica."""
        with self._lock:
            self._pending_publishes.add(str(scene_id))

    # -- planning -------------------------------------------------------------

    def _width(self, rps: float) -> int:
        opt = self.options
        if rps < opt.hot_rps:
            return 1
        extra = int((rps - opt.hot_rps) // opt.width_rps)
        return min(opt.max_width, opt.hot_width + extra)

    def _scene_bytes(self, sid: str, states: dict) -> int:
        if self.scene_bytes_fn is not None:
            try:
                return int(self.scene_bytes_fn(sid))
            # graftlint: ok(swallow: byte estimate only; the mean fallback keeps the pack running)
            except Exception:
                pass
        total = sum(int(s.get("hbm_bytes", 0)) + int(s.get("staging_bytes", 0))
                    for s in states.values())
        count = sum(len(s.get("scenes", ())) + len(s.get("staging", ()))
                    for s in states.values())
        return total // count if count else 0

    def _budget(self, state: dict) -> float:
        opt = self.options
        hbm = opt.hbm_budget_bytes or int(state.get("hbm_budget_bytes", 0))
        stg = (opt.staging_budget_bytes
               or int(state.get("staging_budget_bytes", 0)))
        total = hbm + stg
        return float(total) if total > 0 else float("inf")

    def plan(self, replica_states: dict, heat: dict | None = None, *,
             reason: str = "periodic",
             dispatch_counters: dict | None = None) -> PlacementPlan:
        """One replan: observed residency + heat in, versioned plan out.

        ``replica_states`` is ``replica_id -> {scenes, staging,
        hbm_bytes, staging_bytes, hbm_budget_bytes,
        staging_budget_bytes}`` (:meth:`~.router.Router.residency_view`);
        ``heat`` is ``scene -> {requests_per_s, rays_per_s}``
        (:func:`merge_heat`). Deterministic: identical inputs yield an
        identical assignment, version, and move list."""
        heat = dict(heat or {})
        with self._lock:
            publishes = sorted(self._pending_publishes)
            self._pending_publishes.clear()
            plan = self._plan_locked(replica_states, heat, publishes, reason)
        self._emit_plan(plan, len(replica_states), dispatch_counters)
        return plan

    def _plan_locked(self, states: dict, heat: dict,
                     publishes: list, reason: str) -> PlacementPlan:
        rids = sorted(states)
        resident = {r: set(states[r].get("scenes", ())) for r in rids}
        staged = {r: set(states[r].get("staging", ())) for r in rids}
        # place every scene the fleet has evidence about: measured heat,
        # a resident/staged copy, or a pending publish. The full catalog
        # (10k scenes) is NOT eagerly placed — an unobserved scene costs
        # nothing until its first request, which creates the heat that
        # places it on the next replan.
        scenes = set(heat)
        for r in rids:
            scenes |= resident[r] | staged[r]
        scenes |= set(publishes)
        if self.catalog is not None:
            # only catalog scenes are plannable — "default" (the
            # engine's own checkpoint) and stray heat keys have no
            # record to prefetch or publish from
            scenes &= set(self.catalog.ids())

        def rps(s):
            return float(heat.get(s, {}).get("requests_per_s", 0.0))

        order = sorted(scenes, key=lambda s: (-rps(s), s))
        used = {r: 0.0 for r in rids}
        budget = {r: self._budget(states[r]) for r in rids}
        # a mesh-backed replica serving model-parallel (param_shards > 1)
        # holds only ~1/shards of a scene's params per device, so its
        # budget packs that fraction — a scene a replicated copy would
        # overflow can still be planned onto a sharded replica
        shards = {r: max(1, int(states[r].get("param_shards", 1)))
                  for r in rids}
        assignments: dict[str, tuple] = {}
        for s in order:
            nbytes = self._scene_bytes(s, states)
            width = min(self._width(rps(s)), len(rids))
            ranked = sorted(
                rids, key=lambda r: (s not in resident[r] and
                                     s not in staged[r], used[r], r))
            chosen = []
            for r in ranked:
                if len(chosen) >= width:
                    break
                eff = -(-nbytes // shards[r])
                if used[r] + eff <= budget[r]:
                    chosen.append(r)
                    used[r] += eff
            if chosen:
                assignments[s] = tuple(sorted(chosen))
        moves = self._moves(order, assignments, resident, staged, publishes)
        prev = self.current
        version = prev.version if prev is not None else 0
        if prev is None or prev.assignments != assignments:
            version += 1
            self.n_version_bumps += 1
        plan = PlacementPlan(
            version=version, assignments=assignments, moves=tuple(moves),
            reason=str(reason),
            scene_heat={s: dict(heat[s]) for s in order[:16] if s in heat},
        )
        self.current = plan
        self.pending = list(moves)
        self.n_plans += 1
        self.n_moves_planned += len(moves)
        now = self.clock()
        if moves and self._unconverged_t is None:
            self._unconverged_t = now
        return plan

    def _moves(self, order, assignments, resident, staged,
               publishes) -> list:
        """Ordered deltas: publishes, then prefetches hottest-first,
        then demotes — a planned scene keeps >=1 resident copy through
        the whole sequence because every new copy lands before any old
        one is demoted."""
        moves: list[PlacementMove] = []
        for s in publishes:
            for r in assignments.get(s, ()):
                moves.append(PlacementMove("publish", s, r))
        for s in order:
            for r in assignments.get(s, ()):
                if s not in resident[r]:
                    moves.append(PlacementMove("prefetch", s, r))
        for r in sorted(resident):
            keep = {s for s, rs in assignments.items() if r in rs}
            for s in sorted(resident[r]):
                if s not in keep:
                    moves.append(PlacementMove("demote", s, r))
        return moves

    def replan_from_router(self, router, *, heat=None,
                           reason: str = "periodic") -> PlacementPlan:
        """Replan straight off the router's heartbeat view (what the
        supervisor calls on its step cadence and on scale/death/publish
        events). The plan row carries the router's planned/unplanned
        dispatch counters — the unplanned share tlm_report gates on."""
        if heat is None and self.heat_fn is not None:
            try:
                heat = merge_heat(self.heat_fn())
            # graftlint: ok(swallow: heat is advisory; a replan without it still packs residency correctly)
            except Exception:
                heat = {}
        counters = {
            "planned_hits": int(getattr(router, "n_planned_hits", 0)),
            "unplanned": int(getattr(router, "n_unplanned", 0)),
        }
        return self.plan(router.residency_view(), heat or {}, reason=reason,
                         dispatch_counters=counters)

    # -- convergence + telemetry ----------------------------------------------

    def note_converged(self) -> None:
        """Called by the executor when the pending move list drains (or
        by :meth:`plan` emitting a move-free plan): closes the
        convergence wall-time measurement."""
        if self._unconverged_t is None:
            return
        dt = max(0.0, self.clock() - self._unconverged_t)
        self._unconverged_t = None
        self.convergence_s.append(dt)
        get_metrics().counter("placement_convergences_total")

    def _emit_plan(self, plan: PlacementPlan, n_replicas: int,
                   dispatch_counters: dict | None = None) -> None:
        closed = False
        if plan.converged:
            before = len(self.convergence_s)
            self.note_converged()
            closed = len(self.convergence_s) > before
        by_kind = plan.moves_by_kind()
        row = {
            "version": plan.version,
            "reason": plan.reason,
            "n_scenes": len(plan.assignments),
            "n_replicas": int(n_replicas),
            "n_moves": len(plan.moves),
            "moves_by_kind": by_kind,
            "converged": plan.converged,
            "evidence": {"scene_heat": plan.scene_heat},
        }
        if dispatch_counters:
            row["planned_hits"] = int(
                dispatch_counters.get("planned_hits", 0))
            row["unplanned"] = int(dispatch_counters.get("unplanned", 0))
        if closed:
            row["convergence_s"] = round(self.convergence_s[-1], 4)
        get_emitter().emit("placement_plan", **row)
        mx = get_metrics()
        mx.gauge("placement_plan_version", float(plan.version))
        mx.gauge("placement_pending_moves", float(len(plan.moves)))

    def note_move(self, move: PlacementMove, ok: bool, detail: str,
                  *, skipped: bool = False) -> None:
        """Record one applied move (the executor's write-back) and emit
        its ``placement_move`` row."""
        if skipped:
            self.n_skipped_moves += 1
        elif ok:
            self.n_moves_applied[move.kind] = (
                self.n_moves_applied.get(move.kind, 0) + 1)
        else:
            self.n_failed_moves += 1
        version = self.current.version if self.current is not None else 0
        # the move kind rides the "move" field ("kind" is the row kind)
        get_emitter().emit(
            "placement_move", version=version, move=move.kind,
            scene=move.scene, replica=move.replica, ok=bool(ok),
            **({} if not detail else {"detail": str(detail)[:200]}),
        )
        get_metrics().counter("placement_moves_total", kind=move.kind,
                              ok=str(bool(ok)).lower())

    def stats(self) -> dict:
        plan = self.current
        return {
            "enabled": bool(self.options.enabled),
            "version": 0 if plan is None else plan.version,
            "n_plans": self.n_plans,
            "n_version_bumps": self.n_version_bumps,
            "n_assigned_scenes": 0 if plan is None else len(plan.assignments),
            "n_pending_moves": len(self.pending),
            "n_moves_planned": self.n_moves_planned,
            "moves_applied": dict(self.n_moves_applied),
            "n_failed_moves": self.n_failed_moves,
            "n_skipped_moves": self.n_skipped_moves,
            "n_convergences": len(self.convergence_s),
            "convergence_s_last": (round(self.convergence_s[-1], 4)
                                   if self.convergence_s else None),
        }


class PlacementExecutor:
    """Applies a plan's pending moves against per-replica primitives.

    ``residency_of(replica_id)`` resolves a replica's local
    :class:`~..fleet.ladder.TieredResidencyManager` (None for a remote
    ``serve.py`` child — its prefetches realize lazily via routed
    traffic and its demotes via its own ladder TTL);
    ``publisher_of(replica_id)`` resolves its
    :class:`~..fleet.publish.ScenePublisher`; ``catalog`` supplies the
    record a publish move pushes."""

    def __init__(self, *, residency_of=None, publisher_of=None,
                 catalog=None):
        self.residency_of = residency_of
        self.publisher_of = publisher_of
        self.catalog = catalog
        self.n_executed = 0

    def _apply(self, move: PlacementMove) -> tuple[bool, str, bool]:
        """(ok, detail, skipped) for one move."""
        mgr = (self.residency_of(move.replica)
               if self.residency_of is not None else None)
        if move.kind == "prefetch":
            if mgr is None:
                return True, "lazy", True  # routed traffic realizes it
            return bool(mgr.prefetch(move.scene)), "", False
        if move.kind == "demote":
            if mgr is None:
                return True, "remote_ttl", True  # the child's ladder owns it
            # evict() is the ladder's tier transition: it REFUSES a
            # pinned lease (returns False) — that refusal is the
            # never-raw-evict contract and counts as a failed move
            ok = bool(mgr.evict(move.scene))
            return ok, "" if ok else "pinned", False
        if move.kind == "publish":
            pub = (self.publisher_of(move.replica)
                   if self.publisher_of is not None else None)
            if pub is None or self.catalog is None:
                return True, "no_publisher", True
            try:
                pub.publish(self.catalog.get(move.scene))
                return True, "", False
            # graftlint: ok(swallow: one failed publish move must not stall the move queue; it is counted and gated in --diff)
            except Exception as exc:
                return False, f"{type(exc).__name__}: {exc}", False
        return False, f"unknown kind {move.kind!r}", False

    def execute(self, planner: PlacementPlanner,
                limit: int | None = None) -> dict:
        """Pop and apply up to ``limit`` pending moves (all when None).
        Returns ``{applied, failed, skipped, remaining}``; drained-to-
        empty closes the planner's convergence measurement."""
        applied = failed = skipped = 0
        n = len(planner.pending) if limit is None else min(
            int(limit), len(planner.pending))
        for _ in range(n):
            move = planner.pending.pop(0)
            ok, detail, was_skipped = self._apply(move)
            planner.note_move(move, ok, detail, skipped=was_skipped)
            self.n_executed += 1
            if was_skipped:
                skipped += 1
            elif ok:
                applied += 1
            else:
                failed += 1
        if not planner.pending:
            planner.note_converged()
        return {"applied": applied, "failed": failed, "skipped": skipped,
                "remaining": len(planner.pending)}
