"""Horizontal scale-out for the serving stack (docs/scaleout.md).

Three pieces turn the single-device, single-process server into the
heavy-traffic shape the ROADMAP north star asks for:

* **Mesh-sharded dispatch** (:mod:`.mesh_dispatch`) — the engine's
  bucket×tier executables run under ``shard_map`` over the data-parallel
  mesh (parallel/mesh.py), so one big micro-batch spans every chip.
  Params/grid replicate; the padded ray chunks shard over the leading
  chunk axis. Per-ray math is untouched, so the mesh render is
  BITWISE-equal to the single-device path, and a size-1 mesh falls back
  to plain ``jax.jit`` — CPU tier-1 covers everything.
* **Replica runtime** (:mod:`.replica` + :mod:`.router`) — multi-process
  replicas behind serve.py that warm-start from the shared ``.aot``
  artifact store (a fresh replica serves in seconds with
  ``warm_source == "disk"`` and zero compiles), registered via heartbeat
  with a front-door :class:`Router` doing least-loaded dispatch with
  scene-affinity and drain-before-retire.
* **Supervisor** (:mod:`.supervisor`) — a closed loop that spawns and
  retires replicas against SLO attainment and per-tenant deny rate,
  with hysteresis, cooldowns, and min/max bounds from the ``scale:``
  config block. With an evidence source attached (the fleet metrics
  aggregator), every decision row links to the attainment series, queue
  depths, and exemplar trace ids it acted on.
* **Fleet metrics** (:mod:`.fleet_metrics`) — per-replica ``/metrics``
  scrapes merged into one Prometheus body with a ``replica`` label
  (``GET /fleet/metrics``), plus the fleet SLO view the supervisor
  reads — one signal for the loop and the operator both.
* **Placement** (:mod:`.placement`) — the scene placement planner:
  versioned plans (hot scenes replicated by measured heat, cold scenes
  bin-packed under byte budgets) the router consults before its
  passive affinity and the supervisor executes as ordered
  prefetch/demote/publish moves.
* **Launcher** (:mod:`.launcher`) — real ``serve.py`` child processes
  behind the ProcessReplica surface: port allocation, spawn against
  the shared ``.aot`` warm-start dir, ready-wait, drain-before-retire,
  kill + 1:1 replace.
"""

from .fleet_metrics import (
    FleetMetricsAggregator,
    make_fleet_server,
    merge_scrapes,
)
from .launcher import LaunchError, ProcessLauncher, allocate_port
from .mesh_dispatch import (
    MeshDispatchError,
    mesh_from_scale_cfg,
    mesh_jit,
    validate_mesh_buckets,
)
from .options import (
    MeshShapeError,
    PlacementOptions,
    ScaleOptions,
    parse_mesh_shape,
)
from .placement import (
    PlacementExecutor,
    PlacementMove,
    PlacementPlan,
    PlacementPlanner,
    merge_heat,
)
from .replica import (
    InProcessReplica,
    ProcessReplica,
    ReplicaState,
    ReplicaUnavailableError,
)
from .router import NoCapableReplicaError, NoReplicaAvailableError, Router
from .supervisor import Supervisor

__all__ = [
    "FleetMetricsAggregator",
    "InProcessReplica",
    "LaunchError",
    "MeshDispatchError",
    "MeshShapeError",
    "NoCapableReplicaError",
    "NoReplicaAvailableError",
    "PlacementExecutor",
    "PlacementMove",
    "PlacementOptions",
    "PlacementPlan",
    "PlacementPlanner",
    "ProcessLauncher",
    "ProcessReplica",
    "ReplicaState",
    "ReplicaUnavailableError",
    "Router",
    "ScaleOptions",
    "Supervisor",
    "allocate_port",
    "make_fleet_server",
    "merge_heat",
    "merge_scrapes",
    "mesh_from_scale_cfg",
    "mesh_jit",
    "parse_mesh_shape",
    "validate_mesh_buckets",
]
