"""Fleet metrics aggregation: one Prometheus surface for N replicas.

PR 10 gave each serving process ``GET /metrics``; PR 14 made the fleet
multi-replica — and left the operator scraping N ports and eyeballing
the union. This module closes that gap AND closes the supervisor's
evidence gap with the same object:

* :func:`merge_scrapes` — merge per-replica Prometheus text bodies into
  one, every sample gaining a ``replica`` label (an already-present
  ``replica`` label is renamed ``exported_replica``, the classic
  federation collision rule). Exemplar suffixes ride along untouched.
* :class:`FleetMetricsAggregator` — scrapes every registered replica
  through the router (``scrape_metrics()`` on the replica surface),
  dedups in-process replicas that share one registry (their
  ``metrics_source_id()`` is the process, not the replica), skips dead/
  retired/unreachable replicas (counted, surfaced), and derives the
  fleet SLO view — both cumulative and per-window deltas, which is the
  attainment/deny-rate signal the supervisor acts on. One signal,
  two consumers: what the loop decides on is what operators see.
* :func:`make_fleet_server` — the router-side HTTP face:
  ``GET /fleet/metrics`` (merged text) and ``GET /fleet/slo`` (JSON).

Host-side pure Python; no jax import anywhere in scale/.
"""

from __future__ import annotations

import bisect
import json
import re
import threading

from ..obs.metrics import get_metrics
from .replica import ReplicaState

# one exposition sample: name{labels} value [# {exemplar-labels} value]
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"      # metric name
    r"(?:\{(.*?)\})?"                    # label body (lazy: stop before value/exemplar)
    r"\s+(-?[0-9.eE+\-]+|NaN|[+-]Inf)"   # value
    r"(\s+#\s+\{.*\}\s+\S+)?\s*$"        # optional exemplar suffix
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def parse_labels(body: str | None) -> dict[str, str]:
    return dict(_LABEL_RE.findall(body or ""))


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in sorted(labels.items())) \
        + "}"


def relabel_sample(line: str, replica: str) -> str:
    """Inject ``replica="<id>"`` into one sample line; a pre-existing
    ``replica`` label (a replica talking about other replicas, e.g. the
    router's own dispatch counter) is renamed ``exported_replica``."""
    m = _SAMPLE_RE.match(line)
    if m is None:
        return line  # not a sample (defensive) — pass through
    name, body, value, exemplar = m.groups()
    labels = parse_labels(body)
    if "replica" in labels:
        labels["exported_replica"] = labels.pop("replica")
    labels["replica"] = str(replica)
    return f"{name}{_fmt_labels(labels)} {value}{exemplar or ''}"


def merge_scrapes(scrapes: dict[str, str]) -> str:
    """Merge ``{source_id: prometheus_text}`` into one exposition body:
    one ``# TYPE`` line per metric (first scrape wins), samples grouped
    by metric, each carrying its source's ``replica`` label."""
    types: dict[str, str] = {}
    samples: dict[str, list[str]] = {}
    for rid in sorted(scrapes):
        for line in scrapes[rid].splitlines():
            line = line.strip()
            if not line:
                continue
            if line.startswith("# TYPE "):
                parts = line.split()
                if len(parts) >= 3:
                    types.setdefault(parts[2], line)
                continue
            if line.startswith("#"):
                continue  # HELP/comments don't merge meaningfully
            m = _SAMPLE_RE.match(line)
            if m is None:
                continue
            name = m.group(1)
            # bucket/sum/count series group under their histogram's name
            base = re.sub(r"_(bucket|sum|count)$", "", name)
            key = base if base in types else name
            samples.setdefault(key, []).append(relabel_sample(line, rid))
    lines: list[str] = []
    for name in sorted(samples):
        if name in types:
            lines.append(types[name])
        lines.extend(samples[name])
    return "\n".join(lines) + "\n"


class FleetMetricsAggregator:
    """Scrape-merge-derive over a :class:`~.router.Router`'s registry.

    ``slo_target_s`` mirrors the per-replica ``/healthz`` target; the
    attainment read uses the same fixed-bucket rule as
    ``MetricsRegistry.slo_view`` (smallest edge >= target)."""

    def __init__(self, router, slo_target_s: float = 0.25):
        self.router = router
        self.slo_target_s = float(slo_target_s)
        self._lock = threading.Lock()
        self.last_scrapes: dict[str, str] = {}
        self.skipped: list[dict] = []
        self.n_scrape_rounds = 0
        self.n_scrape_failures = 0
        # cumulative totals at the previous window() call — the deltas
        # between calls ARE the supervisor's observation window
        self._prev: dict | None = None

    # -- scraping -------------------------------------------------------------

    def scrape(self) -> dict[str, str]:
        """One scrape round across the fleet. Returns source_id → text;
        dead/retired/unreachable replicas are skipped and recorded in
        ``self.skipped`` (the operator sees the hole, not a silent
        shorter list)."""
        scrapes: dict[str, str] = {}
        skipped: list[dict] = []
        for r in self.router.replicas():
            rid = r.replica_id
            if r.state in (ReplicaState.DEAD, ReplicaState.RETIRED):
                skipped.append({"replica": rid, "reason": r.state})
                continue
            scrape_fn = getattr(r, "scrape_metrics", None)
            if scrape_fn is None:
                skipped.append({"replica": rid, "reason": "no_scrape"})
                continue
            sid = str(getattr(r, "metrics_source_id", lambda: rid)())
            if sid in scrapes:
                continue  # in-process replicas share one registry
            try:
                scrapes[sid] = scrape_fn()
            # graftlint: ok(swallow: an unreachable replica must not fail the fleet scrape; the skip is recorded and counted)
            except Exception as exc:
                self.n_scrape_failures += 1
                skipped.append({"replica": rid,
                                "reason": f"unreachable: {exc}"[:120]})
        with self._lock:
            self.last_scrapes = scrapes
            self.skipped = skipped
            self.n_scrape_rounds += 1
        return scrapes

    def render(self) -> str:
        """Fresh scrape → one merged Prometheus text body (the
        ``GET /fleet/metrics`` payload)."""
        return merge_scrapes(self.scrape())

    # -- derived views --------------------------------------------------------

    def _totals(self, merged: str) -> dict:
        """Cumulative fleet counts from one merged body: latency
        histogram (attained-at-target / total), request/shed/deny
        counters, and the SLO-miss exemplar trace ids seen."""
        series: dict[tuple, dict[float, float]] = {}
        inf: dict[tuple, float] = {}
        counters = {"requests": 0.0, "sheds": 0.0, "admits": 0.0,
                    "denies": 0.0, "no_replica": 0.0}
        exemplars: list[tuple[float, str]] = []
        for line in merged.splitlines():
            m = _SAMPLE_RE.match(line.strip())
            if m is None:
                continue
            name, body, value, exemplar = m.groups()
            try:
                val = float(value)
            except ValueError:
                continue
            labels = parse_labels(body)
            if name == "serve_request_latency_seconds_bucket":
                le = labels.pop("le", None)
                key = tuple(sorted(labels.items()))
                if le == "+Inf":
                    inf[key] = val
                else:
                    try:
                        edge = float(le)
                    except (TypeError, ValueError):
                        continue
                    series.setdefault(key, {})[edge] = val
                    if exemplar and edge >= self.slo_target_s:
                        tid = parse_labels(exemplar).get("trace_id")
                        if tid:
                            exemplars.append((edge, tid))
            elif name == "serve_requests_total":
                counters["requests"] += val
            elif name == "serve_sheds_total":
                counters["sheds"] += val
            elif name == "tenant_admits_total":
                counters["admits"] += val
                if labels.get("decision") == "deny":
                    counters["denies"] += val
            elif (name == "scale_router_events_total"
                    and labels.get("event") == "no_replica"):
                counters["no_replica"] += val
        attained = 0.0
        total = 0.0
        for key, buckets in series.items():
            edges = sorted(buckets)
            i = bisect.bisect_left(edges, self.slo_target_s)
            cum_inf = inf.get(key, buckets[edges[-1]] if edges else 0.0)
            attained += buckets[edges[i]] if i < len(edges) else cum_inf
            total += cum_inf
        return {"attained": attained, "latency_count": total,
                **counters, "exemplars": exemplars}

    def slo_view(self) -> dict:
        """Cumulative fleet SLO verdict (the ``GET /fleet/slo`` body)."""
        merged = merge_scrapes(self.scrape())
        t = self._totals(merged)
        total = t["latency_count"]
        admits = t["admits"]
        return {
            "target_ms": round(self.slo_target_s * 1e3, 3),
            "replicas_scraped": len(self.last_scrapes),
            "replicas_skipped": len(self.skipped),
            "skipped": list(self.skipped),
            "requests": int(t["requests"]),
            "attainment": (round(t["attained"] / total, 4)
                           if total else None),
            "deny_rate": (round(t["denies"] / admits, 4) if admits else 0.0),
            "shed_rate": (round(t["sheds"] / t["requests"], 4)
                          if t["requests"] else 0.0),
            "no_replica": int(t["no_replica"]),
        }

    def window(self) -> dict:
        """One observation window: the DELTAS since the previous call
        (cumulative counters make each window independent of restart
        timing). This is the supervisor's input — attainment None means
        nothing completed this window, which the caller must distinguish
        between idle and wedged (see Supervisor.step_from_fleet)."""
        merged = merge_scrapes(self.scrape())
        now = self._totals(merged)
        with self._lock:
            prev = self._prev or {k: 0.0 for k in
                                  ("attained", "latency_count", "requests",
                                   "sheds", "admits", "denies", "no_replica")}
            self._prev = now
        d = {k: max(0.0, now[k] - prev.get(k, 0.0))
             for k in ("attained", "latency_count", "requests", "sheds",
                       "admits", "denies", "no_replica")}
        admits = d["admits"]
        return {
            "attainment": (round(d["attained"] / d["latency_count"], 4)
                           if d["latency_count"] else None),
            "deny_rate": (round(d["denies"] / admits, 4) if admits else 0.0),
            "requests": int(d["requests"]),
            "no_replica": int(d["no_replica"]),
            "exemplar_trace_ids": self.slo_miss_exemplars(),
        }

    def slo_miss_exemplars(self, target_s: float | None = None,
                           limit: int = 8) -> list[str]:
        """Exemplar trace ids from SLO-missing latency buckets across
        the LAST scrape round (slowest first, deduped) — the evidence
        trace ids a scale decision links to. ``target_s`` mirrors the
        :meth:`~..obs.metrics.MetricsRegistry.slo_miss_exemplars`
        surface (the Supervisor's evidence_source duck type); the
        aggregator's own ``slo_target_s`` is the floor either way."""
        target = self.slo_target_s if target_s is None else float(target_s)
        with self._lock:
            scrapes = dict(self.last_scrapes)
        merged = merge_scrapes(scrapes) if scrapes else ""
        pool = sorted((e for e in self._totals(merged)["exemplars"]
                       if e[0] >= target), reverse=True)
        out: list[str] = []
        for _edge, tid in pool:
            if tid not in out:
                out.append(tid)
            if len(out) >= limit:
                break
        return out

    def capacity_view(self) -> dict:
        """Fresh scrape → the fleet's capacity ledger, per replica: the
        ``capacity_*`` gauges each replica's
        :class:`~..obs.capacity.CapacityLedger` published, grouped by
        the ``replica`` label the merge injected (the ``GET
        /fleet/capacity`` payload — what the placement planner reads)."""
        merged = merge_scrapes(self.scrape())
        replicas: dict[str, dict] = {}
        for line in merged.splitlines():
            m = _SAMPLE_RE.match(line.strip())
            if m is None:
                continue
            name, body, value, _ex = m.groups()
            if not name.startswith("capacity_"):
                continue
            try:
                val = float(value)
            except ValueError:
                continue
            labels = parse_labels(body)
            rep = replicas.setdefault(labels.get("replica", ""), {})
            if name == "capacity_scene_requests_per_s":
                rep.setdefault("scenes", {}).setdefault(
                    labels.get("scene", ""), {})["requests_per_s"] = val
            elif name == "capacity_scene_rays_per_s":
                rep.setdefault("scenes", {}).setdefault(
                    labels.get("scene", ""), {})["rays_per_s"] = val
            elif name == "capacity_scene_cold_loads":
                rep.setdefault("scenes", {}).setdefault(
                    labels.get("scene", ""), {})["cold_loads"] = int(val)
            elif name == "capacity_scene_repromotions":
                rep.setdefault("scenes", {}).setdefault(
                    labels.get("scene", ""), {})["repromotions"] = int(val)
            elif name == "capacity_device_share":
                rep.setdefault("device_share", {})[
                    labels.get("family", "")] = val
            else:
                # the byte watermarks: capacity_hbm_bytes etc.
                rep[name[len("capacity_"):]] = int(val)
        return {"replicas": replicas, "n_replicas": len(replicas)}

    def stats(self) -> dict:
        with self._lock:
            return {
                "n_scrape_rounds": self.n_scrape_rounds,
                "n_scrape_failures": self.n_scrape_failures,
                "sources": sorted(self.last_scrapes),
                "skipped": list(self.skipped),
            }


def make_fleet_server(aggregator: FleetMetricsAggregator,
                      host: str = "127.0.0.1", port: int = 0):
    """The router-side HTTP face of the aggregator: ``GET
    /fleet/metrics`` (merged Prometheus text), ``GET /fleet/slo``
    (JSON), and ``GET /fleet/capacity`` (the per-replica capacity
    ledger). Returns the configured ``ThreadingHTTPServer`` (caller
    serves it; ``server.server_address[1]`` is the bound port)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet: telemetry rows, not stderr
            pass

        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            try:
                if self.path == "/fleet/metrics":
                    text = aggregator.render()
                    get_metrics().counter("fleet_scrapes_total")
                    self._send(200, text.encode(),
                               "text/plain; version=0.0.4")
                elif self.path == "/fleet/slo":
                    body = json.dumps(aggregator.slo_view()).encode()
                    self._send(200, body, "application/json")
                elif self.path == "/fleet/capacity":
                    body = json.dumps(aggregator.capacity_view()).encode()
                    self._send(200, body, "application/json")
                else:
                    self._send(404, b'{"error": "not found"}',
                               "application/json")
            # graftlint: ok(swallow: one bad scrape must not kill the fleet endpoint thread; the 500 carries the error)
            except Exception as exc:
                detail = json.dumps(
                    {"error": f"{type(exc).__name__}: {exc}"[:200]}
                ).encode()
                self._send(500, detail, "application/json")

    return ThreadingHTTPServer((host, port), Handler)
