"""Front-door router: least-loaded dispatch with scene-affinity.

The router owns the replica registry. Replicas register at spawn and are
swept by PULL heartbeats (one code path for in-process and HTTP
replicas): a beat that keeps failing past ``heartbeat_timeout_s`` marks
the replica dead — transient hiccups inside the window don't, so a GC
pause can't trigger a spurious replacement.

Dispatch picks among accepting replicas by **scene-affinity first**
(prefer a replica whose fleet ladder already holds the request's scene
resident — routing there is an argument swap; routing elsewhere pays a
disk load), **least-loaded second** (queue depth from the last beat),
id-ordered for determinism. Failover is synchronous: a replica that
refuses or dies mid-submit is excluded and the next candidate tried, so
the caller sees one submit, not the failure.

With a :class:`~.placement.PlacementPlanner` attached
(:meth:`Router.set_planner`), the current plan is consulted BEFORE the
passive ordering: candidates the plan assigned the scene to are
stably promoted to the front (within the planned and unplanned groups
the affinity/load/id order is untouched), so a plan hit routes to the
planned replica and a plan miss — or an empty/disabled plan — is
bitwise today's behavior. Dispatches against an active plan are
counted planned/unplanned; the unplanned share is tlm_report's
is-the-plan-working signal.

Candidates are also filtered on the replica ``capabilities`` flag
(ray-level ``submit`` vs whole-pose ``render``): a capability mismatch
is a FILTER, not a failover — the replica is healthy, it just doesn't
speak that protocol — and when no capable replica exists the typed
:class:`NoCapableReplicaError` says so instead of a generic no-replica.

Retirement is drain-before-retire: the replica leaves the candidate set
FIRST (no new admissions), renders everything already queued, and only
then stops — zero in-flight requests fail (tests/test_scale.py holds
the count to exactly 0).
"""

from __future__ import annotations

import threading
import time

from ..obs import get_emitter
from ..obs.metrics import get_metrics
from ..obs.trace import get_tracer
from .replica import ReplicaState, ReplicaUnavailableError


class NoReplicaAvailableError(RuntimeError):
    """Every registered replica is draining, retired, or dead."""


class NoCapableReplicaError(NoReplicaAvailableError):
    """Accepting replicas exist, but none serves this request shape
    (e.g. a ray-level submit against a pose-only HTTP fleet)."""


class _Entry:
    def __init__(self, replica, now: float):
        self.replica = replica
        self.last_ok_t = now
        self.beat: dict = {}


class Router:
    def __init__(self, heartbeat_timeout_s: float = 10.0,
                 clock=time.monotonic):
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._entries: dict[str, _Entry] = {}
        self.planner = None  # optional scale/placement.PlacementPlanner
        self.n_dispatches = 0
        self.n_affinity_hits = 0
        self.n_planned_hits = 0
        self.n_unplanned = 0
        self.n_failovers = 0
        self.n_dead_marked = 0

    def set_planner(self, planner) -> None:
        """Attach a placement planner; its current plan is consulted on
        every dispatch (None or an empty plan leaves dispatch exactly
        as before)."""
        self.planner = planner

    # -- registry -------------------------------------------------------------

    def register(self, replica) -> None:
        with self._lock:
            self._entries[replica.replica_id] = _Entry(replica, self.clock())
        get_metrics().gauge("scale_replicas_registered",
                            len(self._entries))

    def deregister(self, replica_id: str) -> None:
        with self._lock:
            self._entries.pop(str(replica_id), None)

    def replicas(self) -> list:
        with self._lock:
            return [e.replica for e in self._entries.values()]

    def _snapshot(self) -> list:
        """Entries at a point in time: readers (sweep, candidate scans,
        views) iterate the snapshot so a concurrent register/deregister
        never mutates the dict under them — and slow replica probes
        (heartbeat, load) run with the router lock NOT held."""
        with self._lock:
            return list(self._entries.values())

    def n_ready(self) -> int:
        return sum(
            1 for r in self.replicas()
            if r.state in (ReplicaState.STARTING, ReplicaState.READY)
        )

    def sweep(self) -> dict:
        """Pull one heartbeat round. A replica whose beats have failed
        for longer than ``heartbeat_timeout_s`` is marked dead (and its
        queued work is already failing — the supervisor replaces it)."""
        now = self.clock()
        dead: list[str] = []
        for entry in self._snapshot():
            r = entry.replica
            if r.state in (ReplicaState.RETIRED, ReplicaState.DEAD):
                continue
            try:
                entry.beat = r.heartbeat()
                entry.last_ok_t = now
            # graftlint: ok(swallow: the timeout ladder IS the handler — failures inside the window are the hysteresis, past it _mark_dead emits)
            except Exception as exc:
                if now - entry.last_ok_t >= self.heartbeat_timeout_s:
                    self._mark_dead(r, f"heartbeat: {exc}")
                    dead.append(r.replica_id)
        return {"t": now, "dead": dead, "n_ready": self.n_ready()}

    def _mark_dead(self, replica, detail: str) -> None:
        if replica.state in (ReplicaState.DEAD, ReplicaState.DRAINING,
                             ReplicaState.RETIRED):
            # draining/retired is a deliberate exit, not a death — marking
            # it dead would make the supervisor "replace" a retirement
            return
        replica.state = ReplicaState.DEAD
        self.n_dead_marked += 1
        get_emitter().emit("router", event="dead",
                           replica=replica.replica_id,
                           detail=detail[:200])
        get_metrics().counter("scale_router_events_total", event="dead")

    # -- dispatch -------------------------------------------------------------

    def _planned_set(self, scene) -> frozenset:
        """Replica ids the current placement plan wants ``scene`` on
        (empty without a planner / active plan / plan entry — every one
        of those leaves dispatch bitwise pre-placement)."""
        if self.planner is None or scene is None:
            return frozenset()
        try:
            return frozenset(self.planner.planned_replicas(scene))
        # graftlint: ok(swallow: the plan is advisory; a planner error must degrade to passive dispatch, not fail the request)
        except Exception:
            return frozenset()

    def _count_plan_hit(self, replica_id: str, planned: frozenset) -> None:
        if self.planner is None or not getattr(
                self.planner, "active", lambda: False)():
            return
        if replica_id in planned:
            self.n_planned_hits += 1
        else:
            self.n_unplanned += 1
            get_metrics().counter("scale_router_events_total",
                                  event="unplanned_dispatch")

    def _candidates(self, scene, need=None) -> list:
        """Accepting replicas as (no_affinity, load, id, replica), sorted
        so ``[0]`` is the pick: affinity beats load beats id. ``need``
        filters on the replica ``capabilities`` flag (replicas without
        one are assumed universal — test doubles predate the flag). A
        planned scene stably promotes its planned replicas to the
        front; an empty plan changes nothing."""
        out = []
        for entry in self._snapshot():
            r = entry.replica
            if not r.accepting():
                continue
            caps = getattr(r, "capabilities", None)
            if need is not None and caps is not None and need not in caps:
                continue
            affinity = (
                scene is not None
                and scene in entry.beat.get("scenes", ())
            )
            try:
                load = int(r.load())
            # graftlint: ok(swallow: routing probe; a failed load sorts the replica last, the sweep owns dead-marking)
            except Exception:
                load = 1 << 30
            out.append((not affinity, load, r.replica_id, r))
        out.sort(key=lambda c: c[:3])
        planned = self._planned_set(scene)
        if planned:
            # stable: planned candidates first, passive order within
            out.sort(key=lambda c: c[2] not in planned)
        return out

    def pick(self, scene=None):
        """The replica the next request for ``scene`` should land on."""
        cands = self._candidates(scene)
        if not cands:
            raise NoReplicaAvailableError(
                f"no accepting replica among {len(self.replicas())} "
                "registered"
            )
        return cands[0][3]

    def _no_replica(self, scene, need=None) -> NoReplicaAvailableError:
        entries = self._snapshot()
        n_accepting = sum(1 for e in entries
                          if e.replica.accepting())
        if need is not None and n_accepting:
            # accepting replicas exist but every one was capability-
            # filtered: a protocol mismatch, not an availability outage
            get_emitter().emit("router", event="no_capable",
                               need=str(need),
                               **({} if scene is None
                                  else {"scene": str(scene)}))
            get_metrics().counter("scale_router_events_total",
                                  event="no_capable")
            return NoCapableReplicaError(
                f"{n_accepting} accepting replicas, none capable of "
                f"{need!r} requests"
            )
        get_emitter().emit("router", event="no_replica",
                           **({} if scene is None
                              else {"scene": str(scene)}))
        get_metrics().counter("scale_router_events_total",
                              event="no_replica")
        return NoReplicaAvailableError(
            f"no accepting replica among {len(entries)} registered"
        )

    def _record_failover(self, trs, replica, exc, n_left, scene,
                         t0: float) -> None:
        self.n_failovers += 1
        self._mark_dead(replica, f"submit: {exc}")
        get_emitter().emit(
            "router", event="failover",
            replica=replica.replica_id,
            n_candidates=n_left,
            **({} if scene is None else {"scene": str(scene)}),
        )
        trs.record("route.failover", start_s=t0, stage="failover",
                   replica=replica.replica_id,
                   status=f"error:{type(exc).__name__}")
        get_metrics().counter("scale_router_events_total",
                              event="failover")

    def submit(self, rays, near, far, scene=None, tenant=None):
        """One request through the front door: pick, submit, fail over.

        A replica that refuses (draining/closed/dead) is skipped; one
        that dies mid-submit is marked dead and the NEXT candidate gets
        the request — the caller never sees a failover.

        Runs under a ``route.submit`` span (stage ``route``) covering
        pick + enqueue; the replica's queue/batch/scatter spans parent
        under it (in-process: the ctx is passed as an argument), so a
        routed request stays ONE trace."""
        trs = get_tracer()
        with trs.span("route.submit", stage="route",
                      **({} if scene is None
                         else {"scene": str(scene)})) as sp:
            cands = self._candidates(scene, need="rays")
            if not cands:
                raise self._no_replica(scene, need="rays")
            planned = self._planned_set(scene)
            last_exc: Exception | None = None
            for i, (no_aff, load, _rid, replica) in enumerate(cands):
                t_try = trs.now()
                try:
                    # FakeReplica doubles in tests predate the ctx
                    # argument — only replicas advertising accepts_ctx
                    # get the explicit SpanContext
                    if getattr(replica, "accepts_ctx", False):
                        future = replica.submit(rays, near, far, scene=scene,
                                                tenant=tenant, ctx=sp.ctx)
                    else:
                        future = replica.submit(rays, near, far, scene=scene,
                                                tenant=tenant)
                except (ReplicaUnavailableError, RuntimeError) as exc:
                    # RuntimeError covers a closed batcher (a racing
                    # kill/retire): treat both as this-replica failures
                    last_exc = exc
                    self._record_failover(trs, replica, exc,
                                          len(cands) - i - 1, scene, t_try)
                    continue
                self.n_dispatches += 1
                if not no_aff:
                    self.n_affinity_hits += 1
                self._count_plan_hit(replica.replica_id, planned)
                sp.set(replica=replica.replica_id)
                get_metrics().counter("scale_router_dispatch_total",
                                      replica=replica.replica_id)
                return future
            raise NoReplicaAvailableError(
                f"all {len(cands)} accepting replicas failed the submit"
            ) from last_exc

    def render(self, body: dict, scene=None, timeout_s: float = 30.0) -> dict:
        """Route one whole-pose request to an HTTP replica (the
        :class:`~.replica.ProcessReplica` surface): pick, POST /render
        with the span ctx stamped as the Traceparent header, fail over on
        a 5xx/transport failure. The root ``route.submit`` span plus a
        ``route.dispatch`` span per attempt make the router's share of
        the wall time explicit in the merged fleet trace."""
        import urllib.error

        trs = get_tracer()
        scene = scene if scene is not None else body.get("scene")
        with trs.span("route.submit",
                      **({} if scene is None
                         else {"scene": str(scene)})) as root:
            cands = [c for c in self._candidates(scene, need="pose")
                     if hasattr(c[3], "render")]
            if not cands:
                raise self._no_replica(scene, need="pose")
            planned = self._planned_set(scene)
            last_exc: Exception | None = None
            for i, (no_aff, _load, _rid, replica) in enumerate(cands):
                t_try = trs.now()
                try:
                    # route.dispatch wraps the whole HTTP round trip; the
                    # child's serve.request parents under ITS ctx via the
                    # propagated header
                    with trs.span("route.dispatch", stage="route",
                                  replica=replica.replica_id):
                        out = replica.render(body, timeout_s=timeout_s)
                except urllib.error.HTTPError as exc:
                    if exc.code < 500:
                        raise  # the request is bad, not the replica
                    last_exc = exc
                    self._record_failover(trs, replica, exc,
                                          len(cands) - i - 1, scene, t_try)
                    continue
                except (ReplicaUnavailableError, urllib.error.URLError,
                        OSError) as exc:
                    last_exc = exc
                    self._record_failover(trs, replica, exc,
                                          len(cands) - i - 1, scene, t_try)
                    continue
                self.n_dispatches += 1
                if not no_aff:
                    self.n_affinity_hits += 1
                self._count_plan_hit(replica.replica_id, planned)
                root.set(replica=replica.replica_id)
                get_metrics().counter("scale_router_dispatch_total",
                                      replica=replica.replica_id)
                return out
            raise NoReplicaAvailableError(
                f"all {len(cands)} accepting replicas failed the render"
            ) from last_exc

    # -- retirement -----------------------------------------------------------

    def drain(self, replica_id: str, timeout_s: float = 60.0) -> int:
        """Drain-before-retire ``replica_id``. Returns the in-flight
        failure count (the contract wants 0). The replica leaves the
        candidate set at the state flip inside ``drain`` — before any
        queued render — so no new work can race in."""
        with self._lock:
            entry = self._entries.get(str(replica_id))
        if entry is None:
            return 0
        load_before = 0
        try:
            load_before = int(entry.replica.load())
        # graftlint: ok(swallow: telemetry-only load snapshot; the drain below is the real work)
        except Exception:
            pass
        failed = entry.replica.drain(timeout_s=timeout_s)
        get_emitter().emit("router", event="drain", replica=str(replica_id),
                           load=load_before, n_failed=int(failed))
        get_metrics().counter("scale_router_events_total", event="drain")
        return failed

    def residency_view(self) -> dict[str, dict]:
        """Per-replica residency state off the last heartbeat round —
        the placement planner's fleet-side input (scene sets, byte
        watermarks, ladder budgets; zeros for replicas whose beats
        predate the planner fields)."""
        out: dict[str, dict] = {}
        for entry in self._snapshot():
            r = entry.replica
            if not r.accepting():
                continue
            b = entry.beat
            out[r.replica_id] = {
                "scenes": list(b.get("scenes", ())),
                "staging": list(b.get("staging", ())),
                "hbm_bytes": int(b.get("hbm_bytes", 0)),
                "staging_bytes": int(b.get("staging_bytes", 0)),
                "hbm_budget_bytes": int(b.get("hbm_budget_bytes", 0)),
                "staging_budget_bytes": int(
                    b.get("staging_budget_bytes", 0)),
                "param_shards": int(b.get("param_shards", 1)),
            }
        return out

    def load_view(self) -> dict[str, int]:
        """Per-replica queue depth from the last heartbeat round — the
        ``queue_depths`` half of a scale decision's evidence block."""
        out: dict[str, int] = {}
        for entry in self._snapshot():
            load = entry.beat.get("load")
            if load is not None:
                out[entry.replica.replica_id] = int(load)
        return out

    def stats(self) -> dict:
        per = {}
        entries = self._snapshot()
        for entry in entries:
            per[entry.replica.replica_id] = {
                "state": entry.replica.state,
                "load": entry.beat.get("load"),
                "warm_source": entry.beat.get("warm_source"),
            }
        return {
            "n_registered": len(entries),
            "n_ready": self.n_ready(),
            "n_dispatches": self.n_dispatches,
            "n_affinity_hits": self.n_affinity_hits,
            "n_planned_hits": self.n_planned_hits,
            "n_unplanned": self.n_unplanned,
            "n_failovers": self.n_failovers,
            "n_dead_marked": self.n_dead_marked,
            "replicas": per,
        }
