"""The ``scale:`` config block, parsed once (config/config.py declares
the defaults; docs/scaleout.md is the operator reference)."""

from __future__ import annotations

from dataclasses import dataclass, field


class MeshShapeError(ValueError):
    """``scale.mesh_shape`` is malformed or doesn't factor over devices.

    Defined here (not mesh_dispatch) so config parsing can raise it
    without importing the dispatch layer; mesh_dispatch re-exports it."""


def parse_mesh_shape(raw) -> "tuple[int, int] | None":
    """Parse a ``scale.mesh_shape: [D, M]`` value.

    ``None``/missing means "let ``scale.mesh`` decide" (the pre-2-D
    behaviour: all devices on data, model=1). ``D`` may be ``-1`` for
    "all remaining devices after carving M-wide model groups". Every
    malformed value raises :class:`MeshShapeError` naming the offence —
    a sharded-serving misconfiguration must never quietly fall back to
    replication."""
    if raw is None:
        return None
    if isinstance(raw, str):
        parts = [p for p in raw.replace(",", " ").split() if p]
    elif isinstance(raw, (list, tuple)):
        parts = list(raw)
    else:
        raise MeshShapeError(
            f"scale.mesh_shape must be a [D, M] pair, got {type(raw).__name__} {raw!r}"
        )
    if len(parts) != 2:
        raise MeshShapeError(
            f"scale.mesh_shape must have exactly 2 entries [data, model], got {raw!r}"
        )
    try:
        d, m = (int(p) for p in parts)
    except (TypeError, ValueError):
        raise MeshShapeError(
            f"scale.mesh_shape entries must be integers, got {raw!r}"
        ) from None
    if m < 1:
        raise MeshShapeError(
            f"scale.mesh_shape model size must be >= 1, got {m} (from {raw!r})"
        )
    if d != -1 and d < 1:
        raise MeshShapeError(
            f"scale.mesh_shape data size must be -1 (all remaining) or >= 1, "
            f"got {d} (from {raw!r})"
        )
    return (d, m)


@dataclass(frozen=True)
class PlacementOptions:
    """The ``scale.placement:`` sub-block: the planner's policy knobs.

    ``enabled: false`` keeps the router's passive affinity/least-loaded
    dispatch bitwise unchanged (tier-1 asserts the parity). Heat is the
    capacity ledger's windowed requests/s per scene: a scene at/above
    ``hot_rps`` is hot and gets ``hot_width`` replicas, plus one more per
    ``width_rps`` of additional heat, capped at ``max_width``. Byte
    budgets of 0 defer to each replica's own ladder budgets."""

    enabled: bool = False
    hot_width: int = 2
    max_width: int = 4
    hot_rps: float = 0.5
    width_rps: float = 2.0
    hbm_budget_bytes: int = 0
    staging_budget_bytes: int = 0
    replan_every_s: float = 10.0
    max_moves_per_step: int = 4

    @classmethod
    def from_cfg_block(cls, p) -> "PlacementOptions":
        return cls(
            enabled=bool(p.get("enabled", False)),
            hot_width=max(1, int(p.get("hot_width", 2))),
            max_width=max(1, int(p.get("max_width", 4))),
            hot_rps=float(p.get("hot_rps", 0.5)),
            width_rps=max(1e-9, float(p.get("width_rps", 2.0))),
            hbm_budget_bytes=int(p.get("hbm_budget_bytes", 0)),
            staging_budget_bytes=int(p.get("staging_budget_bytes", 0)),
            replan_every_s=float(p.get("replan_every_s", 10.0)),
            max_moves_per_step=max(1, int(p.get("max_moves_per_step", 4))),
        )


@dataclass(frozen=True)
class ScaleOptions:
    """Supervisor policy + replica-runtime knobs.

    The out/in thresholds are deliberately ASYMMETRIC (hysteresis): a
    replica is added when attainment sags below ``out_below`` (or the
    tenant deny rate climbs past ``deny_above``) for ``out_windows``
    consecutive evaluations, but removed only after ``in_windows``
    consecutive evaluations at or above the STRICTER ``in_above`` — so
    attainment hovering between the two thresholds changes nothing, and
    the fleet cannot flap. Cooldowns additionally space actions out so
    one bad window after a spawn can't immediately trigger another.
    """

    enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 4
    # SLO signal (obs/metrics.slo_view attainment in [0, 1], or the
    # bench's windowed equivalent) + fleet/qos deny rate
    out_below: float = 0.90      # attainment below this asks for a replica
    in_above: float = 0.98       # attainment at/above this allows retire
    deny_above: float = 0.05     # tenant deny rate above this asks for one
    out_windows: int = 2         # consecutive bad windows before scale-out
    in_windows: int = 5          # consecutive good windows before scale-in
    cooldown_out_s: float = 30.0
    cooldown_in_s: float = 120.0
    # replica lifecycle
    heartbeat_interval_s: float = 2.0
    heartbeat_timeout_s: float = 10.0
    drain_timeout_s: float = 60.0
    # mesh-sharded dispatch: shard each executable's ray chunks over the
    # data-parallel mesh. "auto" enables it when >1 device is visible;
    # "force" builds the mesh path even on one device (the parity/test
    # configuration); "off" keeps plain jax.jit.
    mesh: str = "off"
    # 2-D mesh shape [data, model] for model-parallel serving. None
    # keeps the 1-D default (all devices on data). model > 1 shards the
    # param tree by parallel/sharding._TP_RULES: embedding/hash tables
    # row-sharded, MLP width column-parallel, heads replicated.
    mesh_shape: "tuple[int, int] | None" = None
    # scene placement planner (scale/placement.py)
    placement: PlacementOptions = field(default_factory=PlacementOptions)

    @classmethod
    def from_cfg(cls, cfg) -> "ScaleOptions":
        s = cfg.get("scale", {})
        return cls(
            enabled=bool(s.get("enabled", False)),
            min_replicas=max(1, int(s.get("min_replicas", 1))),
            max_replicas=max(1, int(s.get("max_replicas", 4))),
            out_below=float(s.get("out_below", 0.90)),
            in_above=float(s.get("in_above", 0.98)),
            deny_above=float(s.get("deny_above", 0.05)),
            out_windows=max(1, int(s.get("out_windows", 2))),
            in_windows=max(1, int(s.get("in_windows", 5))),
            cooldown_out_s=float(s.get("cooldown_out_s", 30.0)),
            cooldown_in_s=float(s.get("cooldown_in_s", 120.0)),
            heartbeat_interval_s=float(s.get("heartbeat_interval_s", 2.0)),
            heartbeat_timeout_s=float(s.get("heartbeat_timeout_s", 10.0)),
            drain_timeout_s=float(s.get("drain_timeout_s", 60.0)),
            mesh=str(s.get("mesh", "off")),
            mesh_shape=parse_mesh_shape(s.get("mesh_shape", None)),
            placement=PlacementOptions.from_cfg_block(
                s.get("placement", {})),
        )
