"""Closed-loop replica supervision against the SLO signal.

PR 10 built the signal (``/metrics`` + the ``/healthz`` SLO block), PR 12
added per-tenant deny rates; this loop finally ACTS on them. Each
``step(attainment, deny_rate)`` evaluates one observation window:

* **scale-out** — attainment below ``out_below`` OR deny rate above
  ``deny_above`` for ``out_windows`` CONSECUTIVE windows, the out
  cooldown has elapsed, and the fleet is under ``max_replicas``:
  spawn one replica (warm-started from the shared artifact store, so
  the capacity arrives in seconds).
* **scale-in** — attainment at/above the STRICTER ``in_above`` and deny
  rate at/below ``deny_above`` for ``in_windows`` consecutive windows,
  the in cooldown has elapsed, and the fleet is over ``min_replicas``:
  drain-before-retire the least-loaded replica.
* **replace** — a dead replica (missed heartbeats, crash) is replaced
  immediately, outside the cooldowns: that is capacity repair, not a
  scaling decision, and waiting out a cooldown would serve the outage.

The asymmetric thresholds + consecutive-window streaks are the
hysteresis; the cooldowns bound the rate of change. Both exist so the
loop converges instead of flapping (tests/test_scale.py drives the
decision table on a fake clock).

Every decision (including holds) emits a ``scale_decision`` telemetry
row, so ``tlm_report`` can show the loop's reasoning and ``--diff`` can
gate on grown SLO-miss windows / replica churn.
"""

from __future__ import annotations

import time
from collections import deque

from ..obs import get_emitter
from ..obs.metrics import get_metrics
from ..resil import dump_flight, note_flight
from .options import ScaleOptions
from .replica import ReplicaState


class Supervisor:
    """One decision loop over a :class:`~.router.Router`.

    ``spawn_fn(index) -> replica`` builds a new replica (serve_bench
    passes an engine factory against the shared artifact dir; tests pass
    fakes). The supervisor registers what it spawns.

    ``evidence_source`` (optional) links every decision to what the loop
    saw: any object with ``slo_miss_exemplars(target_s)`` — the process
    :class:`~..obs.metrics.MetricsRegistry` or a fleet
    :class:`~.fleet_metrics.FleetMetricsAggregator`. With it attached,
    each ``scale_decision`` row carries an ``evidence`` block (attainment
    series, per-replica queue depths, deny rate, exemplar trace ids of
    SLO-missing requests) and every out/in dumps a
    ``flight_scale_<dir>.json`` naming that evidence."""

    def __init__(self, router, spawn_fn, options: ScaleOptions | None = None,
                 clock=time.monotonic, evidence_source=None,
                 slo_target_s: float = 0.25, alerts=None,
                 planner=None, placement_executor=None):
        self.router = router
        self.spawn_fn = spawn_fn
        self.options = options or ScaleOptions()
        self.clock = clock
        self.evidence_source = evidence_source
        self.slo_target_s = float(slo_target_s)
        # optional obs.alerts.AlertEngine fed the fleet-merged window in
        # step_from_fleet — the burn-rate alerts see what the loop sees
        self.alerts = alerts
        # optional scale/placement wiring: the supervisor owns the plan
        # lifecycle — replan on scale-out/in, replica death, and scene
        # publish, plus a periodic cadence; execute pending moves (rate-
        # limited) on every step
        self.planner = planner
        self.placement_executor = placement_executor
        self._last_plan_t = -float("inf")
        self._publish_pending = False
        self._spawn_index = 0
        self._out_streak = 0
        self._in_streak = 0
        # cooldown anchors start "elapsed": the first legitimate streak
        # may act immediately
        self._last_out_t = -float("inf")
        self._last_in_t = -float("inf")
        self._attainment_history: deque = deque(maxlen=16)
        self._last_deny_rate = 0.0
        self.n_spawned = 0
        self.n_retired = 0
        self.n_replaced = 0
        self.n_miss_windows = 0
        self.drain_failures = 0
        self.decisions: list[dict] = []

    # -- capacity actions -----------------------------------------------------

    def _spawn(self, reason: str) -> object:
        replica = self.spawn_fn(self._spawn_index)
        self._spawn_index += 1
        self.n_spawned += 1
        self.router.register(replica)
        get_emitter().emit(
            "replica", replica=replica.replica_id, event="spawn",
            state=replica.state, n_ready=self.router.n_ready(),
            detail=reason,
        )
        return replica

    def ensure_min(self) -> int:
        """Bring the fleet up to ``min_replicas`` (boot path)."""
        spawned = 0
        while self.router.n_ready() < self.options.min_replicas:
            self._spawn("ensure_min")
            spawned += 1
        return spawned

    def replace_dead(self) -> int:
        """Sweep heartbeats and replace every dead replica 1:1 (bounded
        by ``max_replicas``). Runs outside the cooldowns — repair, not
        scaling."""
        self.router.sweep()
        replaced = 0
        for r in self.router.replicas():
            if r.state != ReplicaState.DEAD:
                continue
            self.router.deregister(r.replica_id)
            if self.router.n_ready() < self.options.max_replicas:
                fresh = self._spawn(f"replace:{r.replica_id}")
                replaced += 1
                self._decide("replace", f"dead:{r.replica_id}",
                             replica=fresh.replica_id)
        self.n_replaced += replaced
        if replaced:
            # capacity repair invalidates the plan: the dead replica's
            # assignments must land somewhere that exists
            self._placement_tick("replace")
        return replaced

    # -- placement ------------------------------------------------------------

    def note_publish(self, scene_id: str) -> None:
        """A scene version went out (fleet/publish.py): replan at the
        next step so publish moves push it to every assigned replica."""
        if self.planner is not None:
            self.planner.note_publish(scene_id)
            self._publish_pending = True

    def _placement_tick(self, action: str) -> None:
        """One plan-lifecycle beat: replan when triggered (scale/death/
        publish) or the cadence is due, then apply up to
        ``max_moves_per_step`` pending moves."""
        if self.planner is None:
            return
        popt = self.options.placement
        now = self.clock()
        trigger = action in ("out", "in", "replace")
        if self._publish_pending:
            trigger, action = True, "publish"
            self._publish_pending = False
        if trigger or now - self._last_plan_t >= popt.replan_every_s:
            self._last_plan_t = now
            self.planner.replan_from_router(
                self.router,
                reason=action if trigger else "periodic")
        if self.placement_executor is not None and self.planner.pending:
            self.placement_executor.execute(
                self.planner, limit=popt.max_moves_per_step)

    def _retire_pick(self):
        """Least-loaded ready replica (fastest drain, least disruption)."""
        ready = [r for r in self.router.replicas()
                 if r.state == ReplicaState.READY]
        if not ready:
            return None

        def load_of(r):
            try:
                return int(r.load())
            # graftlint: ok(swallow: retire-pick probe; an unreadable load just makes the replica least attractive)
            except Exception:
                return 1 << 30

        return min(ready, key=lambda r: (load_of(r), r.replica_id))

    # -- the decision loop ----------------------------------------------------

    def _evidence(self) -> dict | None:
        """The metric-window snapshot a decision links to (None when no
        evidence source is attached — the pre-PR-15 decision shape)."""
        if self.evidence_source is None:
            return None
        try:
            tids = list(self.evidence_source.slo_miss_exemplars(
                self.slo_target_s))
        # graftlint: ok(swallow: evidence must never fail the decision that cites it; an empty id list is itself visible to the --diff gate)
        except Exception:
            tids = []
        return {
            "attainment_series": [None if a is None else round(float(a), 4)
                                  for a in self._attainment_history],
            "queue_depths": self.router.load_view(),
            "deny_rate": round(float(self._last_deny_rate), 4),
            "exemplar_trace_ids": tids,
        }

    def _decide(self, action: str, reason: str, *, attainment=None,
                deny_rate=None, streak=0, replica=None) -> str:
        n = self.router.n_ready()
        row = {"action": action, "reason": reason, "n_replicas": n,
               "streak": int(streak)}
        if attainment is not None:
            row["attainment"] = float(attainment)
        if deny_rate is not None:
            row["deny_rate"] = float(deny_rate)
        if replica is not None:
            row["replica"] = str(replica)
        evidence = self._evidence()
        if evidence is not None:
            row["evidence"] = evidence
        self.decisions.append(row)
        get_emitter().emit("scale_decision", **row)
        mx = get_metrics()
        mx.counter("scale_decisions_total", action=action)
        mx.gauge("scale_replicas_ready", n)
        if action in ("out", "in"):
            # the post-mortem trail: the flight ring gets the decision
            # with its evidence, then flight_scale_<dir>.json snapshots
            # the spans (the exemplar traces among them) at the moment
            # the loop acted
            note_flight(point="scale.decision", action=action,
                        reason=reason, n_replicas=n,
                        **({} if replica is None
                           else {"replica": str(replica)}),
                        **({} if evidence is None
                           else {"evidence": evidence}))
            dump_flight(f"scale_{action}",
                        detail=f"{reason}; exemplars="
                               + ",".join((evidence or {}).get(
                                   "exemplar_trace_ids", [])[:4]))
        return action

    def step(self, attainment: float | None, deny_rate: float = 0.0) -> str:
        """Evaluate one observation window; returns the action taken
        (``out`` / ``in`` / ``replace`` / ``hold``). ``attainment`` is
        the window's SLO attainment in [0, 1] (None = no traffic, which
        counts toward scale-IN: an idle fleet should shrink). With a
        planner attached, every step also beats the plan lifecycle
        (replan on scale actions / publish / cadence, then apply a
        bounded batch of pending moves)."""
        action = self._step_window(attainment, deny_rate)
        if action != "replace":  # replace_dead already ticked the plan
            self._placement_tick(action)
        return action

    def _step_window(self, attainment: float | None,
                     deny_rate: float = 0.0) -> str:
        opt = self.options
        now = self.clock()
        self._attainment_history.append(
            None if attainment is None else float(attainment))
        self._last_deny_rate = float(deny_rate)
        if self.replace_dead():
            return "replace"
        missing = (attainment is not None and attainment < opt.out_below)
        denying = deny_rate > opt.deny_above
        good = ((attainment is None or attainment >= opt.in_above)
                and deny_rate <= opt.deny_above)
        if missing or denying:
            self.n_miss_windows += 1
            self._out_streak += 1
            self._in_streak = 0
        elif good:
            self._in_streak += 1
            self._out_streak = 0
        else:
            # the hysteresis band: neither streak advances
            self._out_streak = 0
            self._in_streak = 0
        n = self.router.n_ready()
        if (self._out_streak >= opt.out_windows
                and now - self._last_out_t >= opt.cooldown_out_s
                and n < opt.max_replicas):
            self._last_out_t = now
            self._out_streak = 0
            reason = "deny_rate" if (denying and not missing) else "slo_miss"
            fresh = self._spawn(reason)
            return self._decide("out", reason, attainment=attainment,
                                deny_rate=deny_rate,
                                streak=opt.out_windows,
                                replica=fresh.replica_id)
        if (self._in_streak >= opt.in_windows
                and now - self._last_in_t >= opt.cooldown_in_s
                and n > opt.min_replicas):
            self._last_in_t = now
            self._in_streak = 0
            victim = self._retire_pick()
            if victim is not None:
                failed = self.router.drain(victim.replica_id,
                                           timeout_s=opt.drain_timeout_s)
                self.drain_failures += int(failed)
                self.n_retired += 1
                return self._decide("in", "sustained_attainment",
                                    attainment=attainment,
                                    deny_rate=deny_rate,
                                    streak=opt.in_windows,
                                    replica=victim.replica_id)
        return self._decide(
            "hold",
            "miss_streak" if self._out_streak else
            ("good_streak" if self._in_streak else "steady"),
            attainment=attainment, deny_rate=deny_rate,
            streak=max(self._out_streak, self._in_streak),
        )

    def step_from_fleet(self, aggregator) -> str:
        """One window read straight off the fleet aggregator — the loop
        acts on the SAME merged signal ``GET /fleet/metrics`` shows the
        operator. A window where nothing completed (attainment None) but
        replicas hold queued work is total overload, not idleness: it
        counts as attainment 0.0 so the loop scales OUT instead of
        reading a wedged fleet as a shrink signal."""
        w = aggregator.window()
        attainment = w["attainment"]
        if attainment is None:
            backlog = sum(aggregator.router.load_view().values())
            if backlog > 0 or w.get("no_replica", 0) > 0:
                attainment = 0.0
        if self.alerts is not None:
            # a wedged fleet completes nothing, so weight the forced-0.0
            # attainment by at least one observation or no bad count
            # would ever accumulate and the page would never fire
            n = max(int(w.get("requests", 0)),
                    1 if attainment is not None else 0)
            self.alerts.observe_window(attainment, w["deny_rate"], n)
            self.alerts.evaluate()
        return self.step(attainment, deny_rate=w["deny_rate"])

    def stats(self) -> dict:
        return {
            "n_spawned": self.n_spawned,
            "n_retired": self.n_retired,
            "n_replaced": self.n_replaced,
            "n_miss_windows": self.n_miss_windows,
            "drain_failures": self.drain_failures,
            "churn": self.n_spawned + self.n_retired,
            "n_decisions": len(self.decisions),
            "router": self.router.stats(),
            "placement": (None if self.planner is None
                          else self.planner.stats()),
        }
