"""Real process fleet: spawn/ready/drain/replace for ``serve.py`` children.

:class:`~.replica.ProcessReplica` has always known how to TALK to a
``serve.py`` child (heartbeat = ``GET /healthz``, drain = ``POST
/drain``) but nothing ever spawned one — the crashed-process scale
story ran only on in-process fakes. :class:`ProcessLauncher` closes the
gap:

* **port allocation** — bind an ephemeral socket, read the port, hand
  it to the child. The tiny close-to-bind race window is accepted: a
  collision surfaces as the child exiting during ready-wait, which the
  caller handles exactly like any other failed spawn;
* **spawn** — ``python serve.py --cfg_file <cfg> --host --port`` with
  ``cwd`` at the repo root and ``SCALE_REPLICA_ID`` in the env. The cfg
  points ``compile.dir`` at the SHARED ``.aot`` artifact dir, so every
  child warms from disk (``warm_source == "disk"``, zero compiles) —
  fleet capacity arrives in seconds, not a compile;
* **ready-wait** — poll the child's heartbeat until it answers (which
  flips the replica ``starting -> ready``) or the deadline passes; a
  child that exits early is reaped and reported with its exit code;
* **drain-before-retire** — ``retire`` delegates to the replica's
  ``drain`` (``POST /drain``, wait for the queue to empty, terminate);
* **kill + 1:1 replace** — the chaos shape: ``replace`` kills (or
  buries) a replica and spawns a fresh one on a fresh port.

The launcher is the supervisor's ``spawn_fn`` (it is callable with a
spawn index), so ``serve_bench --replicas --processes`` and
``chaos_run --replicas --processes`` drive the real multi-process
fleet through the same router/supervisor/planner code the in-process
bench uses.
"""

from __future__ import annotations

import os
import socket
import sys
import time

from .replica import ProcessReplica

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


class LaunchError(RuntimeError):
    """A child failed to reach ready (exited early or timed out)."""


def allocate_port(host: str = "127.0.0.1") -> int:
    """An ephemeral port the OS just proved free on ``host``."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class ProcessLauncher:
    """Spawns ``serve.py`` children wearing the ProcessReplica surface.

    ``cfg_file`` is the config every child boots from (the caller bakes
    the shared ``compile.dir`` into it); ``env`` overlays the child
    environment (e.g. ``JAX_PLATFORMS=cpu`` for a host-only fleet);
    ``ready_timeout_s`` bounds the spawn-to-serving wait."""

    def __init__(self, cfg_file: str, *, host: str = "127.0.0.1",
                 python: str | None = None, env: dict | None = None,
                 cwd: str | None = None, ready_timeout_s: float = 120.0,
                 poll_s: float = 0.25, healthz_ttl_s: float = 0.5,
                 clock=time.monotonic):
        self.cfg_file = str(cfg_file)
        self.host = str(host)
        self.python = python or sys.executable
        self.env = dict(env or {})
        self.cwd = cwd or _REPO_ROOT
        self.ready_timeout_s = float(ready_timeout_s)
        self.poll_s = float(poll_s)
        self.healthz_ttl_s = float(healthz_ttl_s)
        self.clock = clock
        self._spawn_seq = 0
        self.replicas: list[ProcessReplica] = []
        self.n_spawned = 0
        self.n_replaced = 0
        self.n_retired = 0

    # the supervisor's spawn_fn signature
    def __call__(self, index: int) -> ProcessReplica:
        return self.spawn(index)

    def spawn(self, index: int | None = None) -> ProcessReplica:
        """Spawn one child and block until it serves (ready-wait on its
        heartbeat). Raises :class:`LaunchError` on early exit/timeout."""
        seq = self._spawn_seq if index is None else int(index)
        self._spawn_seq = max(self._spawn_seq, seq) + 1
        port = allocate_port(self.host)
        replica = ProcessReplica(
            f"proc{seq}", self.cfg_file, self.host, port,
            python=self.python, clock=self.clock,
            healthz_ttl_s=self.healthz_ttl_s,
        )
        replica.spawn(env=self.env, cwd=self.cwd)
        self.wait_ready(replica)
        self.replicas.append(replica)
        self.n_spawned += 1
        return replica

    def wait_ready(self, replica: ProcessReplica) -> None:
        deadline = self.clock() + self.ready_timeout_s
        last = ""
        while self.clock() < deadline:
            if replica.proc is not None and replica.proc.poll() is not None:
                raise LaunchError(
                    f"replica {replica.replica_id} exited during startup "
                    f"(code {replica.proc.returncode})")
            try:
                replica.heartbeat()  # first ok beat flips starting->ready
                return
            # graftlint: ok(swallow: startup polling — the child is not listening yet; the deadline below is the failure path)
            except Exception as exc:
                last = str(exc)
            time.sleep(self.poll_s)
        replica.kill()
        raise LaunchError(
            f"replica {replica.replica_id} not ready after "
            f"{self.ready_timeout_s:.0f}s ({last})")

    def retire(self, replica: ProcessReplica,
               timeout_s: float = 60.0) -> int:
        """Drain-before-retire one child; returns its in-flight failure
        count (the contract wants 0)."""
        failed = replica.drain(timeout_s=timeout_s)
        self.n_retired += 1
        return failed

    def replace(self, replica: ProcessReplica) -> ProcessReplica:
        """Kill (or bury) ``replica`` and spawn its 1:1 replacement on a
        fresh port."""
        if replica.proc is None or replica.proc.poll() is None:
            replica.kill()
        if replica.proc is not None:
            try:
                replica.proc.wait(timeout=10.0)
            # graftlint: ok(swallow: a zombie that outlives the wait still freed its port; the fresh spawn binds a new one)
            except Exception:
                pass
        fresh = self.spawn()
        self.n_replaced += 1
        return fresh

    def shutdown(self) -> None:
        """Kill every child still running (bench/chaos teardown)."""
        for replica in self.replicas:
            if replica.proc is not None and replica.proc.poll() is None:
                replica.kill()
        for replica in self.replicas:
            if replica.proc is not None:
                try:
                    replica.proc.wait(timeout=10.0)
                # graftlint: ok(swallow: teardown best-effort; an unkillable child is the OS's problem now)
                except Exception:
                    pass

    def stats(self) -> dict:
        return {
            "n_spawned": self.n_spawned,
            "n_replaced": self.n_replaced,
            "n_retired": self.n_retired,
            "alive": sum(1 for r in self.replicas
                         if r.proc is not None and r.proc.poll() is None),
        }
