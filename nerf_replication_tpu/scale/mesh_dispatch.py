"""Mesh-sharded executable dispatch: one micro-batch spans the mesh.

The engine's bucketed executables ``lax.map`` over ``[n_chunks, chunk,
C]`` ray chunks. Data parallelism here shards the LEADING chunk axis
over the mesh's ``data`` axis with ``shard_map``: each device runs the
identical per-chunk program over its local slice of chunks while the
params / occupancy grid / bbox replicate. No collective ever runs inside
the render — every ray's math is the same op sequence on one device as
on many — so the mesh render is **bitwise-equal** to the single-device
path (tests/test_scale.py proves it on a forced size-1 mesh, the CPU
tier-1 configuration).

Had the sharding gone over the per-chunk ray axis instead, the packed
march's cross-ray candidate sort would have turned into cross-device
collectives; sharding whole chunks keeps the executable communication-
free and the parity exact. The cost is a divisibility constraint:
``bucket // chunk`` must divide by the mesh's data size
(:func:`validate_mesh_buckets` rejects a config that would silently
pad or gather at engine construction, not at request time).
"""

from __future__ import annotations


class MeshDispatchError(ValueError):
    """The serve bucket layout cannot shard over the configured mesh."""


def validate_mesh_buckets(buckets, chunk: int, mesh) -> None:
    """Reject bucket sets whose chunk counts don't divide over the mesh.

    Called at engine construction (install time), so a bad
    ``serve.buckets`` / ``scale.mesh`` combination fails loudly before
    warm-up instead of as a mid-request reshard."""
    from ..parallel.mesh import DATA_AXIS

    n_dev = int(mesh.shape[DATA_AXIS])
    bad = [int(b) for b in buckets if (int(b) // int(chunk)) % n_dev]
    if bad:
        raise MeshDispatchError(
            f"buckets {bad} have chunk counts not divisible by the mesh "
            f"data size {n_dev} (chunk={chunk}); adjust serve.buckets so "
            f"every bucket holds a multiple of {n_dev} chunks"
        )


def mesh_jit(body, mesh, has_grid: bool):
    """``jax.jit`` of ``body`` with its chunk axis sharded over ``mesh``.

    ``body`` is the engine's UN-jitted executable body — signature
    ``(params, chunks[, grid, bbox]) -> dict`` with every output leaf
    carrying the ``n_chunks`` leading axis. Params/grid/bbox replicate
    (``P()``); chunks and outputs shard over the data axis."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS

    rep, data = P(), P(DATA_AXIS)
    in_specs = (rep, data) + ((rep, rep) if has_grid else ())
    # check_rep off: the body is collective-free by construction (whole
    # chunks shard; params replicate), and the replication checker costs
    # trace time without adding safety here
    mapped = shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=data,
                       check_rep=False)
    # graftlint: ok(aot: the engine warm path registers every finalized executable with AOTRegistry)
    return jax.jit(mapped)


def mesh_from_scale_cfg(cfg):
    """The serving mesh the ``scale:`` block asks for (None = off).

    ``scale.mesh`` values: ``"off"`` keeps plain ``jax.jit``; ``"auto"``
    builds the data-parallel mesh only when more than one device is
    visible (so CPU tier-1 and single-chip serving keep the default
    path); ``"force"`` builds it even on one device — the parity-test
    and bring-up configuration."""
    from .options import ScaleOptions

    mode = ScaleOptions.from_cfg(cfg).mesh
    if mode not in ("off", "auto", "force"):
        raise MeshDispatchError(
            f"scale.mesh must be off|auto|force, got {mode!r}"
        )
    if mode == "off":
        return None
    import jax

    if mode == "auto" and len(jax.devices()) <= 1:
        return None
    from ..parallel.mesh import make_mesh

    # data-parallel only: every device on the data axis (model_axis=1),
    # matching the replicated-params partition rules the serve path uses
    return make_mesh(data_axis=-1, model_axis=1)
