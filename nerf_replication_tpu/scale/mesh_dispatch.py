"""Mesh-sharded executable dispatch: one micro-batch spans the mesh.

The engine's bucketed executables ``lax.map`` over ``[n_chunks, chunk,
C]`` ray chunks. Data parallelism here shards the LEADING chunk axis
over the mesh's ``data`` axis with ``shard_map``: each device runs the
identical per-chunk program over its local slice of chunks while the
params / occupancy grid / bbox replicate. No collective ever runs inside
the render — every ray's math is the same op sequence on one device as
on many — so the mesh render is **bitwise-equal** to the single-device
path (tests/test_scale.py proves it on a forced size-1 mesh, the CPU
tier-1 configuration).

Had the sharding gone over the per-chunk ray axis instead, the packed
march's cross-ray candidate sort would have turned into cross-device
collectives; sharding whole chunks keeps the executable communication-
free and the parity exact. The cost is a divisibility constraint:
``bucket // chunk`` must divide by the mesh's data size
(:func:`validate_mesh_buckets` rejects a config that would silently
pad or gather at engine construction, not at request time).

**Model parallelism** (``scale.mesh_shape: [D, M]`` with ``M > 1``)
switches :func:`mesh_jit` from the collective-free ``shard_map`` path to
GSPMD: the param tree is sharded by ``parallel/sharding.py``'s partition
rules (hash/embedding tables row-sharded over ``model``, MLP hidden
width column-parallel, output heads replicated) and XLA inserts the
collectives — an all-gather (or psum of partial features) at the sharded
encoder table lookup, all-gathers around the column-parallel matmuls.
Ray chunks still split whole-chunks over ``data``. Collectives reorder
float math, so the M>1 path promises allclose, not bitwise; ``M == 1``
keeps the exact shard_map path, which is tier-1's parity bar. The win is
capacity: each device holds ~1/M of the scene's params, so a scene
larger than one chip's HBM budget becomes servable (docs/scaleout.md
"Model-parallel serving").
"""

from __future__ import annotations

from .options import MeshShapeError  # noqa: F401  (re-export; raised here too)


class MeshDispatchError(ValueError):
    """The serve bucket layout cannot shard over the configured mesh."""


def validate_mesh_buckets(buckets, chunk: int, mesh) -> None:
    """Reject bucket sets whose chunk counts don't divide over the mesh.

    Called at engine construction (install time), so a bad
    ``serve.buckets`` / ``scale.mesh``/``mesh_shape`` combination fails
    loudly before warm-up instead of as a mid-request reshard. Only the
    DATA axis constrains the ray layout — the model axis shards params,
    not chunks — but the error names the full 2-D shape so the operator
    sees which mesh the layout failed against."""
    from ..parallel.mesh import DATA_AXIS, MODEL_AXIS

    n_data = int(mesh.shape[DATA_AXIS])
    n_model = int(mesh.shape.get(MODEL_AXIS, 1))
    bad = [int(b) for b in buckets if (int(b) // int(chunk)) % n_data]
    if bad:
        raise MeshDispatchError(
            f"buckets {bad} have chunk counts not divisible by the data "
            f"size {n_data} of the ({n_data}, {n_model}) mesh "
            f"(chunk={chunk}); adjust serve.buckets so every bucket "
            f"holds a multiple of {n_data} chunks"
        )


def model_size(mesh) -> int:
    """The mesh's model-axis extent (1 when absent or mesh is None)."""
    if mesh is None:
        return 1
    from ..parallel.mesh import MODEL_AXIS

    return int(mesh.shape.get(MODEL_AXIS, 1))


def mesh_jit(body, mesh, has_grid: bool, params_template=None):
    """``jax.jit`` of ``body`` with its chunk axis sharded over ``mesh``.

    ``body`` is the engine's UN-jitted executable body — signature
    ``(params, chunks[, grid, bbox]) -> dict`` with every output leaf
    carrying the ``n_chunks`` leading axis.

    With a size-1 model axis, params/grid/bbox replicate (``P()``) and
    chunks/outputs shard over the data axis under ``shard_map`` — the
    collective-free, bitwise path. With ``model > 1``,
    ``params_template`` (any pytree with the executable's param
    shapes/dtypes — abstract leaves fine) selects the GSPMD path: params
    carry the TP-rule shardings and the body is vmapped over
    data-axis-sized groups of chunks so ``lax.map`` stays per-device
    sequential instead of serializing across the sharded chunk axis.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS

    if model_size(mesh) > 1:
        if params_template is None:
            raise MeshDispatchError(
                "mesh_jit needs a params_template to derive partition "
                "specs when the mesh has a model axis > 1"
            )
        return _mesh_jit_sharded(body, mesh, has_grid, params_template)

    from ..parallel.compat import shard_map

    rep, data = P(), P(DATA_AXIS)
    in_specs = (rep, data) + ((rep, rep) if has_grid else ())
    # check_rep off: the body is collective-free by construction (whole
    # chunks shard; params replicate), and the replication checker costs
    # trace time without adding safety here
    mapped = shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=data,
                       check_vma=False)
    # graftlint: ok(aot: the engine warm path registers every finalized executable with AOTRegistry)
    return jax.jit(mapped)


def _mesh_jit_sharded(body, mesh, has_grid: bool, params_template):
    """The GSPMD model-parallel finalizer (``mesh_shape`` M > 1).

    The body's ``lax.map`` over the chunk axis is a scan — under plain
    GSPMD jit, a scan over a sharded axis would serialize and replicate.
    So the wrapper reshapes ``[n, chunk, C] -> [D, n/D, chunk, C]`` and
    ``vmap``s the body over the leading data-group axis: the vmapped
    dimension shards cleanly over ``data`` (each device group runs its
    own sequential ``lax.map`` over n/D chunks, exactly shard_map's
    schedule), while inside the body XLA places the model-axis
    collectives the sharded params demand. ``validate_mesh_buckets``
    guarantees ``D | n`` at engine construction."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS
    from ..parallel.sharding import tree_shardings

    n_data = int(mesh.shape[DATA_AXIS])
    param_sh = tree_shardings(params_template, mesh)
    rep = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P(DATA_AXIS))
    in_sh = (param_sh, data) + ((rep, rep) if has_grid else ())

    def wrapped(params, chunks, *rest):
        n = chunks.shape[0]
        groups = chunks.reshape((n_data, n // n_data) + chunks.shape[1:])
        out = jax.vmap(lambda ch: body(params, ch, *rest))(groups)
        return jax.tree.map(
            lambda a: a.reshape((n,) + a.shape[2:]), out
        )

    # graftlint: ok(aot: the engine warm path registers every finalized executable with AOTRegistry)
    return jax.jit(wrapped, in_shardings=in_sh, out_shardings=data)


def mesh_from_scale_cfg(cfg):
    """The serving mesh the ``scale:`` block asks for (None = off).

    ``scale.mesh`` values: ``"off"`` keeps plain ``jax.jit``; ``"auto"``
    builds the data-parallel mesh only when more than one device is
    visible (so CPU tier-1 and single-chip serving keep the default
    path); ``"force"`` builds it even on one device — the parity-test
    and bring-up configuration. ``scale.mesh_shape: [D, M]`` picks an
    explicit 2-D layout (``D = -1`` means all remaining devices); it
    must factor over the visible devices or :class:`MeshShapeError`
    says exactly what didn't fit."""
    from .options import ScaleOptions

    opts = ScaleOptions.from_cfg(cfg)
    mode = opts.mesh
    if mode not in ("off", "auto", "force"):
        raise MeshDispatchError(
            f"scale.mesh must be off|auto|force, got {mode!r}"
        )
    if mode == "off":
        return None
    import jax

    n_dev = len(jax.devices())
    if mode == "auto" and n_dev <= 1:
        return None
    from ..parallel.mesh import make_mesh

    if opts.mesh_shape is None:
        # data-parallel only: every device on the data axis (model_axis=1),
        # matching the replicated-params partition rules the serve path uses
        return make_mesh(data_axis=-1, model_axis=1)
    d, m = opts.mesh_shape
    if n_dev % m:
        raise MeshShapeError(
            f"scale.mesh_shape ({d}, {m}): model size {m} does not "
            f"divide the {n_dev} visible devices"
        )
    want = (n_dev // m if d == -1 else d) * m
    if want > n_dev:
        raise MeshShapeError(
            f"scale.mesh_shape ({d}, {m}) needs {want} devices, only "
            f"{n_dev} visible"
        )
    try:
        return make_mesh(data_axis=d, model_axis=m)
    except ValueError as e:
        raise MeshShapeError(
            f"scale.mesh_shape ({d}, {m}) does not factor over the "
            f"{n_dev} visible devices: {e}"
        ) from None
