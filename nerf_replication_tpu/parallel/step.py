"""Sharded train-step builders over the device mesh.

Two idioms, both producing a single compiled step that never touches the host
(SURVEY.md §7 "DDP/NCCL → mesh + shard_map/pjit"):

* :func:`build_dp_step` — ``shard_map`` data parallelism with *explicit*
  collectives: each device samples its own ray batch from its local bank
  shard (disjoint RNG via the mesh axis index), computes grads, and
  ``pmean``s them over the ``data`` axis — the exact seat of the reference's
  DDP all-reduce (reference trainer.py:59-62) as an in-graph collective.
* :func:`build_gspmd_step` — ``jit`` + ``NamedSharding`` constraints (GSPMD):
  one global batch sharded over ``data``, params column-sharded over
  ``model`` (TP), and XLA inserts the collectives. This is the dp×tp path
  `dryrun_multichip` exercises.

Both builders emit the SAME traced program on every controller process
(multi-controller SPMD requires it): per-shard RNG decorrelation comes from
`lax.axis_index` inside the graph, never from host-side `process_index`.
The step semantics live in train/step_core.py, shared with the single-chip
trainer.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, PartitionSpec as P

from .compat import shard_map

from ..datasets.sampling import sample_rays, sample_step_key
from ..train.step_core import sampled_grad_step, scan_k_steps
from .collectives import tree_pmean
from .mesh import DATA_AXIS
from .sharding import data_sharding, tree_shardings
from ..utils.platform import donation_argnums


def build_dp_step(
    mesh: Mesh,
    loss,
    n_rays_global: int,
    near: float,
    far: float,
    k_steps: int = 1,
    with_pool: bool = False,
    grad_accum: int = 1,
):
    """shard_map DP step: ``(state, bank_rays, bank_rgbs, base_key[, pool])
    -> (state, stats)`` with the bank sharded over the data axis.

    ``k_steps > 1`` scans K optimizer steps inside the one dispatch (the
    trainer's scan-burst idiom — PERF.md round 3: +33% on the latency-bound
    flagship shape). ``with_pool`` adds a data-sharded local index pool for
    precrop warm-up (each shard draws from ITS pool segment of shard-local
    indices; see sharding.shard_index_pool). Signature matches the
    single-chip ``Trainer._build_step`` so the epoch loop drives either.
    """
    n_data = mesh.shape[DATA_AXIS]
    if n_rays_global % n_data != 0:
        raise ValueError(
            f"n_rays_global={n_rays_global} must divide the data axis "
            f"({n_data}) — a silent round-down would train a different "
            "effective batch than configured"
        )
    n_local = n_rays_global // n_data

    def one_step(st, bank_rays, bank_rgbs, base_key, pool):
        # disjoint stream per (step, device-shard) — axis_index is global
        # across processes, so this is multi-controller-safe
        with jax.named_scope("dp_step"):
            key = sample_step_key(base_key, st.step)
            key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))
            k_sample, k_render = jax.random.split(key)
            grads, stats = sampled_grad_step(
                loss, st.params, bank_rays, bank_rgbs, n_local, near, far,
                k_sample, k_render, index_pool=pool, grad_accum=grad_accum,
                step=st.step,
            )
            with jax.named_scope("grad_allreduce"):
                grads = tree_pmean(grads, DATA_AXIS)
                stats = tree_pmean(stats, DATA_AXIS)
            return st.apply_gradients(grads=grads), stats

    def body(state, bank_rays, bank_rgbs, base_key, *pool):
        p = pool[0] if pool else None
        return scan_k_steps(
            lambda st: one_step(st, bank_rays, bank_rgbs, base_key, p),
            state, k_steps,
        )

    in_specs = (P(), P(DATA_AXIS), P(DATA_AXIS), P())
    if with_pool:
        in_specs = in_specs + (P(DATA_AXIS),)
    smap = shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(smap, donate_argnums=donation_argnums(0))


def build_gspmd_step(
    mesh: Mesh,
    loss,
    n_rays: int,
    near: float,
    far: float,
    k_steps: int = 1,
    grad_accum: int = 1,
):
    """GSPMD dp×tp step: sharding constraints on the batch (data axis) and on
    params (model axis, via sharding rules); XLA derives the collectives.
    ``k_steps > 1`` scans K optimizer steps inside the one dispatch (same
    burst idiom as ``build_dp_step``)."""
    batch_sh = data_sharding(mesh)
    n_data = mesh.shape[DATA_AXIS]
    if n_rays % n_data != 0:
        raise ValueError(
            f"n_rays={n_rays} must divide the data axis ({n_data}) — a "
            "silent round-down would train a different effective batch "
            "than configured"
        )
    n_local = n_rays // n_data

    # per-shard sampling: each data-shard draws its rays from its LOCAL bank
    # shard (disjoint RNG via the axis index). A global random gather here
    # would make XLA materialize cross-chip collectives on the whole bank
    # every step; tests/test_parallel.py asserts the compiled HLO carries no
    # all-gather of the bank.
    def make_sampler(n):
        def _sample_local(k, bank_rays, bank_rgbs):
            with jax.named_scope("bank_draw"):
                k = jax.random.fold_in(k, jax.lax.axis_index(DATA_AXIS))
                return sample_rays(k, bank_rays, bank_rgbs, n)

        return shard_map(
            _sample_local,
            mesh=mesh,
            in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            check_vma=False,
        )

    sample_sharded = make_sampler(n_local)

    if grad_accum > 1 and n_local % grad_accum != 0:
        raise ValueError(
            f"per-shard batch {n_local} must be divisible by "
            f"task_arg.grad_accum={grad_accum}"
        )
    n_micro = max(n_local // grad_accum, 1)
    sample_sharded_micro = make_sampler(n_micro)

    def _grads_for(p_ref, sampler, bank_rays, bank_rgbs, ks, kr, step):
        rays, rgbs = sampler(ks, bank_rays, bank_rgbs)
        rays = jax.lax.with_sharding_constraint(rays, batch_sh)
        rgbs = jax.lax.with_sharding_constraint(rgbs, batch_sh)
        batch = {"rays": rays, "rgbs": rgbs, "near": near, "far": far,
                 "step": step}

        def loss_fn(p):
            _, l, stats = loss({"params": p}, batch, key=kr, train=True)
            return l, stats

        (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(p_ref)
        return grads, stats

    def one_step(st, bank_rays, bank_rgbs, base_key):
        key = sample_step_key(base_key, st.step)
        k_sample, k_render = jax.random.split(key)

        if grad_accum > 1:
            # microbatch accumulation: activation memory bounded by one
            # microbatch (same contract as step_core.sampled_grad_step)
            import jax.numpy as jnp

            def body(carry, keys):
                ks, kr = keys
                grads, stats = _grads_for(
                    st.params, sample_sharded_micro, bank_rays, bank_rgbs,
                    ks, kr, st.step,
                )
                return jax.tree_util.tree_map(
                    lambda a, b: a + b, carry, grads
                ), stats

            zeros = jax.tree_util.tree_map(jnp.zeros_like, st.params)
            gsum, stats_seq = jax.lax.scan(
                body, zeros,
                (jax.random.split(k_sample, grad_accum),
                 jax.random.split(k_render, grad_accum)),
            )
            grads = jax.tree_util.tree_map(lambda g: g / grad_accum, gsum)
            from ..train.step_core import fix_accum_psnr

            stats = fix_accum_psnr(jax.tree_util.tree_map(
                lambda x: x.mean(axis=0), stats_seq
            ))
        else:
            grads, stats = _grads_for(
                st.params, sample_sharded, bank_rays, bank_rgbs,
                k_sample, k_render, st.step,
            )
        new_state = st.apply_gradients(grads=grads)
        return new_state, stats

    def step(state, bank_rays, bank_rgbs, base_key):
        def body(st):
            with jax.named_scope("gspmd_step"):
                return one_step(st, bank_rays, bank_rgbs, base_key)

        return scan_k_steps(body, state, k_steps)

    return jax.jit(step, donate_argnums=donation_argnums(0))


def aot_register_dp_step(
    registry, name: str, abstract_args: tuple, *, mesh: Mesh, loss,
    n_rays_global: int, near: float, far: float, k_steps: int = 1,
    with_pool: bool = False, grad_accum: int = 1, serialize: bool = False,
) -> str:
    """Register the shard_map DP train step with a compile/AOTRegistry so
    the sharded executable builds during warm-up (overlapping dataset
    loading) instead of on the first burst. ``abstract_args`` is
    ``compile.abstract_like`` of the real ``(state, bank_rays, bank_rgbs,
    base_key[, pool])`` — shardings included, or the compiled executable
    rejects its own inputs."""
    registry.register(
        name,
        build_dp_step(
            mesh, loss, n_rays_global, near, far, k_steps=k_steps,
            with_pool=with_pool, grad_accum=grad_accum,
        ),
        abstract_args,
        serialize=serialize,
    )
    return name


def aot_register_gspmd_step(
    registry, name: str, abstract_args: tuple, *, mesh: Mesh, loss,
    n_rays: int, near: float, far: float, k_steps: int = 1,
    grad_accum: int = 1, serialize: bool = False,
) -> str:
    """Register the GSPMD dp×tp train step with a compile/AOTRegistry
    (same contract as :func:`aot_register_dp_step`)."""
    registry.register(
        name,
        build_gspmd_step(
            mesh, loss, n_rays, near, far, k_steps=k_steps,
            grad_accum=grad_accum,
        ),
        abstract_args,
        serialize=serialize,
    )
    return name


def shard_train_state(state, mesh: Mesh):
    """Place a TrainState on the mesh per the partition rules (params and
    optimizer moments column-sharded over ``model``; scalars replicated)."""
    return jax.device_put(state, tree_shardings(state, mesh))
