"""Parameter/array partition rules over the ``(data, model)`` mesh.

The reference has no tensor parallelism to mirror (SURVEY.md §2.3: data
parallel only) — these rules are the TPU-native capability extension: MLP
hidden width is column-sharded over the ``model`` axis (kernels
``P(None, "model")``, biases ``P("model")``), output heads and scalar state
replicated, and GSPMD propagates/inserts the collectives. Rules are keyed on
parameter *path names*, so they apply uniformly to params and to optimizer
moments (adam ``mu``/``nu`` carry the same sub-paths).
"""

from __future__ import annotations

import re

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, MODEL_AXIS

# path-regex → (kernel spec, bias spec); first match wins.
_TP_RULES: list[tuple[str, tuple[P, P]]] = [
    # output heads stay replicated: tiny, and compositing wants full vectors
    (r"(alpha_linear|rgb_linear|output_linear)", (P(), P())),
    # trunk / feature / view branches: column-parallel over hidden width
    (r"(pts_linear_\d+|feature_linear|views_linear_\d+)", (P(None, MODEL_AXIS), P(MODEL_AXIS))),
    # hash/grid embedding tables: shard the (large) entries dim over model
    (r"(embeddings|table)", (P(MODEL_AXIS), P(MODEL_AXIS))),
]


def _path_str(path) -> str:
    return "/".join(
        str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
        for p in path
    )


def spec_for_path(path, leaf) -> P:
    """PartitionSpec for one pytree leaf, keyed on its path."""
    s = _path_str(path)
    ndim = getattr(leaf, "ndim", 0)
    if ndim == 0:
        return P()
    for pattern, (kernel_spec, bias_spec) in _TP_RULES:
        if re.search(pattern, s):
            spec = kernel_spec if ndim >= 2 else bias_spec
            # trim spec to rank
            return P(*tuple(spec)[:ndim]) if len(tuple(spec)) > ndim else spec
    return P()


def tree_specs(tree):
    """PartitionSpec pytree matching ``tree`` (params, TrainState, …)."""
    return jax.tree_util.tree_map_with_path(spec_for_path, tree)


def tree_shardings(tree, mesh):
    """NamedSharding pytree for ``tree`` over ``mesh``."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, spec_for_path(path, leaf)), tree
    )


def _leaf_shard_nbytes(spec: P, leaf, mesh) -> int:
    """Per-device bytes of one leaf under ``spec`` over ``mesh``.

    Derived from the partition spec alone (no placement needed): each
    sharded dim is split into ``ceil(dim / axis_size)`` blocks, so the
    largest shard of the leaf holds the product of the rounded-up block
    sizes. This is the figure HBM admission must check — the max, not
    the mean, because residency is all-shards-or-none."""
    shape = tuple(getattr(leaf, "shape", ()))
    itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", 4)
    n = int(itemsize)
    for dim, axes in zip(shape, tuple(spec) + (None,) * len(shape)):
        size = 1
        if axes is not None:
            for ax in (axes if isinstance(axes, tuple) else (axes,)):
                size *= int(mesh.shape[ax])
        n *= -(-int(dim) // size)
    return n


def tree_shard_nbytes(tree, mesh) -> int:
    """Per-device peak bytes of ``tree`` sharded by the TP rules.

    Sums, over all leaves, the largest single shard each leaf
    contributes to one device. With ``model=1`` every spec degenerates
    to replication and this equals the plain whole-tree byte count, so
    callers can use it unconditionally."""
    total = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        total += _leaf_shard_nbytes(spec_for_path(path, leaf), leaf, mesh)
    return total


def data_sharding(mesh) -> NamedSharding:
    """Batch/bank sharding: leading dim over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def chunk_sharding(mesh) -> NamedSharding:
    """Serve-path chunk sharding: ``[n_chunks, chunk, C]`` ray chunks
    split whole-chunks-per-device over the data axis (scale/
    mesh_dispatch.py). Same leading-axis spec as :func:`data_sharding`;
    named separately because the serve path's divisibility contract
    (``n_chunks %% mesh data size == 0``, validated at engine
    construction) is its own invariant, not the bank-truncation one."""
    return NamedSharding(mesh, P(DATA_AXIS))


def shard_index_pool(pool, bank_n: int, mesh):
    """Shard a precrop index pool over the data axis as LOCAL indices.

    The single-chip pool holds global flat ray indices; a data-sharded bank
    gives shard ``d`` rows ``[d*L, (d+1)*L)``, so each shard needs the pool
    members that fall inside its slice, rebased to shard-local offsets.
    Segments are padded to equal length by cycling (sampling is uniform-
    with-replacement already, so a cycled duplicate only nudges per-index
    weights within a shard during the short precrop warm-up).
    """
    import numpy as np

    n_data = mesh.shape[DATA_AXIS]
    local = (bank_n // n_data)
    pool = np.asarray(pool)
    segments = []
    for d in range(n_data):
        seg = pool[(pool >= d * local) & (pool < (d + 1) * local)] - d * local
        if seg.size == 0:
            # a shard with no precrop rays (image rows split across shards)
            # falls back to its whole slice rather than sampling nothing
            seg = np.arange(local, dtype=pool.dtype)
        segments.append(seg)
    cap = max(s.size for s in segments)
    padded = np.concatenate([np.resize(s, cap) for s in segments])
    return jax.device_put(padded, data_sharding(mesh))


def shard_bank(bank_rays, bank_rgbs, mesh):
    """Place the ray bank sharded over the data axis (each chip holds
    1/n of the rays — memory scaling the reference's full-bank-per-GPU
    precompute lacks, blender.py:105-108). Truncates to a divisible
    size, and says so: any dropped tail is announced on stdout and as a
    ``bank_shard`` telemetry row (the "no silent caps" rule)."""
    n_data = int(mesh.shape[DATA_AXIS])
    total = int(bank_rays.shape[0])
    n = (total // n_data) * n_data
    dropped = total - n
    if dropped:
        print(
            f"[shard_bank] bank of {total} rays truncated to {n} "
            f"({dropped} dropped) to divide over {n_data} data shards"
        )
    from ..obs import get_emitter

    get_emitter().emit(
        "bank_shard",
        n_rays=total,
        n_kept=n,
        n_dropped=dropped,
        n_shards=n_data,
    )
    sh = data_sharding(mesh)
    return (
        jax.device_put(bank_rays[:n], sh),
        jax.device_put(bank_rgbs[:n], sh),
    )
