"""Named-axis collective wrappers + host-level synchronization.

The reference's collective layer is NCCL behind DDP plus an explicit barrier
helper (reference train.py:100-112); JAX has no user-visible backend object,
but the framework still exposes the capability surface here (SURVEY.md §2.3):
in-graph collectives over the mesh axes for code running under
``shard_map``, and host-level barrier/broadcast for the processes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .mesh import DATA_AXIS


def psum(x, axis: str = DATA_AXIS):
    """All-reduce sum over a mesh axis (≙ NCCL all_reduce inside DDP
    backward, reference trainer.py:59-62)."""
    return jax.lax.psum(x, axis)


def pmean(x, axis: str = DATA_AXIS):
    """All-reduce mean — the gradient reduction DDP performs implicitly."""
    return jax.lax.pmean(x, axis)


def all_gather(x, axis: str = DATA_AXIS, tiled: bool = False):
    return jax.lax.all_gather(x, axis, tiled=tiled)


def ppermute(x, perm, axis: str = DATA_AXIS):
    """Ring shift — the building block for ring-style sequence parallelism."""
    return jax.lax.ppermute(x, axis, perm)


def axis_index(axis: str = DATA_AXIS):
    return jax.lax.axis_index(axis)


def barrier(name: str = "barrier") -> None:
    """Host-level barrier across processes (parity: `synchronize()`,
    reference train.py:100-112). No-op single-process."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def broadcast_from_chief(x):
    """Broadcast host data from process 0 to all processes."""
    if jax.process_count() == 1:
        return x
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(x)


def device_count() -> int:
    return jax.device_count()


def process_count() -> int:
    return jax.process_count()


def tree_pmean(tree, axis: str = DATA_AXIS):
    """pmean over every leaf of a pytree (gradients, metrics)."""
    return jax.tree_util.tree_map(lambda x: jax.lax.pmean(x, axis), tree)
