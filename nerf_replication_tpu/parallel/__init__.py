"""Parallelism & distributed communication (SURVEY.md §2.3).

The explicit architectural seat of the capabilities the reference gets from
`torch.distributed` + NCCL + DDP (reference train.py:116-120,
trainer.py:17-22): mesh construction over ICI/DCN, named-axis collectives,
sharding rules, and sharded train steps (shard_map DP, GSPMD dp×tp).
"""

from .collectives import (  # noqa: F401
    all_gather,
    axis_index,
    barrier,
    broadcast_from_chief,
    device_count,
    pmean,
    ppermute,
    process_count,
    psum,
    tree_pmean,
)
from .mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    is_chief,
    make_mesh,
    make_mesh_from_cfg,
    multihost_init,
)
from .sharding import (  # noqa: F401
    chunk_sharding,
    data_sharding,
    shard_bank,
    tree_shardings,
    tree_specs,
)
from .step import (  # noqa: F401
    build_dp_step,
    build_gspmd_step,
    shard_train_state,
)
