"""Device-mesh construction and multi-host process topology.

This module is the explicit architectural seat of the reference's distributed
backend (SURVEY.md §2.3): where the reference calls
`torch.distributed.init_process_group("nccl", env://)` (reference
train.py:116-120) and shards work by `RANK`, the TPU-native design builds one
`jax.sharding.Mesh` over the chips and lets XLA place the collectives on
ICI/DCN. Axis names:

* ``"data"`` — data parallelism over the ray batch (the reference's only
  parallelism: DDP gradient all-reduce ≙ `psum` over this axis).
* ``"model"`` — tensor parallelism over MLP hidden width (no referent in the
  reference; a TPU-native capability extension used when ``model_axis > 1``).

Mesh axes map to the physical topology by `mesh_utils.create_device_mesh`,
which orders axes so the innermost ("model", most communication-hungry) rides
ICI neighbours first — the scaling-book recipe.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


_multihost_initialized = False


def multihost_init(cfg=None) -> None:
    """Initialize the multi-host JAX runtime (parity: the NCCL process-group
    init, reference train.py:116-120).

    Must be called before any other JAX API touches the backend (the same
    contract as `jax.distributed.initialize` itself). Runs when the config
    opts in (``parallel.multihost: true`` — the analogue of the reference's
    ``args.launcher == "pytorch"`` gate, train.py:116) or when a coordinator
    address is present in the environment; `initialize()` itself auto-detects
    the coordinator from TPU pod metadata. Real initialization failures
    propagate rather than being swallowed, so a multi-host job can never
    silently degrade into N disconnected single-host runs.
    """
    global _multihost_initialized
    import os

    if _multihost_initialized:
        return
    want = bool(cfg is not None and cfg.get("parallel", {}).get("multihost", False))
    want = want or bool(
        os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("COORDINATOR_ADDRESS")
    )
    if want:
        try:
            jax.distributed.initialize()
        except RuntimeError as e:
            if "already" not in str(e).lower():
                raise
    _multihost_initialized = True


def is_chief() -> bool:
    """Rank-0 guard (parity: `local_rank == 0` checks, reference
    trainer.py:64-65, recorder.py:51)."""
    return jax.process_index() == 0


def make_mesh(
    data_axis: int = -1,
    model_axis: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Build a ``(data, model)`` mesh.

    ``data_axis == -1`` means "all remaining devices" (the common case:
    pure DP over every chip). ``model_axis`` > 1 carves tensor-parallel
    groups out of the device set first.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if model_axis < 1 or n % model_axis != 0:
        raise ValueError(
            f"model_axis={model_axis} does not divide device count {n}"
        )
    if data_axis != -1 and data_axis < 1:
        raise ValueError(f"data_axis must be -1 or >= 1, got {data_axis}")
    data = n // model_axis if data_axis == -1 else data_axis
    if data * model_axis != n:
        # allow a sub-mesh (fewer devices than available)
        devices = devices[: data * model_axis]
        if len(devices) != data * model_axis:
            raise ValueError(
                f"mesh {data}x{model_axis} needs {data * model_axis} devices, "
                f"have {n}"
            )
    try:
        dev_array = mesh_utils.create_device_mesh(
            (data, model_axis), devices=devices
        )
    except (ValueError, AssertionError):
        # non-toroidal device sets (CPU emulation, sub-meshes): plain reshape
        dev_array = np.asarray(devices).reshape(data, model_axis)
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))


def make_mesh_from_cfg(cfg) -> Mesh:
    par = cfg.get("parallel", None)
    if par is None:
        return make_mesh()
    return make_mesh(
        data_axis=int(par.get("data_axis", -1)),
        model_axis=int(par.get("model_axis", 1)),
    )
