"""Sequence-axis (ray-axis) parallelism: multi-chip single-image rendering.

The reference has no sequence axis to parallelize — its long axis is the
ray/sample axis, which it scales by a serial chunking loop
(volume_renderer.py:160; SURVEY.md §5 "Long-context"). The TPU-native
first-class treatment: shard the ray axis of ONE image across the mesh's
``data`` axis with `shard_map` — each chip renders its ray slice through the
full coarse+fine pipeline, and the per-chip results concatenate back on the
host. This is the long-sequence scaling story of this framework (a 640k-ray
image is a 640k-token sequence): compute scales linearly over ICI with no
cross-chip traffic during the march, because volume rendering is
embarrassingly parallel over rays — the all-gather happens once at the end.

Both builders here (the vanilla coarse+fine renderer and the
occupancy-accelerated ESS+ERT march) share one chunk/pad skeleton:
``_chunked_over_rays`` bounds per-device memory inside the shard, and
``_pad_shard_call`` pads the global ray axis to the shard count and slices
the results back.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..renderer.volume import render_rays
from .compat import shard_map
from .mesh import DATA_AXIS


def _chunked_over_rays(render_chunk, rays, chunk_size: int | None):
    """Apply ``render_chunk([chunk, C]) -> dict`` over a ray slice in
    fixed-size ``lax.map`` chunks (zero-padded; per-ray outputs are unpadded
    back to the slice length; C = 6, or 7 with the time column).
    ``chunk_size >= n`` short-circuits to one direct call."""
    n = rays.shape[0]  # static: per-shard slice length
    if chunk_size is None or chunk_size >= n:
        return render_chunk(rays)
    n_chunks = -(-n // chunk_size)
    pad = n_chunks * chunk_size - n
    rays_c = jnp.pad(rays, ((0, pad), (0, 0))).reshape(
        n_chunks, chunk_size, rays.shape[-1]
    )
    out = jax.lax.map(render_chunk, rays_c)
    return {k: v.reshape((-1,) + v.shape[2:])[:n] for k, v in out.items()}


def _pad_shard_call(smap_fn, n_shards: int, rays, *extra):
    """Pad the global ray axis to a multiple of ``n_shards``, run the
    shard-mapped function, slice every output back to the true length."""
    n = rays.shape[0]
    pad = (-n) % n_shards
    rays_p = jnp.pad(rays, ((0, pad), (0, 0)))
    return {k: v[:n] for k, v in smap_fn(rays_p, *extra).items()}


def build_sequence_parallel_renderer(
    mesh, network, options, near, far, chunk_size: int | None = None
):
    """Returns ``render(params, rays [N, 6]) -> dict`` with the ray axis
    sharded over ``mesh``'s data axis. N is padded to the shard count.

    ``chunk_size`` bounds per-device memory the way ``render_chunked`` does
    on one chip: each shard marches its ray slice in fixed-size ``lax.map``
    chunks, so a full 640k-ray eval image fits HBM at any device count
    (each device holds chunk_size × 256-sample activations, not N/shards)."""
    n_shards = mesh.shape[DATA_AXIS]

    def shard_body(params, rays):
        apply_fn = lambda pts, vd, model: network.apply(  # noqa: E731
            params, pts, vd, model=model
        )
        return _chunked_over_rays(
            lambda rc: render_rays(apply_fn, rc, near, far, None, options),
            rays,
            chunk_size,
        )

    smap = jax.jit(
        shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(P(), P(DATA_AXIS)),
            out_specs=P(DATA_AXIS),
            check_vma=False,
        )
    )

    def render(params, rays):
        return _pad_shard_call(
            lambda rays_p: smap(params, rays_p), n_shards, rays
        )

    # the sharded executable itself, exposed for AOT registration
    # (aot_register_sequence_renderer) — the wrapper above only pads/slices
    render.jitted = smap
    return render


def build_sequence_parallel_march(
    mesh, network, march_options, near, far, chunk_size: int | None = None
):
    """Sequence-parallel ESS+ERT march: the occupancy-accelerated renderer
    (renderer/accelerated.py) with the ray axis sharded over ``mesh``'s data
    axis. The baked grid + bbox are replicated (a 128³ bool grid is 2 MB —
    broadcast once, gathered locally on every chip); rays shard like the
    vanilla sequence renderer, with the same in-shard chunk bound.

    Returns ``march(params, rays [N,6], grid, bbox) -> dict`` (the
    ``n_truncated`` diagnostic sums per-ray flags after pad rows are
    sliced off)."""
    from ..renderer.accelerated import march_rays_accelerated

    n_shards = mesh.shape[DATA_AXIS]

    def shard_body(params, rays, grid, bbox):
        apply_fn = lambda pts, vd, model: network.apply(  # noqa: E731
            params, pts, vd, model=model
        )
        return _chunked_over_rays(
            lambda rc: march_rays_accelerated(
                apply_fn, rc, near, far, grid, bbox, march_options
            ),
            rays,
            chunk_size,
        )

    smap = jax.jit(
        shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(P(), P(DATA_AXIS), P(), P()),
            out_specs=P(DATA_AXIS),
            check_vma=False,
        )
    )

    def march(params, rays, grid, bbox):
        out = _pad_shard_call(
            lambda rays_p, g, b: smap(params, rays_p, g, b),
            n_shards, rays, grid, bbox,
        )
        out["n_truncated"] = jnp.sum(out.pop("truncated"))
        return out

    march.jitted = smap
    return march


# -- AOT registration --------------------------------------------------------
def _padded_rays(n_rays: int, mesh) -> int:
    n_shards = mesh.shape[DATA_AXIS]
    return n_rays + (-n_rays) % n_shards


def aot_register_sequence_renderer(
    registry, params, n_rays: int, mesh, network, options, near, far,
    chunk_size: int | None = None, serialize: bool = False,
) -> str:
    """Register the sequence-parallel renderer's sharded executable with a
    compile/AOTRegistry: the build happens during warm-up instead of on
    the first eval image. ``registry.take(name)`` yields the precompiled
    smap — callers wrap it with the same pad/slice the builder applies."""
    from ..compile.registry import abstract_like

    n_pad = _padded_rays(n_rays, mesh)
    name = f"seqpar_render_{n_pad}"
    registry.register(
        name,
        build_sequence_parallel_renderer(
            mesh, network, options, near, far, chunk_size
        ).jitted,
        (abstract_like(params),
         jax.ShapeDtypeStruct((n_pad, 6), jnp.float32)),
        serialize=serialize,
    )
    return name


def aot_register_sequence_march(
    registry, params, n_rays: int, grid, bbox, mesh, network, march_options,
    near, far, chunk_size: int | None = None, serialize: bool = False,
) -> str:
    """Register the sequence-parallel ESS+ERT march's sharded executable
    (grid + bbox replicated) with a compile/AOTRegistry."""
    from ..compile.registry import abstract_like

    n_pad = _padded_rays(n_rays, mesh)
    name = f"seqpar_march_{n_pad}"
    registry.register(
        name,
        build_sequence_parallel_march(
            mesh, network, march_options, near, far, chunk_size
        ).jitted,
        (abstract_like(params),
         jax.ShapeDtypeStruct((n_pad, 6), jnp.float32),
         abstract_like(grid), abstract_like(bbox)),
        serialize=serialize,
    )
    return name
