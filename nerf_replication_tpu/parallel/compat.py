"""JAX version compatibility for the sharding surface.

The step/render builders target the modern spelling (``jax.shard_map``
with ``check_vma=``, jax >= 0.6); this environment's jax 0.4.x only has
``jax.experimental.shard_map.shard_map`` with the older ``check_rep=``
knob. One shim resolves the import and translates the kwarg so every
builder (parallel/step.py, parallel/sequence.py, train/ngp.py) and test
imports ``shard_map`` from here instead of guessing the jax layout.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.6: top-level export
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4.x/0.5.x: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

# the replication-check kwarg was renamed check_rep -> check_vma; key off
# the actual signature, not the import location
_CHECK_KW = (
    "check_vma"
    if "check_vma" in inspect.signature(_shard_map).parameters
    else "check_rep"
)


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``shard_map`` with the modern signature on either jax line."""
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )
