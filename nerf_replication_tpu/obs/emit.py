"""Schema-versioned JSONL emitter: one ``telemetry.jsonl`` per run dir.

Chief-guarded like ``Recorder`` (non-chief processes construct a no-op
emitter, so call sites never branch on rank) and flushed crash-safely:
every row is one ``write`` of a full line on a line-buffered handle,
fsync'd periodically and at close, so a SIGKILL mid-run loses at most the
rows since the last sync and can never tear a line in half.

The module keeps one active emitter per process (``init_run`` /
``get_emitter``) so deep call sites — the trainer's epoch loop, the
recorder's val records, the render gate — reach the run's stream without
threading it through every signature.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time

from .schema import SCHEMA_VERSION

# -- row taps ----------------------------------------------------------------
# In-process consumers of the telemetry stream (the alert engine, the
# incident correlator, the capacity ledger — obs/alerts.py etc.) subscribe
# here and see every emitted row as a dict, the same pattern as
# ``Tracer.add_sink``. Taps fire on BOTH emitters — a non-chief process
# (NullEmitter) still feeds its local engines even though nothing reaches
# disk — and a raising tap is dropped from the fan-out, never allowed to
# break emission.

_row_taps: list = []


def add_row_tap(fn) -> None:
    """Subscribe ``fn(row_dict)`` to every emitted telemetry row."""
    if fn not in _row_taps:
        _row_taps.append(fn)


def remove_row_tap(fn) -> None:
    try:
        _row_taps.remove(fn)
    except ValueError:
        pass


def _fire_row_taps(row: dict) -> None:
    for fn in list(_row_taps):
        try:
            fn(row)
        # graftlint: ok(swallow: a broken tap must not break telemetry emission; it is dropped from the fan-out)
        except Exception:
            remove_row_tap(fn)


class NullEmitter:
    """No-op emitter: what non-chief processes (and uninitialized call
    sites) write through, so emission is unconditional at call sites.
    Row taps still fire — in-process consumers see the stream even when
    nothing reaches disk."""

    chief = False
    path = None
    run_id = ""

    def emit(self, kind: str, **fields) -> None:
        if _row_taps:
            _fire_row_taps(
                {"v": SCHEMA_VERSION, "kind": kind, "t": time.time(),
                 **fields})

    def close(self) -> None:
        pass


class Emitter:
    """Append typed rows to a JSONL file; rows stamped {v, kind, t}."""

    FSYNC_EVERY = 50  # rows between fsyncs (every row is still flushed)

    def __init__(self, path: str, chief: bool = True, run_id: str | None = None):
        self.chief = chief
        self.path = path
        self.run_id = run_id or f"{int(time.time())}-{os.getpid()}"
        self._fh = None
        self._rows_since_sync = 0
        if not chief:
            return
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # append: a resumed run adds a new run_meta row to the same file
        # rather than destroying the previous run's telemetry
        self._fh = open(path, "a", buffering=1)

    def emit(self, kind: str, **fields) -> None:
        if self._fh is None:
            return
        row = {"v": SCHEMA_VERSION, "kind": kind, "t": time.time(), **fields}
        self._fh.write(json.dumps(row, default=_jsonable) + "\n")
        self._rows_since_sync += 1
        if self._rows_since_sync >= self.FSYNC_EVERY:
            self._sync()
        if _row_taps:
            _fire_row_taps(row)

    def _sync(self) -> None:
        try:
            os.fsync(self._fh.fileno())
        except OSError:
            pass
        self._rows_since_sync = 0

    def close(self) -> None:
        if self._fh is not None:
            self._sync()
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _jsonable(value):
    """Last-resort coercion for device scalars/arrays reaching emit()."""
    try:
        import numpy as np

        if isinstance(value, np.generic):
            return value.item()
        if isinstance(value, np.ndarray):
            return value.tolist()
    # graftlint: ok(swallow: telemetry layer itself; str() fallback below is the record)
    except Exception:
        pass
    return str(value)


_active: Emitter | NullEmitter = NullEmitter()


def get_emitter() -> Emitter | NullEmitter:
    """The process's active emitter (NullEmitter before init_run)."""
    return _active


def config_hash(cfg) -> str:
    """Stable short hash of the merged config (run identity for diffs)."""
    try:
        dump = cfg.dump()
    # graftlint: ok(swallow: repr fallback is still hashed into the run identity)
    except Exception:
        dump = repr(cfg)
    return hashlib.sha256(dump.encode()).hexdigest()[:12]


def init_run(cfg, component: str = "train", path: str | None = None):
    """Open the run's telemetry stream and emit its ``run_meta`` row.

    ``path`` defaults to ``<cfg.record_dir>/telemetry.jsonl`` — run-scoped
    the same way the TensorBoard events are. Only the chief process writes
    (every process still gets a valid no-op emitter back). Re-initializing
    (a second fit() in-process, tests) closes the previous stream.
    """
    global _active
    import jax

    from ..parallel.mesh import is_chief

    _active.close()
    if path is None:
        telem_dir = str(cfg.get("record_dir", "."))
        path = os.path.join(telem_dir, "telemetry.jsonl")
    emitter = Emitter(path, chief=is_chief())
    devices = jax.devices()
    emitter.emit(
        "run_meta",
        run_id=emitter.run_id,
        component=component,
        config_hash=config_hash(cfg),
        task=str(cfg.get("task", "")),
        scene=str(cfg.get("scene", "")),
        exp_name=str(cfg.get("exp_name", "")),
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        device_count=len(devices),
        local_device_count=jax.local_device_count(),
        platform=devices[0].platform if devices else "unknown",
        device_kind=getattr(devices[0], "device_kind", "") if devices else "",
        argv=list(sys.argv),
        jax_version=jax.__version__,
    )
    _active = emitter
    return emitter


def append_jsonl(path: str, row: dict) -> None:
    """One-shot append of a bench-style row (crash-safe single write).

    The bench scripts' shared write path: one JSON line per call, parent
    dir created, file flushed before return — so a killed sweep keeps
    every completed point.
    """
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a", buffering=1) as fh:
        fh.write(json.dumps(row, default=_jsonable) + "\n")
        fh.flush()
        try:
            os.fsync(fh.fileno())
        except OSError:
            pass
