"""Lock-cheap live aggregation: counters, gauges, fixed-bucket histograms.

``telemetry.jsonl`` answers questions after a run; an operator curl-ing a
serving replica needs answers *now*. This registry is the live side:
every serve-path event also bumps an in-memory aggregate — O(1) dict
updates under one short-held lock, no allocation proportional to traffic
— and two read surfaces render it on demand:

* :meth:`MetricsRegistry.render_prometheus` — Prometheus text exposition
  (``GET /metrics`` on serve.py) with counters, gauges, and cumulative
  ``_bucket``/``_sum``/``_count`` histogram series.
* :meth:`MetricsRegistry.slo_view` — the operator's one-glance health
  verdict folded into ``/healthz``: latency attainment against the
  configured target plus shed/timeout/error/breaker rates.

Histogram buckets are FIXED at registration (the classic Prometheus
latency ladder) rather than adaptive: fixed buckets make the hot-path
update a bisect + increment, and make attainment a cumulative-count read
with no quantile estimation. Snapshots serialize as the
``metrics_snapshot`` row kind for offline diffing.

Host-side only — no jax import, nothing here runs under trace.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque

# Prometheus' classic latency ladder, in seconds. serve targets sit
# around 50-250 ms, so the ladder brackets the SLO from both sides.
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _label_str(key: tuple) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"


class WindowRing:
    """Time-bucketed accumulator: ``add`` now, ``total`` over any window.

    The primitive behind windowed rates (and the burn-rate alert engine,
    obs/alerts.py): values land in coarse time slots (``slot_s``), slots
    older than ``horizon_s`` are pruned on write, and a window query sums
    the slots it covers. Memory is bounded by horizon/slot regardless of
    traffic; an idle series holds nothing. Whole-lifetime counters dilute
    a fresh regression under hours of healthy history — a windowed read
    cannot (the PR-16 ``slo_view`` fix).
    """

    __slots__ = ("slot_s", "horizon_s", "_slots")

    def __init__(self, slot_s: float = 5.0, horizon_s: float = 6 * 3600.0):
        self.slot_s = float(slot_s)
        self.horizon_s = float(horizon_s)
        self._slots: deque = deque()  # (slot_index, accumulated value)

    def add(self, value: float, now: float) -> None:
        idx = int(now // self.slot_s)
        if self._slots and self._slots[-1][0] == idx:
            self._slots[-1][1] += value
        else:
            self._slots.append([idx, float(value)])
            floor = idx - int(self.horizon_s / self.slot_s) - 1
            while self._slots and self._slots[0][0] < floor:
                self._slots.popleft()

    def total(self, window_s: float, now: float) -> float:
        """Sum over slots that overlap [now - window_s, now]."""
        cutoff = int((now - float(window_s)) // self.slot_s)
        return sum(v for i, v in self._slots if i >= cutoff)

    def __len__(self) -> int:
        return len(self._slots)


class _Histogram:
    __slots__ = ("buckets", "counts", "total", "count", "exemplars")

    def __init__(self, buckets: tuple[float, ...]):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # +1: the +Inf bucket
        self.total = 0.0
        self.count = 0
        # bucket index -> (trace_id, value): last-seen sampling keeps the
        # exemplar fresh at O(1) with no reservoir bookkeeping
        self.exemplars: dict[int, tuple[str, float]] = {}

    def observe(self, value: float, trace_id: str | None = None) -> None:
        i = bisect.bisect_left(self.buckets, value)
        self.counts[i] += 1
        self.total += value
        self.count += 1
        if trace_id is not None:
            self.exemplars[i] = (str(trace_id), float(value))

    def cumulative(self) -> list[int]:
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out


class MetricsRegistry:
    """Names → labeled series. One lock; every mutation is a dict update."""

    def __init__(self, buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                 clock=time.monotonic):
        self.buckets = buckets
        self.clock = clock
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, tuple], float] = {}
        self._gauges: dict[tuple[str, tuple], float] = {}
        self._hists: dict[tuple[str, tuple], _Histogram] = {}
        # windowed shadows: every counter bump and histogram observation
        # also lands in a WindowRing, so rate reads (slo_view, the alert
        # engine) can scope to a recent window instead of process lifetime
        self._cwin: dict[tuple[str, tuple], WindowRing] = {}
        self._hwin: dict[tuple[str, tuple, int], WindowRing] = {}

    # -- writes (hot path) ---------------------------------------------------

    def counter(self, name: str, value: float = 1.0, **labels) -> None:
        k = (name, _label_key(labels))
        now = self.clock()
        with self._lock:
            self._counters[k] = self._counters.get(k, 0.0) + value
            ring = self._cwin.get(k)
            if ring is None:
                ring = self._cwin[k] = WindowRing()
            ring.add(value, now)

    def gauge(self, name: str, value: float, **labels) -> None:
        k = (name, _label_key(labels))
        with self._lock:
            self._gauges[k] = float(value)

    def observe(self, name: str, value: float, trace_id: str | None = None,
                **labels) -> None:
        """Histogram update; ``trace_id`` (when the caller is inside a
        traced request) is kept as the bucket's exemplar — the join key
        from an aggregate back to one concrete trace."""
        k = (name, _label_key(labels))
        now = self.clock()
        with self._lock:
            h = self._hists.get(k)
            if h is None:
                h = self._hists[k] = _Histogram(self.buckets)
            h.observe(float(value), trace_id=trace_id)
            i = bisect.bisect_left(self.buckets, float(value))
            wk = (name, k[1], i)
            ring = self._hwin.get(wk)
            if ring is None:
                ring = self._hwin[wk] = WindowRing()
            ring.add(1.0, now)

    # -- reads ---------------------------------------------------------------

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: (h.buckets, h.cumulative(), h.total, h.count,
                         dict(h.exemplars))
                     for k, h in self._hists.items()}
        lines: list[str] = []
        seen: set[str] = set()
        for (name, key), val in sorted(counters.items()):
            if name not in seen:
                lines.append(f"# TYPE {name} counter")
                seen.add(name)
            lines.append(f"{name}{_label_str(key)} {_fmt(val)}")
        for (name, key), val in sorted(gauges.items()):
            if name not in seen:
                lines.append(f"# TYPE {name} gauge")
                seen.add(name)
            lines.append(f"{name}{_label_str(key)} {_fmt(val)}")
        for (name, key), (buckets, cum, total, count, ex) in sorted(
                hists.items()):
            if name not in seen:
                lines.append(f"# TYPE {name} histogram")
                seen.add(name)
            for i, (edge, c) in enumerate(zip(buckets, cum)):
                le = dict(key)
                le["le"] = _fmt(edge)
                line = f"{name}_bucket{_label_str(_label_key(le))} {c}"
                if i in ex:  # OpenMetrics exemplar suffix
                    tid, val = ex[i]
                    line += f' # {{trace_id="{tid}"}} {_fmt(val)}'
                lines.append(line)
            inf = dict(key)
            inf["le"] = "+Inf"
            line = f"{name}_bucket{_label_str(_label_key(inf))} {cum[-1]}"
            if len(buckets) in ex:
                tid, val = ex[len(buckets)]
                line += f' # {{trace_id="{tid}"}} {_fmt(val)}'
            lines.append(line)
            lines.append(f"{name}_sum{_label_str(key)} {_fmt(total)}")
            lines.append(f"{name}_count{_label_str(key)} {count}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-able aggregate state — the ``metrics_snapshot`` row body."""
        with self._lock:
            return {
                "counters": {f"{n}{_label_str(k)}": v
                             for (n, k), v in self._counters.items()},
                "gauges": {f"{n}{_label_str(k)}": v
                           for (n, k), v in self._gauges.items()},
                "histograms": {
                    f"{n}{_label_str(k)}": {
                        "buckets": list(h.buckets),
                        "counts": list(h.counts),
                        "sum": h.total,
                        "count": h.count,
                        "exemplars": {
                            str(i): {"trace_id": t, "value": v}
                            for i, (t, v) in sorted(h.exemplars.items())
                        },
                    }
                    for (n, k), h in self._hists.items()
                },
            }

    def slo_miss_exemplars(self, target_s: float, limit: int = 8,
                           name: str = "serve_request_latency_seconds",
                           ) -> list[str]:
        """Exemplar trace ids of requests that missed the latency target:
        the evidence a ``scale_decision`` row links to. Reads exemplars
        from every bucket whose edge is >= ``target_s``; when no miss has
        an exemplar (yet), falls back to the slowest exemplars seen so an
        observed fleet always yields at least one join key."""
        miss: list[tuple[float, str]] = []
        seen_any: list[tuple[float, str]] = []
        with self._lock:
            for (n, _key), h in self._hists.items():
                if n != name:
                    continue
                lo = bisect.bisect_left(h.buckets, target_s)
                for i, (tid, val) in h.exemplars.items():
                    seen_any.append((val, tid))
                    if i >= lo:
                        miss.append((val, tid))
        pool = miss if miss else seen_any
        out: list[str] = []
        for _val, tid in sorted(pool, reverse=True):
            if tid not in out:
                out.append(tid)
            if len(out) >= limit:
                break
        return out

    def window_counter(self, name: str, window_s: float,
                       now: float | None = None, **label_filter) -> float:
        """Sum of a counter over the trailing window, across every label
        set matching ``label_filter`` (empty filter = all label sets)."""
        now = self.clock() if now is None else now
        want = set(label_filter.items())
        with self._lock:
            return sum(
                ring.total(window_s, now)
                for (n, key), ring in self._cwin.items()
                if n == name and want.issubset(dict(key).items())
            )

    def window_hist(self, name: str, target_s: float, window_s: float,
                    now: float | None = None) -> tuple[float, float]:
        """(attained, total) observation counts over the trailing window
        for histogram ``name``, attained = value <= the smallest bucket
        edge >= target (the same fixed-bucket rule as the lifetime read).
        The windowed primitive the burn-rate engine divides."""
        now = self.clock() if now is None else now
        ti = bisect.bisect_left(self.buckets, float(target_s))
        attained = total = 0.0
        with self._lock:
            for (n, _key, i), ring in self._hwin.items():
                if n != name:
                    continue
                c = ring.total(window_s, now)
                total += c
                if i <= ti:
                    attained += c
        return attained, total

    def slo_view(self, target_s: float, window_s: float | None = None) -> dict:
        """Attainment vs. the latency target + failure rates, aggregated
        across labels. Attainment is read at the smallest histogram edge
        >= target (fixed buckets: no interpolation, no estimator).

        ``window_s`` scopes every rate to the trailing window (the
        PR-16 fix: lifetime rates let hours of healthy history dilute a
        fresh regression); None keeps the whole-lifetime read for
        back-compat and offline snapshot diffing."""
        if window_s is not None:
            return self._slo_view_windowed(target_s, float(window_s))
        with self._lock:
            lat_count = 0
            lat_attained = 0
            for (name, _key), h in self._hists.items():
                if name != "serve_request_latency_seconds":
                    continue
                cum = h.cumulative()
                i = bisect.bisect_left(h.buckets, target_s)
                edge_hits = cum[min(i, len(cum) - 1)] if i < len(h.buckets) \
                    else cum[-1]
                lat_attained += edge_hits
                lat_count += h.count
            totals: dict[str, float] = {}
            by_status: dict[str, float] = {}
            breaker_opens = 0.0
            for (name, key), v in self._counters.items():
                totals[name] = totals.get(name, 0.0) + v
                if name == "serve_requests_total":
                    status = dict(key).get("status", "")
                    by_status[status] = by_status.get(status, 0.0) + v
                elif (name == "serve_breaker_transitions_total"
                        and dict(key).get("state") == "open"):
                    breaker_opens += v
        requests = totals.get("serve_requests_total", 0.0)

        def rate(n: float) -> float:
            return round(n / requests, 4) if requests else 0.0

        return {
            "target_ms": round(target_s * 1e3, 3),
            "requests": int(requests),
            "attainment": round(lat_attained / lat_count, 4)
            if lat_count else None,
            "shed_rate": rate(totals.get("serve_sheds_total", 0.0)),
            "timeout_rate": rate(by_status.get("timeout", 0.0)),
            "error_rate": rate(sum(v for s, v in by_status.items()
                                   if s.startswith("error"))),
            "breaker_opens": int(breaker_opens),
        }

    def _slo_view_windowed(self, target_s: float, window_s: float) -> dict:
        now = self.clock()
        attained, lat_count = self.window_hist(
            "serve_request_latency_seconds", target_s, window_s, now=now)
        with self._lock:
            totals: dict[str, float] = {}
            by_status: dict[str, float] = {}
            breaker_opens = 0.0
            for (name, key), ring in self._cwin.items():
                v = ring.total(window_s, now)
                if not v:
                    continue
                totals[name] = totals.get(name, 0.0) + v
                if name == "serve_requests_total":
                    status = dict(key).get("status", "")
                    by_status[status] = by_status.get(status, 0.0) + v
                elif (name == "serve_breaker_transitions_total"
                        and dict(key).get("state") == "open"):
                    breaker_opens += v
        requests = totals.get("serve_requests_total", 0.0)

        def rate(n: float) -> float:
            return round(n / requests, 4) if requests else 0.0

        return {
            "target_ms": round(target_s * 1e3, 3),
            "window_s": round(window_s, 3),
            "requests": int(requests),
            "attainment": round(attained / lat_count, 4)
            if lat_count else None,
            "shed_rate": rate(totals.get("serve_sheds_total", 0.0)),
            "timeout_rate": rate(by_status.get("timeout", 0.0)),
            "error_rate": rate(sum(v for s, v in by_status.items()
                                   if s.startswith("error"))),
            "breaker_opens": int(breaker_opens),
        }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._cwin.clear()
            self._hwin.clear()


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


_registry = MetricsRegistry()


def get_metrics() -> MetricsRegistry:
    """The process's live registry (always on; writing is cheap enough
    that there is no disable switch — tracing has one, metrics don't)."""
    return _registry


def reset_metrics() -> None:
    """Test isolation: wipe the process registry between cases."""
    _registry.reset()
