"""Request-scoped span tracing across the serve pipeline's thread seams.

A request entering ``serve.py`` crosses four asynchronous boundaries —
the HTTP handler thread, the micro-batcher worker, the fleet prefetch
threads, and the engine's async dispatch — and since PR 1 every one of
them has emitted *flat* rows that cannot be joined back into "where did
this request's 240 ms go?". This module adds the join key: every unit of
work runs under a :class:`Span` carrying a ``(trace_id, span_id)``
context, propagated within a thread by a ``contextvars.ContextVar`` and
across threads by explicitly capturing :func:`current_ctx` into whatever
object crosses the seam (a ``_Pending`` queue entry, a prefetch closure).

Finished spans become schema-versioned ``span`` rows in the run's
``telemetry.jsonl`` (see ``obs/schema.py``) and fan out to registered
sinks — the resil flight recorder rings them, ``serve_bench`` aggregates
them — while stage-tagged spans also feed the live metrics histograms
(``obs/metrics.py``). ``scripts/trace_view.py`` exports any span source
to Chrome-trace JSON for chrome://tracing / Perfetto.

Everything here is host-side Python: no jax import, no work inside a
jitted body, and a disabled tracer costs one attribute load plus a null
context manager per call site, preserving the zero-steady-state-recompile
invariant (asserted with tracing ON in tests/test_serve.py).

Span identities come from a process-local counter, not ``uuid4`` — runs
are deterministic under a seeded test and ids stay 8 hex chars. Clocks
are injectable (tests pass a fake; production uses ``perf_counter``).
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time
from contextlib import contextmanager

from .emit import get_emitter

# sentinel: "inherit the calling thread's current span as parent"
_INHERIT = object()

# the HTTP header that carries a span context across a process boundary
# (W3C-traceparent-shaped: one value, ids joined by a dash)
TRACE_HEADER = "Traceparent"

_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "obs_trace_current", default=None
)


class SpanContext:
    """The portable half of a span: what crosses a thread seam — or, via
    :meth:`to_header` / :meth:`from_header`, a process boundary."""

    __slots__ = ("trace_id", "span_id", "remote")

    def __init__(self, trace_id: str, span_id: str, remote: bool = False):
        self.trace_id = trace_id
        self.span_id = span_id
        # True when this ctx was restored from a header: children record
        # ``remote_parent`` so fleet merges can tell propagated parents
        # from locally-missing ones
        self.remote = bool(remote)

    def to_header(self) -> str:
        """``trace_id-span_id`` — ids are alphanumeric by construction
        (hex counters, sanitized prefixes), so the dash is unambiguous."""
        return f"{self.trace_id}-{self.span_id}"

    @classmethod
    def from_header(cls, value: str | None) -> "SpanContext | None":
        """Parse a :data:`TRACE_HEADER` value; None on anything
        malformed (propagation must never fail a request)."""
        if not value or not isinstance(value, str):
            return None
        trace_id, sep, span_id = value.strip().rpartition("-")
        if not sep or not trace_id or not span_id:
            return None
        if not (trace_id.isalnum() and span_id.isalnum()):
            return None
        return cls(trace_id, span_id, remote=True)

    def __repr__(self) -> str:  # debugging aid only
        flag = "!remote" if self.remote else ""
        return f"SpanContext({self.trace_id}/{self.span_id}{flag})"


def trace_headers(ctx: "SpanContext | None" = None) -> dict[str, str]:
    """Headers to stamp on an outbound fleet HTTP call: the given ctx
    (or the calling thread's current one) as :data:`TRACE_HEADER`, or
    ``{}`` when there is nothing to propagate."""
    if ctx is None:
        ctx = current_ctx()
    if ctx is None:
        return {}
    return {TRACE_HEADER: ctx.to_header()}


class Span:
    """One timed unit of work. Created by :meth:`Tracer.span`; finished
    rows carry name/start/dur plus whatever attributes the body ``set``."""

    __slots__ = ("tracer", "name", "context", "parent_id", "start_s", "attrs")

    def __init__(self, tracer: "Tracer", name: str, context: SpanContext,
                 parent_id: str | None, start_s: float, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.start_s = start_s
        self.attrs = attrs

    @property
    def ctx(self) -> SpanContext:
        return self.context

    def set(self, **attrs) -> "Span":
        """Attach attributes discovered mid-span (tier picked at cut time,
        ``joined`` source of a prefetch, error status)."""
        self.attrs.update(attrs)
        return self


class _NullSpan:
    """What a disabled tracer hands out: absorbs the span protocol for
    free so call sites never branch on ``tracer.enabled``."""

    __slots__ = ()
    ctx = None
    context = None
    parent_id = None

    def set(self, **attrs) -> "_NullSpan":
        return self


_NULL_SPAN = _NullSpan()


class Tracer:
    """Span factory + sink fan-out. One per process via :func:`get_tracer`;
    tests construct their own with a fake clock for determinism."""

    def __init__(self, enabled: bool = False, clock=time.perf_counter,
                 id_prefix: str = ""):
        self.enabled = bool(enabled)
        self.clock = clock
        # ids must stay alphanumeric (the header joins them with a dash,
        # from_header splits on it) — strip anything else from the prefix
        self.id_prefix = "".join(c for c in str(id_prefix) if c.isalnum())
        self._ids = itertools.count(1)
        self._id_lock = threading.Lock()
        self._sinks: list = []
        self.n_spans = 0
        self.n_remote_parented = 0
        self.n_dropped_sink = 0

    # -- ids / clock ---------------------------------------------------------

    def _next_id(self) -> str:
        with self._id_lock:
            return f"{self.id_prefix}{next(self._ids):08x}"

    def now(self) -> float:
        """The tracer's clock — call sites stamp seam-crossing times with
        this so explicit-time spans share one timebase."""
        return self.clock()

    # -- sinks ---------------------------------------------------------------

    def add_sink(self, sink) -> None:
        """``sink(row: dict)`` is called with every finished span row (the
        flight recorder's ring, serve_bench's aggregator)."""
        if sink not in self._sinks:
            self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        if sink in self._sinks:
            self._sinks.remove(sink)

    # -- span lifecycle ------------------------------------------------------

    def _resolve_parent(self, parent) -> tuple[str, str | None, bool]:
        """(trace_id, parent_span_id, remote) for a new span. ``parent``
        is the _INHERIT sentinel (use this thread's current span), None
        (new root/trace), or an explicit SpanContext carried across a
        seam — possibly one restored from a :data:`TRACE_HEADER`."""
        if parent is _INHERIT:
            cur = _current.get()
            parent = cur.context if cur is not None else None
        if parent is None:
            return self._next_id(), None, False
        return (parent.trace_id, parent.span_id,
                bool(getattr(parent, "remote", False)))

    @contextmanager
    def span(self, name: str, *, parent=_INHERIT, **attrs):
        """Run the body under a new span; the span becomes the thread's
        current context for the duration (children nest automatically).
        An escaping exception stamps ``status: error:<Type>`` and
        re-raises — tracing never swallows."""
        if not self.enabled:
            yield _NULL_SPAN
            return
        trace_id, parent_id, remote = self._resolve_parent(parent)
        ctx = SpanContext(trace_id, self._next_id())
        sp = Span(self, name, ctx, parent_id, self.clock(), dict(attrs))
        if remote:
            sp.attrs.setdefault("remote_parent", True)
        token = _current.set(sp)
        try:
            yield sp
        except BaseException as exc:
            sp.attrs.setdefault("status", f"error:{type(exc).__name__}")
            raise
        finally:
            _current.reset(token)
            self._finish(sp, self.clock())

    def record(self, name: str, *, start_s: float, end_s: float | None = None,
               dur_s: float | None = None, parent=_INHERIT, **attrs) -> None:
        """Emit an already-elapsed span from explicit timestamps — the
        shape for intervals observed after the fact (queue wait measured
        at cut time, scatter measured per-request inside the batch)."""
        if not self.enabled:
            return
        trace_id, parent_id, remote = self._resolve_parent(parent)
        ctx = SpanContext(trace_id, self._next_id())
        sp = Span(self, name, ctx, parent_id, start_s, dict(attrs))
        if remote:
            sp.attrs.setdefault("remote_parent", True)
        if dur_s is None:
            dur_s = (end_s if end_s is not None else self.clock()) - start_s
        self._finish(sp, start_s + max(0.0, dur_s))

    def _finish(self, sp: Span, end_s: float) -> None:
        row = {
            "trace_id": sp.context.trace_id,
            "span_id": sp.context.span_id,
            "name": sp.name,
            "start_s": sp.start_s,
            "dur_s": max(0.0, end_s - sp.start_s),
            "parent_id": sp.parent_id,
            "thread": threading.current_thread().name,
            **sp.attrs,
        }
        self.n_spans += 1
        if row.get("remote_parent"):
            self.n_remote_parented += 1
        # graftlint: ok(emit-hot: span finish is the telemetry boundary itself, host-side after dispatch)
        get_emitter().emit("span", **row)
        stage = row.get("stage")
        if stage is not None:
            from .metrics import get_metrics

            # graftlint: ok(emit-hot: fixed-bucket histogram update, lock-cheap host-side)
            get_metrics().observe("serve_stage_seconds", row["dur_s"],
                                  stage=str(stage))
        for sink in list(self._sinks):
            try:
                sink(row)
            # graftlint: ok(swallow: a broken sink must not fail the traced request; the drop is counted and surfaced via stats()/healthz)
            except Exception:
                self.n_dropped_sink += 1

    def stats(self) -> dict:
        """Tracing health for ``/healthz`` and heartbeats: spans emitted,
        sink drops, and how many spans parented under a remote ctx."""
        return {
            "enabled": self.enabled,
            "spans": self.n_spans,
            "dropped_sink": self.n_dropped_sink,
            "remote_parented": self.n_remote_parented,
        }


def current_ctx() -> SpanContext | None:
    """The calling thread's current span context, or None — what gets
    captured into a queue entry / closure to cross a thread seam."""
    cur = _current.get()
    return cur.context if cur is not None else None


def current_span() -> Span | None:
    """The live span itself, for attaching attributes from deep callees
    (``acquire`` marking a prefetch join on whatever span is running)."""
    return _current.get()


_tracer = Tracer(enabled=False)


def get_tracer() -> Tracer:
    """The process's tracer (disabled until :func:`configure_tracing`)."""
    return _tracer


def configure_tracing(enabled: bool = True, clock=None,
                      id_prefix: str = "") -> Tracer:
    """Replace the process tracer (serve.py startup, test setup). A fresh
    tracer resets the id counter — deterministic ids per configure.
    ``id_prefix`` (e.g. the replica id) keeps span ids unique across the
    fleet so a ``--fleet`` merge joins on propagated ids collision-free."""
    global _tracer
    _tracer = Tracer(enabled=enabled, clock=clock or time.perf_counter,
                     id_prefix=id_prefix)
    return _tracer
