"""Per-scene / per-replica capacity-and-heat ledger.

The placement planner (ROADMAP) needs to answer "what loads where":
which scenes are hot, how much HBM and host-RAM staging each replica is
actually using at peak, and where device time goes per executable
family. Those facts all exist in the telemetry stream — this ledger
folds them into a committed, replayable accounting surface:

* **byte watermarks** — current + peak resident-HBM and staging bytes,
  fed by the residency managers (:meth:`note_residency`, wired through
  ``fleet/ladder.py``) and, as a fallback, by ``scene_load`` /
  ``scene_evict`` rows that carry ``resident_bytes``/``staging_bytes``;
* **scene heat** — request rate and rays/s per scene over a sliding
  window (``serve_request`` rows or explicit :meth:`note_request` on the
  replica submit path);
* **device-time share** — fraction of windowed device seconds per
  executable family (``span`` rows with ``stage="device"``);
* **churn** — cold loads (from disk) vs re-promotions (from staging) per
  scene, the ladder's effectiveness signal.

Read surfaces: labeled ``capacity_*`` gauges on /metrics (no local
``replica`` label — the fleet merge injects one), ``GET
/fleet/capacity`` (scale/fleet_metrics.py), and a schema-versioned
``capacity_snapshot`` telemetry row per :meth:`snapshot` — the
planner's replayable input format.

Host-side pure Python, injectable clock, thread-safe.
"""

from __future__ import annotations

import threading
import time

from .emit import add_row_tap, get_emitter, remove_row_tap
from .metrics import WindowRing, get_metrics


class _SceneHeat:
    __slots__ = ("requests", "rays", "cold_loads", "repromotions")

    def __init__(self, slot_s: float):
        self.requests = WindowRing(slot_s=slot_s)
        self.rays = WindowRing(slot_s=slot_s)
        self.cold_loads = 0
        self.repromotions = 0


class CapacityLedger:
    """Folds residency/serve/span telemetry into capacity accounting.

    ``window_s`` is the sliding window rates and shares are computed
    over; ``replica`` stamps emitted ``capacity_snapshot`` rows (NOT the
    gauges — ``merge_scrapes`` injects the replica label fleet-side).
    """

    def __init__(self, *, replica: str = "", window_s: float = 300.0,
                 clock=time.monotonic):
        self.replica = str(replica)
        self.window_s = float(window_s)
        self.clock = clock
        slot = max(0.25, min(5.0, self.window_s / 20.0))
        self._slot = slot
        self._lock = threading.Lock()
        self._scenes: dict[str, _SceneHeat] = {}
        self._device: dict[str, WindowRing] = {}  # family -> device seconds
        self.hbm_bytes = 0
        self.hbm_peak_bytes = 0
        self.staging_bytes = 0
        self.staging_peak_bytes = 0
        self.n_snapshots = 0

    # -- feeds ---------------------------------------------------------------

    def attach(self) -> "CapacityLedger":
        add_row_tap(self._on_row)
        return self

    def detach(self) -> None:
        remove_row_tap(self._on_row)

    def _scene(self, name: str) -> _SceneHeat:
        h = self._scenes.get(name)
        if h is None:
            h = self._scenes[name] = _SceneHeat(self._slot)
        return h

    def note_request(self, scene: str, n_rays: int,
                     now: float | None = None) -> None:
        """One served request against ``scene`` (replica submit path)."""
        now = self.clock() if now is None else now
        with self._lock:
            h = self._scene(str(scene) or "default")
            h.requests.add(1.0, now)
            h.rays.add(float(n_rays), now)

    def note_residency(self, resident_bytes: int, staging_bytes: int) -> None:
        """Authoritative byte watermarks from a residency manager (the
        ladder calls this at every tier transition, under its lock)."""
        with self._lock:
            self._note_residency_locked(int(resident_bytes),
                                        int(staging_bytes))

    def _note_residency_locked(self, rb: int, sb: int) -> None:
        self.hbm_bytes = rb
        self.staging_bytes = sb
        if rb > self.hbm_peak_bytes:
            self.hbm_peak_bytes = rb
        if sb > self.staging_peak_bytes:
            self.staging_peak_bytes = sb

    def _on_row(self, row: dict) -> None:
        kind = row.get("kind")
        now = self.clock()
        with self._lock:
            if kind == "serve_request":
                h = self._scene(str(row.get("scene") or "default"))
                h.requests.add(1.0, now)
                h.rays.add(float(row.get("n_rays", 0)), now)
            elif kind == "scene_load":
                h = self._scene(str(row.get("scene", "")))
                if row.get("source") == "staging":
                    h.repromotions += 1
                else:
                    h.cold_loads += 1
                self._row_residency(row)
            elif kind == "scene_evict":
                self._row_residency(row)
            elif kind == "span":
                if row.get("stage") == "device":
                    fam = str(row.get("family") or row.get("name") or "")
                    ring = self._device.get(fam)
                    if ring is None:
                        ring = self._device[fam] = WindowRing(
                            slot_s=self._slot)
                    ring.add(float(row.get("dur_s", 0.0)), now)

    def _row_residency(self, row: dict) -> None:
        # rows carry the manager's post-transition totals when present
        rb = row.get("resident_bytes")
        if rb is None:
            return
        sb = row.get("staging_bytes", self.staging_bytes)
        self._note_residency_locked(int(rb), int(sb))

    # -- read surfaces -------------------------------------------------------

    def view(self, now: float | None = None) -> dict:
        """The ledger's current accounting (the /fleet/capacity shape)."""
        now = self.clock() if now is None else now
        w = self.window_s
        with self._lock:
            scenes = {}
            total_req = 0.0
            total_rays = 0.0
            for name, h in sorted(self._scenes.items()):
                nreq = h.requests.total(w, now)
                nrays = h.rays.total(w, now)
                total_req += nreq
                total_rays += nrays
                scenes[name] = {
                    "requests_per_s": round(nreq / w, 4),
                    "rays_per_s": round(nrays / w, 1),
                    "cold_loads": h.cold_loads,
                    "repromotions": h.repromotions,
                }
            dev = {f: r.total(w, now) for f, r in self._device.items()}
            dev_total = sum(dev.values())
            share = {f: round(s / dev_total, 4)
                     for f, s in sorted(dev.items()) if dev_total > 0}
            return {
                "replica": self.replica,
                "window_s": w,
                "hbm_bytes": self.hbm_bytes,
                "hbm_peak_bytes": self.hbm_peak_bytes,
                "staging_bytes": self.staging_bytes,
                "staging_peak_bytes": self.staging_peak_bytes,
                "requests_per_s": round(total_req / w, 4),
                "rays_per_s": round(total_rays / w, 1),
                "cold_loads": sum(h.cold_loads
                                  for h in self._scenes.values()),
                "repromotions": sum(h.repromotions
                                    for h in self._scenes.values()),
                "device_share": share,
                "scenes": scenes,
            }

    def publish_gauges(self, now: float | None = None) -> None:
        """Export the ledger as ``capacity_*`` gauges on /metrics."""
        v = self.view(now)
        mx = get_metrics()
        mx.gauge("capacity_hbm_bytes", float(v["hbm_bytes"]))
        mx.gauge("capacity_hbm_peak_bytes", float(v["hbm_peak_bytes"]))
        mx.gauge("capacity_staging_bytes", float(v["staging_bytes"]))
        mx.gauge("capacity_staging_peak_bytes",
                 float(v["staging_peak_bytes"]))
        for name, s in v["scenes"].items():
            mx.gauge("capacity_scene_requests_per_s",
                     s["requests_per_s"], scene=name)
            mx.gauge("capacity_scene_rays_per_s",
                     s["rays_per_s"], scene=name)
            mx.gauge("capacity_scene_cold_loads",
                     float(s["cold_loads"]), scene=name)
            mx.gauge("capacity_scene_repromotions",
                     float(s["repromotions"]), scene=name)
        for fam, share in v["device_share"].items():
            mx.gauge("capacity_device_share", share, family=fam)

    def snapshot(self, now: float | None = None) -> dict:
        """Commit a ``capacity_snapshot`` telemetry row (+ refresh the
        gauges): the planner's replayable input format."""
        v = self.view(now)
        self.publish_gauges(now)
        get_emitter().emit(
            "capacity_snapshot",
            replica=self.replica,
            scenes=v["scenes"],
            hbm_bytes=v["hbm_bytes"],
            hbm_peak_bytes=v["hbm_peak_bytes"],
            staging_bytes=v["staging_bytes"],
            staging_peak_bytes=v["staging_peak_bytes"],
            window_s=v["window_s"],
            device_share=v["device_share"],
            requests_per_s=v["requests_per_s"],
            rays_per_s=v["rays_per_s"],
            cold_loads=v["cold_loads"],
            repromotions=v["repromotions"],
        )
        self.n_snapshots += 1
        return v

    def stats(self) -> dict:
        with self._lock:
            return {"n_scenes": len(self._scenes),
                    "n_families": len(self._device),
                    "n_snapshots": self.n_snapshots,
                    "hbm_peak_bytes": self.hbm_peak_bytes,
                    "staging_peak_bytes": self.staging_peak_bytes}
