"""Multi-window multi-burn-rate SLO alerting (the SRE workbook shape).

The fleet emits rich raw telemetry — spans, /metrics, flight dumps,
evidence-linked scale decisions — but until PR 16 nothing *consumed* it
automatically: an operator had to run tlm_report by hand to learn the
fleet was burning its SLO an hour ago. This engine closes that loop
while traffic flows.

**Burn rate** is error budget spent per unit budget: with a 99% SLO the
budget is 1%, so an error rate of 14.4% burns at 14.4x — the classic
page threshold (a 30-day budget gone in ~2 days). A burn-rate alert
fires only when BOTH a short and a long window exceed the threshold:
the short window makes the alert fast to clear, the long window keeps a
10-second blip from paging. Two severities ride the same math:

* **page** — fast windows (5m / 1h), burn >= ``fast_burn`` (14.4x)
* **ticket** — slow windows (30m / 6h), burn >= ``slow_burn`` (6x)

evaluated against two budgeted signals (SLO latency attainment, tenant
deny rate) plus three direct conditions: breaker open (page while any
dispatch breaker is open), orphan-span rate (spans whose parent never
arrived — broken propagation), and staging thrash (demote->re-promote
churn at the residency ladder). Hysteresis: an alert clears only after
its condition has been continuously false for ``clear_hold_s`` — no
flapping at the threshold.

Feeds, either or both:

* :meth:`AlertEngine.attach` — subscribe to the telemetry row stream
  (``obs.emit.add_row_tap``): serve_request / tenant_admit / breaker /
  span / scene_load / scene_evict rows update the windows in-process
  (serve.py's shape).
* :meth:`AlertEngine.observe_window` — explicit (attainment, deny_rate,
  n) samples: the Supervisor's fleet-merged view
  (``Supervisor.step_from_fleet``), where the engine sees what the
  closed loop sees.

Every state TRANSITION (never steady state) emits a schema-versioned
``alert`` telemetry row and notifies listeners — the incident correlator
(obs/incidents.py) opens/mitigates on these. ``GET /alerts`` renders
:meth:`status`; ``/healthz`` carries the firing set.

Host-side pure Python: no jax import, injectable clock, deterministic
under a fake clock (tests/test_alerts.py drives the window math).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from .emit import add_row_tap, get_emitter, remove_row_tap
from .metrics import WindowRing, get_metrics

# page when the fast windows burn >= 14.4x (a 30-day budget in ~2 days);
# ticket when the slow windows burn >= 6x (budget in ~5 days)
DEFAULT_FAST_BURN = 14.4
DEFAULT_SLOW_BURN = 6.0


class AlertOptions:
    """Targets + windows for the engine (defaults mirror cfg.obs.alerts).

    ``slo_objective``/``deny_objective`` are attainment objectives in
    (0, 1); the error budget each burn rate divides is ``1 - objective``.
    The latency target itself (what "attained" means) is the engine's
    ``slo_target_s``, not an option here — it mirrors ``obs.slo_target_ms``.
    """

    def __init__(self, *,
                 slo_objective: float = 0.99,
                 deny_objective: float = 0.99,
                 fast_burn: float = DEFAULT_FAST_BURN,
                 slow_burn: float = DEFAULT_SLOW_BURN,
                 fast_short_s: float = 300.0,
                 fast_long_s: float = 3600.0,
                 slow_short_s: float = 1800.0,
                 slow_long_s: float = 21600.0,
                 clear_hold_s: float = 60.0,
                 min_count: float = 1.0,
                 orphan_grace_s: float = 30.0,
                 orphan_rate_max: float = 0.05,
                 thrash_per_min_max: float = 6.0):
        self.slo_objective = float(slo_objective)
        self.deny_objective = float(deny_objective)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.fast_short_s = float(fast_short_s)
        self.fast_long_s = float(fast_long_s)
        self.slow_short_s = float(slow_short_s)
        self.slow_long_s = float(slow_long_s)
        self.clear_hold_s = float(clear_hold_s)
        self.min_count = float(min_count)
        self.orphan_grace_s = float(orphan_grace_s)
        self.orphan_rate_max = float(orphan_rate_max)
        self.thrash_per_min_max = float(thrash_per_min_max)

    @classmethod
    def from_cfg(cls, cfg) -> "AlertOptions":
        """Options from the ``obs.alerts`` config block."""
        return cls(
            slo_objective=float(cfg.obs.alerts.slo_objective),
            deny_objective=float(cfg.obs.alerts.deny_objective),
            fast_burn=float(cfg.obs.alerts.fast_burn),
            slow_burn=float(cfg.obs.alerts.slow_burn),
            fast_short_s=float(cfg.obs.alerts.fast_short_s),
            fast_long_s=float(cfg.obs.alerts.fast_long_s),
            slow_short_s=float(cfg.obs.alerts.slow_short_s),
            slow_long_s=float(cfg.obs.alerts.slow_long_s),
            clear_hold_s=float(cfg.obs.alerts.clear_hold_s),
            orphan_grace_s=float(cfg.obs.alerts.orphan_grace_s),
            orphan_rate_max=float(cfg.obs.alerts.orphan_rate_max),
            thrash_per_min_max=float(cfg.obs.alerts.thrash_per_min_max),
        )


class _BudgetSignal:
    """bad/total event pair over time — the burn-rate numerator."""

    __slots__ = ("bad", "total")

    def __init__(self, slot_s: float):
        self.bad = WindowRing(slot_s=slot_s)
        self.total = WindowRing(slot_s=slot_s)

    def rate(self, window_s: float, now: float) -> tuple[float, float]:
        n = self.total.total(window_s, now)
        if not n:
            return 0.0, 0.0
        return self.bad.total(window_s, now) / n, n


class AlertEngine:
    """Burn-rate + direct-condition alerting over the telemetry stream.

    ``slo_target_s`` is the per-request latency target a serve_request
    row is judged against (row-tap feed); the fleet feed
    (:meth:`observe_window`) brings pre-judged attainment instead.
    ``replica`` stamps emitted alert rows (multi-replica merges).
    """

    def __init__(self, options: AlertOptions | None = None,
                 slo_target_s: float = 0.25,
                 clock=time.monotonic, replica: str = ""):
        self.options = options or AlertOptions()
        self.slo_target_s = float(slo_target_s)
        self.clock = clock
        self.replica = str(replica)
        opt = self.options
        # slot resolution scales with the shortest window so bench/test
        # configurations with second-scale windows still resolve
        slot = max(0.25, min(5.0, opt.fast_short_s / 10.0))
        self._lock = threading.Lock()
        self._slo = _BudgetSignal(slot)
        self._deny = _BudgetSignal(slot)
        self._orphan = _BudgetSignal(slot)
        self._demote = WindowRing(slot_s=slot)
        self._repromote = WindowRing(slot_s=slot)
        self._breaker: dict[str, str] = {}          # point -> last state
        self._seen_spans: set[str] = set()
        self._seen_q: deque = deque()
        self._pending_parents: deque = deque()      # (t, parent_id)
        self._states: dict[str, dict] = {}
        self._listeners: list = []
        self.transitions: list[dict] = []
        self.alert_seconds: dict[str, float] = {}
        self.self_s = 0.0  # wall seconds spent in the engine (overhead)

    # -- feeds ---------------------------------------------------------------

    def attach(self) -> "AlertEngine":
        """Subscribe to the process's telemetry row stream."""
        add_row_tap(self._on_row)
        return self

    def detach(self) -> None:
        remove_row_tap(self._on_row)

    def add_listener(self, fn) -> None:
        """``fn(event_dict)`` on every fire/clear transition (the
        incident correlator's hook)."""
        if fn not in self._listeners:
            self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def _on_row(self, row: dict) -> None:
        t0 = time.perf_counter()
        kind = row.get("kind")
        now = self.clock()
        with self._lock:
            if kind == "serve_request":
                bad = (float(row.get("latency_s", 0.0)) > self.slo_target_s
                       or str(row.get("status", "ok")) not in ("ok", ""))
                self._slo.total.add(1.0, now)
                if bad:
                    self._slo.bad.add(1.0, now)
            elif kind == "tenant_admit":
                self._deny.total.add(1.0, now)
                if row.get("decision") == "deny":
                    self._deny.bad.add(1.0, now)
            elif kind == "breaker":
                self._breaker[str(row.get("point") or "")] = \
                    str(row.get("state", ""))
            elif kind == "span":
                self._note_span(row, now)
            elif kind == "scene_evict":
                if row.get("reason") == "demoted":
                    self._demote.add(1.0, now)
            elif kind == "scene_load":
                if row.get("source") == "staging":
                    self._repromote.add(1.0, now)
        self.self_s += time.perf_counter() - t0

    def _note_span(self, row: dict, now: float) -> None:
        # children finish BEFORE their parents, so a parent id unseen at
        # child-finish time is normal: judge only after a grace period
        sid = row.get("span_id")
        if isinstance(sid, str):
            self._seen_spans.add(sid)
            self._seen_q.append(sid)
            while len(self._seen_q) > 8192:
                self._seen_spans.discard(self._seen_q.popleft())
        pid = row.get("parent_id")
        if isinstance(pid, str) and not row.get("remote_parent"):
            self._pending_parents.append((now, pid))

    def observe_window(self, attainment: float | None, deny_rate: float,
                       n: int, now: float | None = None) -> None:
        """One fleet-merged observation window (Supervisor feed):
        ``n`` completed requests at ``attainment``, admissions denied at
        ``deny_rate``. ``attainment`` None with n==0 records nothing."""
        now = self.clock() if now is None else now
        n = max(0, int(n))
        with self._lock:
            if attainment is not None:
                k = max(n, 1)
                self._slo.total.add(float(k), now)
                self._slo.bad.add((1.0 - float(attainment)) * k, now)
            if n:
                self._deny.total.add(float(n), now)
                self._deny.bad.add(float(deny_rate) * n, now)

    # -- evaluation ----------------------------------------------------------

    def _judge_pending(self, now: float) -> None:
        grace = self.options.orphan_grace_s
        while self._pending_parents and \
                now - self._pending_parents[0][0] >= grace:
            _t, pid = self._pending_parents.popleft()
            self._orphan.total.add(1.0, now)
            if pid not in self._seen_spans:
                self._orphan.bad.add(1.0, now)

    def _conditions(self, now: float) -> list[dict]:
        """Raw per-alert condition verdicts at ``now`` (lock held)."""
        opt = self.options
        out: list[dict] = []

        def burn(name, signal, sig, objective, severity, thr, short_s,
                 long_s):
            budget = max(1e-9, 1.0 - objective)
            r_s, n_s = sig.rate(short_s, now)
            r_l, _n_l = sig.rate(long_s, now)
            b_s, b_l = r_s / budget, r_l / budget
            out.append({
                "name": name, "signal": signal, "severity": severity,
                "threshold": thr, "window_s": short_s,
                "burn_fast": round(b_s, 2), "burn_slow": round(b_l, 2),
                "value": round(r_s, 4),
                "condition": (n_s >= opt.min_count and b_s >= thr
                              and b_l >= thr),
            })

        burn("slo_burn_page", "slo", self._slo, opt.slo_objective,
             "page", opt.fast_burn, opt.fast_short_s, opt.fast_long_s)
        burn("slo_burn_ticket", "slo", self._slo, opt.slo_objective,
             "ticket", opt.slow_burn, opt.slow_short_s, opt.slow_long_s)
        burn("deny_burn_page", "deny", self._deny, opt.deny_objective,
             "page", opt.fast_burn, opt.fast_short_s, opt.fast_long_s)
        burn("deny_burn_ticket", "deny", self._deny, opt.deny_objective,
             "ticket", opt.slow_burn, opt.slow_short_s, opt.slow_long_s)

        open_points = sorted(p for p, s in self._breaker.items()
                             if s == "open")
        out.append({
            "name": "breaker_open", "signal": "breaker", "severity": "page",
            "threshold": 1.0, "window_s": 0.0,
            "burn_fast": None, "burn_slow": None,
            "value": float(len(open_points)),
            "condition": bool(open_points),
            "detail": ",".join(open_points),
        })

        self._judge_pending(now)
        orate, on = self._orphan.rate(opt.fast_short_s, now)
        out.append({
            "name": "orphan_spans", "signal": "orphan_spans",
            "severity": "ticket", "threshold": opt.orphan_rate_max,
            "window_s": opt.fast_short_s,
            "burn_fast": None, "burn_slow": None,
            "value": round(orate, 4),
            "condition": (on >= opt.min_count
                          and orate >= opt.orphan_rate_max),
        })

        minutes = max(opt.fast_short_s / 60.0, 1e-9)
        churn = min(self._demote.total(opt.fast_short_s, now),
                    self._repromote.total(opt.fast_short_s, now)) / minutes
        out.append({
            "name": "staging_thrash", "signal": "staging_thrash",
            "severity": "ticket", "threshold": opt.thrash_per_min_max,
            "window_s": opt.fast_short_s,
            "burn_fast": None, "burn_slow": None,
            "value": round(churn, 2),
            "condition": churn >= opt.thrash_per_min_max,
        })
        return out

    def evaluate(self, now: float | None = None) -> dict:
        """One evaluation pass: update every alert's state machine,
        emit ``alert`` rows + notify listeners on transitions, return
        the current status (the ``GET /alerts`` body)."""
        t0 = time.perf_counter()
        now = self.clock() if now is None else now
        hold = self.options.clear_hold_s
        fired: list[dict] = []
        statuses: list[dict] = []
        with self._lock:
            for c in self._conditions(now):
                st = self._states.setdefault(
                    c["name"], {"state": "ok", "since": now,
                                "clear_since": None})
                if c.pop("condition"):
                    st["clear_since"] = None
                    if st["state"] != "firing":
                        st["state"] = "firing"
                        st["since"] = now
                        fired.append({**c, "state": "firing"})
                else:
                    if st["state"] == "firing":
                        if st["clear_since"] is None:
                            st["clear_since"] = now
                        if now - st["clear_since"] >= hold:
                            self.alert_seconds[c["name"]] = (
                                self.alert_seconds.get(c["name"], 0.0)
                                + (now - st["since"]))
                            st["state"] = "ok"
                            st["since"] = now
                            st["clear_since"] = None
                            fired.append({**c, "state": "resolved"})
                statuses.append({**c, "state": st["state"],
                                 "since": st["since"]})
        # transitions emit/notify OUTSIDE the lock: the emitted alert row
        # re-enters this engine through its own row tap
        mx = get_metrics()
        for ev in fired:
            ev = dict(ev)
            ev.setdefault("detail", "")
            self.transitions.append({**ev, "t": now})
            get_emitter().emit(
                "alert", name=ev["name"], state=ev["state"],
                severity=ev["severity"], signal=ev["signal"],
                burn_fast=ev["burn_fast"], burn_slow=ev["burn_slow"],
                value=ev["value"], threshold=ev["threshold"],
                window_s=ev["window_s"], replica=self.replica,
                detail=ev["detail"],
            )
            mx.counter("alert_transitions_total", alert=ev["name"],
                       state=ev["state"])
            for fn in list(self._listeners):
                try:
                    fn({**ev, "t": now})
                # graftlint: ok(swallow: a broken listener must not break alerting; it is dropped)
                except Exception:
                    self.remove_listener(fn)
        for s in statuses:
            mx.gauge("alert_firing", 1.0 if s["state"] == "firing" else 0.0,
                     alert=s["name"])
        firing = [s["name"] for s in statuses if s["state"] == "firing"]
        self.self_s += time.perf_counter() - t0
        return {"t": now, "firing": firing, "alerts": statuses}

    # -- read surfaces -------------------------------------------------------

    def active(self) -> list[str]:
        with self._lock:
            return sorted(n for n, st in self._states.items()
                          if st["state"] == "firing")

    def status(self, now: float | None = None) -> dict:
        """The ``GET /alerts`` body: a fresh evaluation + totals."""
        view = self.evaluate(now)
        with self._lock:
            now_t = view["t"]
            seconds = dict(self.alert_seconds)
            for name, st in self._states.items():
                if st["state"] == "firing":
                    seconds[name] = (seconds.get(name, 0.0)
                                     + (now_t - st["since"]))
        view["enabled"] = True
        view["n_transitions"] = len(self.transitions)
        view["alert_seconds"] = {k: round(v, 3)
                                 for k, v in sorted(seconds.items())}
        return view

    def healthz_block(self) -> dict:
        """The compact ``alerts`` block /healthz carries."""
        firing = self.active()
        return {"firing": firing, "n_firing": len(firing),
                "n_transitions": len(self.transitions)}

    def stats(self) -> dict:
        with self._lock:
            return {
                "n_transitions": len(self.transitions),
                "firing": sorted(n for n, st in self._states.items()
                                 if st["state"] == "firing"),
                "self_s": round(self.self_s, 4),
                "breaker_points": dict(self._breaker),
            }
