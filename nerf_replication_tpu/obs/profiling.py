"""Config-driven ``jax.profiler`` windows + trace annotations.

``train.profile: {start_step, num_steps, dir}`` captures an xplane trace
around exactly those steps of the hot loop — the profiler runs for a
bounded window instead of the whole run (a full-run trace of a 200k-step
job is unopenable). The window is ticked with the HOST step counter, so
it composes with scan bursts: capture starts at the first burst touching
``start_step`` and stops at the first burst boundary past
``start_step + num_steps`` (a burst is one device dispatch — there is no
tighter host-side seam).

``annotate(name)`` is the host-side ``TraceAnnotation`` scope the
entrypoints put around bank draw / step dispatch / grid update /
validation so the xplane timeline is legible; inside jitted code the step
builders use ``jax.named_scope`` (which lands in the compiled op names)
instead.
"""

from __future__ import annotations

import os


def annotate(name: str):
    """Named host-side region on the profiler timeline."""
    import jax

    return jax.profiler.TraceAnnotation(name)


class ProfileWindow:
    """Start/stop a ``jax.profiler`` trace around a configured step span."""

    def __init__(self, start_step: int = -1, num_steps: int = 0,
                 trace_dir: str = "", chief: bool | None = None):
        if chief is None:
            from ..parallel.mesh import is_chief

            chief = is_chief()
        self.start_step = int(start_step)
        self.num_steps = int(num_steps)
        self.trace_dir = trace_dir
        self.enabled = chief and self.start_step >= 0 and self.num_steps > 0
        self.active = False
        self.done = False

    @classmethod
    def from_cfg(cls, cfg):
        prof = cfg.get("train", {}).get("profile", None)
        if not prof:
            return cls()  # disabled
        trace_dir = str(prof.get("dir", "")) or os.path.join(
            str(cfg.get("record_dir", ".")), "profile"
        )
        return cls(
            start_step=int(prof.get("start_step", -1)),
            num_steps=int(prof.get("num_steps", 0)),
            trace_dir=trace_dir,
        )

    def tick(self, host_step: int) -> None:
        """Advance the window; call with the post-burst host step counter.

        Starts capture when the NEXT dispatch would overlap the window,
        stops once the window's last step has executed.
        """
        if not self.enabled or self.done:
            return
        import jax

        if self.active and host_step >= self.start_step + self.num_steps:
            jax.profiler.stop_trace()
            self.active = False
            self.done = True
            return
        if not self.active and host_step >= self.start_step:
            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)
            self.active = True

    def stop(self) -> None:
        """Safety stop (end of training / exception unwind)."""
        if self.active:
            import jax

            jax.profiler.stop_trace()
            self.active = False
            self.done = True
