"""Run-scoped telemetry subsystem (the observability layer PERF.md's
hand-rolled timers grew into).

Every entrypoint (train.py, run.py, the bench scripts) writes through one
schema-versioned JSONL emitter: a single ``telemetry.jsonl`` per run dir
with typed rows — ``run_meta`` / ``step`` / ``epoch`` / ``eval`` /
``compile`` / ``memory`` / ``heartbeat`` — chief-guarded like ``Recorder``
and flushed crash-safely. ``scripts/tlm_report.py`` summarizes or diffs
runs; ``scripts/check_telemetry_schema.py`` validates any telemetry or
bench JSONL against the versioned schema.

The classic failure modes of a fully-jitted TPU hot loop are invisible
ones — silent recompilation storms, HBM creep, host-dispatch stalls that
only show up as a slow ``eta:`` line. The hooks here make each one a typed
row: ``obs.hooks.CompileTracker`` counts compiles/retraces per compiled
function, ``obs.hooks.sample_memory`` snapshots per-device
``memory_stats()``, and the trainer's dispatch-vs-block step-time split
distinguishes latency-bound from compute-bound regressions.

The ops-intelligence layer closes the loop from telemetry to action:
``obs/alerts.py`` (multi-window multi-burn-rate SLO alerting),
``obs/incidents.py`` (auto-correlated incident reports with an
open→mitigated→resolved lifecycle), and ``obs/capacity.py`` (the
per-scene capacity/heat ledger the placement planner reads) — all fed
in-process from the emitter's row-tap bus (``add_row_tap``).
"""

from .alerts import AlertEngine, AlertOptions
from .capacity import CapacityLedger
from .emit import (
    Emitter,
    NullEmitter,
    add_row_tap,
    append_jsonl,
    get_emitter,
    init_run,
    remove_row_tap,
)
from .hooks import CompileTracker, sample_memory
from .incidents import IncidentManager, validate_incident_dump
from .metrics import MetricsRegistry, WindowRing, get_metrics, reset_metrics
from .profiling import ProfileWindow, annotate
from .schema import SCHEMA_VERSION, validate_bench_row, validate_row
from .trace import (
    TRACE_HEADER,
    Span,
    SpanContext,
    Tracer,
    configure_tracing,
    current_ctx,
    current_span,
    get_tracer,
    trace_headers,
)

__all__ = [
    "SCHEMA_VERSION",
    "TRACE_HEADER",
    "AlertEngine",
    "AlertOptions",
    "CapacityLedger",
    "Emitter",
    "IncidentManager",
    "MetricsRegistry",
    "NullEmitter",
    "CompileTracker",
    "ProfileWindow",
    "Span",
    "SpanContext",
    "Tracer",
    "WindowRing",
    "add_row_tap",
    "annotate",
    "append_jsonl",
    "configure_tracing",
    "current_ctx",
    "current_span",
    "get_emitter",
    "get_metrics",
    "get_tracer",
    "init_run",
    "remove_row_tap",
    "reset_metrics",
    "sample_memory",
    "trace_headers",
    "validate_bench_row",
    "validate_incident_dump",
    "validate_row",
]
