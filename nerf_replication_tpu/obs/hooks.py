"""Instrumentation hooks: compile/retrace counting, memory sampling, and
the host/device step-time split.

These are the probes for the hot loop's three invisible failure modes:

* **Recompilation storms** — :class:`CompileTracker` wraps each compiled
  function and watches its lowering cache (``jit``'s ``_cache_size``): a
  growing cache on a steady-state step means a retrace (a shape or dtype
  the builder didn't pin), each one worth seconds of wall clock. Every
  growth emits a ``compile`` row carrying the triggering call's wall time
  next to the steady-state median, so the report can price the storm.
* **HBM creep** — :func:`sample_memory` snapshots every local device's
  ``memory_stats()`` (plus host RSS, which also covers backends that
  don't implement device stats) into a ``memory`` row on the epoch
  cadence.
* **Host-dispatch stalls** — :func:`timed_call` splits a step's wall time
  into dispatch (host time to enqueue) and block (device time waited at
  the sync point), so a latency-bound regression (dispatch grows) is
  distinguishable from a compute-bound one (block grows).
"""

from __future__ import annotations

import time
from collections import deque

from .emit import get_emitter


def _cache_size(fn) -> int | None:
    """Lowering-cache size of a ``jax.jit``-returned callable (None when
    the callable doesn't expose one — e.g. a plain python wrapper)."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    # graftlint: ok(swallow: cache probe; None switches to the first-call heuristic)
    except Exception:
        return None


class _TrackedFn:
    """One wrapped compiled function: counts calls and compiles."""

    def __init__(self, name: str, fn, steady_window: int = 64):
        self.name = name
        self.fn = fn
        self.n_calls = 0
        self.n_compiles = 0
        self._steady = deque(maxlen=steady_window)

    def steady_p50(self) -> float | None:
        if not self._steady:
            return None
        ordered = sorted(self._steady)
        return ordered[len(ordered) // 2]

    def __call__(self, *args, **kwargs):
        before = _cache_size(self.fn)
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        wall = time.perf_counter() - t0
        self.n_calls += 1
        after = _cache_size(self.fn)
        if after is not None and before is not None:
            compiled = after > before
        else:
            # no cache probe: the first call is the one that compiles
            compiled = self.n_calls == 1
        if compiled:
            self.n_compiles += 1
            get_emitter().emit(
                "compile",
                name=self.name,
                n_compiles=self.n_compiles,
                wall_s=wall,
                call_index=self.n_calls,
                steady_p50_s=self.steady_p50(),
            )
        else:
            self._steady.append(wall)
        return out


class CompileTracker:
    """Registry of tracked compiled functions for one trainer/run.

    ``wrap(name, fn)`` returns a drop-in callable; compile counts
    accumulate per name even when a builder is re-invoked (scan-burst
    variants, precrop retirement), so ``counts()`` is the run's honest
    compile inventory.
    """

    def __init__(self):
        self._fns: dict[str, _TrackedFn] = {}

    def wrap(self, name: str, fn):
        tracked = self._fns.get(name)
        if tracked is None or tracked.fn is not fn:
            tracked = _TrackedFn(name, fn)
            prev = self._fns.get(name)
            if prev is not None:
                # same logical step rebuilt (fresh executable): carry the
                # cumulative compile count forward
                tracked.n_compiles = prev.n_compiles
                tracked.n_calls = prev.n_calls
            self._fns[name] = tracked
        return tracked

    def note_compile(self, name: str, wall_s: float) -> None:
        """Account one build that happened OUTSIDE a wrapped call — the
        AOT registry compiling an entrypoint up front (compile/registry).
        The later ``wrap`` of the precompiled fn under the same name
        carries this count forward, so ``counts()`` stays the run's honest
        inventory whether an executable was built lazily or ahead of
        time; precompiled dispatches themselves can never re-count (their
        lowering-cache probe is a constant)."""
        tracked = self._fns.get(name)
        if tracked is None:
            tracked = self._fns[name] = _TrackedFn(name, fn=None)
        tracked.n_compiles += 1
        get_emitter().emit(
            "compile",
            name=name,
            n_compiles=tracked.n_compiles,
            wall_s=wall_s,
            call_index=tracked.n_calls,
            steady_p50_s=tracked.steady_p50(),
        )

    def counts(self) -> dict[str, int]:
        return {name: t.n_compiles for name, t in self._fns.items()}

    def total_compiles(self) -> int:
        return sum(t.n_compiles for t in self._fns.values())


def timed_call(fn, *args, block: bool = False, **kwargs):
    """``(out, dispatch_s, block_s)`` — block_s is None unless ``block``.

    With ``block=False`` this adds only two clock reads to the call, so
    the hot loop can stay asynchronous between logging points; at the
    logging cadence the caller passes ``block=True`` and pays the one
    sync it was about to pay anyway for host-side stats.
    """
    import jax

    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    dispatch_s = time.perf_counter() - t0
    block_s = None
    if block:
        t1 = time.perf_counter()
        jax.block_until_ready(out)
        block_s = time.perf_counter() - t1
    return out, dispatch_s, block_s


def device_memory() -> tuple[list[dict], int | None]:
    """``(devices, host_rss_bytes)`` snapshot for a ``memory`` row."""
    import jax

    devices = []
    for d in jax.local_devices():
        stats = {}
        try:
            stats = d.memory_stats() or {}
        # graftlint: ok(swallow: backends without stats emit null fields in the memory row)
        except Exception:
            pass
        devices.append({
            "id": int(d.id),
            "platform": str(d.platform),
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
        })
    rss = None
    try:
        import resource

        # linux reports ru_maxrss in KiB
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    # graftlint: ok(swallow: host RSS probe; null field in the memory row is the record)
    except Exception:
        pass
    return devices, rss


def sample_memory(step: int | None = None, epoch: int | None = None) -> None:
    """Emit one ``memory`` row (per-device stats + host RSS)."""
    emitter = get_emitter()
    if not emitter.chief:
        return
    devices, rss = device_memory()
    fields = {"devices": devices, "host_rss_bytes": rss}
    if step is not None:
        fields["step"] = int(step)
    if epoch is not None:
        fields["epoch"] = int(epoch)
    emitter.emit("memory", **fields)
