"""Incident correlator: alerts and flight dumps assemble their own evidence.

When something fires — a burn-rate alert (obs/alerts.py), a flight-data
dump (resil/flight.py), or an injected chaos fault — the on-call question
is always the same: *what else was happening?* This module answers it
automatically. The manager taps the telemetry row stream
(``obs.emit.add_row_tap``), keeps a bounded ring of recent rows, and on a
trigger walks that ring backward to assemble a causal timeline: fault /
retry / breaker transitions, scale_decision rows (whose evidence carries
exemplar trace ids), scene_load / scene_evict residency moves, tenant
denials, shed decisions, replica lifecycle — plus the spans matching any
exemplar trace id, so the incident links directly into the traces that
missed their SLO.

Each incident is written atomically (tmp + rename, the flight-dump
discipline) as ``incident_<id>.json`` next to the run's telemetry plus a
human-readable ``incident_<id>.md``, and follows an
open -> mitigated -> resolved lifecycle tied to alert clearing: the
triggering alert resolving mitigates the incident; a quiet period (or an
explicit :meth:`resolve_open` from the chaos harness) resolves it. Every
lifecycle transition emits a schema-versioned ``incident`` telemetry row,
so tlm_report can gate on unresolved incidents without reading dumps.

With ``open_on_fault=True`` (the chaos harness), injected fault rows
themselves open incidents — every chaos scenario self-documents, and a
clean run produces zero incident files by construction.

Dependency direction: obs never imports resil — the *caller* (serve.py,
chaos_run) wires ``resil.flight.add_dump_listener(mgr.on_flight_dump)``.
Host-side pure Python, injectable clock, thread-safe (RLock: emitting an
``incident`` row from inside a row tap re-enters :meth:`_on_row` on the
same thread).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from .emit import add_row_tap, get_emitter, remove_row_tap

INCIDENT_VERSION = 1

# row kinds worth putting on an incident timeline, and the fields that
# make each one legible in the markdown summary
_TIMELINE_KINDS = {
    "fault": ("point", "fault", "mode"),
    "retry": ("point", "attempt", "outcome"),
    "breaker": ("point", "state", "failures"),
    "scale_decision": ("action", "reason", "n_replicas", "attainment"),
    "scene_load": ("scene", "source", "load_s", "bytes"),
    "scene_evict": ("scene", "reason", "bytes"),
    "tenant_admit": ("tenant", "decision", "reason"),
    "serve_shed": ("reason", "queue_depth"),
    "replica": ("replica", "state", "reason"),
    "router": ("event", "replica"),
    "alert": ("name", "state", "severity", "value"),
}

_STATUSES = ("open", "mitigated", "resolved")
_TRIGGERS = ("alert", "flight_dump", "fault")


class IncidentManager:
    """Correlates telemetry into atomic incident dumps with a lifecycle.

    ``out_dir`` receives ``incident_<id>.json`` / ``.md``. ``clock``
    must be the same timebase as row ``t`` stamps (wall time) — tests
    inject a fake. ``coalesce_s`` merges triggers landing while an
    incident is already open (a breaker storm is one incident, not
    forty); ``lookback_s`` bounds the timeline walk; ``quiet_s`` is the
    auto-mitigate/auto-resolve quiet period :meth:`sweep` applies.
    """

    def __init__(self, out_dir: str, *, clock=time.time,
                 ring_size: int = 4096, lookback_s: float = 120.0,
                 coalesce_s: float = 60.0, quiet_s: float = 300.0,
                 open_on_fault: bool = False, replica: str = ""):
        self.out_dir = str(out_dir)
        self.clock = clock
        self.lookback_s = float(lookback_s)
        self.coalesce_s = float(coalesce_s)
        self.quiet_s = float(quiet_s)
        self.open_on_fault = bool(open_on_fault)
        self.replica = str(replica)
        self._ring: deque = deque(maxlen=int(ring_size))
        self._lock = threading.RLock()
        self._seq = 0
        self.incidents: list[dict] = []  # every incident, open or not

    # -- feeds ---------------------------------------------------------------

    def attach(self) -> "IncidentManager":
        add_row_tap(self._on_row)
        return self

    def detach(self) -> None:
        remove_row_tap(self._on_row)

    def _on_row(self, row: dict) -> None:
        kind = row.get("kind")
        if kind == "incident":
            return  # our own lifecycle rows never feed timelines
        with self._lock:
            if kind in _TIMELINE_KINDS or kind == "span":
                self._ring.append(row)
            if self.open_on_fault and kind == "fault":
                point = str(row.get("point", ""))
                fault = str(row.get("fault", ""))
                self._trigger(
                    trigger="fault",
                    detail=f"injected fault {fault} at {point}",
                    fault_hint=f"{point}:{fault}")

    def on_alert(self, event: dict) -> None:
        """AlertEngine listener: fire opens/coalesces, clear mitigates."""
        name = str(event.get("name", ""))
        if event.get("state") == "firing":
            with self._lock:
                inc = self._trigger(
                    trigger="alert",
                    alert=name,
                    severity=str(event.get("severity", "")),
                    detail=(f"alert {name} firing "
                            f"(value={event.get('value')}, "
                            f"threshold={event.get('threshold')})"))
                if name not in inc["alerts"]:
                    inc["alerts"].append(name)
                    self._write(inc)
            return
        # resolved: mitigate incidents that no longer have a firing alert
        with self._lock:
            for inc in self.incidents:
                if inc["status"] != "open" or name not in inc["alerts"]:
                    continue
                inc["alerts"] = [a for a in inc["alerts"] if a != name]
                if not inc["alerts"]:
                    self._transition(inc, "mitigated",
                                     f"alert {name} resolved")

    def on_flight_dump(self, reason: str, path: str, detail: str = "") -> None:
        """resil.flight dump listener (wired by the caller, not here)."""
        with self._lock:
            inc = self._trigger(
                trigger="flight_dump",
                detail=f"flight dump {reason}: {detail}".strip(": "))
            if path and path not in inc["flight_dumps"]:
                inc["flight_dumps"].append(str(path))
                self._write(inc)

    # -- lifecycle -----------------------------------------------------------

    def _current_open(self, now: float) -> dict | None:
        for inc in reversed(self.incidents):
            if inc["status"] == "open" and \
                    now - inc["last_event_t"] <= self.coalesce_s:
                return inc
        return None

    def _trigger(self, *, trigger: str, detail: str, alert: str = "",
                 severity: str = "", fault_hint: str = "") -> dict:
        now = self.clock()
        inc = self._current_open(now)
        if inc is not None:
            # coalesce: refresh the timeline, note the new trigger
            inc["last_event_t"] = now
            inc["detail"] += f"; {detail}"
            if fault_hint and fault_hint not in inc["fault_points"]:
                inc["fault_points"].append(fault_hint)
            self._assemble(inc, now)
            self._write(inc)
            return inc
        self._seq += 1
        iid = f"inc-{self._seq:04d}"
        inc = {
            "incident_version": INCIDENT_VERSION,
            "incident_id": iid,
            "status": "open",
            "trigger": trigger,
            "alert": alert,
            "severity": severity,
            "detail": detail,
            "replica": self.replica,
            "opened_t": now,
            "last_event_t": now,
            "mitigated_t": None,
            "resolved_t": None,
            "alerts": [alert] if alert else [],
            "flight_dumps": [],
            "fault_points": [fault_hint] if fault_hint else [],
            "trace_ids": [],
            "timeline": [],
            "n_events": 0,
            "path": os.path.join(self.out_dir,
                                 f"incident_{self._seq:04d}.json"),
        }
        self.incidents.append(inc)
        self._assemble(inc, now)
        self._write(inc)
        self._emit(inc)
        return inc

    def _transition(self, inc: dict, status: str, why: str) -> None:
        now = self.clock()
        inc["status"] = status
        inc["detail"] += f"; {why}"
        if status == "mitigated":
            inc["mitigated_t"] = now
        elif status == "resolved":
            inc["resolved_t"] = now
            if inc["mitigated_t"] is None:
                inc["mitigated_t"] = now
            self._assemble(inc, now)  # final timeline includes recovery
        self._write(inc)
        self._emit(inc)

    def sweep(self, now: float | None = None) -> None:
        """Quiet-period automation: an open incident whose alerts have
        all cleared mitigates after ``quiet_s`` without new triggers; a
        mitigated one resolves after another quiet period."""
        now = self.clock() if now is None else now
        with self._lock:
            for inc in self.incidents:
                if inc["status"] == "open" and not inc["alerts"] and \
                        now - inc["last_event_t"] >= self.quiet_s:
                    self._transition(inc, "mitigated",
                                     f"quiet for {self.quiet_s:g}s")
                elif inc["status"] == "mitigated" and \
                        now - (inc["mitigated_t"] or now) >= self.quiet_s:
                    self._transition(inc, "resolved",
                                     f"quiet for {self.quiet_s:g}s")

    def resolve_open(self, detail: str = "operator resolve") -> int:
        """Force-resolve everything still open/mitigated (the chaos
        harness calls this once its recovery checks pass)."""
        n = 0
        with self._lock:
            for inc in self.incidents:
                if inc["status"] != "resolved":
                    self._transition(inc, "resolved", detail)
                    n += 1
        return n

    # -- evidence assembly ---------------------------------------------------

    def _assemble(self, inc: dict, now: float) -> None:
        """Walk the row ring backward into a causal timeline (lock held)."""
        cutoff = now - self.lookback_s
        events: list[dict] = []
        trace_ids: list[str] = list(inc["trace_ids"])
        fault_points: list[str] = list(inc["fault_points"])
        spans_by_trace: dict[str, list[dict]] = {}
        for row in self._ring:
            t = float(row.get("t", now))
            if t < cutoff:
                continue
            kind = row.get("kind")
            if kind == "span":
                tid = row.get("trace_id")
                if isinstance(tid, str):
                    spans_by_trace.setdefault(tid, []).append(row)
                continue
            if kind not in _TIMELINE_KINDS:
                continue
            ev = {"t": t, "kind": kind}
            for f in _TIMELINE_KINDS[kind]:
                if f in row:
                    ev[f] = row[f]
            events.append(ev)
            if kind == "fault":
                fp = f"{row.get('point', '')}:{row.get('fault', '')}"
                if fp not in fault_points:
                    fault_points.append(fp)
            elif kind == "scale_decision":
                # evidence-linked decisions carry exemplar trace ids
                ex = row.get("evidence") or {}
                for tid in (ex.get("exemplar_trace_ids") or []):
                    if isinstance(tid, str) and tid not in trace_ids:
                        trace_ids.append(tid)
        # pull the spans of any exemplar trace onto the timeline
        for tid in trace_ids:
            for srow in spans_by_trace.get(tid, []):
                events.append({
                    "t": float(srow.get("t", now)), "kind": "span",
                    "trace_id": tid, "name": srow.get("name"),
                    "dur_s": srow.get("dur_s"),
                    "status": srow.get("status"),
                })
        events.sort(key=lambda e: e["t"])
        inc["timeline"] = events[-512:]
        inc["n_events"] = len(inc["timeline"])
        inc["trace_ids"] = trace_ids[:64]
        inc["fault_points"] = fault_points

    # -- persistence ---------------------------------------------------------

    def _write(self, inc: dict) -> None:
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            path = inc["path"]
            tmp = path + ".tmp"
            # graftlint: ok(blocking-under-lock: incident persistence is a rare control-plane event — alert fire / flight dump — never the serve dispatch path; writing under the lock serializes dump files against concurrent triggers)
            with open(tmp, "w") as fh:
                json.dump(inc, fh, indent=1, default=str)
            os.replace(tmp, path)
            md = path[:-len(".json")] + ".md"
            tmp = md + ".tmp"
            # graftlint: ok(blocking-under-lock: same rare control-plane write as the json dump above)
            with open(tmp, "w") as fh:
                fh.write(self._markdown(inc))
            os.replace(tmp, md)
        except OSError:
            # graftlint: ok(swallow: incident persistence must never take down the serving path; the in-memory record survives)
            pass

    def _emit(self, inc: dict) -> None:
        # graftlint: ok(blocking-under-lock: incident lifecycle rows are emitted at most a handful of times per incident; the lock orders them against the row tap feeding the ring)
        get_emitter().emit(
            "incident",
            incident_id=inc["incident_id"],
            status=inc["status"],
            trigger=inc["trigger"],
            alert=inc["alert"],
            severity=inc["severity"],
            n_events=inc["n_events"],
            fault_points=list(inc["fault_points"]),
            trace_ids=list(inc["trace_ids"]),
            path=inc["path"],
            opened_t=inc["opened_t"],
            resolved_t=inc["resolved_t"],
            detail=inc["detail"][-500:],
        )

    def _markdown(self, inc: dict) -> str:
        lines = [
            f"# Incident {inc['incident_id']} — {inc['status']}",
            "",
            f"- **trigger**: {inc['trigger']}"
            + (f" (alert `{inc['alert']}`, {inc['severity']})"
               if inc["alert"] else ""),
            f"- **opened**: t={inc['opened_t']:.3f}"
            + (f", resolved t={inc['resolved_t']:.3f}"
               if inc["resolved_t"] else ""),
            f"- **detail**: {inc['detail']}",
        ]
        if inc["fault_points"]:
            lines.append(
                "- **fault points**: " + ", ".join(
                    f"`{p}`" for p in inc["fault_points"]))
        if inc["trace_ids"]:
            lines.append(
                "- **exemplar traces**: " + ", ".join(
                    f"`{t}`" for t in inc["trace_ids"][:8]))
        if inc["flight_dumps"]:
            lines.append(
                "- **flight dumps**: " + ", ".join(inc["flight_dumps"]))
        lines += ["", "## Timeline", ""]
        for ev in inc["timeline"]:
            extras = ", ".join(f"{k}={v}" for k, v in ev.items()
                               if k not in ("t", "kind"))
            lines.append(f"- `t={ev['t']:.3f}` **{ev['kind']}** {extras}")
        lines.append("")
        return "\n".join(lines)

    def stats(self) -> dict:
        with self._lock:
            by = {s: 0 for s in _STATUSES}
            for inc in self.incidents:
                by[inc["status"]] += 1
            return {"n_incidents": len(self.incidents), **by,
                    "ring": len(self._ring)}


def validate_incident_dump(path: str) -> list[str]:
    """Schema problems in an incident dump file ([] == valid) — the
    check_telemetry_schema treatment flight dumps already get."""
    problems: list[str] = []
    try:
        with open(path) as fh:
            inc = json.load(fh)
    except (OSError, ValueError) as e:
        return [f"unreadable: {e}"]
    if not isinstance(inc, dict):
        return ["not a JSON object"]
    if inc.get("incident_version") != INCIDENT_VERSION:
        problems.append(
            f"incident_version {inc.get('incident_version')!r} != "
            f"{INCIDENT_VERSION}")
    for key, typ in (("incident_id", str), ("status", str),
                     ("trigger", str), ("detail", str),
                     ("opened_t", (int, float))):
        if not isinstance(inc.get(key), typ):
            problems.append(f"missing/mistyped field: {key}")
    if inc.get("status") not in _STATUSES:
        problems.append(f"bad status: {inc.get('status')!r}")
    if inc.get("trigger") not in _TRIGGERS:
        problems.append(f"bad trigger: {inc.get('trigger')!r}")
    for key in ("alerts", "fault_points", "trace_ids", "timeline",
                "flight_dumps"):
        if not isinstance(inc.get(key), list):
            problems.append(f"missing/mistyped list: {key}")
    if inc.get("status") == "resolved" and \
            not isinstance(inc.get("resolved_t"), (int, float)):
        problems.append("resolved incident without resolved_t")
    for i, ev in enumerate(inc.get("timeline") or []):
        if not isinstance(ev, dict) or "t" not in ev or "kind" not in ev:
            problems.append(f"timeline[{i}] missing t/kind")
            break
    return problems
