"""Versioned row schema for telemetry and bench JSONL files.

One place declares what a row of ``telemetry.jsonl`` looks like, so the
emitter, the report CLI, and ``scripts/check_telemetry_schema.py`` can
never drift apart (the way the hand-rolled ``BENCH_*.jsonl`` shapes did —
three incompatible row families across ten scripts).

Telemetry rows share three stamped fields:

* ``v``    — schema version (``SCHEMA_VERSION``)
* ``kind`` — one of ``ROW_KINDS``
* ``t``    — unix seconds at emit time

plus the per-kind fields declared in ``ROW_KINDS`` below. Bench rows
(``BENCH_*.jsonl``, ``PROFILE_STEP.jsonl``, quality traces) predate the
schema and are validated structurally by :func:`validate_bench_row`.
"""

from __future__ import annotations

SCHEMA_VERSION = 1

_NUM = (int, float)
_OPT_NUM = (int, float, type(None))

# kind -> (required fields, optional fields); value = allowed types.
# dict/list values are shallow-checked (JSON-serializable containers).
ROW_KINDS: dict[str, tuple[dict, dict]] = {
    "run_meta": (
        {
            "run_id": (str,),
            "component": (str,),
            "config_hash": (str,),
            "process_index": _NUM,
            "process_count": _NUM,
            "device_count": _NUM,
            "local_device_count": _NUM,
            "platform": (str,),
        },
        {
            "task": (str,),
            "scene": (str,),
            "exp_name": (str,),
            "device_kind": (str,),
            "argv": (list,),
            "jax_version": (str,),
        },
    ),
    "step": (
        {"step": _NUM},
        {
            "epoch": _NUM,
            "k": _NUM,                 # burst size the row covers
            "step_time_s": _NUM,       # per-step wall time (window median)
            "step_time_avg_s": _NUM,
            "data_time_s": _NUM,
            "dispatch_s": _NUM,        # host time to enqueue the burst
            "block_s": _NUM,           # device time waited at the sync point
            "lr": _NUM,
            "max_mem_mb": _OPT_NUM,
            "stats": (dict,),          # loss/psnr/... scalars
        },
    ),
    "epoch": (
        {"epoch": _NUM},
        {"steps": _NUM, "wall_s": _NUM, "steps_per_sec": _NUM},
    ),
    "eval": (
        {"metrics": (dict,)},
        {"step": _NUM, "epoch": _NUM, "prefix": (str,), "n_images": _NUM,
         "mean_net_time_s": _NUM, "fps": _NUM},
    ),
    "compile": (
        {"name": (str,), "n_compiles": _NUM, "wall_s": _NUM},
        # cap_old/cap_new: packed-eval stream cap escalation (train/ngp.py
        # render_image) — the rebuild rides a compile row so
        # `tlm_report --diff` flags an escalating run as a regression.
        # phase/skipped_reason: AOT pipeline markers (compile/artifacts.py)
        # — a serialization skip is visible, not silent
        {"call_index": _NUM, "steady_p50_s": _OPT_NUM, "step": _OPT_NUM,
         "cap_old": _NUM, "cap_new": _NUM,
         "phase": (str,), "skipped_reason": (str,)},
    ),
    "memory": (
        {"devices": (list,)},
        {"step": _NUM, "epoch": _NUM, "host_rss_bytes": _OPT_NUM},
    ),
    "heartbeat": (
        {"wall_s": _NUM},
        {"step": _NUM, "epoch": _NUM},
    ),
    # -- serving rows (nerf_replication_tpu/serve) ---------------------------
    # one per completed (or timed-out) render request: end-to-end latency,
    # the degradation tier it was served at, and whether the pose cache hit
    # tenant: which QoS tenant the request billed against (fleet/qos.py;
    # absent on tenant-less requests)
    "serve_request": (
        {"latency_s": _NUM, "n_rays": _NUM, "tier": (str,)},
        {"queue_s": _NUM, "status": (str,), "cache_hit": (bool, int),
         "n_buckets": _NUM, "bucket_rays": _NUM, "scene": (str,),
         "tenant": (str,)},
    ),
    # one per coalesced engine dispatch: how many requests/rays rode the
    # batch and how full the padded buckets were (occupancy = real/padded).
    # scene: which registry scene the batch rendered (multi-tenant serving;
    # absent on default-scene batches)
    "serve_batch": (
        {"n_requests": _NUM, "n_rays": _NUM, "occupancy": _NUM},
        {"tier": (str,), "render_s": _NUM, "queue_depth": _NUM,
         "bucket_rays": _NUM, "scene": (str,), "tenant": (str,)},
    ),
    # -- fleet rows (nerf_replication_tpu/fleet, docs/fleet.md) --------------
    # one per scene materialization onto the device: how it arrived
    # (source: cold = a request blocked on the disk load, prefetch = the
    # background thread had it ready, staging = re-promoted from the
    # host-RAM tier — a device_put, no disk walk, publish = a hot-update
    # swap), the REAL byte footprint charged against fleet.hbm_budget_mb,
    # and the residency set after commit. staging/staging_bytes: host-RAM
    # tier occupancy after commit (tiered ladder only, fleet/ladder.py)
    # total_bytes/param_shards: model-parallel serving (scale.mesh_shape
    # with M > 1) — ``bytes`` is then the per-device shard figure and
    # ``total_bytes`` the whole scene across its ``param_shards`` shards
    # (the two coincide and param_shards == 1 for replicated scenes)
    "scene_load": (
        {"scene": (str,), "bytes": _NUM, "source": (str,)},
        {"load_s": _NUM, "resident": _NUM, "resident_bytes": _NUM,
         "staging": _NUM, "staging_bytes": _NUM,
         "total_bytes": _NUM, "param_shards": _NUM},
    ),
    # one per eviction at either residency tier. reason: budget (one-level
    # manager, drop to admit), demoted (HBM -> host-RAM staging, the
    # arrays survive), lru (dropped with no staged copy / staging LRU),
    # ttl (staged copy expired), manual (operator evict). tier: which
    # tier lost the scene (hbm | staging; absent = hbm, pre-ladder rows)
    "scene_evict": (
        {"scene": (str,), "bytes": _NUM},
        {"reason": (str,), "resident": _NUM, "resident_bytes": _NUM,
         "tier": (str,), "staging": _NUM, "staging_bytes": _NUM},
    ),
    # one per ray-bank placement onto the data-parallel mesh
    # (parallel/sharding.py shard_bank): the bank truncates to a
    # mesh-divisible size, and the dropped-tail count rides a row — the
    # "no silent caps" rule. n_dropped == 0 rows are emitted too, so the
    # report can prove the cap never bit.
    "bank_shard": (
        {"n_rays": _NUM, "n_kept": _NUM, "n_dropped": _NUM},
        {"n_shards": _NUM},
    ),
    # one per load-shed decision: the backlog that triggered a degraded
    # tier (tenant: the per-tenant breaker forced the degrade, fleet/qos.py)
    "serve_shed": (
        {"tier": (str,), "queue_depth": _NUM},
        {"n_requests": _NUM, "n_rays": _NUM, "tenant": (str,)},
    ),
    # -- QoS rows (nerf_replication_tpu/fleet/qos.py) ------------------------
    # one per admission decision at the tenant token bucket: admit (tokens
    # remained) or deny (quota exhausted -> TenantQuotaError, HTTP 429).
    # quota_remaining is the bucket level AFTER the decision.
    "tenant_admit": (
        {"tenant": (str,), "decision": (str,)},
        {"quota_remaining": _NUM, "rate": _NUM, "burst": _NUM,
         "retry_after_s": _NUM},
    ),
    # one per scene hot-update attempt (fleet/publish.py): version N ->
    # N+1 swap with pinned-lease drain. status: ok | torn (checksum fail,
    # version N kept serving) | error. drain_ms: how long in-flight
    # leases on N held the swap.
    "scene_publish": (
        {"scene": (str,), "from_version": _NUM, "to_version": _NUM},
        {"drain_ms": _NUM, "bytes": _NUM, "status": (str,)},
    ),
    # -- traversal (renderer/packed_march.py hierarchical coarse-DDA) --------
    # one per eval image (or bench arm): rows entering the global sort vs
    # occupied rows surviving the fine test — the sweep-efficiency ratio
    # tlm_report summarizes and --diff gates against regression
    "march": (
        {"candidates_in": _NUM, "samples_out": _NUM},
        {"mode": (str,), "surface": (str,), "coarse_occ": _NUM,
         "fine_occ": _NUM, "overflow_frac": _NUM, "truncated": _NUM,
         "n_rays": _NUM, "step": _NUM},
    ),
    # -- learned sampling (renderer/sampling.py proposal resampler) ----------
    # one per validation pass / bench arm: the fine-MLP evaluations per ray
    # the active sampling mode costs (the budget the proposal network
    # exists to cut) next to the quality it bought. tlm_report summarizes
    # these and --diff gates on a grown fine-eval budget.
    "sample": (
        {"mode": (str,), "fine_evals_per_ray": _NUM},
        {"n_proposal": _NUM, "n_fine": _NUM, "psnr": _NUM, "step": _NUM,
         "surface": (str,), "loss_prop": _NUM, "rays_per_s": _NUM},
    ),
    # -- resilience rows (nerf_replication_tpu/resil) ------------------------
    # one per fault at a named fault point: injected (FaultPlan chaos) or
    # detected in the wild (checksum mismatch, torn dir, worker crash).
    # `fault` is the fault kind: io_error | truncate | latency | nan_loss |
    # kill | checksum | torn | crash
    "fault": (
        {"point": (str,), "fault": (str,)},
        {"path": (str,), "delay_s": _NUM, "hit": _NUM,
         "injected": (bool, int), "step": _NUM, "detail": (str,)},
    ),
    # one per retry decision at a load path (resil/retry.py): status is
    # retry (backing off), ok (recovered after >=1 failure), or exhausted
    # (gave up — the unrecovered-fault count tlm_report --diff gates on)
    "retry": (
        {"point": (str,), "attempt": _NUM, "status": (str,)},
        {"error": (str,), "backoff_s": _NUM, "wall_s": _NUM},
    ),
    # one per circuit-breaker state transition (resil/breaker.py): the
    # serve engine degrading through shed tiers / fast-failing under
    # repeated dispatch failures
    "breaker": (
        {"state": (str,)},
        {"point": (str,), "failures": _NUM, "consecutive": _NUM,
         "tier": (str,), "retry_after_s": _NUM},
    ),
    # -- tracing rows (nerf_replication_tpu/obs/trace.py) --------------------
    # one per finished span: a timed unit of work in the serve pipeline,
    # joinable into a per-request tree via (trace_id, parent_id). start_s
    # is on the tracer's clock (perf_counter), NOT unix time — only
    # differences and within-run ordering are meaningful. stage tags the
    # latency taxonomy (queue | acquire | load | dispatch | device |
    # scatter | route | failover); joined/source attribute prefetch joins
    # in fleet residency. remote_parent marks a span whose parent ctx was
    # restored from a Traceparent header (the cross-process join point —
    # trace_view --fleet resolves it in the merged file set, so it is not
    # an orphan); replica names the process that emitted the span.
    "span": (
        {"trace_id": (str,), "span_id": (str,), "name": (str,),
         "start_s": _NUM, "dur_s": _NUM},
        {"parent_id": (str, type(None)), "thread": (str,), "stage": (str,),
         "tier": (str,), "scene": (str, type(None)), "status": (str,),
         "tenant": (str, type(None)), "n_rays": _NUM, "n_requests": _NUM,
         "joined": (str,), "source": (str,), "family": (str,),
         "bucket": _NUM, "queue_depth": _NUM, "detail": (str,),
         "remote_parent": (bool, int), "replica": (str,)},
    ),
    # one per live-aggregation dump (obs/metrics.py snapshot()): the
    # counters/gauges/histograms behind GET /metrics, serialized for
    # offline diffing; slo is the /healthz attainment view at dump time
    "metrics_snapshot": (
        {"counters": (dict,), "gauges": (dict,), "histograms": (dict,)},
        {"slo": (dict,)},
    ),
    # -- scale-out rows (nerf_replication_tpu/scale, docs/scaleout.md) -------
    # one per replica lifecycle transition: spawn (supervisor asked for
    # capacity), ready (warm-up done — warm_source/total_compiles record
    # whether the shared artifact store made it a zero-build start),
    # drain (no new admissions; queued work rendering out), retire
    # (drain complete; detail carries the in-flight failure count, which
    # the drain-before-retire contract holds at 0), dead (crash or
    # missed heartbeats)
    "replica": (
        {"replica": (str,), "event": (str,)},
        {"state": (str,), "load": _NUM, "warm_source": (str,),
         "total_compiles": _NUM, "n_ready": _NUM, "scenes": (list,),
         "detail": (str,)},
    ),
    # one per NON-routine router event (steady-state dispatches ride
    # metrics counters, not rows): failover (a replica refused or died
    # mid-submit; n_candidates = remaining options), dead (marked by the
    # heartbeat sweep), drain (n_failed must be 0), no_replica (total
    # outage — every candidate gone)
    "router": (
        {"event": (str,)},
        {"replica": (str,), "scene": (str, type(None)),
         "n_candidates": _NUM, "load": _NUM, "n_failed": _NUM,
         "detail": (str,)},
    ),
    # one per supervisor evaluation window: the closed loop's reasoning
    # (action: out | in | replace | hold) against the SLO attainment and
    # tenant deny-rate signals, with the hysteresis streak that led to it.
    # evidence links the decision to what the loop saw: the attainment
    # series, per-replica queue depths, the deny rate, and exemplar trace
    # ids of SLO-missing requests (deep-checked by validate_row) — every
    # out/in must name its evidence, not just assert a miss.
    "scale_decision": (
        {"action": (str,), "reason": (str,), "n_replicas": _NUM},
        {"attainment": _OPT_NUM, "deny_rate": _NUM, "streak": _NUM,
         "replica": (str,), "evidence": (dict,)},
    ),
    # one per placement replan (scale/placement.py): the versioned
    # scene->replicas plan the router consults before passive affinity.
    # version bumps only when the assignment changes (identical inputs
    # => identical plan); moves_by_kind counts the ordered rebalance
    # deltas (publish | prefetch | demote); converged means the move
    # list is empty and convergence_s (present only on the plan that
    # closed it) is the wall time from first unconverged plan to here.
    # evidence carries the scene-heat snapshot the plan acted on
    # (deep-checked by validate_row).
    "placement_plan": (
        {"version": _NUM, "reason": (str,), "n_scenes": _NUM,
         "n_replicas": _NUM, "n_moves": _NUM, "moves_by_kind": (dict,),
         "converged": (bool,)},
        {"convergence_s": _NUM, "evidence": (dict,),
         # the router's cumulative planned/unplanned dispatch counters
         # at replan time — the unplanned share tlm_report gates on
         "planned_hits": _NUM, "unplanned": _NUM},
    ),
    # one per APPLIED placement move (the executor's write-back; the
    # move kind lives in "move" — "kind" is the row kind): prefetch/
    # demote ride the fleet ladder's tier transitions, publish rides
    # the scene publisher — never a raw evict of a pinned lease (a
    # pinned refusal lands here as ok=false, detail=pinned, and the
    # tlm_report --diff gate counts it).
    "placement_move": (
        {"version": _NUM, "move": (str,), "scene": (str,),
         "replica": (str,), "ok": (bool,)},
        {"detail": (str,)},
    ),
    # -- ops-intelligence rows (obs/alerts.py / obs/incidents.py /
    # obs/capacity.py, docs/observability.md) --------------------------------
    # one per alert state TRANSITION (firing | resolved), not per
    # evaluation: the burn-rate engine's multi-window verdict against one
    # signal (slo | deny | breaker | orphan_spans | staging_thrash).
    # burn_fast/burn_slow are the short/long-window burn rates at the
    # transition (burn-rate alerts only); value is the raw signal level
    # for direct-condition alerts. window_s names the SHORT window.
    "alert": (
        {"name": (str,), "state": (str,), "severity": (str,),
         "signal": (str,)},
        {"burn_fast": _OPT_NUM, "burn_slow": _OPT_NUM, "value": _OPT_NUM,
         "threshold": _NUM, "window_s": _NUM, "replica": (str,),
         "detail": (str,)},
    ),
    # one per incident lifecycle transition (open | mitigated | resolved):
    # the correlator's record that a timeline dump landed at `path`.
    # trigger: alert | flight_dump | fault. fault_points/trace_ids are
    # what the assembled timeline named (the chaos assertion's join keys).
    "incident": (
        {"incident_id": (str,), "status": (str,), "trigger": (str,)},
        {"alert": (str,), "severity": (str,), "n_events": _NUM,
         "fault_points": (list,), "trace_ids": (list,), "path": (str,),
         "opened_t": _NUM, "resolved_t": _OPT_NUM, "detail": (str,)},
    ),
    # one per capacity-ledger snapshot (obs/capacity.py): the per-scene
    # heat/byte accounting the placement planner replays. scenes maps
    # scene id -> {requests_per_s, rays_per_s, bytes, cold_loads,
    # repromotions}; device_share maps executable family -> device-time
    # share over the window; byte fields are the replica's HBM/staging
    # watermarks (current + peak-since-last-snapshot).
    "capacity_snapshot": (
        {"replica": (str,), "scenes": (dict,)},
        {"hbm_bytes": _NUM, "hbm_peak_bytes": _NUM, "staging_bytes": _NUM,
         "staging_peak_bytes": _NUM, "window_s": _NUM,
         "device_share": (dict,), "requests_per_s": _NUM,
         "rays_per_s": _NUM, "cold_loads": _NUM, "repromotions": _NUM},
    ),
    # -- static analysis (nerf_replication_tpu/analysis) ---------------------
    # one per scripts/graftlint.py run: finding counts split new-vs-baseline
    # so the report can watch the baseline shrink (and flag a lint gate
    # that started failing)
    "lint_run": (
        {"n_findings": _NUM, "n_new": _NUM, "n_baselined": _NUM,
         "duration_s": _NUM},
        {"rule_counts": (dict,), "n_files": _NUM, "exit_code": _NUM,
         "baseline_path": (str,), "rule_times_s": (dict,),
         "new_rule_counts": (dict,)},
    ),
    # one per runtime lock-order sanitizer teardown (analysis/sanitizer.py
    # LockOrderRecorder.emit): the observed per-thread acquisition DAG over
    # the instrumented fleet locks — acyclic=False carries the cycle the
    # static R10 rule would have had to prove
    "lock_order": (
        {"n_locks": _NUM, "n_edges": _NUM, "acyclic": (bool,)},
        {"n_threads": _NUM, "cycle": (list,), "locks": (list,),
         "source": (str,)},
    ),
}


def validate_row(row) -> list[str]:
    """Errors for one telemetry row (empty list = valid)."""
    if not isinstance(row, dict):
        return [f"row is {type(row).__name__}, not an object"]
    errors = []
    v = row.get("v")
    if not isinstance(v, int):
        errors.append("missing/non-int schema version field 'v'")
    elif v > SCHEMA_VERSION:
        errors.append(f"schema version {v} is newer than {SCHEMA_VERSION}")
    kind = row.get("kind")
    if kind not in ROW_KINDS:
        return errors + [f"unknown kind {kind!r}"]
    if not isinstance(row.get("t"), _NUM):
        errors.append("missing/non-numeric timestamp field 't'")
    required, optional = ROW_KINDS[kind]
    for field, types in required.items():
        if field not in row:
            errors.append(f"{kind}: missing required field {field!r}")
        elif not isinstance(row[field], types):
            errors.append(
                f"{kind}: field {field!r} is {type(row[field]).__name__}"
            )
    known = {"v", "kind", "t", *required, *optional}
    for field, value in row.items():
        if field not in known:
            errors.append(f"{kind}: unknown field {field!r}")
        elif field in optional and not isinstance(value, optional[field]):
            errors.append(
                f"{kind}: field {field!r} is {type(value).__name__}"
            )
    if kind == "span":
        errors += _validate_span_ctx(row)
    elif kind == "scale_decision" and isinstance(row.get("evidence"), dict):
        errors += _validate_evidence(row["evidence"])
    elif kind == "alert":
        if row.get("state") not in ("firing", "resolved"):
            errors.append(
                f"alert: state {row.get('state')!r} not in firing|resolved")
        if row.get("severity") not in ("page", "ticket"):
            errors.append(
                f"alert: severity {row.get('severity')!r} not in page|ticket")
    elif kind == "incident":
        if row.get("status") not in ("open", "mitigated", "resolved"):
            errors.append(f"incident: status {row.get('status')!r} not in "
                          "open|mitigated|resolved")
        if row.get("trigger") not in ("alert", "flight_dump", "fault"):
            errors.append(f"incident: trigger {row.get('trigger')!r} not in "
                          "alert|flight_dump|fault")
    elif kind == "placement_move":
        if row.get("move") not in ("publish", "prefetch", "demote"):
            errors.append(f"placement_move: move {row.get('move')!r} not "
                          "in publish|prefetch|demote")
    elif kind == "placement_plan" and isinstance(row.get("evidence"), dict):
        errors += _validate_placement_evidence(row["evidence"])
    elif kind == "lock_order":
        if row.get("acyclic") is False and not row.get("cycle"):
            errors.append(
                "lock_order: acyclic=false must name the observed cycle")
        if row.get("acyclic") is True and row.get("cycle"):
            errors.append(
                "lock_order: acyclic=true contradicts a non-empty cycle")
    return errors


def _validate_span_ctx(row: dict) -> list[str]:
    """Deep checks for the propagated span context: ids must stay
    alphanumeric (the Traceparent header joins them with a dash), and a
    remote-parented span must actually name its parent."""
    errors = []
    for field in ("trace_id", "span_id"):
        val = row.get(field)
        if isinstance(val, str) and not val.isalnum():
            errors.append(
                f"span: {field} {val!r} is not alphanumeric "
                "(breaks Traceparent propagation)"
            )
    if row.get("remote_parent") and not isinstance(row.get("parent_id"), str):
        errors.append("span: remote_parent set but parent_id missing")
    return errors


def _validate_evidence(ev: dict) -> list[str]:
    """Deep checks for a scale_decision evidence block (the shape the
    supervisor commits and docs/scaleout.md documents)."""
    errors = []
    series = ev.get("attainment_series")
    if not isinstance(series, list) or not all(
            isinstance(a, (*_NUM, type(None))) for a in series):
        errors.append("scale_decision: evidence.attainment_series must be "
                      "a list of numbers/nulls")
    depths = ev.get("queue_depths")
    if not isinstance(depths, dict) or not all(
            isinstance(k, str) and isinstance(v, _NUM)
            for k, v in (depths or {}).items()):
        errors.append("scale_decision: evidence.queue_depths must map "
                      "replica id -> depth")
    if not isinstance(ev.get("deny_rate"), _NUM):
        errors.append("scale_decision: evidence.deny_rate must be numeric")
    tids = ev.get("exemplar_trace_ids")
    if not isinstance(tids, list) or not all(
            isinstance(t, str) and t.isalnum() for t in tids):
        errors.append("scale_decision: evidence.exemplar_trace_ids must be "
                      "a list of alphanumeric trace ids")
    known = {"attainment_series", "queue_depths", "deny_rate",
             "exemplar_trace_ids", "window"}
    for field in ev:
        if field not in known:
            errors.append(
                f"scale_decision: unknown evidence field {field!r}")
    return errors


def _validate_placement_evidence(ev: dict) -> list[str]:
    """Deep checks for a placement_plan evidence block: the scene-heat
    snapshot the plan acted on (scene id -> windowed rates)."""
    errors = []
    heat = ev.get("scene_heat")
    if not isinstance(heat, dict) or not all(
            isinstance(k, str) and isinstance(v, dict)
            and all(isinstance(x, _NUM) for x in v.values())
            for k, v in (heat or {}).items()):
        errors.append("placement_plan: evidence.scene_heat must map "
                      "scene id -> {rate: number}")
    for field in ev:
        if field != "scene_heat":
            errors.append(
                f"placement_plan: unknown evidence field {field!r}")
    return errors


# -- bench rows (pre-schema JSONL: BENCH_*.jsonl, PROFILE_STEP.jsonl) --------
# Three row families grew across the bench scripts; each is keyed by its
# discriminator. A row must belong to exactly one family (or be an error
# row), so a script that drifts shape fails the checker instead of
# producing a fourth silent family.

_BENCH_FAMILIES: dict[str, tuple[str, ...]] = {
    # bench.py / bench_sweep.py / bench_hash_step.py headline rows
    "metric": ("value",),
    # bench_ngp.py A/B arm rows
    "arm": ("rays_per_sec",),
    # bench_hash.py / bench_primitives*.py kernel-shootout rows
    "impl": (),
    # profile_step.py cost-analysis / timing rows
    "section": (),
    "xla_flops_per_step": (),
    "s_per_step": (),
    # quality_run.py trace headers / samples / eval-fps rows
    "run_start": (),
    "t_s": ("step",),
    "eval_fps_path": ("fps",),
    # bench_hash_step.py / bench_primitives*.py per-stage rows
    "stage": (),
    # scale_check.py render-path / executable-census rows
    "path": (),
    "chunked_fns": (),
    # scripts/serve_bench.py summary rows (BENCH_SERVE.jsonl): one row per
    # closed/open-loop run of the serving load generator
    "serve_mode": ("n_requests", "p50_ms"),
    # scripts/bench_cold_start.py rows (BENCH_COLDSTART.jsonl): one row per
    # child process measuring start→first-step / start→first-response under
    # a cold vs warm compile cache. NOTE: these rows must not carry any
    # earlier discriminator key above (bench_family is first-match).
    "coldstart": ("mode", "wall_s"),
    # scripts/bench_traversal.py rows (BENCH_TRAVERSAL.jsonl): one row per
    # (traversal arm × occupancy regime) — flat vs hierarchical vs fused
    # (``--fused``, the ops/fused_march.py mega-kernel arm, which also
    # carries the modeled peak_intermediate_bytes ledger and its
    # speedup_vs_staged_x headline) candidate stream size and throughput.
    # NOTE: must not carry any earlier discriminator key (bench_family is
    # first-match), hence the traversal-specific field names.
    "traversal_mode": ("grid_occ", "candidates_per_ray", "rays_per_s"),
    # scripts/serve_bench.py --scenes/--churn rows (BENCH_FLEET.jsonl): one
    # row per multi-scene churn run — residency churn (evictions, prefetch
    # hit rate) next to the scene-switch latency penalty (p95 of requests
    # that switched scenes vs stayed on one). NOTE: must not carry any
    # earlier discriminator key (bench_family is first-match), hence
    # fleet_mode rather than reusing serve_mode.
    "fleet_mode": ("n_scenes", "evictions", "prefetch_hit_rate",
                   "p95_same_ms", "p95_switch_ms"),
    # scripts/bench_sampling.py rows (BENCH_SAMPLING.jsonl): one row per
    # sampling arm (coarse_fine baseline vs proposal resampler) trained to
    # the same budget on the same scene — PSNR at matched training next to
    # the fine-MLP eval budget and render throughput. NOTE: must not carry
    # any earlier discriminator key (bench_family is first-match), hence
    # sampling_mode rather than reusing arm/metric.
    "sampling_mode": ("fine_evals_per_ray", "rays_per_s", "psnr"),
    # scripts/serve_bench.py --tenants rows (BENCH_QOS.jsonl): one row per
    # multi-tenant open-loop run — the quiet tenant's p95 while a hot
    # tenant runs saturated under weighted fair batching, against its
    # solo-run p95, plus the residency-ladder re-promotion vs cold-load
    # split. NOTE: must not carry any earlier discriminator key
    # (bench_family is first-match), hence qos_mode and the qos-specific
    # field names.
    "qos_mode": ("tenants", "hot_share", "quiet_p95_ms", "quiet_solo_p95_ms"),
    # scripts/serve_bench.py --replicas rows (BENCH_SCALE.jsonl): one row
    # per multi-replica open-loop run through a full scale-out/scale-in
    # cycle — attainment sagging under single-replica overload, the
    # supervisor's spawn (the fresh replica's warm source and compile
    # count record the shared-artifact warm start), recovery, and the
    # drain-before-retire scale-in. NOTE: must not carry any earlier
    # discriminator key (bench_family is first-match), hence scale_mode
    # and the scale-specific field names.
    "scale_mode": ("replicas_peak", "attainment_low",
                   "attainment_recovered", "scale_outs", "scale_ins"),
    # scripts/serve_bench.py --replicas --placement rows
    # (BENCH_SCALE.jsonl): one row per placement-planned fleet run —
    # plan convergence (final version, move mix, convergence wall
    # time), the hot scene's achieved replication width vs target, the
    # budget check (replicas over their HBM+staging budget must be 0),
    # the unplanned-dispatch share, and the kill-repair outcome (failed
    # in-flight requests and steady-state recompiles, both held at 0).
    # NOTE: must not carry any earlier discriminator key (bench_family
    # is first-match), hence placement_mode and the placement-specific
    # field names.
    "placement_mode": ("plan_version", "hot_width_target",
                       "hot_width_achieved", "over_budget_replicas",
                       "unplanned_share", "kill_repair_failed"),
    # scripts/bench_traversal.py --mesh-shape rows (BENCH_TRAVERSAL.jsonl):
    # one row per (replicated | sharded) arm of the model-parallel serving
    # bench — rays/s through the mesh_jit path next to the MEASURED
    # per-device peak param bytes (max over each leaf's addressable
    # shards), with the sharded arm carrying its byte-reduction headline
    # vs the replicated baseline and the allclose check against the
    # single-device render. NOTE: must not carry any earlier
    # discriminator key (bench_family is first-match), hence shard_mode
    # and the shard-specific field names.
    "shard_mode": ("mesh_shape", "rays_per_s", "param_bytes_per_device",
                   "param_bytes_total"),
}


def bench_family(row: dict) -> str | None:
    """The family discriminator present in ``row`` (None if no match)."""
    for key in _BENCH_FAMILIES:
        if key in row:
            return key
    return None


def validate_bench_row(row) -> list[str]:
    """Structural errors for one bench/quality JSONL row."""
    if not isinstance(row, dict):
        return [f"row is {type(row).__name__}, not an object"]
    if not row:
        return ["empty row"]
    family = bench_family(row)
    if family is None:
        if "error" in row:  # bare failure rows are legal in every family
            return []
        return [
            "row matches no known bench family (expected one of "
            + ", ".join(sorted(_BENCH_FAMILIES)) + ", or an 'error' row)"
        ]
    if "error" in row:
        return []
    missing = [f for f in _BENCH_FAMILIES[family] if f not in row]
    if missing:
        return [f"family {family!r}: missing fields {missing}"]
    return []
