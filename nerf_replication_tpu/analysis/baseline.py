"""Baseline workflow: accepted legacy findings, diffed on every run.

The gate (scripts/graftlint.py, tier-1's lint test) fails only on findings
NOT in the committed ``graftlint_baseline.json`` — so adopting the linter
didn't require fixing every legacy finding at once, while any NEW hazard
fails review immediately. Fixing a baselined finding shrinks the baseline
(``--write-baseline`` regenerates it; the diff shows the shrink).

A finding's identity deliberately excludes the line number: it is
``(rule, path, stripped source line, occurrence index)``, so unrelated
edits shifting a file don't churn the baseline, while touching the flagged
line itself (you're editing the hazard — re-judge it) or adding another
identical hazard does.

File schema (validated by scripts/check_telemetry_schema.py)::

    {"version": 1, "tool": "graftlint",
     "findings": [{"rule": ..., "path": ..., "snippet": ..., "index": 0,
                   "line": 123, "message": ...}, ...]}

``line``/``message`` are informational; only the identity fields match.
"""

from __future__ import annotations

import json
from collections import Counter

from .core import Finding

BASELINE_VERSION = 1
BASELINE_FILENAME = "graftlint_baseline.json"

_IDENTITY_FIELDS = ("rule", "path", "snippet", "index")


def fingerprints(findings: list[Finding]) -> list[tuple[Finding, tuple]]:
    """Pair each finding with its identity tuple; identical (rule, path,
    snippet) occurrences are disambiguated by order of appearance."""
    seen: Counter = Counter()
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        base = (f.rule, f.path.replace("\\", "/"), f.snippet)
        out.append((f, base + (seen[base],)))
        seen[base] += 1
    return out


def to_baseline(findings: list[Finding]) -> dict:
    rows = []
    for f, fp in fingerprints(findings):
        rows.append(
            {
                "rule": fp[0],
                "path": fp[1],
                "snippet": fp[2],
                "index": fp[3],
                "line": f.line,
                "message": f.message,
            }
        )
    return {"version": BASELINE_VERSION, "tool": "graftlint", "findings": rows}


def save_baseline(path: str, findings: list[Finding]) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(to_baseline(findings), fh, indent=1, sort_keys=True)
        fh.write("\n")


def load_baseline(path: str) -> set[tuple]:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    errors = validate_baseline_data(data)
    if errors:
        raise ValueError(f"{path}: " + "; ".join(errors[:3]))
    return {
        tuple(row[k] for k in _IDENTITY_FIELDS) for row in data["findings"]
    }


def diff_baseline(
    findings: list[Finding], baseline: set[tuple]
) -> tuple[list[Finding], list[Finding], int]:
    """``(new, accepted, n_fixed)`` — findings not in / in the baseline,
    and the count of baseline entries no longer observed (fixed or moved:
    the shrink ``--write-baseline`` would commit)."""
    new: list[Finding] = []
    accepted: list[Finding] = []
    observed: set[tuple] = set()
    for f, fp in fingerprints(findings):
        observed.add(fp)
        (accepted if fp in baseline else new).append(f)
    return new, accepted, len(baseline - observed)


def validate_baseline_data(data) -> list[str]:
    """Structural errors for a parsed baseline file (empty = valid).
    Mirrors obs/schema.py's validate_* contract so the schema checker can
    gate the committed file."""
    if not isinstance(data, dict):
        return [f"baseline is {type(data).__name__}, not an object"]
    errors: list[str] = []
    v = data.get("version")
    if not isinstance(v, int):
        errors.append("missing/non-int field 'version'")
    elif v > BASELINE_VERSION:
        errors.append(f"baseline version {v} is newer than {BASELINE_VERSION}")
    if data.get("tool") != "graftlint":
        errors.append("field 'tool' must be 'graftlint'")
    rows = data.get("findings")
    if not isinstance(rows, list):
        return errors + ["missing/non-list field 'findings'"]
    for i, row in enumerate(rows):
        if not isinstance(row, dict):
            errors.append(f"findings[{i}]: not an object")
            continue
        for k in _IDENTITY_FIELDS:
            if k not in row:
                errors.append(f"findings[{i}]: missing field {k!r}")
            elif k == "index" and not isinstance(row[k], int):
                errors.append(f"findings[{i}]: field 'index' is not an int")
            elif k != "index" and not isinstance(row[k], str):
                errors.append(f"findings[{i}]: field {k!r} is not a string")
        if len(errors) > 10:
            errors.append("... (truncated)")
            break
    return errors
