"""Runtime companion to the static pass: assert a code region is clean.

``with sanitizer(tracker):`` brackets a steady-state region (warm train
steps, a serving request stream) and raises :class:`SanitizerError` on
exit if the region retraced — the PR-1 :class:`~..obs.hooks.CompileTracker`
is the counter, so anything the tracker wraps (every built step/render
executable) is covered. ``transfers="disallow"`` additionally arms
``jax.transfer_guard`` for the region, so an implicit host↔device transfer
(a numpy array sneaking into a warm executable, a stray device pull)
raises AT the offending call with a precise XLA error instead of showing
up later as a dispatch stall. Explicit ``jax.device_put`` /
``jax.device_get`` remain allowed — the guard flags exactly the implicit
transfers R1 hunts statically.

Typical test usage (tests/test_analysis.py, tests/test_serve.py idiom)::

    tracker = CompileTracker()
    step = tracker.wrap("step", jax.jit(step_fn))
    step(state, batch)                      # warm-up compile, outside
    with sanitizer(tracker) as probe:
        for _ in range(8):
            state, _ = step(state, batch)   # any retrace here -> raises
    assert probe.compiles == 0

The guard level is per-thread (jax's own switch), so a sanitized test
doesn't disturb concurrent engine threads.
"""

from __future__ import annotations

from contextlib import ExitStack, contextmanager
from dataclasses import dataclass, field


class SanitizerError(AssertionError):
    """A sanitized region retraced or transferred unexpectedly."""


@dataclass
class SanitizerProbe:
    """What the region did; populated on (clean) exit."""

    compiles: int = 0
    allow_compiles: int = 0
    compile_names: dict = field(default_factory=dict)


@contextmanager
def sanitizer(
    tracker=None,
    transfers: str | None = "disallow",
    allow_compiles: int = 0,
    name: str = "sanitizer",
):
    """Assert zero-retrace / zero-implicit-transfer over a region.

    ``tracker``: a CompileTracker whose total_compiles() must not grow by
    more than ``allow_compiles`` inside the region (None skips the check).
    ``transfers``: jax.transfer_guard level for the region — "disallow"
    (default), "log", or None/"allow" to leave transfers unguarded.
    Yields a :class:`SanitizerProbe` filled in on exit.
    """
    import jax

    probe = SanitizerProbe(allow_compiles=allow_compiles)
    before_total = tracker.total_compiles() if tracker is not None else 0
    before_counts = dict(tracker.counts()) if tracker is not None else {}
    with ExitStack() as stack:
        if transfers and transfers != "allow":
            stack.enter_context(jax.transfer_guard(transfers))
        yield probe
    if tracker is not None:
        probe.compiles = tracker.total_compiles() - before_total
        probe.compile_names = {
            k: v - before_counts.get(k, 0)
            for k, v in tracker.counts().items()
            if v - before_counts.get(k, 0) > 0
        }
        if probe.compiles > allow_compiles:
            raise SanitizerError(
                f"{name}: {probe.compiles} compile(s) inside a sanitized "
                f"region (allowed {allow_compiles}) — retrace storm; "
                f"offenders: {probe.compile_names} — pin shapes/dtypes or "
                "pad into buckets (docs/static_analysis.md)"
            )
