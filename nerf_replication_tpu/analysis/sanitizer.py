"""Runtime companion to the static pass: assert a code region is clean.

``with sanitizer(tracker):`` brackets a steady-state region (warm train
steps, a serving request stream) and raises :class:`SanitizerError` on
exit if the region retraced — the PR-1 :class:`~..obs.hooks.CompileTracker`
is the counter, so anything the tracker wraps (every built step/render
executable) is covered. ``transfers="disallow"`` additionally arms
``jax.transfer_guard`` for the region, so an implicit host↔device transfer
(a numpy array sneaking into a warm executable, a stray device pull)
raises AT the offending call with a precise XLA error instead of showing
up later as a dispatch stall. Explicit ``jax.device_put`` /
``jax.device_get`` remain allowed — the guard flags exactly the implicit
transfers R1 hunts statically.

Typical test usage (tests/test_analysis.py, tests/test_serve.py idiom)::

    tracker = CompileTracker()
    step = tracker.wrap("step", jax.jit(step_fn))
    step(state, batch)                      # warm-up compile, outside
    with sanitizer(tracker) as probe:
        for _ in range(8):
            state, _ = step(state, batch)   # any retrace here -> raises
    assert probe.compiles == 0

The guard level is per-thread (jax's own switch), so a sanitized test
doesn't disturb concurrent engine threads.
"""

from __future__ import annotations

from contextlib import ExitStack, contextmanager
from dataclasses import dataclass, field


class SanitizerError(AssertionError):
    """A sanitized region retraced or transferred unexpectedly."""


@dataclass
class SanitizerProbe:
    """What the region did; populated on (clean) exit."""

    compiles: int = 0
    allow_compiles: int = 0
    compile_names: dict = field(default_factory=dict)


@contextmanager
def sanitizer(
    tracker=None,
    transfers: str | None = "disallow",
    allow_compiles: int = 0,
    name: str = "sanitizer",
):
    """Assert zero-retrace / zero-implicit-transfer over a region.

    ``tracker``: a CompileTracker whose total_compiles() must not grow by
    more than ``allow_compiles`` inside the region (None skips the check).
    ``transfers``: jax.transfer_guard level for the region — "disallow"
    (default), "log", or None/"allow" to leave transfers unguarded.
    Yields a :class:`SanitizerProbe` filled in on exit.
    """
    import jax

    probe = SanitizerProbe(allow_compiles=allow_compiles)
    before_total = tracker.total_compiles() if tracker is not None else 0
    before_counts = dict(tracker.counts()) if tracker is not None else {}
    with ExitStack() as stack:
        if transfers and transfers != "allow":
            stack.enter_context(jax.transfer_guard(transfers))
        yield probe
    if tracker is not None:
        probe.compiles = tracker.total_compiles() - before_total
        probe.compile_names = {
            k: v - before_counts.get(k, 0)
            for k, v in tracker.counts().items()
            if v - before_counts.get(k, 0) > 0
        }
        if probe.compiles > allow_compiles:
            raise SanitizerError(
                f"{name}: {probe.compiles} compile(s) inside a sanitized "
                f"region (allowed {allow_compiles}) — retrace storm; "
                f"offenders: {probe.compile_names} — pin shapes/dtypes or "
                "pad into buckets (docs/static_analysis.md)"
            )


# --------------------------------------------------------------------------
# runtime lock-order sanitizer — the dynamic witness for R10 (lock-order)
# --------------------------------------------------------------------------


class LockOrderError(AssertionError):
    """Two threads acquired instrumented locks in conflicting orders."""


class _InstrumentedLock:
    """Transparent proxy: records edges in the recorder, forwards the
    rest. ``wait``/``notify`` keep working because the INNER lock really
    is acquired — the proxy only observes."""

    __slots__ = ("_rec", "_name", "_inner")

    def __init__(self, recorder: "LockOrderRecorder", name: str, inner):
        self._rec = recorder
        self._name = name
        self._inner = inner

    def acquire(self, *a, **kw):
        # inner first, record second: the recorder's own mutex is only
        # ever taken AFTER a real lock, never around one — the
        # instrumentation cannot itself create a lock-order cycle
        got = self._inner.acquire(*a, **kw)
        if got:
            self._rec._note_acquire(self._name)
        return got

    def release(self, *a, **kw):
        self._rec._note_release(self._name)
        return self._inner.release(*a, **kw)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


class LockOrderRecorder:
    """Per-thread acquisition-order DAG over instrumented locks.

    Wrap the fleet's locks (``instrument(obj, "_lock")`` swaps the
    attribute for a recording proxy), run the live multi-threaded
    traffic, then ``assert_acyclic()`` at teardown: a cycle in the
    observed held->acquired edges is the dynamic witness of the deadlock
    R10 reports statically, and the error names the two stacks."""

    def __init__(self):
        import threading

        self._mu = threading.Lock()       # guards edges/threads maps only
        self._tls = threading.local()     # per-thread held stack
        self._names: list[str] = []
        # (held, acquired) -> (stack_held, stack_acquired, thread_name)
        self.edges: dict = {}
        self.n_acquires = 0
        self._threads: set = set()

    # -- instrumentation -------------------------------------------------------

    def wrap(self, name: str, lock) -> _InstrumentedLock:
        with self._mu:
            if name not in self._names:
                self._names.append(name)
        return _InstrumentedLock(self, name, lock)

    def instrument(self, obj, *attrs, cls_name: str | None = None) -> None:
        """Swap ``obj.<attr>`` for a recording proxy, named
        ``ClassName.attr`` to match the static lock model's spelling."""
        prefix = cls_name or type(obj).__name__
        for attr in attrs:
            inner = getattr(obj, attr)
            if isinstance(inner, _InstrumentedLock):
                continue
            setattr(obj, attr, self.wrap(f"{prefix}.{attr}", inner))

    # -- recording (called from the proxies) -----------------------------------

    def _stack(self) -> list[str]:
        import traceback

        # drop the two proxy/recorder frames at the top
        return [ln.rstrip() for ln in traceback.format_stack()[:-2][-8:]]

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _note_acquire(self, name: str) -> None:
        import threading

        held = self._held()
        first = name not in [h for h, _ in held]
        stack = self._stack() if first else None
        if first:
            tname = threading.current_thread().name
            with self._mu:
                self.n_acquires += 1
                self._threads.add(tname)
                for h, hstack in held:
                    if h == name:
                        continue
                    self.edges.setdefault(
                        (h, name), (hstack, stack, tname))
        # re-entrant re-acquires still push, for release balancing
        held.append((name, stack))

    def _note_release(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                del held[i]
                return

    # -- teardown assertions ---------------------------------------------------

    def find_cycle(self) -> list[str] | None:
        graph: dict[str, set] = {}
        for a, b in self.edges:
            graph.setdefault(a, set()).add(b)
        on_path: list[str] = []
        visited: set = set()

        def dfs(n: str) -> list[str] | None:
            if n in on_path:
                return on_path[on_path.index(n):] + [n]
            if n in visited or n not in graph:
                return None
            visited.add(n)
            on_path.append(n)
            for nxt in sorted(graph[n]):
                cyc = dfs(nxt)
                if cyc:
                    return cyc
            on_path.pop()
            return None

        for start in sorted(graph):
            cyc = dfs(start)
            if cyc:
                return cyc
        return None

    def assert_acyclic(self, name: str = "lock-order") -> None:
        cycle = self.find_cycle()
        if cycle is None:
            return
        pairs = [(a, b) for a, b in zip(cycle, cycle[1:])
                 if (a, b) in self.edges]
        detail = []
        for a, b in pairs[:2]:
            hstack, astack, tname = self.edges[(a, b)]
            frames = "\n".join((astack or hstack or ["<no stack>"])[-3:])
            detail.append(
                f"{a} -> {b} (thread {tname}):\n{frames}")
        raise LockOrderError(
            f"{name}: lock-order cycle {' -> '.join(cycle)} observed at "
            "runtime — two threads acquired these locks in conflicting "
            "orders; acquisition sites:\n" + "\n".join(detail)
        )

    def emit(self, emitter=None, source: str = "tier1") -> dict:
        """One ``lock_order`` telemetry row summarizing the run."""
        cycle = self.find_cycle()
        row = dict(
            n_locks=len(self._names),
            n_edges=len(self.edges),
            acyclic=cycle is None,
            n_threads=len(self._threads),
            locks=sorted(self._names),
            source=source,
        )
        if cycle is not None:
            row["cycle"] = cycle
        if emitter is None:
            from ..obs.emit import get_emitter

            emitter = get_emitter()
        emitter.emit("lock_order", **row)
        return row
