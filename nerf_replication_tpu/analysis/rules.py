"""graftlint rules R1-R9 — JAX hazards tuned to this codebase's idioms.

Each rule encodes one of the failure modes PR 1's telemetry made observable
at runtime (obs/: CompileTracker retraces, dispatch-vs-block stalls, HBM
creep) as a review-time check. docs/static_analysis.md carries the catalog
with a worked example diff per rule.

=====================  ==========================================================
rule id                hazard
=====================  ==========================================================
``host-sync``   (R1)   ``.item()`` / ``float()`` / ``np.asarray`` on device
                       values in traced or dispatch-hot code
``retrace``     (R2)   jit built inside a loop; varying shapes / shape-derived
                       scalars flowing into jit call sites without
                       ``static_argnums`` or bucket padding
``donate``      (R3)   train-step-shaped jit (state in, state out) without
                       ``donate_argnums`` — doubles parameter+optimizer HBM
``rng``         (R4)   hardcoded ``PRNGKey(const)`` in library code; a key
                       consumed twice without an intervening ``split``
``side-effect`` (R5)   ``print`` / ``global`` / closure-mutation inside a
                       traced body — runs at trace time, leaks tracers
``config-key``  (R6)   ``cfg.*`` accesses that no default/YAML defines, and
                       default keys nothing reads
``aot``         (R7)   library-code ``jax.jit`` not routed through the AOT
                       registry (compile/registry.py) — first caller pays
                       the compile inline at dispatch time
``swallow``     (R8)   ``except Exception`` / bare ``except`` in library
                       code that neither re-raises nor emits telemetry —
                       the failure disappears from every record
``emit-hot``    (R9)   ``Emitter.emit`` / metrics-registry calls inside a
                       jit-traced or dispatch-hot body — telemetry runs at
                       trace time (traced) or per dispatch (hot); move to
                       batch cadence or suppress with a reason
=====================  ==========================================================
"""

from __future__ import annotations

import ast
import os

from .core import (
    Finding,
    FunctionInfo,
    ModuleContext,
    ProjectContext,
    Rule,
    _attr_chain,
    jit_call_of,
    jit_static_kwargs,
    is_jit_expr,
    register,
)

_NUMPY_NAMES = {"np", "numpy", "onp"}
_JAX_ROOTS = {"jnp", "jax", "lax"}


def _contains_jax_call(node: ast.expr) -> bool:
    """True when the expression subtree calls into jnp/jax/lax — i.e. its
    value is a device computation, not a trace-time python constant."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            chain = _attr_chain(sub.func)
            if chain and chain[0] in _JAX_ROOTS:
                return True
    return False


def _walk_scope(fn: ast.AST):
    """Walk ``fn``'s own body without descending into nested function
    scopes (their RNG/locals are separate runtime instances)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop(0)
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack[0:0] = list(ast.iter_child_nodes(node))


def _iter_functions(
    module: ModuleContext, traced: bool | None = None, hot: bool | None = None
):
    for info in module.functions.values():
        if traced is not None and info.traced != traced:
            continue
        if hot is not None and info.hot != hot:
            continue
        yield info


# --------------------------------------------------------------------------
# R1 host-sync
# --------------------------------------------------------------------------


@register
class HostSyncRule(Rule):
    rule_id = "host-sync"
    doc = (
        "host synchronization on a jit-traced or dispatch-hot path: "
        ".item(), float()/int(), np.asarray() on device values"
    )

    def check(self, module: ModuleContext) -> list[Finding]:
        findings: list[Finding] = []
        for info in module.functions.values():
            if not (info.traced or info.hot):
                continue
            where = "jit-traced" if info.traced else "dispatch-hot"
            for node in _walk_scope(info.node):
                if not isinstance(node, ast.Call):
                    continue
                f = self._classify(node, traced=info.traced)
                if f is None:
                    continue
                call_desc, hint = f
                finding = module.finding(
                    self.rule_id,
                    node,
                    f"{call_desc} inside {where} `{info.qualname}` — {hint}",
                )
                if finding:
                    findings.append(finding)
        return findings

    def _classify(self, node: ast.Call, traced: bool):
        func = node.func
        chain = _attr_chain(func)
        # np.asarray / np.array / jax.device_get — a device pull (hot) or a
        # trace-time constant-fold surprise (traced)
        if chain[:1] and chain[0] in _NUMPY_NAMES and chain[-1] in (
            "asarray", "array", "copy"
        ):
            return (
                f"`{'.'.join(chain)}(...)`",
                "pulls the buffer to host; hoist off the hot path, use "
                "jax.block_until_ready for sync-only, or mark intentional "
                "with `# graftlint: ok(host-sync: why)`",
            )
        if chain in (["jax", "device_get"], ["device_get"]):
            return (
                "`jax.device_get(...)`",
                "device pull; hoist or mark intentional",
            )
        # .item() on anything
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "item"
            and not node.args
        ):
            return (
                "`.item()`",
                "blocks on the device value; keep scalars on device or "
                "sync once at the logging cadence",
            )
        # float()/int()/bool() casts only matter under trace (they force
        # concretization) and only when the value is demonstrably a jax
        # computation — `int(x.shape[0])` / `int(cfg.level_dim)` are
        # trace-time constants and idiomatic
        if traced and isinstance(func, ast.Name) and func.id in (
            "float", "int", "bool"
        ):
            if node.args and _contains_jax_call(node.args[0]):
                return (
                    f"`{func.id}(...)` on a jax computation",
                    "forces concretization of a traced value (works only on "
                    "trace-time constants, errors on tracers); use jnp ops "
                    "or hoist to the host side",
                )
        return None


# --------------------------------------------------------------------------
# R2 retrace
# --------------------------------------------------------------------------


@register
class RetraceRule(Rule):
    rule_id = "retrace"
    doc = (
        "retrace hazards: jax.jit constructed inside a loop; varying-shape "
        "slices or shape-derived scalars flowing into jit call sites "
        "without static_argnums/bucket padding"
    )

    def check(self, module: ModuleContext) -> list[Finding]:
        findings: list[Finding] = []
        findings += self._jit_in_loop(module)
        findings += self._varying_shapes(module)
        return findings

    def _jit_in_loop(self, module: ModuleContext) -> list[Finding]:
        out: list[Finding] = []
        for top in ast.walk(module.tree):
            if not isinstance(top, (ast.For, ast.While)):
                continue
            for node in ast.walk(top):
                if node is top:
                    continue
                # a def inside the loop is its own (cached) construction
                # site only if called immediately; flag the direct calls
                call = jit_call_of(node) if isinstance(node, ast.Call) else None
                if call is None and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    for dec in node.decorator_list:
                        if is_jit_expr(dec) or jit_call_of(dec) is not None:
                            call = dec  # type: ignore[assignment]
                            break
                if call is None:
                    continue
                f = module.finding(
                    self.rule_id,
                    node,
                    "jax.jit constructed inside a loop — every iteration "
                    "builds a fresh callable with an empty cache (a "
                    "recompile per iteration); hoist the jit out of the "
                    "loop or cache it keyed on its static config",
                )
                if f:
                    out.append(f)
        return out

    def _jitted_callables(self, module: ModuleContext) -> dict[str, bool]:
        """name -> has static_argnums/argnames, for names that are jit
        executables in this module (assigned from jax.jit(...) or
        jit-decorated defs)."""
        jitted: dict[str, bool] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign):
                call = jit_call_of(node.value)
                if call is not None:
                    has_static = any(
                        k in ("static_argnums", "static_argnames")
                        for k in jit_static_kwargs(call)
                    )
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            jitted[t.id] = has_static
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    call = jit_call_of(dec)
                    if is_jit_expr(dec):
                        jitted[node.name] = False
                    elif call is not None:
                        jitted[node.name] = any(
                            k in ("static_argnums", "static_argnames")
                            for k in jit_static_kwargs(call)
                        )
        return jitted

    def _varying_shapes(self, module: ModuleContext) -> list[Finding]:
        out: list[Finding] = []
        jitted = self._jitted_callables(module)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = node.func.id if isinstance(node.func, ast.Name) else None
            if name not in jitted:
                continue
            has_static = jitted[name]
            for arg in node.args:
                hazard = self._shape_hazard(arg, has_static)
                if hazard is None:
                    continue
                f = module.finding(
                    self.rule_id,
                    arg,
                    f"{hazard} flows into jit executable `{name}` — every "
                    "distinct shape compiles a fresh executable; pad into "
                    "a fixed bucket (cf. serve/engine.py buckets) or "
                    "declare it static_argnums",
                )
                if f:
                    out.append(f)
        return out

    def _shape_hazard(self, arg: ast.expr, has_static: bool) -> str | None:
        # x[:n] / x[i:j] with non-constant bounds => data-dependent shape
        if isinstance(arg, ast.Subscript) and isinstance(arg.slice, ast.Slice):
            s = arg.slice
            for bound in (s.lower, s.upper):
                if bound is not None and not isinstance(bound, ast.Constant):
                    return "a variable-length slice"
        if has_static:
            return None
        # len(...) / x.shape[i] as a bare argument: a host scalar that is
        # almost always about to be used as a dimension
        if (
            isinstance(arg, ast.Call)
            and isinstance(arg.func, ast.Name)
            and arg.func.id == "len"
        ):
            return "a `len(...)` host scalar"
        if (
            isinstance(arg, ast.Subscript)
            and isinstance(arg.value, ast.Attribute)
            and arg.value.attr == "shape"
        ):
            return "a `.shape[...]` host scalar"
        return None


# --------------------------------------------------------------------------
# R3 donate
# --------------------------------------------------------------------------

_STATE_PARAM_NAMES = {"state", "train_state", "opt_state"}


def _is_train_step_shaped(fn: ast.AST) -> bool:
    args = getattr(fn, "args", None)
    if args is None:
        return False
    pos = list(args.posonlyargs) + list(args.args)
    first = pos[0].arg if pos else ""
    if first in ("self", "cls") and len(pos) > 1:
        first = pos[1].arg
    if first in _STATE_PARAM_NAMES:
        return True
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "apply_gradients"
        ):
            return True
    return False


def _has_donate(call: ast.Call | None) -> bool:
    if call is None:
        return False  # bare @jax.jit has no kwargs at all
    return any(
        k in ("donate_argnums", "donate_argnames")
        for k in jit_static_kwargs(call)
    )


@register
class DonateRule(Rule):
    rule_id = "donate"
    doc = (
        "train-step-shaped jit (state in / state out) without "
        "donate_argnums: params + optimizer moments get double-buffered "
        "in HBM every step"
    )

    _MSG = (
        "train-step-shaped jit without donate_argnums — the old state "
        "stays live across the update, doubling parameter+optimizer HBM; "
        "donate the state argument (cf. train/trainer.py, parallel/step.py)"
    )

    def check(self, module: ModuleContext) -> list[Finding]:
        findings: list[Finding] = []
        local_defs = {
            info.name: info.node for info in module.functions.values()
        }
        for node in ast.walk(module.tree):
            # decorated defs
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    call = jit_call_of(dec)
                    if not (is_jit_expr(dec) or call is not None):
                        continue
                    if _is_train_step_shaped(node) and not _has_donate(call):
                        f = module.finding(self.rule_id, dec, self._MSG)
                        if f:
                            findings.append(f)
            # jax.jit(f, ...) call-form
            elif isinstance(node, ast.Call):
                call = jit_call_of(node)
                if call is None or call is not node:
                    continue
                args = node.args
                if args and is_jit_expr(args[0]):  # partial(jax.jit, f)
                    args = args[1:]
                if not args:
                    continue
                wrapped = args[0]
                target = None
                if isinstance(wrapped, ast.Lambda):
                    target = wrapped
                elif isinstance(wrapped, ast.Name):
                    target = local_defs.get(wrapped.id)
                if target is None:
                    continue
                if _is_train_step_shaped(target) and not _has_donate(node):
                    f = module.finding(self.rule_id, node, self._MSG)
                    if f:
                        findings.append(f)
        return findings


# --------------------------------------------------------------------------
# R4 rng
# --------------------------------------------------------------------------

# non-consuming jax.random calls: factories, and fold_in (deriving
# per-(key, data) streams from one key is the DESIGNED pattern —
# datasets/sampling.py) — using the parent key raw afterwards still pairs
# with any later real consumption
_KEY_FACTORIES = {"PRNGKey", "key", "key_data", "wrap_key_data", "fold_in"}


def _children_with_arms(node: ast.AST):
    """Children of ``node`` tagged with the branch arm they belong to
    (if/else arms, try/except handlers) — None for non-branching fields."""
    if isinstance(node, ast.If):
        yield node.test, None
        for c in node.body:
            yield c, "if"
        for c in node.orelse:
            yield c, "else"
        return
    if isinstance(node, ast.Try):
        for c in node.body:
            yield c, "try"
        for i, h in enumerate(node.handlers):
            for c in h.body:
                yield c, f"except{i}"
        for c in node.orelse + node.finalbody:
            yield c, None
        return
    if isinstance(node, ast.IfExp):
        yield node.test, None
        yield node.body, "if"
        yield node.orelse, "else"
        return
    for c in ast.iter_child_nodes(node):
        yield c, None


def _exclusive_branches(b1: tuple, b2: tuple) -> bool:
    """True when two branch paths sit in different arms of a common
    branching statement (so control flow can never reach both)."""
    arms1 = dict(b1)
    return any(
        nid in arms1 and arms1[nid] != arm for nid, arm in b2
    )


def _random_call(node: ast.Call) -> str | None:
    """The jax.random function name when ``node`` is a jax.random call."""
    chain = _attr_chain(node.func)
    if len(chain) >= 2 and chain[-2] == "random" and chain[0] in (
        "jax", "random", "jrandom", "jr"
    ):
        return chain[-1]
    if len(chain) == 2 and chain[0] in ("jrandom", "jr"):
        return chain[1]
    return None


@register
class RngRule(Rule):
    rule_id = "rng"
    doc = (
        "RNG hygiene: hardcoded PRNGKey(const) in library code; a key "
        "consumed twice (or in a loop) without an intervening split"
    )

    # experiment/bench scripts pin keys for reproducibility on purpose;
    # the hardcoded-seed check covers library code only
    HARDCODED_EXEMPT_PREFIXES = ("scripts", "tests")

    def check(self, module: ModuleContext) -> list[Finding]:
        findings: list[Finding] = []
        findings += self._hardcoded(module)
        for info in module.functions.values():
            findings += self._reuse(module, info)
        return findings

    def _hardcoded(self, module: ModuleContext) -> list[Finding]:
        rel = module.rel_path.replace(os.sep, "/")
        if any(
            rel.startswith(p + "/") or rel == p
            for p in self.HARDCODED_EXEMPT_PREFIXES
        ):
            return []
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not (chain and chain[-1] in ("PRNGKey", "key")):
                continue
            if chain[-1] == "key" and chain[:-1] not in (
                ["jax", "random"], ["random"], ["jrandom"], ["jr"]
            ):
                continue  # `.key` attributes that aren't jax.random.key
            if node.args and isinstance(node.args[0], ast.Constant):
                f = module.finding(
                    self.rule_id,
                    node,
                    f"hardcoded `{'.'.join(chain)}"
                    f"({node.args[0].value!r})` in library code — callers "
                    "can never vary the stream; thread the config seed "
                    "(cfg.seed) through instead",
                )
                if f:
                    out.append(f)
        return out

    def _reuse(self, module: ModuleContext, info: FunctionInfo) -> list[Finding]:
        # flow-light traversal: record each consumption/rebind with its
        # branch path (which arm of which If/Try it sits in) so draws in
        # mutually-exclusive branches never pair up as "reuse"
        consumptions: list[tuple[int, str, ast.Call, tuple]] = []
        rebinds: list[tuple[int, str, tuple]] = []
        loops: list[tuple[int, int, set[str]]] = []  # (start, end, rebinds)

        def visit(node: ast.AST, branch: tuple):
            for child, arm in _children_with_arms(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue  # nested scope: separate runtime instance
                sub_branch = branch + ((id(node), arm),) if arm else branch
                if isinstance(child, (ast.For, ast.While)):
                    body_rebinds = {
                        n.id
                        for n in ast.walk(child)
                        if isinstance(n, ast.Name)
                        and isinstance(n.ctx, ast.Store)
                    }
                    loops.append(
                        (child.lineno,
                         getattr(child, "end_lineno", child.lineno),
                         body_rebinds)
                    )
                if isinstance(child, ast.Name) and isinstance(
                    child.ctx, ast.Store
                ):
                    rebinds.append((child.lineno, child.id, sub_branch))
                if isinstance(child, ast.Call):
                    fn_name = _random_call(child)
                    if fn_name is not None and fn_name not in _KEY_FACTORIES:
                        if child.args and isinstance(child.args[0], ast.Name):
                            consumptions.append(
                                (child.lineno, child.args[0].id, child,
                                 sub_branch)
                            )
                visit(child, sub_branch)

        visit(info.node, ())

        findings: list[Finding] = []
        by_key: dict[str, list[tuple[int, ast.Call, tuple]]] = {}
        for line, key, node, branch in consumptions:
            by_key.setdefault(key, []).append((line, node, branch))
        for key, events in by_key.items():
            events.sort(key=lambda e: e[0])
            for (l1, _n1, b1), (l2, n2, b2) in zip(events, events[1:]):
                if _exclusive_branches(b1, b2):
                    continue
                # a rebind on l1's own line covers `key = fold_in(key, ..)`
                # style self-renewal
                if any(
                    l1 <= rl <= l2 and rn == key
                    and not _exclusive_branches(rb, b2)
                    for rl, rn, rb in rebinds
                ):
                    continue
                f = module.finding(
                    self.rule_id,
                    n2,
                    f"key `{key}` consumed again (first used at line {l1}) "
                    "without a split/rebind in between — both draws see the "
                    "same stream; jax.random.split the key first",
                )
                if f:
                    findings.append(f)
        # single consumption inside a loop that never rebinds the key:
        # every iteration draws the identical stream
        for line, key, node, _branch in consumptions:
            for lo, hi, body_rebinds in loops:
                if lo <= line <= hi and key not in body_rebinds:
                    f = module.finding(
                        self.rule_id,
                        node,
                        f"key `{key}` consumed inside a loop without being "
                        "split/folded per iteration — every iteration draws "
                        "identical randomness",
                    )
                    if f:
                        findings.append(f)
                    break
        return findings


# --------------------------------------------------------------------------
# R5 side-effect
# --------------------------------------------------------------------------

_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault"}


@register
class SideEffectRule(Rule):
    rule_id = "side-effect"
    doc = (
        "side effects in jit-traced bodies: print, global mutation, "
        "appending to closed-over containers — they run once at trace "
        "time and can leak tracers"
    )

    def check(self, module: ModuleContext) -> list[Finding]:
        findings: list[Finding] = []
        for info in _iter_functions(module, traced=True):
            locals_ = info.local_names
            for node in _walk_scope(info.node):
                msg = None
                if isinstance(node, ast.Global):
                    msg = (
                        "`global` inside a jit-traced body — the mutation "
                        "happens once at trace time, not per call"
                    )
                elif isinstance(node, ast.Call):
                    chain = _attr_chain(node.func)
                    if chain == ["print"]:
                        msg = (
                            "`print` inside a jit-traced body runs at trace "
                            "time only (and prints tracers); use "
                            "jax.debug.print for per-call output"
                        )
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in _MUTATORS
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id not in locals_
                    ):
                        msg = (
                            f"`{node.func.value.id}.{node.func.attr}(...)` "
                            "mutates a closed-over container from a traced "
                            "body — it fires once at trace time and leaks "
                            "tracers into host state"
                        )
                if msg is None:
                    continue
                f = module.finding(self.rule_id, node, msg)
                if f:
                    findings.append(f)
        return findings


# --------------------------------------------------------------------------
# R6 config-key
# --------------------------------------------------------------------------

# containers whose sub-keys are task-plugin/YAML-defined, not template
# defaults — unknown keys under them are expected
_DYNAMIC_CONTAINERS = {
    "task_arg", "sampler_meta", "train_dataset", "test_dataset", "network",
}

# dict/ConfigNode methods that terminate a key chain
_NODE_METHODS = {
    "items", "keys", "values", "merge", "merge_from_list", "merge_from_file",
    "freeze", "defrost", "clone", "dump", "to_dict", "is_frozen",
    "setdefault", "pop", "update", "copy", "popitem", "clear",
}


def _dict_literal_paths(node: ast.expr, prefix: tuple[str, ...]):
    """Key paths of a (possibly nested / ConfigNode-wrapped) dict literal."""
    if isinstance(node, ast.Call) and node.args:
        chain = _attr_chain(node.func)
        if chain and chain[-1] == "ConfigNode":
            node = node.args[0]
    if not isinstance(node, ast.Dict):
        return
    for k, v in zip(node.keys, node.values):
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            path = prefix + (k.value,)
            yield path, k.lineno
            yield from _dict_literal_paths(v, path)


def collect_config_keys(
    repo_root: str, with_defaults: bool = False
):
    """Known config key-paths: template defaults (config/config.py
    ``cfg.<k> = ...`` assignments, nested dict literals included) plus
    every YAML under configs/. ``with_defaults`` also returns the
    default-template leaf paths with their definition lines (for the
    dead-key check)."""
    known: set[tuple[str, ...]] = set()
    default_leaves: dict[tuple[str, ...], int] = {}

    cfg_py = os.path.join(
        repo_root, "nerf_replication_tpu", "config", "config.py"
    )
    if os.path.exists(cfg_py):
        with open(cfg_py, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=cfg_py)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            chain = _attr_chain(t)
            if len(chain) >= 2 and chain[0] == "cfg":
                path = tuple(chain[1:])
                known.add(path)
                nested = list(_dict_literal_paths(node.value, path))
                for sub, line in nested:
                    known.add(sub)
                if nested:
                    # leaves = nested paths with no deeper nested path
                    for sub, line in nested:
                        if not any(
                            other[: len(sub)] == sub and other != sub
                            for other, _ in nested
                        ):
                            default_leaves[sub] = line
                else:
                    default_leaves[path] = node.lineno

    def _yaml_paths(data, prefix=()):
        if isinstance(data, dict):
            for k, v in data.items():
                if isinstance(k, str):
                    yield prefix + (k,)
                    yield from _yaml_paths(v, prefix + (k,))

    configs_dir = os.path.join(repo_root, "configs")
    if os.path.isdir(configs_dir):
        try:
            import yaml
        except ImportError:  # pragma: no cover - yaml ships with the repo
            yaml = None
        if yaml is not None:
            for root, _dirs, files in os.walk(configs_dir):
                for f in sorted(files):
                    if not f.endswith((".yaml", ".yml")):
                        continue
                    try:
                        with open(os.path.join(root, f), encoding="utf-8") as fh:
                            data = yaml.safe_load(fh) or {}
                    except Exception:
                        continue
                    known.update(_yaml_paths(data))

    if with_defaults:
        return known, default_leaves
    return known


def _cfg_access_path(node: ast.expr) -> tuple[str, ...] | None:
    """Resolve ``cfg.a.b`` / ``self.cfg.get("a").b`` ... into a key path
    rooted at the config; None when not a cfg access."""
    parts: list[str] = []
    cur = node
    while True:
        if isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        elif (
            isinstance(cur, ast.Call)
            and isinstance(cur.func, ast.Attribute)
            and cur.func.attr == "get"
            and cur.args
            and isinstance(cur.args[0], ast.Constant)
            and isinstance(cur.args[0].value, str)
        ):
            parts.append(cur.args[0].value)
            cur = cur.func.value
        else:
            break
    if isinstance(cur, ast.Name) and cur.id == "cfg":
        pass
    elif (
        isinstance(cur, ast.Attribute)
        and cur.attr == "cfg"
        and isinstance(cur.value, ast.Name)
        and cur.value.id == "self"
    ):
        pass
    else:
        return None
    path = tuple(parts[::-1])
    # truncate at the first dict/node method ("cfg.train.items" -> train)
    for i, seg in enumerate(path):
        if seg in _NODE_METHODS or seg == "get":
            return path[:i]
    return path


@register
class ConfigKeyRule(Rule):
    rule_id = "config-key"
    doc = (
        "cfg.* accesses that neither the config template nor any YAML "
        "defines (typos, silently-dead .get defaults), and template "
        "default keys nothing in the repo reads"
    )
    project_wide = True

    def check_project(self, project: ProjectContext) -> list[Finding]:
        if project.config_keys is None:
            return []
        known = project.config_keys
        top_level = {p[0] for p in known if len(p) == 1} | {
            p[0] for p in known
        }
        findings: list[Finding] = []
        accessed: set[tuple[str, ...]] = set()

        for module in project.modules:
            if module.skip_file:
                continue
            rel = module.rel_path.replace(os.sep, "/")
            # the template/merge machinery reads keys too (parse_cfg
            # consumes exp_name_tag/save_tag) — its accesses count as
            # usage, but unknown-key findings there would be circular
            flag_unknown = not rel.startswith("nerf_replication_tpu/config/")
            for scope_node, scope_name in self._scopes(module):
                accesses = []
                for node in _walk_scope(scope_node):
                    if isinstance(node, (ast.Attribute, ast.Call)):
                        # an assignment TARGET (cfg.x = ...) defines, it
                        # doesn't read — else default_cfg's own template
                        # assignments would mark every key as used
                        if isinstance(node, ast.Attribute) and isinstance(
                            node.ctx, (ast.Store, ast.Del)
                        ):
                            continue
                        path = _cfg_access_path(node)
                        if path:
                            accesses.append((path, node))
                if not accesses:
                    continue
                # a scope's `cfg` is the ROOT config only if it touches at
                # least one known top-level key — encoder/task sub-configs
                # are also conventionally named `cfg`
                is_root = any(p[0] in top_level for p, _ in accesses if p)
                # keep only the outermost access per location (cfg.a.b also
                # matches cfg.a; the longest path at a line wins)
                best: dict[tuple[int, int], tuple[tuple[str, ...], ast.AST]] = {}
                for path, node in accesses:
                    loc = (node.lineno, node.col_offset)
                    # prefer the access that STARTS earliest on the line
                    # and is longest
                    cur = None
                    for (l, c), (p, n) in list(best.items()):
                        if l == node.lineno and abs(c - node.col_offset) <= 1:
                            cur = (l, c)
                    if cur is not None:
                        if len(path) > len(best[cur][0]):
                            best[cur] = (path, node)
                    else:
                        best[loc] = (path, node)
                for path, node in best.values():
                    if not path:
                        continue
                    for i in range(1, len(path) + 1):
                        accessed.add(path[:i])
                    if not is_root or not flag_unknown:
                        continue
                    unknown = self._first_unknown(path, known)
                    if unknown is None:
                        continue
                    f = module.finding(
                        self.rule_id,
                        node,
                        f"config key `{'.'.join(path)}` is not defined by "
                        "the template defaults (config/config.py) or any "
                        "YAML under configs/ — a typo reads the .get "
                        "fallback forever; add the key to default_cfg or "
                        "fix the access",
                    )
                    if f:
                        findings.append(f)

        findings += self._dead_keys(project, accessed)
        return findings

    def _scopes(self, module: ModuleContext):
        yield module.tree, "<module>"
        for info in module.functions.values():
            yield info.node, info.qualname

    def _first_unknown(
        self, path: tuple[str, ...], known: set[tuple[str, ...]]
    ) -> int | None:
        for i in range(1, len(path) + 1):
            prefix = path[:i]
            if prefix in known:
                continue
            # anything under a dynamic container is plugin-defined
            if any(seg in _DYNAMIC_CONTAINERS for seg in prefix[:i]):
                return None
            # a known LEAF's sub-access (cfg.train.scheduler.milestones
            # where scheduler is a dict default) — parent known, child not:
            # only flag if the parent is itself unknown at top level
            return i
        return None

    def _dead_keys(
        self, project: ProjectContext, accessed: set[tuple[str, ...]]
    ) -> list[Finding]:
        if not project.is_full_scan or project.repo_root is None:
            return []
        _, default_leaves = collect_config_keys(
            project.repo_root, with_defaults=True
        )
        cfg_module = next(
            (
                m for m in project.modules
                if m.rel_path.replace(os.sep, "/").endswith(
                    "nerf_replication_tpu/config/config.py"
                )
            ),
            None,
        )
        if cfg_module is None:
            return []
        out: list[Finding] = []
        for path, line in sorted(default_leaves.items()):
            if any(seg in _DYNAMIC_CONTAINERS for seg in path):
                continue
            if any(path[:i] in accessed for i in range(1, len(path) + 1)):
                continue
            if cfg_module.is_suppressed(self.rule_id, line):
                continue
            out.append(
                Finding(
                    rule=self.rule_id,
                    path=cfg_module.rel_path,
                    line=line,
                    col=0,
                    message=(
                        f"default config key `{'.'.join(path)}` is never "
                        "read anywhere in the scanned tree — dead weight "
                        "or a key the reader spells differently; delete "
                        "it or mark `# graftlint: ok(config-key: why)`"
                    ),
                    snippet=cfg_module.snippet(line),
                )
            )
        return out


# --------------------------------------------------------------------------
# R7 aot
# --------------------------------------------------------------------------


@register
class AotRule(Rule):
    rule_id = "aot"
    doc = (
        "library-code jax.jit not routed through the AOT registry "
        "(compile/registry.py): the first caller pays the compile inline "
        "at dispatch time instead of during warm-up, and the executable "
        "never reaches the serialized-artifact cache"
    )

    # only package code owes the registry a signature; scripts, tests and
    # the CLI entrypoints are one-shot processes where lazy jit is fine,
    # and the registry itself obviously builds executables directly
    LIB_PREFIX = "nerf_replication_tpu/"
    EXEMPT_PREFIXES = (
        "nerf_replication_tpu/compile/",
        "nerf_replication_tpu/analysis/",
    )

    _MSG = (
        "jax.jit constructed in library code without AOT-registry routing "
        "— the first call pays the compile inline at dispatch time; hand "
        "the callable to AOTRegistry.register (compile/registry.py) so it "
        "is built during warm-up (and can be served from the artifact "
        "cache), or mark intentional with `# graftlint: ok(aot: why)`"
    )

    def check(self, module: ModuleContext) -> list[Finding]:
        rel = module.rel_path.replace(os.sep, "/")
        if not rel.startswith(self.LIB_PREFIX):
            return []
        if any(rel.startswith(p) for p in self.EXEMPT_PREFIXES):
            return []
        routed_names, routed_nodes = self._register_routing(module)
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            site: ast.AST | None = None
            owner: str | None = None
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if is_jit_expr(dec) or jit_call_of(dec) is not None:
                        site, owner = dec, node.name
                        break
            elif isinstance(node, ast.Call):
                if jit_call_of(node) is node:
                    site = node
            if site is None or id(site) in routed_nodes:
                continue
            if self._routed(module, node, site, owner, routed_names):
                continue
            f = module.finding(self.rule_id, site, self._MSG)
            if f:
                findings.append(f)
        return findings

    def _register_routing(self, module: ModuleContext):
        """Names and jit-Call nodes that flow into ``*.register(...)``
        calls on an aot/registry object anywhere in the module. A builder
        whose NAME is handed to the registry (``aot.register("k",
        self._build_step(...), sig)``) routes every jit it constructs."""
        names: set[str] = set()
        nodes: set[int] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain or chain[-1] != "register":
                continue
            if not any(seg in ("aot", "registry") for seg in chain[:-1]):
                continue
            for arg in node.args + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
                    elif isinstance(sub, ast.Attribute):
                        names.add(sub.attr)
                    if isinstance(sub, ast.Call) and jit_call_of(sub) is sub:
                        nodes.add(id(sub))
        return names, nodes

    def _routed(
        self,
        module: ModuleContext,
        node: ast.AST,
        site: ast.AST,
        owner: str | None,
        routed_names: set[str],
    ) -> bool:
        if not routed_names:
            return False
        if owner is not None and owner in routed_names:
            return True
        line = getattr(site, "lineno", getattr(node, "lineno", 1))
        info = module.enclosing_function(line)
        if info is None:
            return False
        return any(seg in routed_names for seg in info.qualname.split("."))


# ---------------------------------------------------------------------------
# R8: swallowed exceptions
# ---------------------------------------------------------------------------

#: call names (terminal segment of the callee chain) that count as "the
#: failure left a trace" — telemetry rows, log lines, or collected errors.
_SWALLOW_SIGNALS = frozenset(
    {
        "emit",
        "warn",
        "warning",
        "warnings",
        "log",
        "print",
        "report",
        "record",
        "error",
        "exception",
        "debug",
        "info",
        "fail",
        "fault_point",
    }
)


@register
class SwallowRule(Rule):
    """R8: broad except handlers in library code must re-raise or emit.

    A ``try``/``except Exception`` (or bare ``except``) whose handler body
    neither contains a ``raise`` nor calls anything that records the failure
    (``report``/``emit``/``warn``/``log``/...) makes the error vanish: no
    telemetry row, no log line, no propagation.  In a fault-injected run
    these are exactly the sites where an injected IOError disappears and
    the chaos harness cannot attribute the recovery.

    Narrow handlers (``except OSError``, ``except (KeyError, ValueError)``)
    are out of scope — catching a specific exception is a statement of
    intent; catching *everything* silently is not.
    """

    rule_id = "swallow"
    doc = (
        "broad `except Exception`/bare `except` in library code that "
        "neither re-raises nor emits telemetry — the failure vanishes; "
        "re-raise, call resil.report()/emitter.emit(), or suppress with "
        "a reason"
    )

    LIB_PREFIX = "nerf_replication_tpu/"
    #: the lint engine itself parses/walks arbitrary source and recovers
    #: from malformed modules by design; its handlers are not failure sinks.
    EXEMPT_PREFIXES = ("nerf_replication_tpu/analysis/",)

    _BROAD = ("Exception", "BaseException")

    def check(self, module: ModuleContext) -> list[Finding]:
        rel = module.rel_path.replace(os.sep, "/")
        if not rel.startswith(self.LIB_PREFIX):
            return []
        if any(rel.startswith(p) for p in self.EXEMPT_PREFIXES):
            return []
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not self._is_broad(handler):
                    continue
                if self._leaves_trace(handler):
                    continue
                f = module.finding(
                    self.rule_id,
                    handler,
                    "broad except swallows the failure: handler neither "
                    "re-raises nor emits telemetry/logging — add "
                    "resil.report(...)/raise, narrow the exception type, "
                    "or suppress with `# graftlint: ok(swallow: reason)`",
                )
                if f is not None:
                    findings.append(f)
        return findings

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        if handler.type is None:  # bare `except:`
            return True
        types = (
            handler.type.elts
            if isinstance(handler.type, ast.Tuple)
            else [handler.type]
        )
        for t in types:
            chain = _attr_chain(t)
            if chain and chain[-1] in self._BROAD:
                return True
        return False

    def _leaves_trace(self, handler: ast.ExceptHandler) -> bool:
        for sub in ast.walk(handler):
            if isinstance(sub, ast.Raise):
                return True
            if isinstance(sub, ast.Call):
                chain = _attr_chain(sub.func)
                if chain and chain[-1] in _SWALLOW_SIGNALS:
                    return True
        return False


# --------------------------------------------------------------------------
# R9 emit-hot
# --------------------------------------------------------------------------


@register
class EmitHotRule(Rule):
    """R9: telemetry/metrics writes inside traced or dispatch-hot bodies.

    Inside a **jit-traced** body an ``emit``/metrics call runs at TRACE
    time — once per compile, never per step — so the telemetry it appears
    to produce is a lie, and the file/lock side effects leak into tracing.
    Inside a **dispatch-hot** body (``# graftlint: hot``) the call is real
    but rides the latency-critical path on every dispatch; the sanctioned
    shapes are batch-cadence records and post-sync completion rows, which
    suppress with a reason (the serve batcher's per-batch rows are the
    worked example).

    Matched receivers: ``get_emitter().emit`` / ``<...>emitter.emit``
    (obs/emit.py) and ``get_metrics().counter|gauge|observe`` /
    ``metrics.*`` / ``mx.*`` (obs/metrics.py). Span context managers are
    deliberately NOT flagged — obs/trace.py is the sanctioned hot-path
    instrument and its disabled cost is one null contextmanager.
    """

    rule_id = "emit-hot"
    doc = (
        "Emitter.emit / metrics-registry call inside a jit-traced or "
        "dispatch-hot body — traced: runs at trace time, not per step; "
        "hot: telemetry rides the latency-critical path on every "
        "dispatch; move to batch cadence / post-sync or suppress with "
        "a reason"
    )

    _METRIC_METHODS = ("counter", "gauge", "observe")
    _METRIC_RECEIVERS = ("metrics", "mx", "registry")

    def check(self, module: ModuleContext) -> list[Finding]:
        findings: list[Finding] = []
        for info in module.functions.values():
            if not (info.traced or info.hot):
                continue
            where = "jit-traced" if info.traced else "dispatch-hot"
            for node in _walk_scope(info.node):
                if not isinstance(node, ast.Call):
                    continue
                desc = self._classify(node)
                if desc is None:
                    continue
                f = module.finding(
                    self.rule_id,
                    node,
                    f"`{desc}` inside {where} `{info.qualname}` — "
                    + ("telemetry in a traced body runs at trace time "
                       "(once per compile), not per step"
                       if info.traced else
                       "telemetry on the dispatch-hot path; keep it at "
                       "batch cadence / post-sync, or suppress with a "
                       "reason"),
                )
                if f is not None:
                    findings.append(f)
        return findings

    def _classify(self, node: ast.Call) -> str | None:
        func = node.func
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        recv = func.value
        # get_emitter().emit(...) / get_metrics().observe(...): the chain
        # helper bottoms out at a Call, so match the inner call directly
        if isinstance(recv, ast.Call):
            inner = _attr_chain(recv.func)
            base = inner[-1] if inner else ""
            if attr == "emit" and base == "get_emitter":
                return "get_emitter().emit"
            if attr in self._METRIC_METHODS and base == "get_metrics":
                return f"get_metrics().{attr}"
            return None
        chain = _attr_chain(recv)
        if not chain:
            return None
        last = chain[-1]
        if attr == "emit" and last.endswith("emitter"):
            return ".".join(chain + [attr])
        if attr in self._METRIC_METHODS and (
            last in self._METRIC_RECEIVERS or last.endswith("metrics")
        ):
            return ".".join(chain + [attr])
        return None
