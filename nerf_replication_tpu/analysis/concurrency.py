"""graftlint rules R10-R13 — interprocedural concurrency analysis.

The serving fleet holds real thread state (batcher cuts, residency
demotions, router failover, prefetch closures); these rules build a
module-spanning **lock model** + **call graph** and check the discipline
the per-function R1-R9 rules cannot see.

=====================  ======================================================
rule id                hazard
=====================  ======================================================
``lock-order``   (R10) a cycle in the static lock-acquisition graph —
                       two call chains that take the same locks in
                       opposite orders deadlock under load; also the
                       re-acquisition of a non-reentrant ``Lock``
``unguarded-shared``   a field written inside some lock's critical
                 (R11) section and also read/written from a ``Thread``
                       target / timer / executor closure without that
                       lock — a data race the GIL hides until it doesn't
``blocking-under-lock``u rlopen / socket / subprocess / sleep /
                 (R12) ``device_put`` / ``block_until_ready`` / file-I/O
                       (incl. the global telemetry emitter) reachable
                       while a lock is held — every waiter pays the wait
``thread-hygiene``     non-daemon threads never joined, ``Condition.wait``
                 (R13) without a predicate loop, ``current_ctx()`` read
                       inside a thread-entry closure (capture it on the
                       submitting thread — fleet/residency.py prefetch)
=====================  ======================================================

Lock model
----------
Every ``threading.Lock/RLock/Condition`` bound to an attribute
(``self._lock = threading.Lock()``), a module-level name, or a function
local becomes a **named lock** (``Router._lock``, ``native:_LOCK``).
``with self._lock:`` blocks and ``acquire()``/``release()`` pairs define
critical sections. Receiver types resolve through parameter annotations
and ``self.attr = annotated_param`` assignments, so
``res = self.residency; with res._cond:`` names
``ResidencyManager._cond``. ``Condition()`` wraps an RLock, so conditions
count as reentrant.

Interprocedural facts flow along a project-wide call graph
(``self.m()`` resolves through the MRO plus subclass overrides; other
receivers resolve by annotation type or, failing that, by a
project-unique method name), with two fixpoints: the set of locks a
function may transitively acquire, and the blocking calls it may
transitively reach. A third fixpoint recovers the **held-at-entry** set
for contract functions ("called under the lock"): the intersection of
the locks held at every observed call site.

Annotations
-----------
``# graftlint: guards(f1, f2)`` on (or the line above) a lock's
assignment declares the lock's guarded-field set **exactly** — inference
for that lock is replaced by the declaration, so documented
single-writer counters that are merely *touched* under the lock stop
counting as guarded (the R11 suppression path for the batcher's
worker-owned counters). Per-call-site allowlisting for intentional
blocking uses the standard ``# graftlint: ok(blocking-under-lock:
reason)`` suppression; the reason is the audit trail.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field as dc_field

from .core import (
    Finding,
    ModuleContext,
    ProjectContext,
    Rule,
    _attr_chain,
    register,
)

_GUARDS_RE = re.compile(r"#\s*graftlint:\s*guards\(([^)]*)\)")

_LOCK_CTORS = {
    ("threading", "Lock"): "lock",
    ("Lock",): "lock",
    ("threading", "RLock"): "rlock",
    ("RLock",): "rlock",
    ("threading", "Condition"): "condition",
    ("Condition",): "condition",
}

# Condition() builds on an RLock: re-entry by the owning thread is legal
_REENTRANT_KINDS = ("rlock", "condition")

# method names shared with stdlib containers/files/threads: the
# unique_named fallback must never claim these for a project class
_UBIQUITOUS_METHODS = frozenset((
    "get", "put", "pop", "add", "append", "extend", "update", "clear",
    "copy", "close", "items", "keys", "values", "join", "start", "run",
    "read", "write", "send", "recv", "next", "set", "remove", "discard",
    "count", "index", "insert", "sort", "reverse", "wait", "notify",
    "notify_all", "acquire", "release", "submit", "result", "done",
    "cancel", "flush", "seek", "tell", "open", "stop", "reset", "step",
    "setdefault", "move_to_end", "popitem", "format", "strip", "split",
))


@dataclass(frozen=True)
class LockInfo:
    """One named lock: where it was constructed and what it guards."""

    name: str            # "Router._lock" | "native:_LOCK" | "f.<local>lk"
    kind: str            # lock | rlock | condition
    module: str          # rel_path of the defining module
    line: int
    cls: str | None      # owning class (None: module-level / local)
    attr: str
    guards: frozenset | None = None  # declared guarded fields (None: infer)


@dataclass
class ClassInfo:
    name: str
    module: ModuleContext
    bases: tuple[str, ...]
    methods: dict = dc_field(default_factory=dict)     # name -> FuncNode
    locks: dict = dc_field(default_factory=dict)       # attr -> LockInfo
    attr_types: dict = dc_field(default_factory=dict)  # attr -> class name


@dataclass(eq=False)  # identity hash: nodes are graph keys
class FuncNode:
    """One function/method/nested closure plus its concurrency facts."""

    qual: str
    name: str
    cls: str | None
    module: ModuleContext
    node: ast.AST
    parent: "FuncNode | None" = None
    children: dict = dc_field(default_factory=dict)    # name -> FuncNode
    # facts (filled by the walker)
    acquires: list = dc_field(default_factory=list)    # (LockInfo, held, node)
    calls: list = dc_field(default_factory=list)       # (targets, held, node)
    blocking: list = dc_field(default_factory=list)    # (label, held, node)
    accesses: list = dc_field(default_factory=list)    # (cls, attr, rw, held, node)
    waits: list = dc_field(default_factory=list)       # (LockInfo, in_while, node)
    ctx_calls: list = dc_field(default_factory=list)   # current_ctx() nodes
    spawns: list = dc_field(default_factory=list)      # (node, daemon, bind)
    is_thread_target = False
    # fixpoint results
    trans_locks: set = dc_field(default_factory=set)       # lock names
    trans_blocking: dict = dc_field(default_factory=dict)  # label -> via
    held_in: frozenset = frozenset()                       # lock names

    def short(self) -> str:
        return self.qual.split("::", 1)[-1]


def _lock_ctor_kind(node: ast.AST) -> str | None:
    if isinstance(node, ast.Call):
        return _LOCK_CTORS.get(tuple(_attr_chain(node.func)))
    return None


def _ann_name(ann: ast.AST | None) -> str | None:
    """Last segment of a simple annotation (``ResidencyManager``,
    ``residency.ResidencyManager``, ``"Router"``)."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.split(".")[-1].strip() or None
    chain = _attr_chain(ann)
    return chain[-1] if chain else None


# --------------------------------------------------------------------------
# the shared model (built once per ProjectContext, cached on it)
# --------------------------------------------------------------------------


class ConcurrencyModel:
    """Locks + classes + call graph + fixpoints over one project scan."""

    def __init__(self, project: ProjectContext):
        self.project = project
        self.classes: dict[str, ClassInfo] = {}
        self.subclasses: dict[str, set[str]] = {}
        self.module_locks: dict[str, dict[str, LockInfo]] = {}  # rel -> name
        self.module_funcs: dict[str, dict[str, FuncNode]] = {}
        self.funcs: list[FuncNode] = []
        self.methods_named: dict[str, list[FuncNode]] = {}
        self.locks_by_name: dict[str, LockInfo] = {}
        self.lock_attr_owners: dict[str, list[str]] = {}  # attr -> [cls]
        self.joins: dict[str, set] = {}        # rel_path -> joined chains
        self.daemon_later: dict[str, set] = {}  # rel_path -> chains
        self.module_imports: dict[str, set] = {}  # rel_path -> import names
        self._pending_attr_types: list[tuple] = []  # (cls, attr, ctor name)
        for m in project.modules:
            if not m.skip_file:
                self._collect(m)
        for cinfo, attr, ctor in self._pending_attr_types:
            if ctor in self.classes:
                cinfo.attr_types.setdefault(attr, ctor)
        self._attach_guards()
        for fn in self.funcs:
            _FactWalker(self, fn).run()
        self._resolve_calls()
        self._fix_trans_locks()
        self._fix_trans_blocking()
        self._fix_held_in()
        self._flood_thread_ctx()

    @classmethod
    def of(cls, project: ProjectContext) -> "ConcurrencyModel":
        model = getattr(project, "_concurrency_model", None)
        if model is None:
            model = cls(project)
            project._concurrency_model = model
        return model

    # -- pass 1: classes / functions / locks ---------------------------------

    def _collect(self, module: ModuleContext) -> None:
        model = self
        rel = module.rel_path
        model.module_locks.setdefault(rel, {})
        model.module_funcs.setdefault(rel, {})
        model.joins.setdefault(rel, set())
        model.daemon_later.setdefault(rel, set())
        imports = model.module_imports.setdefault(rel, set())
        for sub in ast.walk(module.tree):
            if isinstance(sub, ast.Import):
                for a in sub.names:
                    imports.add(a.asname or a.name.split(".", 1)[0])

        class Collector(ast.NodeVisitor):
            def __init__(self):
                self.cls_stack: list[ClassInfo] = []
                self.fn_stack: list[FuncNode] = []

            def visit_ClassDef(self, node):
                bases = tuple(
                    c[-1] for b in node.bases if (c := _attr_chain(b))
                )
                info = ClassInfo(node.name, module, bases)
                model.classes.setdefault(node.name, info)
                for b in bases:
                    model.subclasses.setdefault(b, set()).add(node.name)
                self.cls_stack.append(model.classes[node.name])
                self.generic_visit(node)
                self.cls_stack.pop()

            def _fn(self, node):
                cls = self.cls_stack[-1].name if self.cls_stack else None
                scope = [f.name for f in self.fn_stack] + [node.name]
                if cls:
                    scope = [cls] + scope
                fn = FuncNode(
                    qual=f"{rel}::{'.'.join(scope)}", name=node.name,
                    cls=cls, module=module, node=node,
                    parent=self.fn_stack[-1] if self.fn_stack else None,
                )
                model.funcs.append(fn)
                if fn.parent is not None:
                    fn.parent.children[node.name] = fn
                elif cls:
                    self.cls_stack[-1].methods.setdefault(node.name, fn)
                    model.methods_named.setdefault(node.name, []).append(fn)
                else:
                    model.module_funcs[rel].setdefault(node.name, fn)
                self.fn_stack.append(fn)
                self._scan_method_body(node)
                self.generic_visit(node)
                self.fn_stack.pop()

            visit_FunctionDef = _fn
            visit_AsyncFunctionDef = _fn

            def _scan_method_body(self, node):
                """Class attr types + ``self.x = threading.Lock()``."""
                if not self.cls_stack or len(self.fn_stack) != 1:
                    return
                cinfo = self.cls_stack[-1]
                ann_params = {}
                args = node.args
                for a in (list(args.posonlyargs) + list(args.args)
                          + list(args.kwonlyargs)):
                    t = _ann_name(a.annotation)
                    if t:
                        ann_params[a.arg] = t
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        for tgt in sub.targets:
                            chain = _attr_chain(tgt)
                            if len(chain) != 2 or chain[0] != "self":
                                continue
                            attr = chain[1]
                            kind = _lock_ctor_kind(sub.value)
                            if kind:
                                lk = model._add_lock(
                                    f"{cinfo.name}.{attr}", kind, rel,
                                    sub.lineno, cinfo.name, attr)
                                cinfo.locks.setdefault(attr, lk)
                            vchain = _attr_chain(sub.value)
                            if len(vchain) == 1 and vchain[0] in ann_params:
                                cinfo.attr_types.setdefault(
                                    attr, ann_params[vchain[0]])
                            if (isinstance(sub.value, ast.Call)
                                    and (c := _attr_chain(sub.value.func))):
                                # deferred: the ctor's class may live in a
                                # module not collected yet
                                model._pending_attr_types.append(
                                    (cinfo, attr, c[-1]))
                    elif isinstance(sub, ast.AnnAssign):
                        chain = _attr_chain(sub.target)
                        t = _ann_name(sub.annotation)
                        if len(chain) == 2 and chain[0] == "self" and t:
                            cinfo.attr_types.setdefault(chain[1], t)

            def visit_Assign(self, node):
                if not self.fn_stack and not self.cls_stack:
                    kind = _lock_ctor_kind(node.value)
                    if kind:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                stem = rel.rsplit("/", 1)[-1]
                                stem = stem[:-3] if stem.endswith(".py") \
                                    else stem
                                lk = model._add_lock(
                                    f"{stem}:{tgt.id}", kind, rel,
                                    node.lineno, None, tgt.id)
                                model.module_locks[rel][tgt.id] = lk
                self.generic_visit(node)

            def visit_Call(self, node):
                # module-wide join / daemon-late-assignment census
                chain = _attr_chain(node.func)
                if len(chain) >= 2 and chain[-1] == "join":
                    model.joins[rel].add(tuple(chain[:-1]))
                self.generic_visit(node)

            def visit_Attribute(self, node):
                self.generic_visit(node)

        collector = Collector()
        collector.visit(module.tree)
        for sub in ast.walk(module.tree):
            if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                    and (c := _attr_chain(sub.targets[0]))
                    and c[-1] == "daemon"
                    and isinstance(sub.value, ast.Constant)
                    and sub.value.value is True):
                model.daemon_later[rel].add(tuple(c[:-1]))

    def _add_lock(self, name, kind, rel, line, cls, attr) -> LockInfo:
        lk = self.locks_by_name.get(name)
        if lk is None:
            lk = LockInfo(name, kind, rel, line, cls, attr)
            self.locks_by_name[name] = lk
            if cls:
                self.lock_attr_owners.setdefault(attr, []).append(cls)
        return lk

    def _attach_guards(self) -> None:
        """``# graftlint: guards(f1, f2)`` on (or above) a lock assign."""
        by_loc = {(lk.module, lk.line): name
                  for name, lk in self.locks_by_name.items()}
        for module in self.project.modules:
            for i, line in enumerate(module.lines, 1):
                m = _GUARDS_RE.search(line)
                if not m:
                    continue
                target = i + 1 if line.split("#", 1)[0].strip() == "" else i
                name = by_loc.get((module.rel_path, target))
                if name is None:
                    continue
                fields = frozenset(
                    f.strip() for f in m.group(1).split(",") if f.strip()
                )
                old = self.locks_by_name[name]
                new = LockInfo(old.name, old.kind, old.module, old.line,
                               old.cls, old.attr, guards=fields)
                self.locks_by_name[name] = new
                if old.cls and old.cls in self.classes:
                    self.classes[old.cls].locks[old.attr] = new

    # -- resolution helpers --------------------------------------------------

    def mro(self, cls: str) -> list[ClassInfo]:
        out, seen, queue = [], set(), [cls]
        while queue:
            c = queue.pop(0)
            if c in seen or c not in self.classes:
                continue
            seen.add(c)
            info = self.classes[c]
            out.append(info)
            queue.extend(info.bases)
        return out

    def family_root(self, cls: str) -> str:
        cur = cls
        seen = set()
        while cur in self.classes and cur not in seen:
            seen.add(cur)
            nxt = next((b for b in self.classes[cur].bases
                        if b in self.classes), None)
            if nxt is None:
                return cur
            cur = nxt
        return cur

    def _descendants(self, cls: str) -> list[str]:
        out, queue = [], [cls]
        while queue:
            c = queue.pop(0)
            for s in self.subclasses.get(c, ()):
                if s not in out:
                    out.append(s)
                    queue.append(s)
        return out

    def find_lock(self, cls: str | None, attr: str) -> LockInfo | None:
        if cls is None:
            return None
        for info in self.mro(cls):
            lk = info.locks.get(attr)
            if lk is not None:
                return lk
        return None

    def unique_attr_lock(self, attr: str) -> LockInfo | None:
        """Lock attr defined by exactly one class family project-wide."""
        owners = self.lock_attr_owners.get(attr, [])
        roots = {self.family_root(c) for c in owners}
        if len(roots) == 1:
            return self.find_lock(owners[0], attr)
        return None

    def family_methods(self, cls: str, name: str) -> list[FuncNode]:
        """``name`` resolved through cls's MRO plus subclass overrides
        (a static type's call may dispatch to any override below it)."""
        out = []
        for info in self.mro(cls):
            fn = info.methods.get(name)
            if fn is not None and fn not in out:
                out.append(fn)
        for sub in self._descendants(cls):
            fn = self.classes[sub].methods.get(name)
            if fn is not None and fn not in out:
                out.append(fn)
        return out

    def unique_named(self, name: str) -> list[FuncNode]:
        """Every project def named ``name`` IF they form one class family
        (or a single module-level def) — the over-approximate fallback."""
        if name in _UBIQUITOUS_METHODS:
            # names every container/file/thread also answers to: an
            # unresolved receiver is far more likely a dict or a handle
            # than the one project class that shares the name (a partial
            # --changed scan would otherwise "uniquely" resolve sub.get
            # to SceneStore.get and invent a deadlock)
            return []
        if not self.project.is_full_scan:
            # uniqueness is a project-wide property; a partial (--changed)
            # scan that sees one class family named ``name`` cannot know a
            # second family exists outside the diff
            return []
        methods = self.methods_named.get(name, [])
        mod_fns = [f for fns in self.module_funcs.values()
                   for n, f in fns.items() if n == name]
        if methods and mod_fns:
            return []
        if mod_fns:
            return mod_fns if len(mod_fns) == 1 else []
        roots = {self.family_root(f.cls) for f in methods}
        return methods if len(roots) == 1 else []

    # -- pass 3: call resolution + fixpoints ---------------------------------

    def _resolve_calls(self) -> None:
        self.edges: dict[FuncNode, list] = {}        # f -> [(g, held, node)]
        self.sites: dict[FuncNode, list] = {}        # g -> [(f, held)]
        for f in self.funcs:
            out = []
            for targets, held, node in f.calls:
                for g in targets:
                    out.append((g, held, node))
                    self.sites.setdefault(g, []).append((f, held))
            self.edges[f] = out

    def _fix_trans_locks(self) -> None:
        for f in self.funcs:
            f.trans_locks = {lk.name for lk, _, _ in f.acquires}
        changed = True
        while changed:
            changed = False
            for f in self.funcs:
                for g, _, _ in self.edges[f]:
                    if not g.trans_locks <= f.trans_locks:
                        f.trans_locks |= g.trans_locks
                        changed = True

    def _fix_trans_blocking(self) -> None:
        for f in self.funcs:
            f.trans_blocking = {
                label: f"{label} at {f.module.rel_path}:{node.lineno}"
                for label, _, node in f.blocking
            }
        changed = True
        while changed:
            changed = False
            for f in self.funcs:
                for g, _, _ in self.edges[f]:
                    for label, via in g.trans_blocking.items():
                        if label not in f.trans_blocking:
                            f.trans_blocking[label] = \
                                f"via {g.short()}: {via}"[:200]
                            changed = True

    def _fix_held_in(self) -> None:
        """Held-at-entry: the intersection of locks held at every observed
        call site — honors the repo's "called under the lock" contract
        hooks without an annotation."""
        held: dict[FuncNode, frozenset | None] = {
            f: (frozenset()
                if f.is_thread_target or not self.sites.get(f) else None)
            for f in self.funcs
        }
        for _ in range(len(self.funcs) + 1):
            changed = False
            for f in self.funcs:
                if held[f] == frozenset() and (
                        f.is_thread_target or not self.sites.get(f)):
                    continue  # roots stay empty
                vals = [
                    frozenset(lk.name for lk in site_held) | base
                    for caller, site_held in self.sites.get(f, ())
                    if (base := held.get(caller)) is not None
                ]
                nv = frozenset.intersection(*vals) if vals else None
                if nv is not None and nv != held[f]:
                    held[f] = nv
                    changed = True
            if not changed:
                break
        for f in self.funcs:
            f.held_in = held[f] if held[f] is not None else frozenset()

    def _flood_thread_ctx(self) -> None:
        self.thread_ctx: set[FuncNode] = set()
        queue = [f for f in self.funcs if f.is_thread_target]
        while queue:
            f = queue.pop()
            if f in self.thread_ctx:
                continue
            self.thread_ctx.add(f)
            queue.extend(g for g, _, _ in self.edges.get(f, ()))


# --------------------------------------------------------------------------
# pass 2: the per-function fact walker
# --------------------------------------------------------------------------

_BLOCKING_BARE = {"urlopen": "urlopen", "device_put": "device_put",
                  "open": "file I/O (open)", "sleep": "time.sleep"}

# receivers that are modules, not objects: ``os.replace(...)`` must never
# fall back to a project method that happens to be named ``replace``
_MODULE_RECEIVERS = frozenset((
    "os", "sys", "time", "json", "math", "re", "ast", "io", "shutil",
    "glob", "subprocess", "socket", "threading", "queue", "ctypes",
    "platform", "random", "itertools", "functools", "collections",
    "contextlib", "traceback", "logging", "tempfile", "pickle", "struct",
    "hashlib", "heapq", "bisect", "gc", "signal", "inspect", "copy",
    "enum", "argparse", "dataclasses", "urllib", "warnings", "weakref",
    "pathlib", "typing", "uuid", "datetime", "operator", "statistics",
    "np", "numpy", "jax", "jnp", "lax", "pytest",
))
_SKIP_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class _FactWalker:
    """One function's lexical critical sections and the facts in them."""

    def __init__(self, model: ConcurrencyModel, fn: FuncNode):
        self.model = model
        self.fn = fn
        self.local_types: dict[str, str] = {}
        self.local_locks: dict[str, LockInfo] = {}
        args = fn.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            t = _ann_name(a.annotation)
            if t and t in model.classes:
                self.local_types[a.arg] = t

    def run(self) -> None:
        self._body(self.fn.node.body, (), 0)

    def _block(self, label: str, held: tuple, node: ast.AST) -> None:
        """Record a blocking call — unless the site is allowlisted, in
        which case it neither fires directly nor propagates to callers."""
        if self.fn.module.is_suppressed(
                "blocking-under-lock", getattr(node, "lineno", 0)):
            return
        self.fn.blocking.append((label, held, node))

    # -- lock / type resolution ---------------------------------------------

    def _resolve_lock(self, expr: ast.AST) -> LockInfo | None:
        chain = _attr_chain(expr)
        if not chain:
            return None
        if len(chain) == 1:
            name = chain[0]
            if name in self.local_locks:
                return self.local_locks[name]
            return self.model.module_locks.get(
                self.fn.module.rel_path, {}).get(name)
        attr = chain[-1]
        if chain[0] == "self" and self.fn.cls:
            if len(chain) == 2:
                lk = self.model.find_lock(self.fn.cls, attr)
                if lk is not None:
                    return lk
                return None
            if len(chain) == 3:  # self.res._cond via attr type
                t = self._self_attr_type(chain[1])
                return self.model.find_lock(t, attr) if t else None
            return None
        if len(chain) == 2:
            t = self.local_types.get(chain[0])
            if t:
                return self.model.find_lock(t, attr)
            return self.model.unique_attr_lock(attr)
        return None

    def _self_attr_type(self, attr: str) -> str | None:
        for info in self.model.mro(self.fn.cls or ""):
            t = info.attr_types.get(attr)
            if t:
                return t
        return None

    def _owner_cls(self) -> str | None:
        return self.fn.cls

    # -- statement walk -------------------------------------------------------

    def _body(self, stmts: list, held: tuple, in_while: int) -> None:
        i = 0
        while i < len(stmts):
            s = stmts[i]
            lk = self._acquire_stmt(s)
            if lk is not None:
                self.fn.acquires.append((lk, held, s))
                j = self._find_release(stmts, i + 1, lk)
                self._body(stmts[i + 1:j], held + (lk,), in_while)
                i = j + 1
                continue
            self._stmt(s, held, in_while)
            i += 1

    def _acquire_stmt(self, s: ast.stmt) -> LockInfo | None:
        if (isinstance(s, ast.Expr) and isinstance(s.value, ast.Call)
                and (c := _attr_chain(s.value.func))
                and c[-1] == "acquire"):
            return self._resolve_lock_chain(c[:-1])
        return None

    def _resolve_lock_chain(self, chain: list) -> LockInfo | None:
        if not chain:
            return None
        node: ast.AST = ast.Name(id=chain[0])
        for part in chain[1:]:
            node = ast.Attribute(value=node, attr=part)
        return self._resolve_lock(node)

    def _find_release(self, stmts: list, start: int, lk: LockInfo) -> int:
        for j in range(start, len(stmts)):
            s = stmts[j]
            for sub in ast.walk(s):
                if (isinstance(sub, ast.Call)
                        and (c := _attr_chain(sub.func))
                        and c[-1] == "release"
                        and self._resolve_lock_chain(c[:-1]) is lk):
                    return j
        return len(stmts)

    def _stmt(self, s: ast.stmt, held: tuple, in_while: int) -> None:
        if isinstance(s, _SKIP_SCOPES[:2]):
            return  # nested defs get their own walker
        if isinstance(s, ast.With):
            inner = list(held)
            for item in s.items:
                lk = self._resolve_lock(item.context_expr)
                if lk is not None:
                    self.fn.acquires.append((lk, tuple(inner), item.context_expr))
                    inner.append(lk)
                else:
                    self._expr(item.context_expr, held, in_while)
            self._body(s.body, tuple(inner), in_while)
            return
        if isinstance(s, ast.While):
            self._expr(s.test, held, in_while)
            self._body(s.body, held, in_while + 1)
            self._body(s.orelse, held, in_while)
            return
        if isinstance(s, (ast.If,)):
            self._expr(s.test, held, in_while)
            self._body(s.body, held, in_while)
            self._body(s.orelse, held, in_while)
            return
        if isinstance(s, ast.For):
            self._expr(s.iter, held, in_while)
            self._body(s.body, held, in_while + 1)
            self._body(s.orelse, held, in_while)
            return
        if isinstance(s, ast.Try):
            self._body(s.body, held, in_while)
            for h in s.handlers:
                self._body(h.body, held, in_while)
            self._body(s.orelse, held, in_while)
            self._body(s.finalbody, held, in_while)
            return
        if isinstance(s, ast.Assign):
            self._expr(s.value, held, in_while)
            kind = _lock_ctor_kind(s.value)
            for tgt in s.targets:
                chain = _attr_chain(tgt)
                if kind and len(chain) == 1 and self.fn.cls is None:
                    self.local_locks[chain[0]] = LockInfo(
                        f"{self.fn.name}.{chain[0]}", kind,
                        self.fn.module.rel_path, s.lineno, None, chain[0])
                if len(chain) == 1:
                    vchain = _attr_chain(s.value)
                    if (isinstance(s.value, ast.Call) and vchain
                            and vchain[-1] in self.model.classes):
                        self.local_types[chain[0]] = vchain[-1]
                    elif (isinstance(s.value, ast.Call)
                          and len(vchain) == 2
                          and vchain[0] in self.model.classes
                          and vchain[1].startswith("from")):
                        # alternate-constructor idiom: Cls.from_x() -> Cls
                        self.local_types[chain[0]] = vchain[0]
                    elif (len(vchain) == 2 and vchain[0] == "self"
                          and self.fn.cls):
                        t = self._self_attr_type(vchain[1])
                        if t:
                            self.local_types[chain[0]] = t
                    elif len(vchain) == 1 and vchain[0] in self.local_types:
                        self.local_types[chain[0]] = \
                            self.local_types[vchain[0]]
                self._record_target(tgt, held, s)
            return
        if isinstance(s, ast.AugAssign):
            self._expr(s.value, held, in_while)
            self._record_target(s.target, held, s, aug=True)
            return
        if isinstance(s, ast.Expr):
            self._expr(s.value, held, in_while)
            return
        if isinstance(s, ast.Return) and s.value is not None:
            self._expr(s.value, held, in_while)
            return
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self._expr(child, held, in_while)
            elif isinstance(child, ast.stmt):
                self._stmt(child, held, in_while)

    def _record_target(self, tgt, held, s, aug=False) -> None:
        chain = _attr_chain(tgt)
        if len(chain) >= 2 and chain[0] == "self" and self.fn.cls:
            self.fn.accesses.append(
                (self.fn.cls, chain[1], "write", held, s))
            if aug:
                self.fn.accesses.append(
                    (self.fn.cls, chain[1], "read", held, s))
        elif isinstance(tgt, ast.Tuple):
            for el in tgt.elts:
                self._record_target(el, held, s, aug=aug)

    # -- expression walk ------------------------------------------------------

    def _expr(self, node: ast.AST, held: tuple, in_while: int) -> None:
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, _SKIP_SCOPES):
                continue
            if isinstance(n, ast.Call):
                self._call(n, held, in_while)
            elif isinstance(n, ast.Attribute):
                chain = _attr_chain(n)
                if (len(chain) == 2 and chain[0] == "self" and self.fn.cls
                        and isinstance(n.ctx, ast.Load)):
                    self.fn.accesses.append(
                        (self.fn.cls, chain[1], "read", held, n))
            stack.extend(ast.iter_child_nodes(n))

    def _call(self, node: ast.Call, held: tuple, in_while: int) -> None:
        chain = _attr_chain(node.func)
        if not chain:
            # get_emitter().emit(...) — receiver is itself a call
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "emit"
                    and isinstance(f.value, ast.Call)
                    and (rc := _attr_chain(f.value.func))
                    and rc[-1] == "get_emitter"):
                self._block("telemetry emit (file I/O)", held, node)
            return
        last = chain[-1]
        # waits: predicate-loop discipline + blocking classification
        if last in ("wait", "wait_for") and len(chain) >= 2:
            lk = self._resolve_lock_chain(chain[:-1])
            if lk is not None and lk.kind == "condition":
                if last == "wait":
                    self.fn.waits.append((lk, in_while > 0, node))
                # waiting on a held condition releases it — not blocking
                # w.r.t. itself; other held locks stay a hazard but the
                # repo idiom (park on the manager's own cond) is clean
                return
            if chain[-2] in ("event", "_event") or last == "wait_for":
                self._block(".".join(chain[-2:]), held, node)
            return
        label = self._blocking_label(chain)
        if label is not None:
            self._block(label, held, node)
        if last == "current_ctx":
            self.fn.ctx_calls.append(node)
        self._register_thread_targets(node, chain)
        # call-graph edge
        targets = self._resolve_call(chain)
        if targets:
            self.fn.calls.append((targets, held, node))

    def _blocking_label(self, chain: list) -> str | None:
        last = chain[-1]
        if last in _BLOCKING_BARE and (len(chain) == 1 or chain[0] in (
                "urllib", "request", "time", "jax", "np", "os")):
            if last == "open" and len(chain) > 1:
                return None  # os.open etc.: keep to the builtin
            return _BLOCKING_BARE[last]
        if chain[0] == "subprocess":
            return f"subprocess.{last}"
        if chain[0] == "socket" and len(chain) > 1:
            return f"socket.{last}"
        if last == "block_until_ready":
            return "block_until_ready"
        if last == "emit" and len(chain) >= 2 and "emitter" in chain[-2].lower():
            return "telemetry emit (file I/O)"
        return None

    def _register_thread_targets(self, node: ast.Call, chain: list) -> None:
        target_expr = None
        daemon = None
        if chain[-1] == "Thread" and (len(chain) == 1
                                      or chain[0] == "threading"):
            for kw in node.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
                if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                    daemon = bool(kw.value.value)
            if target_expr is None and node.args:
                target_expr = node.args[0]
            self.fn.spawns.append((node, daemon, None))
        elif chain[-1] == "Timer" and (len(chain) == 1
                                       or chain[0] == "threading"):
            if len(node.args) >= 2:
                target_expr = node.args[1]
            for kw in node.keywords:
                if kw.arg == "function":
                    target_expr = kw.value
        elif (chain[-1] == "submit" and len(chain) >= 2
                and any(k in chain[-2].lower()
                        for k in ("executor", "pool"))):
            if node.args:
                target_expr = node.args[0]
        if target_expr is None:
            return
        for g in self._resolve_call(_attr_chain(target_expr)):
            g.is_thread_target = True

    def _resolve_call(self, chain: list) -> list[FuncNode]:
        if not chain:
            return []
        model = self.model
        name = chain[-1]
        if len(chain) >= 2 and (
                chain[0] in _MODULE_RECEIVERS
                or chain[0] in model.module_imports.get(
                    self.fn.module.rel_path, ())):
            return []  # module function, not a method: no fallback
        if len(chain) == 1:
            fn = self.fn
            while fn is not None:  # nested defs shadow outward
                if name in fn.children:
                    return [fn.children[name]]
                fn = fn.parent
            mod_fn = model.module_funcs.get(
                self.fn.module.rel_path, {}).get(name)
            if mod_fn is not None:
                return [mod_fn]
            if self.fn.cls:  # bare sibling-method call (rare)
                hit = model.family_methods(self.fn.cls, name)
                if hit:
                    return hit
            return model.unique_named(name)
        if chain[0] == "self" and len(chain) == 2 and self.fn.cls:
            hit = model.family_methods(self.fn.cls, name)
            return hit or model.unique_named(name)
        if chain[0] == "self" and len(chain) == 3 and self.fn.cls:
            t = self._self_attr_type(chain[1])
            if t:
                hit = model.family_methods(t, name)
                if hit:
                    return hit
            return model.unique_named(name)
        if len(chain) == 2:
            t = self.local_types.get(chain[0])
            if t:
                hit = model.family_methods(t, name)
                if hit:
                    return hit
            return model.unique_named(name)
        return model.unique_named(name)


# --------------------------------------------------------------------------
# the rules
# --------------------------------------------------------------------------


def _eff_held(f: FuncNode, held: tuple) -> frozenset:
    return frozenset(lk.name for lk in held) | f.held_in


@register
class LockOrderRule(Rule):
    rule_id = "lock-order"
    doc = ("cycle in the static lock-acquisition graph (potential "
           "deadlock), or re-acquisition of a non-reentrant Lock")
    project_wide = True

    def check_project(self, project: ProjectContext) -> list[Finding]:
        model = ConcurrencyModel.of(project)
        findings: list[Finding] = []
        # edge -> (module, node, qual) evidence, first occurrence wins
        edges: dict[tuple[str, str], tuple] = {}

        def add_edge(a: str, b: str, f: FuncNode, node) -> None:
            edges.setdefault((a, b), (f.module, node, f.short()))

        for f in model.funcs:
            for lk, held, node in f.acquires:
                eff = _eff_held(f, held)
                for a in eff:
                    if a == lk.name:
                        if lk.kind not in _REENTRANT_KINDS:
                            fnd = f.module.finding(
                                self.rule_id, node,
                                f"non-reentrant lock {lk.name} re-acquired "
                                f"while already held in {f.short()} — "
                                "immediate self-deadlock; use an RLock or "
                                "split the *_locked helper out",
                            )
                            if fnd:
                                findings.append(fnd)
                        continue
                    add_edge(a, lk.name, f, node)
            for g, held, node in model.edges.get(f, ()):
                eff = _eff_held(f, held)
                if not eff:
                    continue
                for a in eff:
                    for b in g.trans_locks:
                        if b == a:
                            lk = model.locks_by_name.get(b)
                            if lk is not None and \
                                    lk.kind not in _REENTRANT_KINDS:
                                fnd = f.module.finding(
                                    self.rule_id, node,
                                    f"non-reentrant lock {b} re-acquired "
                                    f"via call chain through {g.short()} "
                                    f"while held in {f.short()} — "
                                    "self-deadlock",
                                )
                                if fnd:
                                    findings.append(fnd)
                            continue
                        add_edge(a, b, f, node)

        findings.extend(self._cycles(edges))
        return findings

    def _cycles(self, edges: dict) -> list[Finding]:
        graph: dict[str, set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        findings, seen = [], set()
        for start in sorted(graph):
            path: list[str] = []
            on_path: set[str] = set()

            def dfs(n: str) -> list[str] | None:
                if n in on_path:
                    return path[path.index(n):] + [n]
                if n not in graph:
                    return None
                path.append(n)
                on_path.add(n)
                for nxt in sorted(graph[n]):
                    cyc = dfs(nxt)
                    if cyc:
                        return cyc
                path.pop()
                on_path.discard(n)
                return None

            cycle = dfs(start)
            if not cycle:
                continue
            key = frozenset(cycle)
            if key in seen:
                continue
            seen.add(key)
            pairs = list(zip(cycle, cycle[1:]))
            sites = "; ".join(
                f"{a} -> {b} at {edges[(a, b)][0].rel_path}:"
                f"{edges[(a, b)][1].lineno} (in {edges[(a, b)][2]})"
                for a, b in pairs if (a, b) in edges
            )
            module, node, _ = edges[pairs[0]]
            fnd = module.finding(
                self.rule_id, node,
                "potential deadlock: lock-order cycle "
                + " -> ".join(cycle) + f"; {sites} — pick one global "
                "order or drop the nested acquire",
            )
            if fnd:
                findings.append(fnd)
        return findings


@register
class UnguardedSharedRule(Rule):
    rule_id = "unguarded-shared"
    doc = ("field written under a lock but read/written from a Thread "
           "target / timer / executor closure without that lock")
    project_wide = True

    def check_project(self, project: ProjectContext) -> list[Finding]:
        model = ConcurrencyModel.of(project)
        guards: dict[tuple[str, str], set[str]] = {}
        declared_locks = {name for name, lk in model.locks_by_name.items()
                          if lk.guards is not None}
        # declarations pin a lock's guarded set exactly
        for name, lk in model.locks_by_name.items():
            if lk.guards is None or lk.cls is None:
                continue
            root = model.family_root(lk.cls)
            for fld in lk.guards:
                guards.setdefault((root, fld), set()).add(name)
        # inference: a write under a lock of the same class family
        for f in model.funcs:
            if f.cls is None:
                continue
            for cls, attr, rw, held, _node in f.accesses:
                if rw != "write":
                    continue
                eff = _eff_held(f, held)
                root = model.family_root(cls)
                for ln in eff:
                    lk = model.locks_by_name.get(ln)
                    if lk is None or ln in declared_locks:
                        continue
                    if lk.cls is None or \
                            model.family_root(lk.cls) != root:
                        continue
                    guards.setdefault((root, attr), set()).add(ln)
        lock_attrs = {lk.attr for lk in model.locks_by_name.values()}
        findings, reported = [], set()
        for f in sorted(model.thread_ctx, key=lambda x: x.qual):
            if f.cls is None:
                continue
            for cls, attr, rw, held, node in f.accesses:
                if attr in lock_attrs:
                    continue
                key = (model.family_root(cls), attr)
                need = guards.get(key)
                if not need:
                    continue
                if _eff_held(f, held) & need:
                    continue
                if (f.qual, key) in reported:
                    continue
                reported.add((f.qual, key))
                fnd = f.module.finding(
                    self.rule_id, node,
                    f"field {attr!r} of {cls} is guarded by "
                    f"{'/'.join(sorted(need))} elsewhere but "
                    f"{'written' if rw == 'write' else 'read'} without it "
                    f"in {f.short()}, which runs on a background thread — "
                    "take the lock, or declare the lock's true guarded "
                    "set with # graftlint: guards(...) on its assignment",
                )
                if fnd:
                    findings.append(fnd)
        return findings


@register
class BlockingUnderLockRule(Rule):
    rule_id = "blocking-under-lock"
    doc = ("urlopen/socket/subprocess/sleep/device_put/block_until_ready/"
           "file-I/O reachable while a lock is held")
    project_wide = True

    def check_project(self, project: ProjectContext) -> list[Finding]:
        model = ConcurrencyModel.of(project)
        findings, reported = [], set()
        for f in model.funcs:
            for label, held, node in f.blocking:
                eff = _eff_held(f, held)
                if not eff or (f.qual, node.lineno) in reported:
                    continue
                reported.add((f.qual, node.lineno))
                fnd = f.module.finding(
                    self.rule_id, node,
                    f"blocking {label} while holding "
                    f"{'/'.join(sorted(eff))} in {f.short()} — every "
                    "waiter on the lock pays this wait; move it outside "
                    "the critical section or allowlist the site with "
                    "# graftlint: ok(blocking-under-lock: reason)",
                )
                if fnd:
                    findings.append(fnd)
            for g, held, node in model.edges.get(f, ()):
                if not held or not g.trans_blocking:
                    continue
                if (f.qual, node.lineno) in reported:
                    continue
                reported.add((f.qual, node.lineno))
                label, via = next(iter(sorted(g.trans_blocking.items())))
                locks = "/".join(sorted(lk.name for lk in held))
                fnd = f.module.finding(
                    self.rule_id, node,
                    f"call to {g.short()} while holding {locks} reaches "
                    f"blocking {label} ({via}) — hoist the blocking work "
                    "out of the critical section or allowlist with "
                    "# graftlint: ok(blocking-under-lock: reason)",
                )
                if fnd:
                    findings.append(fnd)
        return findings


@register
class ThreadHygieneRule(Rule):
    rule_id = "thread-hygiene"
    doc = ("non-daemon threads never joined; Condition.wait without a "
           "predicate loop; current_ctx() inside a thread-entry closure")
    project_wide = True

    def check_project(self, project: ProjectContext) -> list[Finding]:
        model = ConcurrencyModel.of(project)
        findings: list[Finding] = []
        for f in model.funcs:
            findings.extend(self._spawns(model, f))
            for lk, in_while, node in f.waits:
                if in_while:
                    continue
                fnd = f.module.finding(
                    self.rule_id, node,
                    f"{lk.name}.wait() outside a predicate loop in "
                    f"{f.short()} — spurious wakeups and missed notifies "
                    "are legal; use `while not pred: cond.wait()` or "
                    "wait_for()",
                )
                if fnd:
                    findings.append(fnd)
            if f.is_thread_target:
                for node in f.ctx_calls:
                    fnd = f.module.finding(
                        self.rule_id, node,
                        f"current_ctx() inside thread-entry {f.short()} "
                        "reads the NEW thread's empty context — capture "
                        "ctx = current_ctx() on the submitting thread "
                        "before the def (fleet/residency.py prefetch "
                        "idiom) and pass it in",
                    )
                    if fnd:
                        findings.append(fnd)
        return findings

    def _spawns(self, model: ConcurrencyModel, f: FuncNode) -> list[Finding]:
        out = []
        rel = f.module.rel_path
        joins = model.joins.get(rel, set())
        daemon_later = model.daemon_later.get(rel, set())
        for node, daemon, _bind in f.spawns:
            if daemon:
                continue
            bind = self._binding(f, node)
            if bind is not None and (bind in joins
                                     or bind in daemon_later):
                continue
            fnd = f.module.finding(
                self.rule_id, node,
                f"non-daemon Thread in {f.short()} is never joined — it "
                "outlives shutdown and blocks interpreter exit; pass "
                "daemon=True or join it on the close path",
            )
            if fnd:
                out.append(fnd)
        return out

    def _binding(self, f: FuncNode, call: ast.Call) -> tuple | None:
        """The name/attr chain the Thread was assigned to, if any."""
        for sub in ast.walk(f.node):
            if isinstance(sub, ast.Assign) and sub.value is call:
                for tgt in sub.targets:
                    chain = _attr_chain(tgt)
                    if chain:
                        return tuple(chain)
        return None
