"""Finding reporters: human text and machine JSON (the CLI's --format)."""

from __future__ import annotations

import json
from collections import Counter

from .core import RULES, Finding


def rule_counts(findings: list[Finding]) -> dict[str, int]:
    return dict(Counter(f.rule for f in findings))


def render_text(
    new: list[Finding],
    accepted: list[Finding],
    n_fixed: int = 0,
    errors: list[str] | None = None,
) -> str:
    lines: list[str] = []
    for f in new:
        lines.append(f"{f.location()}: [{f.rule}] {f.message}")
        if f.snippet:
            lines.append(f"    {f.snippet}")
    for e in errors or ():
        lines.append(f"error: {e}")
    counts = rule_counts(new)
    summary = ", ".join(f"{r}:{n}" for r, n in sorted(counts.items()))
    lines.append(
        f"graftlint: {len(new)} new finding(s)"
        + (f" ({summary})" if summary else "")
        + f", {len(accepted)} baselined"
        + (f", {n_fixed} baseline entr(ies) no longer observed" if n_fixed else "")
    )
    if n_fixed:
        lines.append(
            "    (fixed or moved — regenerate with --write-baseline to "
            "commit the shrink)"
        )
    return "\n".join(lines)


def render_json(
    new: list[Finding],
    accepted: list[Finding],
    n_fixed: int = 0,
    errors: list[str] | None = None,
    duration_s: float | None = None,
    rule_times_s: dict | None = None,
) -> str:
    def row(f: Finding) -> dict:
        return {
            "rule": f.rule,
            "path": f.path.replace("\\", "/"),
            "line": f.line,
            "col": f.col,
            "message": f.message,
            "snippet": f.snippet,
        }

    return json.dumps(
        {
            "tool": "graftlint",
            "version": 1,
            "rules": {rid: r.doc for rid, r in sorted(RULES.items())},
            "new": [row(f) for f in new],
            "baselined": [row(f) for f in accepted],
            "n_new": len(new),
            "n_baselined": len(accepted),
            "n_fixed": n_fixed,
            "rule_counts": rule_counts(new + accepted),
            "new_rule_counts": rule_counts(new),
            "errors": list(errors or ()),
            "duration_s": duration_s,
            "rule_times_s": {
                r: round(t, 4) for r, t in (rule_times_s or {}).items()
            },
        },
        indent=1,
        sort_keys=True,
    )
