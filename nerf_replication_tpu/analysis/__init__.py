"""graftlint: JAX-aware static analysis + runtime sanitizer for this repo.

PR 1 made recompile storms, host-dispatch stalls, and HBM creep observable
at runtime; this package catches them at review time. An AST engine
(``core``) runs seven codebase-tuned rules (``rules``: host-sync, retrace,
donate, rng, side-effect, config-key, aot) over the package and entrypoints,
gated through a committed baseline of accepted legacy findings
(``baseline``, ``graftlint_baseline.json``) so only NEW hazards fail.
PR 18 extends the engine interprocedurally: ``concurrency`` builds a
module-spanning lock model + call graph and runs four more rules
(lock-order, unguarded-shared, blocking-under-lock, thread-hygiene),
paired with a runtime :class:`LockOrderRecorder` whose per-thread
acquisition DAG is the dynamic witness for what R10 claims statically.
``scripts/graftlint.py`` is the CLI; tier-1 runs it via
tests/test_analysis.py. The engine is jax-free by design — only the
runtime ``sanitizer`` imports jax, lazily.

See docs/static_analysis.md for the rule catalog, suppression syntax
(``# graftlint: ok(rule: reason)``, ``# graftlint: hot``), and the
baseline workflow.
"""

from .baseline import (
    BASELINE_FILENAME,
    diff_baseline,
    load_baseline,
    save_baseline,
    to_baseline,
    validate_baseline_data,
)
from .core import (
    CONCURRENCY_RULE_IDS,
    DEFAULT_SCAN,
    RULE_IDS,
    RULES,
    Finding,
    lint_paths,
    lint_source,
)
from .reporters import render_json, render_text, rule_counts
from .sanitizer import (
    LockOrderError,
    LockOrderRecorder,
    SanitizerError,
    SanitizerProbe,
    sanitizer,
)

__all__ = [
    "BASELINE_FILENAME",
    "CONCURRENCY_RULE_IDS",
    "DEFAULT_SCAN",
    "Finding",
    "LockOrderError",
    "LockOrderRecorder",
    "RULES",
    "RULE_IDS",
    "SanitizerError",
    "SanitizerProbe",
    "diff_baseline",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "render_json",
    "render_text",
    "rule_counts",
    "sanitizer",
    "save_baseline",
    "to_baseline",
    "validate_baseline_data",
]
