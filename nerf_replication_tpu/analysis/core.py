"""graftlint engine core: findings, suppressions, module context, registry.

The engine is deliberately jax-free (pure stdlib ``ast`` + ``tokenize``)
so the CI gate and the schema checker can run it anywhere — the same
constraint ``scripts/check_telemetry_schema.py`` lives under. Rules are
visitor-style checkers registered in :data:`RULES`; each receives a
:class:`ModuleContext` (one parsed file plus its hot-path/jit analysis)
or, for project-wide rules, the whole :class:`ProjectContext`.

Two kinds of "hotness" drive the JAX-specific rules (docs/static_analysis.md):

* **traced** — code that runs *inside* a jit trace: functions decorated
  with ``jax.jit`` / ``partial(jax.jit, ...)``, functions wrapped by a
  ``jax.jit(f)`` call in the same module, functions handed to
  ``jax.lax.map`` / ``scan`` / ``vmap`` / ``grad`` from traced code, plus
  everything reachable from those through the intra-module call graph.
  Host syncs here are trace-time constants or errors; side effects leak
  tracers.
* **dispatch-hot** — host code on a per-step/per-request path, marked
  ``# graftlint: hot`` on (or above) its ``def`` line, plus everything it
  calls. Device pulls here (``np.asarray`` on executable outputs) stall
  the dispatch pipeline.

Suppressions are inline comments::

    x = np.asarray(y)  # graftlint: ok(host-sync: scatter back to callers)
    # graftlint: ok(rng)          <- on its own line: applies to the NEXT line
    # graftlint: skip-file        <- first 10 lines: skips the whole file

``ok()`` with no rule list suppresses every rule on that line.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

# rule id -> one-line description (registry filled by rules.py import)
RULES: dict[str, "Rule"] = {}

# R1..R13 short names used in findings, suppressions, and the baseline
RULE_IDS = (
    "host-sync",           # R1
    "retrace",             # R2
    "donate",              # R3
    "rng",                 # R4
    "side-effect",         # R5
    "config-key",          # R6
    "aot",                 # R7
    "swallow",             # R8
    "emit-hot",            # R9
    "lock-order",          # R10
    "unguarded-shared",    # R11
    "blocking-under-lock", # R12
    "thread-hygiene",      # R13
)

# the interprocedural concurrency pass (R10-R13, concurrency.py)
CONCURRENCY_RULE_IDS = RULE_IDS[9:]

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*ok(?:\(([^)]*)\))?")
_HOT_RE = re.compile(r"#\s*graftlint:\s*hot\b")
_SKIP_FILE_RE = re.compile(r"#\s*graftlint:\s*skip-file\b")


@dataclass(frozen=True)
class Finding:
    """One lint finding. ``snippet`` (the stripped source line) rather than
    the line number is the stable part of its identity — see baseline.py."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"


class Rule:
    """A registered checker. Subclasses set ``rule_id``/``doc`` and
    implement ``check`` (per-module) or ``check_project`` (whole scan)."""

    rule_id: str = ""
    doc: str = ""
    project_wide = False

    def check(self, module: "ModuleContext") -> list[Finding]:
        return []

    def check_project(self, project: "ProjectContext") -> list[Finding]:
        return []


def register(rule_cls: type[Rule]) -> type[Rule]:
    RULES[rule_cls.rule_id] = rule_cls()
    return rule_cls


# --------------------------------------------------------------------------
# per-module analysis
# --------------------------------------------------------------------------


@dataclass
class FunctionInfo:
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    qualname: str
    name: str
    cls: str | None  # enclosing class name, for self.method resolution
    traced: bool = False  # runs inside a jit trace
    hot: bool = False  # host-side per-step/per-request path
    calls: set[str] = field(default_factory=set)  # callee names (bare / Cls.m)
    local_names: set[str] = field(default_factory=set)


def _attr_chain(node: ast.AST) -> list[str]:
    """``jax.lax.map`` -> ["jax", "lax", "map"]; [] when not a pure chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


def is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` / bare ``jit`` (from jax import jit) as an expression."""
    chain = _attr_chain(node)
    return chain in (["jax", "jit"], ["jit"]) or (
        len(chain) == 2 and chain[1] == "jit" and chain[0] in ("jax", "jaxlib")
    )


def jit_call_of(node: ast.AST) -> ast.Call | None:
    """The ``jax.jit(...)`` Call under ``node`` when node IS a jit
    construction: ``jax.jit(f, ...)`` or ``partial(jax.jit, ...)``."""
    if isinstance(node, ast.Call):
        if is_jit_expr(node.func):
            return node
        chain = _attr_chain(node.func)
        if chain and chain[-1] == "partial" and node.args:
            if is_jit_expr(node.args[0]):
                return node
    return None


def jit_static_kwargs(call: ast.Call) -> dict[str, ast.expr]:
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


_TRACE_TAKERS = {
    # jax transforms whose callable argument runs traced
    ("jax", "lax", "map"), ("lax", "map"),
    ("jax", "lax", "scan"), ("lax", "scan"),
    ("jax", "lax", "cond"), ("lax", "cond"),
    ("jax", "lax", "while_loop"), ("lax", "while_loop"),
    ("jax", "lax", "fori_loop"), ("lax", "fori_loop"),
    ("jax", "vmap"), ("vmap",),
    ("jax", "pmap"), ("pmap",),
    ("jax", "grad"), ("grad",),
    ("jax", "value_and_grad"), ("value_and_grad",),
    ("jax", "checkpoint",), ("jax", "remat"),
    ("shard_map",),
}


class ModuleContext:
    """One parsed file plus everything the rules need to know about it."""

    def __init__(self, path: str, source: str, rel_path: str | None = None):
        self.path = path
        self.rel_path = rel_path or path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.skip_file = any(
            _SKIP_FILE_RE.search(line) for line in self.lines[:10]
        )
        # line -> set of suppressed rule ids ("*" = all)
        self.suppressions: dict[int, set[str]] = {}
        self.hot_marker_lines: set[int] = set()
        self._scan_comments()
        self.functions: dict[str, FunctionInfo] = {}
        self._jit_wrapped_names: set[str] = set()
        self._collect_functions()
        self._propagate()

    # -- comments ------------------------------------------------------------
    def _scan_comments(self) -> None:
        for i, line in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if m:
                spec = (m.group(1) or "").strip()
                rules = {"*"}
                if spec:
                    # "rule1, rule2: free-text reason" — reason after ':'
                    rule_part = spec.split(":", 1)[0]
                    rules = {
                        r.strip() for r in rule_part.split(",") if r.strip()
                    } or {"*"}
                # a bare-comment line suppresses the next line instead
                target = i + 1 if line.split("#", 1)[0].strip() == "" else i
                self.suppressions.setdefault(target, set()).update(rules)
            if _HOT_RE.search(line):
                self.hot_marker_lines.add(i)

    def is_suppressed(self, rule: str, line: int) -> bool:
        rules = self.suppressions.get(line, ())
        return "*" in rules or rule in rules

    # -- function table ------------------------------------------------------
    def _collect_functions(self) -> None:
        module_jit_args: set[str] = set()

        class Collector(ast.NodeVisitor):
            def __init__(collector):
                collector.stack: list[str] = []
                collector.cls_stack: list[str] = []
                collector.traced_depth = 0

            def visit_ClassDef(collector, node):
                collector.cls_stack.append(node.name)
                collector.generic_visit(node)
                collector.cls_stack.pop()

            def _handle_fn(collector, node):
                qual = ".".join(collector.stack + [node.name])
                traced = collector.traced_depth > 0
                for dec in node.decorator_list:
                    if is_jit_expr(dec) or jit_call_of(dec) is not None:
                        traced = True
                hot = (
                    node.lineno in self.hot_marker_lines
                    or (node.lineno - 1) in self.hot_marker_lines
                    or any(
                        d.lineno in self.hot_marker_lines
                        for d in node.decorator_list
                    )
                )
                info = FunctionInfo(
                    node=node,
                    qualname=qual,
                    name=node.name,
                    cls=collector.cls_stack[-1] if collector.cls_stack else None,
                    traced=traced,
                    hot=hot,
                )
                info.local_names = _local_names(node)
                info.calls = _callee_names(node)
                self.functions[qual] = info
                collector.stack.append(node.name)
                if traced:
                    collector.traced_depth += 1
                collector.generic_visit(node)
                if traced:
                    collector.traced_depth -= 1
                collector.stack.pop()

            visit_FunctionDef = _handle_fn
            visit_AsyncFunctionDef = _handle_fn

            def visit_Call(collector, node):
                # jax.jit(f) / jax.jit(fn_name, ...): mark f traced
                call = jit_call_of(node)
                if call is not None:
                    args = call.args
                    # for partial(jax.jit, f) the wrapped fn is args[1]
                    if args and is_jit_expr(args[0]):
                        args = args[1:]
                    if args and isinstance(args[0], ast.Name):
                        module_jit_args.add(args[0].id)
                # callables handed to trace-taking transforms run traced
                chain = tuple(_attr_chain(node.func))
                if chain in _TRACE_TAKERS:
                    for a in node.args[:1]:
                        if isinstance(a, ast.Name):
                            module_jit_args.add(a.id)
                collector.generic_visit(node)

        Collector().visit(self.tree)
        self._jit_wrapped_names = module_jit_args
        for info in self.functions.values():
            if info.name in module_jit_args:
                info.traced = True

    def _propagate(self) -> None:
        """Flood ``traced``/``hot`` along the intra-module call graph."""
        by_name: dict[str, list[FunctionInfo]] = {}
        for info in self.functions.values():
            by_name.setdefault(info.name, []).append(info)
            if info.cls:
                by_name.setdefault(f"{info.cls}.{info.name}", []).append(info)

        for flag in ("traced", "hot"):
            changed = True
            while changed:
                changed = False
                for info in self.functions.values():
                    if not getattr(info, flag):
                        continue
                    for callee in info.calls:
                        targets = by_name.get(callee, [])
                        if info.cls and "." not in callee:
                            # a bare call inside a method prefers a sibling
                            # method of the same class when one exists
                            scoped = by_name.get(f"{info.cls}.{callee}")
                            if scoped:
                                targets = scoped
                        for t in targets:
                            if not getattr(t, flag):
                                setattr(t, flag, True)
                                changed = True

    # -- lookup helpers ------------------------------------------------------
    def enclosing_function(self, node_line: int) -> FunctionInfo | None:
        """Innermost function whose body spans ``node_line``."""
        best: FunctionInfo | None = None
        best_span = None
        for info in self.functions.values():
            n = info.node
            end = getattr(n, "end_lineno", n.lineno)
            if n.lineno <= node_line <= end:
                span = end - n.lineno
                if best_span is None or span < best_span:
                    best, best_span = info, span
        return best

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding | None:
        line = getattr(node, "lineno", 1)
        if self.is_suppressed(rule, line):
            return None
        return Finding(
            rule=rule,
            path=self.rel_path,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            snippet=self.snippet(line),
        )


def _local_names(fn: ast.AST) -> set[str]:
    """Names bound inside ``fn`` (params + assignments), own scope only."""
    names: set[str] = set()
    args = fn.args
    for a in (
        list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
    ):
        names.add(a.arg)
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    for node in ast.walk(fn):
        if node is fn:
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # don't descend into nested scopes for assignment collection —
            # but ast.walk already flattens; accept the over-approximation
            # (it only ever makes rules QUIETER, never noisier)
            pass
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(node.name)
        elif isinstance(node, ast.comprehension):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _callee_names(fn: ast.AST) -> set[str]:
    """Bare and ``self.``-qualified callee names referenced from ``fn``
    (calls AND bare-name references, so callables passed to ``lax.map`` /
    executors count as edges)."""
    calls: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                calls.add(f.id)
            elif isinstance(f, ast.Attribute):
                chain = _attr_chain(f)
                if chain[:1] == ["self"] and len(chain) == 2:
                    calls.add(chain[1])
            # first-arg callables (lax.map(body, ...), executor.submit(fn))
            for a in node.args[:1]:
                if isinstance(a, ast.Name):
                    calls.add(a.id)
    return calls


# --------------------------------------------------------------------------
# project context
# --------------------------------------------------------------------------

ENTRYPOINTS = (
    "train.py", "run.py", "serve.py", "render_video.py", "bench.py",
    "occupancy_grid.py", "check_grid.py", "plot_loss.py",
)

DEFAULT_SCAN = ("nerf_replication_tpu", "scripts") + ENTRYPOINTS


class ProjectContext:
    """All parsed modules of one scan + repo-level config-key knowledge."""

    def __init__(self, modules: list[ModuleContext], repo_root: str | None,
                 config_keys: set[tuple[str, ...]] | None = None):
        self.modules = modules
        self.repo_root = repo_root
        # known config key-paths, e.g. ("train", "lr"); every prefix of a
        # known path is itself known. None => R6 key checks are skipped.
        self.config_keys = config_keys
        # filled lazily by the config rule
        self.is_full_scan = repo_root is not None and any(
            m.rel_path.replace(os.sep, "/").startswith(
                "nerf_replication_tpu/config/"
            )
            for m in modules
        ) and len(modules) >= 20


def iter_py_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [
                    d for d in dirs
                    if d not in ("__pycache__", ".git", "data", "logs")
                ]
                out.extend(
                    os.path.join(root, f) for f in sorted(files)
                    if f.endswith(".py")
                )
        elif p.endswith(".py") and os.path.exists(p):
            out.append(p)
    return sorted(dict.fromkeys(out))


def lint_source(
    source: str,
    path: str = "<string>",
    rules: tuple[str, ...] | None = None,
    config_keys: set[tuple[str, ...]] | None = None,
) -> list[Finding]:
    """Lint one source string (the test-fixture surface)."""
    module = ModuleContext(path, source)
    project = ProjectContext([module], repo_root=None, config_keys=config_keys)
    return _run_rules(project, rules)


def lint_paths(
    paths: list[str],
    repo_root: str | None = None,
    rules: tuple[str, ...] | None = None,
    config_keys: set[tuple[str, ...]] | None = None,
    timings: dict | None = None,
) -> tuple[list[Finding], list[str]]:
    """Lint files/dirs. Returns ``(findings, errors)`` — errors are files
    that failed to parse (reported, not fatal: a lint gate must not die on
    one syntax error in an unrelated script). ``timings``, when passed,
    is filled with per-rule wall seconds (the CLI's --format json and
    lint_run telemetry surface)."""
    modules: list[ModuleContext] = []
    errors: list[str] = []
    for f in iter_py_files(paths):
        rel = os.path.relpath(f, repo_root) if repo_root else f
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
            modules.append(ModuleContext(f, src, rel_path=rel))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(f"{rel}: {type(e).__name__}: {e}")
    if config_keys is None and repo_root is not None:
        from .rules import collect_config_keys

        config_keys = collect_config_keys(repo_root)
    project = ProjectContext(modules, repo_root, config_keys=config_keys)
    return _run_rules(project, rules, timings=timings), errors


def _run_rules(
    project: ProjectContext, rules: tuple[str, ...] | None,
    timings: dict | None = None,
) -> list[Finding]:
    import time

    from . import concurrency as _conc  # noqa: F401  (populates RULES)
    from . import rules as _rules  # noqa: F401  (populates RULES)

    active = [
        r for rid, r in RULES.items() if rules is None or rid in rules
    ]
    if timings is None:
        timings = {}
    findings: list[Finding] = []
    for module in project.modules:
        if module.skip_file:
            continue
        for rule in active:
            if not rule.project_wide:
                t0 = time.perf_counter()
                findings.extend(rule.check(module))
                timings[rule.rule_id] = (
                    timings.get(rule.rule_id, 0.0)
                    + time.perf_counter() - t0
                )
    for rule in active:
        if rule.project_wide:
            t0 = time.perf_counter()
            findings.extend(rule.check_project(project))
            timings[rule.rule_id] = (
                timings.get(rule.rule_id, 0.0) + time.perf_counter() - t0
            )
    # nested loops / overlapping walks can surface the same hazard twice
    findings = list(dict.fromkeys(findings))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
