"""Scene hot-update: publish version N+1 under live traffic.

Retraining a scene must not mean restarting the server or even dropping
the scene: :class:`ScenePublisher` swaps a resident scene's arrays for a
new checkpoint's **atomically**, while in-flight requests finish on the
old version. The protocol, in order:

1. **Gate.** The new checkpoint's tree checksum is verified and the new
   arrays are loaded + compat-validated *before anything changes* — a
   torn N+1 raises :class:`~.errors.SceneLoadError` with a ``torn``
   fault row, and version N keeps serving untouched (the registry still
   names N's artifacts).
2. **Admit + transfer.** N+1's bytes are admitted against the HBM budget
   (both versions are briefly charged) and device_put — still no
   behavior change.
3. **Drain.** The scene enters the publishing set: NEW acquires park on
   the residency condition (they will render N+1), while the pinned
   leases already held — the same refcounts that block eviction
   mid-batch — drain naturally as their batches complete on N.
   ``drain_ms`` in the ``scene_publish`` row is how long that took; a
   drain past ``drain_timeout_s`` aborts the publish
   (:class:`~.errors.ScenePublishError`), refunds N+1's bytes, and N
   serves on.
4. **Swap.** With zero pins, the resident entry is replaced in one
   assignment under the lock, the registry record is updated to N+1's
   artifacts (write-through on a sharded :class:`~.store.SceneStore`),
   and any staged host copy of N is invalidated (stale bytes must not
   re-promote). Parked acquires wake into N+1.

The swap changes *argument values only* — same shapes, same dtypes, the
same prewarmed executables — so a hot-update is recompile-free by
construction (asserted by CompileTracker in tests/test_control_plane.py).
"""

from __future__ import annotations

import threading
import time

from ..obs import get_emitter
from ..resil import fault_point
from .errors import ScenePublishError
from .residency import ResidencyManager, _Resident, _tree_nbytes


class ScenePublisher:
    """Versioned hot-update surface over one ResidencyManager."""

    def __init__(self, residency: ResidencyManager, *,
                 drain_timeout_s: float = 30.0):
        self.residency = residency
        self.drain_timeout_s = float(drain_timeout_s)
        self._lock = threading.Lock()
        self._versions: dict[str, int] = {}
        self.publishes = 0
        self.failed_publishes = 0

    def version(self, scene_id: str) -> int:
        with self._lock:
            return self._versions.get(scene_id, 1)

    def publish(self, record, *, to_version: int | None = None,
                drain_timeout_s: float | None = None) -> dict:
        """Swap ``record.scene_id`` to the artifacts ``record`` names.

        Returns the ``scene_publish`` row fields. Raises SceneLoadError
        (torn/unloadable N+1 — N keeps serving), SceneCompatError
        (N+1 would need a recompile), or ScenePublishError (drain
        timeout / concurrent publish — N keeps serving)."""
        res = self.residency
        sid = record.scene_id
        timeout = (self.drain_timeout_s if drain_timeout_s is None
                   else float(drain_timeout_s))
        from_version = self.version(sid)
        to_version = from_version + 1 if to_version is None else int(to_version)
        t0 = time.perf_counter()

        # chaos seam: a publish-time fault (io_error/truncate) must fail
        # THIS publish and nothing else
        fault_point("fleet.publish", path=record.checkpoint or None)

        with res._cond:
            if sid in res._publishing:
                raise ScenePublishError(
                    sid, f"scene {sid!r}: publish already in flight")

        # 1. gate: checksum + load + validate, before anything changes.
        # _load_host owns the torn-detection ladder (fault row + typed
        # raise), exactly like a cold load of N+1 would.
        try:
            host = res._load_host(record)
            if res.validate is not None:
                res.validate(host)
        except Exception as err:
            self.failed_publishes += 1
            get_emitter().emit(
                "scene_publish", scene=sid, from_version=from_version,
                to_version=to_version, drain_ms=0.0,
                status="torn" if "torn" in str(err) else "error",
            )
            raise
        nbytes = _tree_nbytes(host)

        # 2. admit + transfer: both versions charged until the swap ends
        res._admit(sid, nbytes)
        try:
            import jax

            params, grid, bbox = jax.tree.map(
                jax.device_put, (host.params, host.grid, host.bbox))
        except BaseException:
            with res._cond:
                res._reserved -= nbytes
                res._cond.notify_all()
            raise
        from dataclasses import replace as _replace

        new_data = _replace(host, params=params, grid=grid, bbox=bbox,
                            nbytes=nbytes)

        # 3. drain: park new acquires, wait out the pinned leases on N
        # AND any in-flight load of N (a prefetch committing after the
        # swap would silently revert the scene to the old version)
        swapped = False
        t_drain = time.perf_counter()
        with res._cond:
            res._publishing.add(sid)
            try:
                deadline = time.monotonic() + timeout
                while True:
                    resident = res._resident.get(sid)
                    pins = 0 if resident is None else resident.refcount
                    if pins == 0 and sid not in res._loading:
                        break
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise ScenePublishError(
                            sid,
                            f"scene {sid!r}: drain for v{to_version} timed "
                            f"out after {timeout:.1f}s ({pins} leases still "
                            f"pinned); v{from_version} keeps serving",
                        )
                    res._cond.wait(timeout=min(left, 0.1))
                # 4. swap: registry record first (a failed write leaves
                # the resident set untouched), then one dict assignment;
                # the old arrays release when the entry — and any stale
                # staged copy of N — lets go
                drain_ms = (time.perf_counter() - t_drain) * 1e3
                res.registry.register(record)
                old = res._resident.pop(sid, None)
                res._reserved -= nbytes
                swapped = True
                entry = _Resident(new_data, "publish")
                entry.ever_acquired = True  # not a prefetch-hit candidate
                res._resident[sid] = entry
                res._resident.move_to_end(sid)
                res.loads += 1
                res.bytes_loaded += nbytes
                if old is not None:
                    res.bytes_evicted += old.data.nbytes
                res._invalidate_staged(sid)
                res._stage_host(sid, host, nbytes)
                n_res = len(res._resident)
                res_bytes = res._resident_bytes()
                tier_fields = res._tier_fields()
            except BaseException:
                # abort: refund N+1's reservation and unpark acquires —
                # version N is still the resident entry
                if not swapped:
                    res._reserved -= nbytes
                self.failed_publishes += 1
                raise
            finally:
                res._publishing.discard(sid)
                res._cond.notify_all()
        # the staging write-through queues its evict rows under the lock
        res._flush_rows()

        with self._lock:
            self._versions[sid] = to_version
            self.publishes += 1
        get_emitter().emit(
            "scene_load", scene=sid, bytes=nbytes, source="publish",
            load_s=round(time.perf_counter() - t0, 4),
            resident=n_res, resident_bytes=res_bytes, **tier_fields,
        )
        row = {
            "scene": sid, "from_version": from_version,
            "to_version": to_version, "drain_ms": round(drain_ms, 3),
            "bytes": nbytes, "status": "ok",
        }
        get_emitter().emit("scene_publish", **row)
        return row

    def stats(self) -> dict:
        with self._lock:
            return {
                "versions": dict(self._versions),
                "publishes": self.publishes,
                "failed_publishes": self.failed_publishes,
            }
