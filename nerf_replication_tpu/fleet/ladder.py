"""Tiered residency ladder: disk -> host-RAM staging -> HBM.

The one-level :class:`~.residency.ResidencyManager` *drops* a scene on
eviction — the next request pays the full cold path again (disk read,
tree-checksum walk, retry ladder, h2d). Under fleet churn that is the
dominant tail cost, and it is unnecessary: host RAM is orders of
magnitude larger than HBM. The :class:`TieredResidencyManager` keeps a
second, host-side tier:

* **Write-through staging.** Every disk load parks its host arrays in a
  byte-budgeted staging tier *before* the ``device_put`` (hook:
  ``_stage_host``). Staging has its own LRU and budget
  (``fleet.staging_mb``), independent of HBM.
* **Eviction demotes.** When the HBM budget pushes a scene out and its
  host copy is still staged, the eviction is a **demotion** — the
  ``scene_evict`` row says ``reason: demoted`` and re-admission is a
  pure ``device_put`` (``scene_load`` row with ``source: staging``): no
  disk, no checksum walk, no re-validation. Only when the staged copy is
  already gone does the row degrade to ``reason: lru`` (a true drop).
* **TTLs.** ``sweep()`` expires staged copies older than
  ``staging_ttl_s`` and demotes HBM residents idle past
  ``resident_ttl_s`` (both 0 = off) with ``reason: ttl`` — a scene
  nobody asked about in an hour should not hold bytes at EITHER tier.
* **Typed eviction reasons.** Every ``scene_evict`` row carries
  ``reason`` (``budget`` stays the one-level manager's spelling;
  the ladder emits ``demoted | lru | ttl | manual``) and ``tier``
  (``hbm | staging``), so ``tlm_report`` can split residency churn from
  actual reload cost.

Demote -> re-promote is bitwise: the staged arrays are the SAME host
buffers the original load produced, and re-promotion device_puts them
unchanged (tests/test_control_plane.py pins this, and that a
re-promotion never recompiles).

Sharded scenes (model-parallel serving mesh) ride the same ladder as a
UNIT: the HBM tier accounts the per-device shard bytes
(``SceneData.nbytes``), while staging accounts the TOTAL host bytes —
host RAM holds the whole unsharded scene, so a demotion parks every
shard's source buffer and a re-promotion re-places all shards from it
in one ``placer`` call (still bitwise: same host buffers, same
partition specs). There is no per-shard demote; a scene is resident
everywhere or nowhere (docs/fleet.md "Per-shard byte accounting").
"""

from __future__ import annotations

import time
from collections import OrderedDict

from .residency import ResidencyManager, SceneData


class _Staged:
    """One host-side staged copy (numpy/host arrays, never device)."""

    __slots__ = ("data", "nbytes", "staged_t")

    def __init__(self, data: SceneData, nbytes: int):
        self.data = data
        self.nbytes = int(nbytes)
        self.staged_t = time.monotonic()


class TieredResidencyManager(ResidencyManager):
    """ResidencyManager with a host-RAM staging tier under the HBM LRU."""

    def __init__(self, registry, loader, budget_bytes: int, *,
                 staging_budget_bytes: int,
                 staging_ttl_s: float = 0.0,
                 resident_ttl_s: float = 0.0,
                 **kw):
        super().__init__(registry, loader, budget_bytes, **kw)
        self.staging_budget_bytes = int(staging_budget_bytes)
        self.staging_ttl_s = float(staging_ttl_s)
        self.resident_ttl_s = float(resident_ttl_s)
        self._staging: OrderedDict[str, _Staged] = OrderedDict()
        # ladder counters (under the lock, like the base set)
        self.demotions = 0          # HBM evictions that kept a staged copy
        self.repromotions = 0       # loads served from staging (no disk)
        self.disk_loads = 0         # loads that walked the cold path
        self.staging_evictions = 0  # staged copies dropped (lru + ttl)
        self.ttl_evictions = 0      # ttl expiries at either tier
        self.manual_evictions = 0

    # -- tier hooks (called by the base manager) ------------------------------

    def _staged_host(self, scene_id: str) -> SceneData | None:
        with self._cond:
            self._sweep_staging_locked(time.monotonic())
            staged = self._staging.get(scene_id)
            if staged is not None:
                self._staging.move_to_end(scene_id)
        self._flush_rows()  # TTL sweep may have queued evict rows
        return staged.data if staged is not None else None

    def _note_load(self, source: str) -> None:
        # commit-time accounting (base hook, under the lock): lookups
        # that never commit (admission overload) must not drift the
        # loads == disk_loads + repromotions ledger
        if source == "staging":
            self.repromotions += 1
        else:
            self.disk_loads += 1

    def _stage_host(self, scene_id: str, host: SceneData, nbytes: int) -> None:
        # called under the lock (commit path)
        if nbytes > self.staging_budget_bytes:
            return  # bigger than the whole tier: not stageable
        staged = self._staging.get(scene_id)
        if staged is not None:
            staged.staged_t = time.monotonic()
            self._staging.move_to_end(scene_id)
            return
        self._staging[scene_id] = _Staged(host, nbytes)
        while self._staging_bytes() > self.staging_budget_bytes:
            self._evict_staged_locked(next(iter(self._staging)), "lru")

    def _invalidate_staged(self, scene_id: str) -> None:
        # called under the lock (publish swap): stale version, silent drop
        self._staging.pop(scene_id, None)

    def _retire(self, scene_id: str, resident) -> str:
        # called under the lock, victim already out of the resident dict
        staged = self._staging.get(scene_id)
        if staged is not None:
            staged.staged_t = time.monotonic()
            self._staging.move_to_end(scene_id)
            self.demotions += 1
            return "demoted"
        return "lru"

    def _tier_fields(self) -> dict:
        sb = self._staging_bytes()
        if self.capacity is not None:
            self.capacity.note_residency(self._resident_bytes(), sb)
        return {"staging": len(self._staging), "staging_bytes": sb}

    # -- staging internals ----------------------------------------------------

    def _staging_bytes(self) -> int:
        return sum(s.nbytes for s in self._staging.values())

    def _evict_staged_locked(self, scene_id: str, reason: str) -> None:
        staged = self._staging.pop(scene_id)
        self.staging_evictions += 1
        if reason == "ttl":
            self.ttl_evictions += 1
        elif reason == "manual":
            self.manual_evictions += 1
        self._queue_row(
            "scene_evict", scene=scene_id, bytes=staged.nbytes,
            reason=reason, tier="staging",
            resident=len(self._resident),
            resident_bytes=self._resident_bytes(),
            **self._tier_fields(),
        )

    def _sweep_staging_locked(self, now: float) -> None:
        if self.staging_ttl_s <= 0:
            return
        expired = [sid for sid, s in self._staging.items()
                   if now - s.staged_t > self.staging_ttl_s]
        for sid in expired:
            self._evict_staged_locked(sid, "ttl")

    # -- TTL / manual surface -------------------------------------------------

    def sweep(self, now: float | None = None) -> dict:
        """Expire TTL-stale entries at both tiers (tests pass a future
        ``now``; production calls it from a maintenance cadence).

        HBM residents idle past ``resident_ttl_s`` demote (their staged
        copy survives — re-promotion stays cheap); staged copies older
        than ``staging_ttl_s`` drop. Returns eviction counts."""
        now = time.monotonic() if now is None else float(now)
        out = {"hbm": 0, "staging": 0}
        with self._cond:
            if self.resident_ttl_s > 0:
                idle = [sid for sid, r in self._resident.items()
                        if r.refcount == 0
                        and now - r.last_used_t > self.resident_ttl_s]
                for sid in idle:
                    victim = self._resident.pop(sid)
                    self.evictions += 1
                    self.ttl_evictions += 1
                    self.bytes_evicted += victim.data.nbytes
                    self._queue_row(
                        "scene_evict", scene=sid, bytes=victim.data.nbytes,
                        reason="ttl", tier="hbm",
                        resident=len(self._resident),
                        resident_bytes=self._resident_bytes(),
                        **self._tier_fields(),
                    )
                    out["hbm"] += 1
            before = self.staging_evictions
            if self.staging_ttl_s > 0:
                expired = [sid for sid, s in self._staging.items()
                           if now - s.staged_t > self.staging_ttl_s]
                for sid in expired:
                    self._evict_staged_locked(sid, "ttl")
            out["staging"] = self.staging_evictions - before
            self._cond.notify_all()
        self._flush_rows()
        return out

    def evict(self, scene_id: str, *, drop_staged: bool = False) -> bool:
        """Operator eviction (``reason: manual``). Demotes the HBM entry
        (unless pinned -> False, nothing happens) and, with
        ``drop_staged``, purges the staged copy too."""
        with self._cond:
            resident = self._resident.get(scene_id)
            if resident is not None:
                if resident.refcount > 0:
                    return False
                self._resident.pop(scene_id)
                self.evictions += 1
                self.manual_evictions += 1
                self.bytes_evicted += resident.data.nbytes
                self._queue_row(
                    "scene_evict", scene=scene_id,
                    bytes=resident.data.nbytes, reason="manual", tier="hbm",
                    resident=len(self._resident),
                    resident_bytes=self._resident_bytes(),
                    **self._tier_fields(),
                )
            if drop_staged and scene_id in self._staging:
                self._evict_staged_locked(scene_id, "manual")
            self._cond.notify_all()
        self._flush_rows()
        return True

    # -- introspection --------------------------------------------------------

    def staged_ids(self) -> list[str]:
        """Staging LRU -> MRU order."""
        with self._cond:
            return list(self._staging)

    def stats(self) -> dict:
        out = super().stats()
        with self._cond:
            out.update(
                staging=list(self._staging),
                staging_bytes=self._staging_bytes(),
                staging_budget_bytes=self.staging_budget_bytes,
                demotions=self.demotions,
                repromotions=self.repromotions,
                disk_loads=self.disk_loads,
                staging_evictions=self.staging_evictions,
                ttl_evictions=self.ttl_evictions,
                manual_evictions=self.manual_evictions,
            )
        return out
