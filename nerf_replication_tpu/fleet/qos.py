"""Per-tenant QoS: admission quotas, fair-share weights, scoped breakers.

One hot client behind the shared micro-batcher can starve every other
tenant — the queue is FIFO, the breaker is global, and nothing meters
submissions. This module gives the serve edge a tenant dimension:

* **Token-bucket admission.** Each tenant owns a bucket (``burst``
  capacity, ``rate`` tokens/s refill). ``admit(tenant)`` takes a token
  or raises :class:`TenantQuotaError` — a typed 429 (``Retry-After`` =
  time until a token exists) that the HTTP edge maps before the request
  touches the queue. Every decision emits a ``tenant_admit`` row and a
  ``tenant_admits_total{tenant,decision}`` counter.
* **Fair-share weights.** ``weight(tenant)`` feeds the micro-batcher's
  weighted fair batch cuts (serve/batcher.py): batch assembly drains
  tenant queues in virtual-time order, so a saturated tenant gets its
  weighted share of rays and no more while a quiet tenant's requests
  never wait behind the flood.
* **Per-tenant breakers.** ``breaker(tenant)`` is a lazily-built
  :class:`~..resil.CircuitBreaker` (point ``tenant.<name>``): dispatch
  failures attributable to one tenant's batches degrade and eventually
  fast-fail THAT tenant (``resil``'s shed ladder and breaker semantics,
  scoped), leaving the engine-level breaker — and every other tenant —
  untouched.

A sustained deny streak (``dump_after_denies``) snapshots the flight
recorder once per tenant, naming the throttled tenant — chaos_run's
multi-tenant scenario asserts the dump exists next to the injected
fault's.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from ..obs import get_emitter
from ..obs.metrics import get_metrics
from ..resil import CircuitBreaker, dump_flight


class TenantQuotaError(RuntimeError):
    """Admission denied: the tenant's token bucket is empty (HTTP 429 +
    Retry-After at the serve edge; never a dispatch failure — the
    engine-level breaker must not see quota pressure)."""

    def __init__(self, tenant: str, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.tenant = tenant
        self.retry_after_s = max(0.0, float(retry_after_s))


@dataclass(frozen=True)
class TenantPolicy:
    """One tenant's quota + share. ``rate`` is sustained requests/s,
    ``burst`` the bucket capacity, ``weight`` the fair-batching share."""

    tenant: str
    rate: float = 200.0
    burst: float = 50.0
    weight: float = 1.0


class _Bucket:
    __slots__ = ("tokens", "last", "admits", "denies", "deny_streak",
                 "dumped")

    def __init__(self, burst: float, now: float):
        self.tokens = float(burst)
        self.last = now
        self.admits = 0
        self.denies = 0
        self.deny_streak = 0
        self.dumped = False


# sentinel policy name for tenant-less requests (classic single-tenant
# serving rides the default bucket/weight and stays API-compatible)
DEFAULT_TENANT = "_default"


class QosController:
    """Admission + weights + scoped breakers for the serve edge."""

    def __init__(self, policies=(), *,
                 default: TenantPolicy | None = None,
                 clock=time.monotonic,
                 breaker_threshold: int = 5,
                 breaker_cooldown_s: float = 5.0,
                 dump_after_denies: int = 8):
        self.clock = clock
        self.default = default or TenantPolicy(DEFAULT_TENANT)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.dump_after_denies = int(dump_after_denies)
        self._policies: dict[str, TenantPolicy] = {
            p.tenant: p for p in policies
        }
        self._lock = threading.Lock()
        self._buckets: dict[str, _Bucket] = {}
        self._breakers: dict[str, CircuitBreaker] = {}

    @classmethod
    def from_cfg(cls, cfg, clock=time.monotonic) -> "QosController | None":
        """Controller from the ``fleet.qos`` block (None when disabled).
        Breaker thresholds ride the shared ``resil:`` knobs so the
        per-tenant ladder degrades exactly like the engine-level one."""
        f = cfg.get("fleet", {}) if cfg is not None else {}
        q = f.get("qos", {})
        if not q or not bool(q.get("enabled", False)):
            return None
        r = cfg.get("resil", {})
        default = TenantPolicy(
            DEFAULT_TENANT,
            rate=float(q.get("default_rate", 200.0)),
            burst=float(q.get("default_burst", 50.0)),
            weight=float(q.get("default_weight", 1.0)),
        )
        policies = []
        for name, spec in dict(q.get("tenants", {})).items():
            spec = dict(spec or {})
            policies.append(TenantPolicy(
                str(name),
                rate=float(spec.get("rate", default.rate)),
                burst=float(spec.get("burst", default.burst)),
                weight=float(spec.get("weight", default.weight)),
            ))
        return cls(
            policies, default=default, clock=clock,
            breaker_threshold=int(r.get("breaker_threshold", 5)),
            breaker_cooldown_s=float(r.get("breaker_cooldown_s", 5.0)),
        )

    # -- policy lookup --------------------------------------------------------

    def policy(self, tenant: str | None) -> TenantPolicy:
        name = DEFAULT_TENANT if tenant is None else str(tenant)
        p = self._policies.get(name)
        if p is None:
            # unknown tenants get the default quota under their own
            # bucket — isolation without preregistration
            p = TenantPolicy(name, rate=self.default.rate,
                             burst=self.default.burst,
                             weight=self.default.weight)
            self._policies[name] = p
        return p

    def weight(self, tenant: str | None) -> float:
        return max(1e-6, float(self.policy(tenant).weight))

    # -- admission ------------------------------------------------------------

    def admit(self, tenant: str | None) -> float:
        """Take one token from the tenant's bucket; returns the level
        after the take. Raises :class:`TenantQuotaError` when empty."""
        p = self.policy(tenant)
        now = self.clock()
        with self._lock:
            b = self._buckets.get(p.tenant)
            if b is None:
                b = self._buckets[p.tenant] = _Bucket(p.burst, now)
            b.tokens = min(p.burst, b.tokens + (now - b.last) * p.rate)
            b.last = now
            if b.tokens >= 1.0:
                b.tokens -= 1.0
                b.admits += 1
                b.deny_streak = 0
                remaining = b.tokens
                denied = False
            else:
                b.denies += 1
                b.deny_streak += 1
                remaining = b.tokens
                denied = True
                retry_after = (1.0 - b.tokens) / max(p.rate, 1e-9)
                dump = (not b.dumped
                        and b.deny_streak >= self.dump_after_denies)
                if dump:
                    b.dumped = True
        decision = "deny" if denied else "admit"
        # graftlint: ok(emit-hot: one row per admission decision, pre-queue host path)
        get_emitter().emit(
            "tenant_admit", tenant=p.tenant, decision=decision,
            quota_remaining=round(remaining, 3), rate=p.rate, burst=p.burst,
            **({"retry_after_s": round(retry_after, 4)} if denied else {}),
        )
        # graftlint: ok(emit-hot: one counter bump per admission decision)
        get_metrics().counter("tenant_admits_total", tenant=p.tenant,
                              decision=decision)
        if denied:
            if dump:
                # once per sustained throttle: name the tenant in the
                # post-mortem ring (chaos_run asserts this dump)
                dump_flight(
                    "tenant_throttled",
                    detail=f"tenant={p.tenant} deny_streak={b.deny_streak} "
                           f"rate={p.rate}/s burst={p.burst}",
                )
            raise TenantQuotaError(
                p.tenant,
                f"tenant {p.tenant!r} over quota ({p.rate:g} req/s, "
                f"burst {p.burst:g}); retry after {retry_after:.3f}s",
                retry_after_s=retry_after,
            )
        return remaining

    # -- scoped breakers ------------------------------------------------------

    def breaker(self, tenant: str | None) -> CircuitBreaker:
        p = self.policy(tenant)
        with self._lock:
            b = self._breakers.get(p.tenant)
            if b is None:
                b = CircuitBreaker(
                    threshold=self.breaker_threshold,
                    cooldown_s=self.breaker_cooldown_s,
                    clock=self.clock,
                    point=f"tenant.{p.tenant}",
                )
                self._breakers[p.tenant] = b
            return b

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        now = self.clock()
        with self._lock:
            tenants = {}
            for name, b in self._buckets.items():
                p = self._policies[name]
                level = min(p.burst, b.tokens + (now - b.last) * p.rate)
                tenants[name] = {
                    "admits": b.admits,
                    "denies": b.denies,
                    "tokens": round(level, 2),
                    "rate": p.rate,
                    "burst": p.burst,
                    "weight": p.weight,
                }
            breakers = {n: brk.snapshot()
                        for n, brk in self._breakers.items()}
        for name, snap in breakers.items():
            tenants.setdefault(name, {})["breaker"] = snap
        return {"tenants": tenants}
