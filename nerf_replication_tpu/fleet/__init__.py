"""fleet/: the multi-scene control plane — catalog, residency, QoS.

One trained scene per :class:`~nerf_replication_tpu.serve.RenderEngine`
was the last single-tenant assumption in the serving stack. This package
removes it, in two layers (docs/fleet.md):

* **Serving data plane** — a :class:`SceneRegistry` (manifest or
  directory scan) or sharded :class:`SceneStore` (manifest shards, lazy
  page-in) names every scene's artifacts; a :class:`ResidencyManager`
  keeps an LRU of device-resident scenes under a byte budget with pinned
  leases and async prefetch — all rendered through the engine's ONE
  prewarmed bucket×tier executable family, zero per-scene compiles.
* **Control plane** — :class:`TieredResidencyManager` adds the host-RAM
  staging tier (eviction demotes, re-promotion is a device_put);
  :class:`QosController` meters tenants (token-bucket admission,
  fair-share weights, per-tenant breakers); :class:`ScenePublisher`
  hot-swaps a scene to a new checkpoint version under live traffic.

``fleet_from_cfg`` is the wiring surface: it reads the ``fleet:`` config
block, builds the catalog + residency ladder, and attaches them to an
engine.
"""

from __future__ import annotations

from .errors import (
    ResidencyOverloadError,
    SceneCompatError,
    SceneError,
    SceneLoadError,
    ScenePublishError,
    UnknownSceneError,
)
from .ladder import TieredResidencyManager
from .publish import ScenePublisher
from .qos import QosController, TenantPolicy, TenantQuotaError
from .registry import SceneRecord, SceneRegistry, checkpoint_loader
from .residency import ResidencyManager, SceneData
from .store import SceneStore, write_sharded

__all__ = [
    "QosController",
    "ResidencyManager",
    "ResidencyOverloadError",
    "SceneCompatError",
    "SceneData",
    "SceneError",
    "SceneLoadError",
    "ScenePublishError",
    "ScenePublisher",
    "SceneRecord",
    "SceneRegistry",
    "SceneStore",
    "TenantPolicy",
    "TenantQuotaError",
    "TieredResidencyManager",
    "UnknownSceneError",
    "checkpoint_loader",
    "fleet_from_cfg",
    "write_sharded",
]


def fleet_from_cfg(cfg, engine):
    """Build + attach the fleet for ``engine`` from the ``fleet:`` block.

    Returns the residency manager, or None when no discovery knob
    (``manifest`` / ``scan_dir`` / ``store_dir``) is set — single-scene
    serving, the API-compatible default. ``staging_mb > 0`` selects the
    tiered ladder (HBM eviction demotes to host RAM) over the classic
    drop-on-evict manager. The byte budgets come from
    ``fleet.hbm_budget_mb`` / ``fleet.staging_mb`` and are enforced
    against real leaf ``nbytes`` at load time."""
    from ..resil import retry_params

    f = cfg.get("fleet", {})
    manifest = str(f.get("manifest", ""))
    scan_dir = str(f.get("scan_dir", ""))
    store_dir = str(f.get("store_dir", ""))
    if not manifest and not scan_dir and not store_dir:
        return None
    if store_dir:
        registry = SceneStore(store_dir)
    elif manifest:
        registry = SceneRegistry.from_manifest(manifest)
    else:
        registry = SceneRegistry.scan(scan_dir)
    loader = checkpoint_loader(
        engine.params, default_near=engine.near, default_far=engine.far
    )
    common = dict(
        budget_bytes=int(float(f.get("hbm_budget_mb", 256.0)) * (1 << 20)),
        prefetch=bool(f.get("prefetch", True)),
        verify_checksums=bool(f.get("verify_checksums", True)),
        cache_entries=engine.options.cache_entries,
        pose_decimals=engine.options.pose_decimals,
        retry_kw=retry_params(cfg),
    )
    staging_mb = float(f.get("staging_mb", 0.0))
    if staging_mb > 0:
        residency = TieredResidencyManager(
            registry, loader,
            staging_budget_bytes=int(staging_mb * (1 << 20)),
            staging_ttl_s=float(f.get("staging_ttl_s", 0.0)),
            resident_ttl_s=float(f.get("resident_ttl_s", 0.0)),
            **common,
        )
    else:
        residency = ResidencyManager(registry, loader, **common)
    engine.attach_fleet(
        residency, default_scene=str(f.get("default_scene", "default"))
    )
    return residency
