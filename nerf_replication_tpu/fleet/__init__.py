"""fleet/: multi-scene serving — scene registry + HBM-budgeted residency.

One trained scene per :class:`~nerf_replication_tpu.serve.RenderEngine`
was the last single-tenant assumption in the serving stack. This package
removes it: a :class:`SceneRegistry` names every scene's artifacts
(manifest or directory scan), and a :class:`ResidencyManager` keeps an
LRU of device-resident scenes under a byte budget with pinned leases and
async prefetch — all rendered through the engine's ONE prewarmed
bucket×tier executable family, zero per-scene compiles (docs/fleet.md).

``fleet_from_cfg`` is the wiring surface: it reads the ``fleet:`` config
block, builds the registry + residency, and attaches them to an engine.
"""

from __future__ import annotations

from .errors import (
    ResidencyOverloadError,
    SceneCompatError,
    SceneError,
    SceneLoadError,
    UnknownSceneError,
)
from .registry import SceneRecord, SceneRegistry, checkpoint_loader
from .residency import ResidencyManager, SceneData

__all__ = [
    "ResidencyManager",
    "ResidencyOverloadError",
    "SceneCompatError",
    "SceneData",
    "SceneError",
    "SceneLoadError",
    "SceneRecord",
    "SceneRegistry",
    "UnknownSceneError",
    "checkpoint_loader",
    "fleet_from_cfg",
]


def fleet_from_cfg(cfg, engine):
    """Build + attach the fleet for ``engine`` from the ``fleet:`` block.

    Returns the :class:`ResidencyManager`, or None when no manifest or
    scan directory is configured (single-scene serving, the API-compatible
    default). The byte budget comes from ``fleet.hbm_budget_mb`` and is
    enforced against real leaf ``nbytes`` at load time."""
    from ..resil import retry_params

    f = cfg.get("fleet", {})
    manifest = str(f.get("manifest", ""))
    scan_dir = str(f.get("scan_dir", ""))
    if not manifest and not scan_dir:
        return None
    registry = (SceneRegistry.from_manifest(manifest) if manifest
                else SceneRegistry.scan(scan_dir))
    loader = checkpoint_loader(
        engine.params, default_near=engine.near, default_far=engine.far
    )
    residency = ResidencyManager(
        registry, loader,
        budget_bytes=int(float(f.get("hbm_budget_mb", 256.0)) * (1 << 20)),
        prefetch=bool(f.get("prefetch", True)),
        verify_checksums=bool(f.get("verify_checksums", True)),
        cache_entries=engine.options.cache_entries,
        pose_decimals=engine.options.pose_decimals,
        retry_kw=retry_params(cfg),
    )
    engine.attach_fleet(
        residency, default_scene=str(f.get("default_scene", "default"))
    )
    return residency
