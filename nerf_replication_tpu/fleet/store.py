"""Sharded, paged scene catalog: the registry past one directory listing.

A :class:`~.registry.SceneRegistry` parses its whole manifest up front —
fine for tens of scenes, wrong for a production catalog of thousands
(the north star: millions of users across many scenes). The
:class:`SceneStore` splits the catalog into **manifest shards** under one
root::

    store/
      index.json        # {"version": 1, "shards": [{"path": ..., "scenes": [...]}]}
      shard-0000.json   # a plain scene manifest (registry.from_manifest format)
      shard-0001.json

Only ``index.json`` (scene_id -> shard) is read eagerly; a shard's
records **page in lazily** on the first ``get`` that lands in it, and at
most ``max_loaded_shards`` stay parsed (LRU) — the resident metadata
footprint is bounded no matter how wide the catalog grows. Each shard
file IS a valid single-file manifest, so every existing manifest tool
keeps working on a shard.

Promotion is atomic end-to-end: :func:`write_sharded` (the
``to_manifest`` analogue) writes every shard through a temp-file
``os.replace`` and writes ``index.json`` **last** — a torn promotion
leaves the previous index naming the previous shards, never a
half-catalog. :meth:`SceneStore.register` (the hot-update path,
fleet/publish.py) rewrites only the owning shard, again atomically.

The store quacks like a registry (``get`` / ``in`` / ``len`` / ``ids``),
so the :class:`~.residency.ResidencyManager` takes either without
knowing which.
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict
from dataclasses import replace

from .errors import UnknownSceneError
from .registry import SceneRecord, SceneRegistry

INDEX_BASENAME = "index.json"
STORE_VERSION = 1


def _shard_name(i: int) -> str:
    return f"shard-{i:04d}.json"


def _abs_paths(record: SceneRecord) -> SceneRecord:
    kw = {}
    if record.checkpoint and not os.path.isabs(record.checkpoint):
        kw["checkpoint"] = os.path.abspath(record.checkpoint)
    if record.grid and not os.path.isabs(record.grid):
        kw["grid"] = os.path.abspath(record.grid)
    return replace(record, **kw) if kw else record


def write_sharded(registry: SceneRegistry, root: str,
                  shard_size: int = 64) -> str:
    """Promote a registry (scan or manifest) into a sharded store.

    Scenes are split into shards of ``shard_size`` in sorted id order;
    every shard is written atomically, and the index last — the
    only-visible states are "old catalog" and "new catalog". Returns the
    index path."""
    if shard_size < 1:
        raise ValueError(f"shard_size must be >= 1, got {shard_size}")
    os.makedirs(root, exist_ok=True)
    ids = registry.ids()
    shards = []
    for i in range(0, max(len(ids), 1), shard_size):
        chunk = ids[i:i + shard_size]
        if not chunk and i > 0:
            break
        shard = _shard_name(len(shards))
        # each shard is a plain manifest: reuse the registry's atomic
        # writer so the format can never fork. Artifact paths are
        # absolutized — the source registry resolved them against ITS
        # anchor (scan root / manifest dir), not against the store.
        SceneRegistry(
            _abs_paths(registry.get(sid)) for sid in chunk
        ).to_manifest(os.path.join(root, shard))
        shards.append({"path": shard, "scenes": chunk})
    index = {"version": STORE_VERSION, "shards": shards}
    path = os.path.join(root, INDEX_BASENAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(index, fh, indent=2)
    os.replace(tmp, path)
    return path


class SceneStore:
    """Lazy, LRU-paged view over a sharded scene catalog."""

    def __init__(self, root: str, *, max_loaded_shards: int = 8):
        self.root = str(root)
        self.max_loaded_shards = int(max_loaded_shards)
        self._lock = threading.Lock()
        self._loaded: OrderedDict[str, dict[str, SceneRecord]] = OrderedDict()
        self._shard_of: dict[str, str] = {}
        self._overrides: dict[str, SceneRecord] = {}
        self.page_ins = 0          # shard files parsed (incl. re-pages)
        self.shard_evictions = 0   # parsed shards dropped to the LRU cap
        self._load_index()

    def _load_index(self) -> None:
        path = os.path.join(self.root, INDEX_BASENAME)
        with open(path, encoding="utf-8") as fh:
            index = json.load(fh)
        version = int(index.get("version", STORE_VERSION))
        if version > STORE_VERSION:
            raise ValueError(f"store index {path}: version {version} is "
                             f"newer than supported ({STORE_VERSION})")
        self._shard_of = {}
        for shard in index.get("shards", []):
            for sid in shard.get("scenes", []):
                self._shard_of[str(sid)] = str(shard["path"])

    # -- registry protocol ----------------------------------------------------

    def get(self, scene_id: str) -> SceneRecord:
        with self._lock:
            record = self._overrides.get(scene_id)
            if record is not None:
                return record
            shard = self._shard_of.get(scene_id)
            if shard is None:
                known = len(self._shard_of)
                raise UnknownSceneError(
                    scene_id,
                    f"unknown scene {scene_id!r} ({known} scenes in "
                    f"store {self.root})",
                )
            # graftlint: ok(blocking-under-lock: single-flight page-in — the lock intentionally serializes shard parses so concurrent readers of one shard never duplicate the I/O)
            records = self._page_in(shard)
            record = records.get(scene_id)
            if record is None:
                # index/shard drift (a hand-edited shard): loud, not a KeyError
                raise UnknownSceneError(
                    scene_id,
                    f"scene {scene_id!r} is indexed to {shard} but the "
                    "shard does not carry it (torn store edit?)",
                )
            return record

    def _page_in(self, shard: str) -> dict[str, SceneRecord]:
        """Parse ``shard`` on first touch; LRU-bound the parsed set.
        Caller holds the lock (shard parse is host-side JSON, cheap
        relative to any scene load it precedes)."""
        records = self._loaded.get(shard)
        if records is not None:
            self._loaded.move_to_end(shard)
            return records
        sub = SceneRegistry.from_manifest(os.path.join(self.root, shard))
        records = {sid: sub.get(sid) for sid in sub.ids()}
        self._loaded[shard] = records
        self.page_ins += 1
        while len(self._loaded) > self.max_loaded_shards:
            self._loaded.popitem(last=False)
            self.shard_evictions += 1
        return records

    def __contains__(self, scene_id: str) -> bool:
        with self._lock:
            return scene_id in self._shard_of or scene_id in self._overrides

    def __len__(self) -> int:
        with self._lock:
            extra = sum(1 for sid in self._overrides
                        if sid not in self._shard_of)
            return len(self._shard_of) + extra

    def ids(self) -> list[str]:
        with self._lock:
            return sorted(set(self._shard_of) | set(self._overrides))

    # -- hot update (fleet/publish.py) ----------------------------------------

    def register(self, record: SceneRecord) -> SceneRecord:
        """Install/replace one scene's record, write-through to its shard.

        An existing scene rewrites its owning shard atomically; a new
        scene lands in the last shard (or a fresh one) and the index is
        rewritten last, same as promotion."""
        sid = record.scene_id
        with self._lock:
            shard = self._shard_of.get(sid)
            if shard is not None:
                # graftlint: ok(blocking-under-lock: write-through shard rewrite must be atomic w.r.t. concurrent gets; hot publishes are rare)
                records = dict(self._page_in(shard))
                records[sid] = record
                SceneRegistry(records.values()).to_manifest(
                    os.path.join(self.root, shard))
                self._loaded[shard] = records
                self._overrides.pop(sid, None)
                return record
            # new scene: keep it queryable immediately; the sharded file
            # set is extended by re-promoting (write_sharded) — an
            # override never shadows an indexed record
            self._overrides[sid] = record
            return record

    def to_registry(self) -> SceneRegistry:
        """Materialize every record (pages in ALL shards) — the
        re-promotion input for :func:`write_sharded`."""
        registry = SceneRegistry()
        for sid in self.ids():
            registry.register(self.get(sid))
        return registry

    def stats(self) -> dict:
        with self._lock:
            return {
                "scenes": len(self._shard_of),
                "shards": len(set(self._shard_of.values())),
                "loaded_shards": len(self._loaded),
                "max_loaded_shards": self.max_loaded_shards,
                "page_ins": self.page_ins,
                "shard_evictions": self.shard_evictions,
                "overrides": len(self._overrides),
            }
