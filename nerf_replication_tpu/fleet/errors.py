"""Fleet error taxonomy — import-free so serve/ and fleet/ can share it
without a cycle.

Every error is scene-scoped by design: a torn checkpoint or an
over-budget residency set must fail THAT scene's requests (503 at the
HTTP edge) while every other resident scene keeps serving. None of these
count as dispatch failures, so they never push the circuit breaker
toward open.
"""

from __future__ import annotations


class SceneError(RuntimeError):
    """Base for all scene-scoped serving failures."""

    def __init__(self, scene_id: str, message: str):
        super().__init__(message)
        self.scene_id = scene_id


class UnknownSceneError(SceneError):
    """The requested scene_id is not in the registry (HTTP 404)."""


class SceneLoadError(SceneError):
    """The scene's artifacts could not be materialized — missing
    checkpoint, exhausted I/O retries, or a torn/corrupt checkpoint
    caught by the tree checksum (HTTP 503 for this scene only)."""


class SceneCompatError(SceneLoadError):
    """The scene loaded but cannot ride the engine's prewarmed
    executables (param-tree/grid-shape/near-far mismatch) — admitting it
    would force a per-scene compile, which the fleet forbids."""


class ResidencyOverloadError(SceneError):
    """The byte budget cannot admit the scene because every resident
    scene is pinned by an in-flight batch (HTTP 503 + Retry-After)."""


class ScenePublishError(SceneError):
    """A hot-update could not swap (drain timeout, concurrent publish).
    The OLD version is still serving — a failed publish never degrades
    the scene (HTTP 503 for the publish call only)."""
