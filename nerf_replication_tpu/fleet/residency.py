"""HBM-budgeted scene residency: which scenes live on the device.

The serve executables already take ``(params, chunks, grid, bbox)`` as
runtime arguments, so ONE prewarmed bucket×tier family can render every
scene — the scaling bottleneck is device memory, not compile time (the
NerfAcc observation: occupancy-grid rendering makes per-ray compute
cheap, so a fleet is bounded by how many representations fit on-chip).
The :class:`ResidencyManager` turns that bottleneck into a managed
budget:

* ``acquire(scene_id)`` returns device-resident ``SceneData`` (params +
  grid + bbox), loading on miss and **evicting LRU scenes** when the
  configured byte budget — sized from the real leaf ``nbytes``, not an
  estimate — would overflow;
* acquire/release are **pin/unpin refcounts**: an in-flight batch holds
  a lease, and a pinned scene can never be evicted under it. If every
  resident scene is pinned and the budget is full, admission fails with
  :class:`ResidencyOverloadError` (503 + Retry-After at the HTTP edge)
  rather than deadlocking or over-committing;
* ``prefetch(scene_id)`` starts the host load + h2d on a background
  thread, so the first request for a new scene overlaps its transfer
  with the batch currently rendering — an ``acquire`` that lands on an
  in-flight prefetch joins it instead of double-loading;
* each scene keeps its own :class:`~..serve.cache.PoseCache` (host-side,
  so it survives eviction cycles — a re-admitted scene's landmark views
  are still warm).

Loads run through the ``fleet.load`` fault point with bounded retry, and
checkpoint directories are gated by a tree SHA-256 (resil/checksum): a
torn scene emits a ``torn`` fault row and fails THAT scene's requests
only. Every materialization emits a ``scene_load`` row and every
eviction a ``scene_evict`` row (obs/schema.py), so ``/stats`` and
``tlm_report`` see residency churn directly.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import dataclass, replace

from ..obs import get_emitter
from ..obs.trace import current_ctx, get_tracer
from ..resil import fault_point, report, verify_tree_checksum, with_retry
from ..serve.cache import PoseCache
from .errors import ResidencyOverloadError, SceneLoadError

# LRU recency is a monotone counter, not a wall clock: eviction order is
# a pure function of the acquire sequence (deterministic under test)
_TOUCH = 0


@dataclass(frozen=True)
class SceneData:
    """One scene's render inputs (host- or device-side; same fields the
    engine's executables take at dispatch). ``nbytes`` is filled by the
    manager from the real leaf sizes once known — it is the PER-DEVICE
    figure (per-shard under a model-parallel serving mesh, where each
    device holds ~1/M of the params); ``total_nbytes`` is the whole
    scene across shards. The two coincide for replicated scenes."""

    scene_id: str
    params: object
    grid: object = None
    bbox: object = None
    near: float = 2.0
    far: float = 6.0
    nbytes: int = 0
    total_nbytes: int = 0


class _Resident:
    """Book-keeping wrapper around one device-resident scene."""

    __slots__ = ("data", "refcount", "touch", "source", "ever_acquired",
                 "last_used_t")

    def __init__(self, data: SceneData, source: str):
        self.data = data
        self.refcount = 0
        self.touch = 0
        self.source = source          # "cold" | "prefetch" | "staging" | ...
        self.ever_acquired = False
        self.last_used_t = time.monotonic()   # wall recency (TTL sweeps)


class _Load:
    """One in-flight load (cold or prefetch) other threads can join."""

    __slots__ = ("event", "error", "source")

    def __init__(self, source: str):
        self.event = threading.Event()
        self.error: BaseException | None = None
        self.source = source


def _tree_nbytes(data: SceneData) -> int:
    """Real byte footprint: every params leaf + grid + bbox."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(data.params):
        total += int(getattr(leaf, "nbytes", 0))
    for aux in (data.grid, data.bbox):
        if aux is not None:
            total += int(getattr(aux, "nbytes", 0))
    return total


class ResidencyManager:
    """Byte-budgeted LRU of device-resident scenes with pinned leases."""

    def __init__(self, registry, loader, budget_bytes: int, *,
                 prefetch: bool = True, verify_checksums: bool = True,
                 cache_entries: int = 64, pose_decimals: int = 3,
                 validate=None, retry_kw: dict | None = None,
                 capacity=None):
        self.registry = registry
        self.loader = loader
        self.budget_bytes = int(budget_bytes)
        # optional obs.capacity.CapacityLedger: fed authoritative byte
        # watermarks at every row-emitting tier transition
        self.capacity = capacity
        self.prefetch_enabled = bool(prefetch)
        self.verify_checksums = bool(verify_checksums)
        self.cache_entries = int(cache_entries)
        self.pose_decimals = int(pose_decimals)
        self.validate = validate
        # sharded-placement hooks (engine.attach_fleet installs them):
        # ``placer`` maps a host (params, grid, bbox) tree onto the
        # serving mesh by the partition rules; ``shard_nbytes`` returns
        # the per-device bytes that placement will occupy (the figure
        # the HBM budget checks — admission is all-shards-or-none, so
        # the max per-device shard is what must fit). None keeps the
        # classic replicated behavior: plain device_put, real leaf bytes.
        self.placer = None
        self.shard_nbytes = None
        self.param_shards = 1
        self.retry_kw = dict(retry_kw or {})
        self._cond = threading.Condition()
        self._resident: OrderedDict[str, _Resident] = OrderedDict()
        self._loading: dict[str, _Load] = {}
        self._reserved = 0            # bytes admitted but not yet committed
        # scenes mid-hot-update (fleet/publish.py): new acquires park on
        # the condition until the version swap lands, so the publisher's
        # refcount drain barrier cannot be starved by fresh pins
        self._publishing: set[str] = set()
        self._pose_caches: dict[str, PoseCache] = {}
        # telemetry rows queued under the lock, emitted after release —
        # the emitter writes a file, and every waiter on the condition
        # would pay that write (graftlint R12 blocking-under-lock)
        self._pending_rows: list[tuple[str, dict]] = []
        # counters (read via stats(); mutated under the lock)
        self.loads = 0
        self.cold_loads = 0
        self.prefetch_issued = 0
        self.prefetch_hits = 0
        self.warm_hits = 0
        self.evictions = 0
        self.overloads = 0
        self.load_errors = 0
        self.bytes_loaded = 0
        self.bytes_evicted = 0

    # -- acquire / release ----------------------------------------------------

    def acquire(self, scene_id: str) -> SceneData:
        """Pin ``scene_id`` on the device and return its SceneData.

        Loads on miss (joining an in-flight prefetch when one is
        running); the caller MUST :meth:`release` — ``lease`` is the
        safe surface."""
        global _TOUCH
        # the acquire span covers the whole pin — a warm hit closes it in
        # microseconds, a prefetch join waits under it (attributed via
        # `joined`), and a cold load nests a child "scene.load" span
        with get_tracer().span("scene.acquire", stage="acquire",
                               scene=scene_id) as sp:
            while True:
                with self._cond:
                    # a publish in flight for this scene: park until the
                    # swap lands (the post-swap pin renders version N+1)
                    while scene_id in self._publishing:
                        self._cond.wait()
                    resident = self._resident.get(scene_id)
                    if resident is not None:
                        resident.refcount += 1
                        _TOUCH += 1
                        resident.touch = _TOUCH
                        resident.last_used_t = time.monotonic()
                        self._resident.move_to_end(scene_id)
                        if not resident.ever_acquired:
                            # first pin after materialization: a prefetch
                            # hit, or the tail of this thread's own cold
                            # load (already counted at load start)
                            if resident.source == "prefetch":
                                self.prefetch_hits += 1
                        else:
                            self.warm_hits += 1
                        resident.ever_acquired = True
                        return resident.data
                    load = self._loading.get(scene_id)
                    if load is None:
                        # miss with no in-flight load: this thread
                        # cold-loads
                        load = _Load("cold")
                        self._loading[scene_id] = load
                        self.cold_loads += 1
                        started_here = True
                    else:
                        started_here = False
                if not started_here:
                    # joining someone else's in-flight load (usually the
                    # prefetch thread): the wait is queue-shaped, not
                    # work-shaped — mark whose load we rode
                    sp.set(joined=load.source)
                    load.event.wait()
                    if load.error is not None:
                        raise load.error
                    continue  # committed by the loader thread; loop to pin
                try:
                    with get_tracer().span("scene.load", stage="load",
                                           scene=scene_id, source="cold"):
                        self._load_and_commit(scene_id, source="cold")
                except BaseException as err:
                    load.error = err
                    raise
                finally:
                    with self._cond:
                        self._loading.pop(scene_id, None)
                    load.event.set()

    def release(self, scene_id: str) -> None:
        with self._cond:
            resident = self._resident.get(scene_id)
            if resident is not None and resident.refcount > 0:
                resident.refcount -= 1
                self._cond.notify_all()

    @contextmanager
    def lease(self, scene_id: str):
        """``with residency.lease(sid) as data:`` — pinned for the block."""
        data = self.acquire(scene_id)
        try:
            yield data
        finally:
            self.release(scene_id)

    # -- prefetch -------------------------------------------------------------

    def prefetch(self, scene_id: str) -> bool:
        """Start a background load of ``scene_id``; True if one was
        actually started (False: disabled / resident / already loading /
        unknown scene — prefetch never raises, errors surface on the
        eventual acquire)."""
        if not self.prefetch_enabled or scene_id not in self.registry:
            return False
        with self._cond:
            if (scene_id in self._resident or scene_id in self._loading
                    or scene_id in self._publishing):
                return False
            load = _Load("prefetch")
            self._loading[scene_id] = load
            self.prefetch_issued += 1

        # capture the SUBMITTING thread's span context now: the prefetch
        # thread has no inherited context, so the load span is explicitly
        # parented to the request that kicked the prefetch — the
        # cross-thread attribution tests/test_trace.py pins down
        ctx = current_ctx()

        def _main():
            try:
                with get_tracer().span("scene.load", parent=ctx,
                                       stage="load", scene=scene_id,
                                       source="prefetch"):
                    self._load_and_commit(scene_id, source="prefetch")
            # graftlint: ok(swallow: error re-raised on the joining acquire; load_errors counted here)
            except BaseException as err:
                load.error = err
                with self._cond:
                    self.load_errors += 1
            finally:
                with self._cond:
                    self._loading.pop(scene_id, None)
                load.event.set()

        threading.Thread(
            target=_main, name=f"fleet-prefetch-{scene_id}", daemon=True
        ).start()
        return True

    def wait_loaded(self, scene_id: str, timeout: float | None = None) -> bool:
        """Block until no load is in flight for ``scene_id`` (test/bench
        barrier; True unless the wait timed out)."""
        with self._cond:
            load = self._loading.get(scene_id)
        return load.event.wait(timeout) if load is not None else True

    # -- load / evict core ----------------------------------------------------

    def _load_and_commit(self, scene_id: str, source: str) -> None:
        global _TOUCH
        record = self.registry.get(scene_id)
        t0 = time.perf_counter()
        # staging fast path (fleet/ladder.py): a demoted scene's host
        # arrays are still in RAM — re-promotion is a device_put, not a
        # disk load + checksum walk (and was validated at original load)
        host = self._staged_host(scene_id)
        if host is not None:
            source = "staging"
        else:
            host = self._load_host(record)
            if self.validate is not None:
                self.validate(host)   # SceneCompatError on mismatch
        total = _tree_nbytes(host)
        # per-device bytes: what one device must actually hold once the
        # scene is placed. Under a model-parallel mesh that is the shard
        # figure from the partition specs; replicated, it IS the total.
        host_tree = (host.params, host.grid, host.bbox)
        nbytes = (
            int(self.shard_nbytes(host_tree))
            if self.shard_nbytes is not None else total
        )
        if nbytes > self.budget_bytes:
            shards = int(self.param_shards)
            sharded = (
                f"{nbytes} bytes/device over {shards} param shard(s) "
                f"({total} bytes total)" if shards > 1
                else f"{nbytes} bytes"
            )
            raise ResidencyOverloadError(
                scene_id,
                f"scene {scene_id!r} needs {sharded}, over the whole "
                f"fleet budget ({self.budget_bytes} bytes/device)",
            )
        self._admit(scene_id, nbytes)
        try:
            import jax

            if self.placer is not None:
                device = self.placer(host_tree)
            else:
                device = jax.tree.map(jax.device_put, host_tree)
        except BaseException:
            with self._cond:
                self._reserved -= nbytes
                self._cond.notify_all()
            raise
        params, grid, bbox = device
        data = replace(host, params=params, grid=grid, bbox=bbox,
                       nbytes=nbytes, total_nbytes=total)
        with self._cond:
            self._reserved -= nbytes
            self._cond.notify_all()
            resident = _Resident(data, source)
            _TOUCH += 1
            resident.touch = _TOUCH
            self._resident[scene_id] = resident
            self._resident.move_to_end(scene_id)
            self.loads += 1
            self._note_load(source)
            self.bytes_loaded += nbytes
            # write-through to the host-RAM staging tier (no-op in the
            # one-level manager): a later HBM eviction demotes instead of
            # dropping because the host copy is already staged. Staged at
            # TOTAL bytes — host RAM holds the whole unsharded scene.
            self._stage_host(scene_id, host, total)
            n_res, res_bytes = len(self._resident), self._resident_bytes()
            tier_fields = self._tier_fields()
        # staging write-through may have queued evict rows under the lock
        self._flush_rows()
        get_emitter().emit(
            "scene_load", scene=scene_id, bytes=nbytes, source=source,
            total_bytes=total, param_shards=int(self.param_shards),
            load_s=round(time.perf_counter() - t0, 4),
            resident=n_res, resident_bytes=res_bytes, **tier_fields,
        )

    def _load_host(self, record) -> SceneData:
        """Host-side artifact load: fault point + checksum gate + retry."""
        def _attempt():
            fault_point("fleet.load", path=record.checkpoint or None)
            if self.verify_checksums and record.checkpoint:
                ok = verify_tree_checksum(record.checkpoint)
                if ok is False:
                    report("fleet.load", "torn", path=record.checkpoint,
                           detail=f"scene {record.scene_id!r}: checkpoint "
                                  "tree checksum mismatch")
                    raise SceneLoadError(
                        record.scene_id,
                        f"scene {record.scene_id!r}: torn checkpoint "
                        f"(tree checksum mismatch at {record.checkpoint})",
                    )
            return self.loader(record)

        try:
            return with_retry(_attempt, point="fleet.load", **self.retry_kw)
        except SceneLoadError:
            with self._cond:
                self.load_errors += 1
            raise
        except OSError as err:
            with self._cond:
                self.load_errors += 1
            report("fleet.load", "io_error", path=record.checkpoint or None,
                   detail=f"{type(err).__name__}: {err}"[:200])
            raise SceneLoadError(
                record.scene_id,
                f"scene {record.scene_id!r}: load failed ({err})",
            ) from err

    def _resident_bytes(self) -> int:
        return sum(r.data.nbytes for r in self._resident.values())

    # -- deferred telemetry ----------------------------------------------------

    def _queue_row(self, kind: str, **fields) -> None:
        """Queue a telemetry row from inside a critical section; the
        emit (a file write) happens at the next ``_flush_rows()``."""
        self._pending_rows.append((kind, fields))

    def _flush_rows(self) -> None:
        """Emit everything queued. Call with the lock NOT held."""
        with self._cond:
            pending, self._pending_rows = self._pending_rows, []
        emitter = get_emitter()
        for kind, fields in pending:
            emitter.emit(kind, **fields)

    def _admit(self, scene_id: str, nbytes: int) -> None:
        """Reserve ``nbytes`` of budget, evicting cold LRU scenes first.

        Eviction happens BEFORE the h2d transfer so the budget is never
        transiently over-committed; pinned scenes are skipped, and if
        nothing evictable remains the admission fails."""
        try:
            with self._cond:
                while (self._resident_bytes() + self._reserved + nbytes
                       > self.budget_bytes):
                    victim_id = next(
                        (sid for sid, r in self._resident.items()
                         if r.refcount == 0),
                        None,
                    )
                    if victim_id is None:
                        if self._reserved > 0:
                            # a concurrent load holds the missing bytes;
                            # once it commits (or fails) its scene is
                            # evictable (or its reservation returns) —
                            # wait, don't fail
                            self._cond.wait(timeout=0.1)
                            continue
                        self.overloads += 1
                        raise ResidencyOverloadError(
                            scene_id,
                            f"cannot admit scene {scene_id!r} "
                            f"({nbytes} bytes/device): all "
                            f"{len(self._resident)} resident scenes are "
                            "pinned by in-flight batches",
                        )
                    victim = self._resident.pop(victim_id)
                    reason = self._retire(victim_id, victim)
                    self.evictions += 1
                    self.bytes_evicted += victim.data.nbytes
                    n_res = len(self._resident)
                    self._queue_row(
                        "scene_evict", scene=victim_id,
                        bytes=victim.data.nbytes, reason=reason,
                        resident=n_res,
                        resident_bytes=self._resident_bytes(),
                        **self._tier_fields(),
                    )
                self._reserved += nbytes
        finally:
            # queued evict rows land even when admission fails
            self._flush_rows()

    # -- residency-tier hooks (overridden by fleet/ladder.py) -----------------

    def _staged_host(self, scene_id: str) -> SceneData | None:
        """Host-side copy of ``scene_id`` if a staging tier holds one
        (None in the one-level manager: every miss is a disk load)."""
        return None

    def _note_load(self, source: str) -> None:
        """Per-source load accounting hook at commit (under the lock).
        Counted HERE and not at the staging lookup so a load that fails
        admission (overload, device_put error) never drifts the ledger:
        ``loads == disk_loads + repromotions`` must hold exactly."""

    def _stage_host(self, scene_id: str, host: SceneData, nbytes: int) -> None:
        """Write-through hook at commit (called under the lock)."""

    def _invalidate_staged(self, scene_id: str) -> None:
        """Drop a staged host copy (called under the lock) — a published
        version swap makes the old staged arrays stale."""

    def _retire(self, scene_id: str, resident: _Resident) -> str:
        """The victim just left the resident dict (under the lock);
        subclasses may keep its host arrays staged instead of dropping.
        Returns the ``scene_evict`` reason."""
        return "budget"

    def _tier_fields(self) -> dict:
        """Extra occupancy fields for scene_load/scene_evict rows.
        Called under the (non-reentrant) lock — do not re-acquire.
        Also the capacity-ledger watermark hook: every row-emitting
        transition passes through here, so the ledger sees every peak."""
        if self.capacity is not None:
            self.capacity.note_residency(self._resident_bytes(), 0)
        return {}

    # -- per-scene pose caches ------------------------------------------------

    def pose_cache(self, scene_id: str) -> PoseCache:
        """The scene's pose->image LRU (host-side: survives eviction, so
        a re-admitted scene's landmark views stay warm)."""
        with self._cond:
            cache = self._pose_caches.get(scene_id)
            if cache is None:
                cache = PoseCache(capacity=self.cache_entries,
                                  decimals=self.pose_decimals)
                self._pose_caches[scene_id] = cache
            return cache

    # -- introspection --------------------------------------------------------

    def resident_ids(self) -> list[str]:
        """LRU -> MRU order (index 0 is the next eviction candidate)."""
        with self._cond:
            return list(self._resident)

    def pinned_ids(self) -> list[str]:
        with self._cond:
            return [sid for sid, r in self._resident.items() if r.refcount]

    def stats(self) -> dict:
        with self._cond:
            loads = self.loads
            cold = self.cold_loads
            hits = self.prefetch_hits
            first_loads = hits + cold
            return {
                "known_scenes": len(self.registry),
                "resident": list(self._resident),
                "pinned": [s for s, r in self._resident.items() if r.refcount],
                "resident_bytes": self._resident_bytes(),
                "budget_bytes": self.budget_bytes,
                # 1 = replicated params; >1 = model-parallel serving,
                # where resident/budget bytes are per-device shard figures
                "param_shards": int(self.param_shards),
                "loads": loads,
                "cold_loads": cold,
                "warm_hits": self.warm_hits,
                "prefetch_issued": self.prefetch_issued,
                "prefetch_hits": hits,
                "prefetch_hit_rate": (hits / first_loads) if first_loads
                                     else 0.0,
                "evictions": self.evictions,
                "overloads": self.overloads,
                "load_errors": self.load_errors,
                "bytes_loaded": self.bytes_loaded,
                "bytes_evicted": self.bytes_evicted,
                "pose_caches": {
                    sid: c.stats() for sid, c in self._pose_caches.items()
                },
            }
