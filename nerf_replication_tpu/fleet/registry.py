"""Scene registry: which scenes exist and where their artifacts live.

A fleet deployment names its scenes in one of two ways:

* a **manifest** — one JSON file mapping scene ids to their checkpoint
  directory, occupancy-pyramid path, and near/far/bbox metadata
  (format: docs/fleet.md); or
* a **directory scan** — every subdirectory of a root that contains an
  orbax checkpoint (``latest/`` or numbered epoch dirs) becomes a scene
  named after the subdirectory, picking up ``occupancy_grid.npz`` beside
  it when present.

The registry is pure host-side metadata — no jax, no I/O beyond the
manifest/scan. Loading a scene's actual arrays is the
:class:`~nerf_replication_tpu.fleet.residency.ResidencyManager`'s job,
through a loader such as :func:`checkpoint_loader`.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field

from .errors import SceneLoadError, UnknownSceneError

MANIFEST_VERSION = 1

# the same baked-grid artifact name the single-scene surfaces use
# (renderer/occupancy.default_grid_path)
GRID_BASENAME = "occupancy_grid.npz"


@dataclass(frozen=True)
class SceneRecord:
    """One scene's artifact locations + render metadata.

    ``checkpoint`` is an orbax checkpoint directory in the trainer's
    layout (train/checkpoint.py: ``latest/`` + numbered epochs); ``grid``
    is an occupancy-pyramid ``.npz`` ("" = no grid — only admissible on a
    volume-path engine). ``near``/``far``/``bbox`` default to the
    engine's baked values when None; a scene declaring DIFFERENT bounds
    is rejected at load (SceneCompatError) because the prewarmed
    executables bake near/far as constants.
    """

    scene_id: str
    checkpoint: str = ""
    grid: str = ""
    near: float | None = None
    far: float | None = None
    bbox: tuple | None = None
    epoch: int = -1
    meta: dict = field(default_factory=dict)


class SceneRegistry:
    """scene_id -> SceneRecord, with manifest / directory-scan discovery."""

    def __init__(self, records=()):
        self._records: dict[str, SceneRecord] = {}
        for record in records:
            self.register(record)

    def register(self, record: SceneRecord) -> SceneRecord:
        self._records[record.scene_id] = record
        return record

    def get(self, scene_id: str) -> SceneRecord:
        record = self._records.get(scene_id)
        if record is None:
            known = ", ".join(sorted(self._records)) or "<none>"
            raise UnknownSceneError(
                scene_id, f"unknown scene {scene_id!r} (known: {known})"
            )
        return record

    def __contains__(self, scene_id: str) -> bool:
        return scene_id in self._records

    def __len__(self) -> int:
        return len(self._records)

    def ids(self) -> list[str]:
        return sorted(self._records)

    # -- discovery ------------------------------------------------------------

    @classmethod
    def from_manifest(cls, path: str) -> "SceneRegistry":
        """Load a scene manifest (JSON; format in docs/fleet.md).

        Relative artifact paths resolve against the manifest's own
        directory, so a manifest travels with its scene store."""
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
        if not isinstance(data, dict) or "scenes" not in data:
            raise ValueError(f"manifest {path}: expected an object with "
                             "a 'scenes' list")
        version = int(data.get("version", MANIFEST_VERSION))
        if version > MANIFEST_VERSION:
            raise ValueError(f"manifest {path}: version {version} is newer "
                             f"than supported ({MANIFEST_VERSION})")
        base = os.path.dirname(os.path.abspath(path))

        def _resolve(p: str) -> str:
            if not p or os.path.isabs(p):
                return p
            return os.path.join(base, p)

        registry = cls()
        for entry in data["scenes"]:
            if "scene_id" not in entry:
                raise ValueError(f"manifest {path}: scene entry missing "
                                 f"'scene_id': {entry!r}")
            bbox = entry.get("bbox")
            registry.register(SceneRecord(
                scene_id=str(entry["scene_id"]),
                checkpoint=_resolve(str(entry.get("checkpoint", ""))),
                grid=_resolve(str(entry.get("grid", ""))),
                near=None if entry.get("near") is None else float(entry["near"]),
                far=None if entry.get("far") is None else float(entry["far"]),
                bbox=None if bbox is None else tuple(map(tuple, bbox)),
                epoch=int(entry.get("epoch", -1)),
                meta=dict(entry.get("meta", {})),
            ))
        return registry

    @classmethod
    def scan(cls, root: str) -> "SceneRegistry":
        """Discover scenes by directory layout: every subdirectory of
        ``root`` holding an orbax checkpoint becomes a scene."""
        registry = cls()
        if not os.path.isdir(root):
            return registry
        for name in sorted(os.listdir(root)):
            scene_dir = os.path.join(root, name)
            if not _has_checkpoint(scene_dir):
                continue
            grid = os.path.join(scene_dir, GRID_BASENAME)
            registry.register(SceneRecord(
                scene_id=name,
                checkpoint=scene_dir,
                grid=grid if os.path.exists(grid) else "",
            ))
        return registry

    def to_manifest(self, path: str) -> None:
        """Write the registry back out as a manifest (atomic)."""
        scenes = []
        for sid in self.ids():
            r = self._records[sid]
            entry: dict = {"scene_id": r.scene_id}
            if r.checkpoint:
                entry["checkpoint"] = r.checkpoint
            if r.grid:
                entry["grid"] = r.grid
            if r.near is not None:
                entry["near"] = r.near
            if r.far is not None:
                entry["far"] = r.far
            if r.bbox is not None:
                entry["bbox"] = [list(row) for row in r.bbox]
            if r.epoch != -1:
                entry["epoch"] = r.epoch
            if r.meta:
                entry["meta"] = r.meta
            scenes.append(entry)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"version": MANIFEST_VERSION, "scenes": scenes}, fh,
                      indent=2)
        os.replace(tmp, path)


def _has_checkpoint(model_dir: str) -> bool:
    """The trainer's checkpoint layout: ``latest/`` or numbered epochs."""
    if not os.path.isdir(model_dir):
        return False
    if os.path.isdir(os.path.join(model_dir, "latest")):
        return True
    return any(re.fullmatch(r"\d+", d) for d in os.listdir(model_dir))


def checkpoint_loader(template_params, *, default_near: float,
                      default_far: float):
    """The production scene loader: orbax checkpoint + occupancy pyramid.

    ``template_params`` (the engine's own param tree) drives the partial
    restore — every fleet scene must share the network architecture, the
    same contract that lets one compiled executable family serve all of
    them. Returns host-side data; the ResidencyManager owns device
    placement, byte accounting, checksums, and fault injection."""
    import numpy as np

    from ..renderer.occupancy import load_occupancy_pyramid
    from ..train.checkpoint import load_network
    from .residency import SceneData

    def load(record: SceneRecord) -> SceneData:
        if not _has_checkpoint(record.checkpoint):
            raise SceneLoadError(
                record.scene_id,
                f"scene {record.scene_id!r}: no checkpoint under "
                f"{record.checkpoint!r}",
            )
        params, _epoch = load_network(record.checkpoint, template_params,
                                      epoch=record.epoch)
        grid = bbox = None
        if record.grid:
            # versioned pyramid artifact (checksum-verified inside); the
            # executables consume the fine level, same as engine_from_cfg
            levels, bbox = load_occupancy_pyramid(record.grid)
            grid = levels[0]
        if record.bbox is not None:
            bbox = np.asarray(record.bbox, np.float32)
        return SceneData(
            scene_id=record.scene_id,
            params=params,
            grid=grid,
            bbox=bbox,
            near=default_near if record.near is None else float(record.near),
            far=default_far if record.far is None else float(record.far),
        )

    return load
