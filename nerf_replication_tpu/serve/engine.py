"""Render-serving engine: one checkpoint, a few executables, any request.

Every batch render surface before this subsystem (run.py, render_video.py,
the gate) pays compile cost per invocation and renders one request at a
time. The engine inverts that: it loads the checkpoint + baked occupancy
grid ONCE and pre-warms a small set of **shape-bucketed** jit executables —
ray-chunk sizes are pinned (the static-shape design of
renderer/packed_march.py and renderer/accelerated.py), so an arbitrary
request shape pads into the smallest bucket that holds it and can never
retrace. With NerfAcc-style occupancy sampling making per-ray FLOPs cheap,
dispatch/batching dominates serving latency; the bucket set is the whole
executable inventory, compiled before the first request arrives.

A handful of executable families exist per bucket — ``full`` / ``bf16`` /
``proposal`` / ``reduced_k`` / ``coarse`` (serve/policy.py's degradation
ladder; ``half_res`` reuses ``coarse`` with host-side ray striding, and
``proposal`` is warmed only for checkpoints that carry the learned-sampler
branch, falling back to ``reduced_k`` otherwise) — so shedding load under
backlog switches executables, never compiles one. ``bf16`` is
the full march budget with the network cloned to bfloat16 COMPUTE (f32
params and f32 compositing — the march's sigmoid/relu/transmittance math
runs outside the network): its own prewarmed bucket set, no new code
path. When the march options enable the hierarchical traversal
(``march_coarse_block``), every grid-backed family routes through the
coarse-DDA packed march (renderer/packed_march.py).

Numerics contract: for the ``full`` tier the per-bucket executable is the
SAME program ``Renderer.render_accelerated`` builds — identical chunking
(``lax.map`` over ``[chunk, 6]`` rows), identical static bounds — so a
padded-bucket render is bitwise-equal to the unbatched path on the real
rows (tests/test_serve.py proves it).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, replace

import numpy as np

from ..fleet.errors import SceneCompatError, UnknownSceneError
from ..obs import CompileTracker, get_emitter
from ..obs.trace import get_tracer
from ..renderer.gate import check_baked_bounds
from ..resil import fault_point
from .cache import PoseCache
from .policy import FAMILIES, TIER_IMPL


@dataclass(frozen=True)
class ServeOptions:
    """Engine/batcher configuration (cfg.serve; docs/serving.md)."""

    buckets: tuple[int, ...] = (4096, 16384)
    max_batch_rays: int = 16384
    max_delay_s: float = 0.005
    request_timeout_s: float = 30.0
    cache_entries: int = 64
    pose_decimals: int = 3
    warmup: bool = True
    shed_queue_depths: tuple[int, ...] = (4, 8, 16, 32)

    @classmethod
    def from_cfg(cls, cfg) -> "ServeOptions":
        s = cfg.get("serve", {})
        return cls(
            buckets=tuple(int(b) for b in s.get("buckets", (4096, 16384))),
            max_batch_rays=int(s.get("max_batch_rays", 16384)),
            max_delay_s=float(s.get("max_delay_ms", 5.0)) / 1e3,
            request_timeout_s=float(s.get("request_timeout_s", 30.0)),
            cache_entries=int(s.get("cache_entries", 64)),
            pose_decimals=int(s.get("pose_decimals", 3)),
            warmup=bool(s.get("warmup", True)),
            shed_queue_depths=tuple(
                int(d) for d in s.get("shed_queue_depths", (4, 8, 16, 32))
            ),
        )


def _has_proposal_branch(params) -> bool:
    """Whether a param tree (concrete or abstract) carries the learned
    sampler's ``proposal`` branch (models/proposal.py) — structure only,
    so it works on the eval_shape templates warm-up runs on."""
    try:
        return "proposal" in params.get("params", {})
    except AttributeError:
        return False


def _normalize_buckets(buckets, chunk: int) -> tuple[int, ...]:
    """Ascending unique bucket sizes, each a multiple of the render chunk
    (the executables ``lax.map`` over [chunk, C] rows, so a bucket that
    isn't a multiple would silently grow a new chunk shape)."""
    norm = {max(chunk, -(-int(b) // chunk) * chunk) for b in buckets}
    return tuple(sorted(norm))


class RenderEngine:
    """Checkpoint-resident render server core.

    Pure compute + bookkeeping: thread-safety for concurrent requests is
    the MicroBatcher's job (one worker thread owns the dispatch); direct
    ``render_request`` calls are single-caller surfaces (render_video, the
    eval CLIs).

    ``grid``/``bbox`` present selects the occupancy-accelerated march
    (eval march budget); absent falls back to the chunked volume renderer
    — same degradation ladder either way.
    """

    def __init__(self, cfg, network, params, near, far, grid=None, bbox=None,
                 tracker: CompileTracker | None = None,
                 warmup_families: tuple[str, ...] | None = None,
                 aot=None, mesh=None):
        import jax.numpy as jnp

        from ..renderer.accelerated import MarchOptions
        from ..renderer.volume import RenderOptions

        self.network = network
        self.params = params
        # a checkpoint trained with sampling.mode: proposal carries the
        # learned-sampler branch; only then is the "proposal" executable
        # family real — without it the tier remaps to reduced_k at render
        # time (TIER ladder in serve/policy.py)
        self.has_proposal = _has_proposal_branch(params)
        self.near = float(near)
        self.far = float(far)
        self.options = ServeOptions.from_cfg(cfg)
        self.use_grid = grid is not None
        self.grid = None if grid is None else jnp.asarray(grid)
        self.bbox = None if bbox is None else jnp.asarray(bbox)
        # the full tier is EXACTLY the eval budget the one-shot surfaces
        # use (Renderer.march_options / eval_options) — parity by
        # construction, not by keeping two configs in sync
        self.march_options = MarchOptions.eval_from_cfg(cfg)
        self.eval_options = RenderOptions.from_cfg(cfg, train=False)
        # stream cap for the packed (hierarchical / clip_bbox) march: the
        # NGP eval knob when set, else the per-ray max budget on average
        self.packed_cap = int(
            cfg.task_arg.get(
                "packed_cap_avg_eval", self.march_options.max_samples
            )
        )
        self.chunk = (
            self.march_options.chunk_size if self.use_grid
            else self.eval_options.chunk_size
        )
        self.buckets = _normalize_buckets(self.options.buckets, self.chunk)
        # mesh-sharded dispatch (scale/mesh_dispatch.py): a data-parallel
        # mesh shards each executable's chunk axis over the mesh devices;
        # None (the default, and always the case on a size-1 mesh unless
        # forced) keeps the plain single-device jit path
        self.mesh = mesh
        self._chunks_sharding = None
        self._model_parallel = False
        if mesh is not None:
            from ..parallel.sharding import chunk_sharding
            from ..scale.mesh_dispatch import model_size, validate_mesh_buckets

            validate_mesh_buckets(self.buckets, self.chunk, mesh)
            self._chunks_sharding = chunk_sharding(mesh)
            # model-parallel serving (mesh_shape [D, M] with M > 1): the
            # param tree shards by the TP rules, so placement must follow
            # the specs — set_params / the fleet placer do the device_put
            self._model_parallel = model_size(mesh) > 1
            if self._model_parallel:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                from ..parallel.sharding import tree_shardings

                # place the engine's own checkpoint by the partition
                # rules NOW: leaving it whole would hold the full
                # replicated copy on device 0 and re-shard on every
                # dispatch — per-device peak bytes must be the shard
                self.params = jax.device_put(
                    params, tree_shardings(params, mesh)
                )
                rep = NamedSharding(mesh, P())
                if self.grid is not None:
                    self.grid = jax.device_put(self.grid, rep)
                if self.bbox is not None:
                    self.bbox = jax.device_put(self.bbox, rep)
        self.tracker = tracker or CompileTracker()
        self.cache = PoseCache(
            capacity=self.options.cache_entries,
            decimals=self.options.pose_decimals,
        )
        self._fns: dict[tuple[int, str], object] = {}
        # serving counters (host-side; read via stats())
        self.n_requests = 0
        self.n_rays_rendered = 0
        self.n_pad_rays = 0
        self.n_truncated = 0
        self.warmup_compiles = 0
        # traversal accounting (packed march only): sums over dispatched
        # chunks, read as means via stats()["march"]
        self.march_chunks = 0
        self.march_candidates = 0.0
        self.march_samples_out = 0.0
        self.march_coarse_occ_sum = 0.0
        self.march_overflow_sum = 0.0
        # AOT registry (compile/registry): executables lower/compile — or
        # deserialize from the artifact store — up front on host threads.
        # With a registry the engine can warm on ABSTRACT params (shape
        # structure only), so a disk cache hit never blocks on checkpoint
        # I/O; engine_from_cfg installs the real weights via set_params.
        self.aot = aot
        self.warm_source: str | None = None
        self.warmup_wall_s = 0.0
        # camera defaults for pose-only surfaces; engine_from_cfg fills it
        self.default_camera: dict | None = None
        # multi-scene residency (fleet/): attach_fleet installs it; None =
        # classic single-tenant serving, and requests without a scene (or
        # naming default_scene) always render the engine's own checkpoint
        self.fleet = None
        self.default_scene = "default"
        if self.options.warmup:
            self.warm_up(warmup_families)

    # -- executable construction --------------------------------------------

    def _families_for_params(self) -> tuple[str, ...]:
        """The executable families this checkpoint can actually serve:
        every ladder family, minus ``proposal`` when the params carry no
        proposal branch (the tier then degrades through reduced_k)."""
        return tuple(
            f for f in FAMILIES if f != "proposal" or self.has_proposal
        )

    def _family_march_options(self, family: str):
        base = self.march_options
        if family in ("full", "bf16"):
            # bf16 keeps the FULL march budget: its quality trade is the
            # compute dtype, not the sample count
            return base
        # reduced_k and coarse share the halved MLP budget; coarse
        # additionally swaps the queried network (in _build_fn)
        return replace(base, max_samples=max(1, base.max_samples // 2))

    def _family_eval_options(self, family: str):
        base = self.eval_options
        s = base.sampling
        if s.mode == "proposal":
            # learned-sampler checkpoint: the coarse branch is untrained
            # (the proposal path never touches it), so every degraded tier
            # stays on the proposal render and sheds by shrinking the
            # histogram / fine budgets instead of swapping networks
            if family in ("full", "bf16"):
                return base
            if family == "proposal":
                s2 = replace(s, n_fine=max(1, s.n_fine // 2))
            elif family == "reduced_k":
                s2 = replace(s, n_proposal=max(2, s.n_proposal // 2),
                             n_fine=max(1, s.n_fine // 2))
            else:  # coarse tier: the deepest shed still renders fine
                s2 = replace(s, n_proposal=max(2, s.n_proposal // 2),
                             n_fine=max(1, s.n_fine // 4))
            return replace(base, sampling=s2)
        if family in ("full", "bf16"):
            return base
        if family == "reduced_k":
            return replace(base, n_importance=base.n_importance // 2)
        return replace(base, n_importance=0)  # coarse-only

    def _family_network(self, family: str):
        if family != "bf16":
            return self.network
        import jax.numpy as jnp

        # bf16 COMPUTE, f32 params: Network builds its submodules from
        # ``compute_dtype`` in setup(), so a clone re-applies the SAME f32
        # checkpoint with bf16 matmuls — no second parameter tree, no new
        # code path, just one more prewarmed executable set
        return self.network.clone(compute_dtype=jnp.bfloat16)

    def _finalize_fn(self, fn):
        """Jit an executable body: plain ``jax.jit`` on the single-device
        path, or the mesh-sharded wrapper when a serving mesh is
        installed. With a size-1 model axis, chunks shard over the data
        axis and params/grid replicate — the body is identical either
        way, which is why that mesh render stays bitwise-equal to the
        single-device one. With model > 1, the params template routes
        mesh_jit onto the GSPMD path (TP-rule-sharded params, XLA-placed
        collectives; allclose, not bitwise)."""
        import jax

        if self.mesh is None:
            # graftlint: ok(aot: warm-up hands every finalized executable to AOTRegistry.register)
            return jax.jit(fn)
        from ..scale.mesh_dispatch import mesh_jit

        return mesh_jit(fn, self.mesh, has_grid=self.use_grid,
                        params_template=self.params)

    def _build_fn(self, bucket: int, family: str):
        import jax
        import jax.numpy as jnp  # noqa: F401  (kept local: no import cost pre-jax)

        from ..renderer.accelerated import march_rays_accelerated
        from ..renderer.packed_march import march_rays_packed
        from ..renderer.volume import render_rays

        network = self._family_network(family)
        near, far = self.near, self.far
        model = "coarse" if family == "coarse" else "fine"

        if self.use_grid and family == "proposal":
            # the learned sampler is the admission structure here: the
            # deterministic resampler produces the candidate depths, the
            # occupancy grid culls the ones in carved-empty space, and the
            # packed compositing stream renders the survivors — the
            # proposal tier inherits the packed speedup instead of riding
            # the dense chunked render. Signature keeps (params, rays_p,
            # grid, bbox) so _dispatch and the AOT warm-up treat every
            # grid-engine family uniformly.
            from ..renderer.packed_march import march_rays_proposal_packed

            options = self._family_march_options(family)
            eval_opts = self._family_eval_options(family)
            sampling = eval_opts.sampling
            lindisp = bool(eval_opts.lindisp)
            cap = self.packed_cap

            def fn(params, rays_p, grid, bbox):
                apply_fn = lambda pts, vd, m: network.apply(  # noqa: E731
                    params, pts, vd, model=m
                )
                return jax.lax.map(
                    lambda rc: march_rays_proposal_packed(
                        apply_fn, rc, near, far, grid, bbox, options,
                        sampling, cap_avg=cap, lindisp=lindisp,
                    ),
                    rays_p,
                )

            return self._finalize_fn(fn)

        if self.use_grid:
            options = self._family_march_options(family)

            if options.march_fused == "full":
                # stage (b) mega-kernel (ops/fused_march.py): whole march
                # in one block-fused program. Built per family, so the
                # bf16 tier's clone yields a bf16-compute spec and the
                # coarse tier streams the coarse branch — the family
                # ladder is a weight/spec swap, never a new code path.
                from ..ops.fused_march import march_rays_fused_full
                from ..ops.fused_mlp import fused_spec_for

                spec = fused_spec_for(network)
                xyz_enc = network.xyz_encoder
                dir_enc = network.dir_encoder

                def fn(params, rays_p, grid, bbox):
                    branch = params["params"][model]
                    return jax.lax.map(
                        lambda rc: march_rays_fused_full(
                            spec, xyz_enc, dir_enc, branch, rc, near, far,
                            grid, bbox, options,
                        ),
                        rays_p,
                    )

                return self._finalize_fn(fn)

            if options.march_fused == "gather":
                # stage (a): fused DDA + gather, MLP + compositing outside
                from ..ops.fused_march import march_rays_fused

                def fn(params, rays_p, grid, bbox):
                    apply_fn = lambda pts, vd, _m, valid=None: network.apply(  # noqa: E731
                        params, pts, vd, model=model
                    )
                    return jax.lax.map(
                        lambda rc: march_rays_fused(
                            apply_fn, rc, near, far, grid, bbox, options
                        ),
                        rays_p,
                    )

                return self._finalize_fn(fn)

            if options.coarse_block > 0 or options.clip_bbox:
                # hierarchical (or clipped) traversal: the packed march,
                # same routing condition as Renderer.render_accelerated —
                # full-tier parity with the one-shot surfaces holds by
                # construction, both switch on the same MarchOptions
                cap = self.packed_cap

                def fn(params, rays_p, grid, bbox):
                    apply_fn = lambda pts, vd, _m: network.apply(  # noqa: E731
                        params, pts, vd, model=model
                    )
                    return jax.lax.map(
                        lambda rc: march_rays_packed(
                            apply_fn, rc, near, far, grid, bbox, options,
                            cap_avg=cap,
                        ),
                        rays_p,
                    )

                return self._finalize_fn(fn)

            def fn(params, rays_p, grid, bbox):
                apply_fn = lambda pts, vd, _m: network.apply(  # noqa: E731
                    params, pts, vd, model=model
                )
                return jax.lax.map(
                    lambda rc: march_rays_accelerated(
                        apply_fn, rc, near, far, grid, bbox, options
                    ),
                    rays_p,
                )

            return self._finalize_fn(fn)

        options = self._family_eval_options(family)

        def fn(params, rays_p):
            apply_fn = lambda pts, vd, m: network.apply(  # noqa: E731
                params, pts, vd, model=m
            )
            return jax.lax.map(
                lambda rc: render_rays(apply_fn, rc, near, far, None, options),
                rays_p,
            )

        return self._finalize_fn(fn)

    def _fn_name(self, bucket: int, family: str) -> str:
        """Registry/tracker name for one executable. A model-parallel
        mesh bakes its shape into the name: a sharded lowering is a
        DIFFERENT artifact from the replicated one (different layouts,
        different collectives), so the two must never share an AOT
        artifact-store slot."""
        base = f"serve/{family}/b{bucket}"
        if self._model_parallel:
            from ..parallel.mesh import DATA_AXIS, MODEL_AXIS

            d = int(self.mesh.shape[DATA_AXIS])
            m = int(self.mesh.shape[MODEL_AXIS])
            return f"{base}/mesh{d}x{m}"
        return base

    def _get_fn(self, bucket: int, family: str):
        key = (bucket, family)
        fn = self._fns.get(key)
        if fn is None:
            fn = self.tracker.wrap(
                self._fn_name(bucket, family), self._build_fn(bucket, family)
            )
            self._fns[key] = fn
        return fn

    # graftlint: hot
    def warm_up(self, families: tuple[str, ...] | None = None) -> int:
        """Build every (bucket, family) executable before traffic.

        With an AOT registry the whole inventory registers with abstract
        signatures and compiles concurrently — or deserializes from the
        artifact store, in which case a warm restart performs ZERO builds
        (``warm_source == "disk"``, CompileTracker count 0) and never
        touches the params (they may still be abstract; see __init__).

        Without a registry, the legacy path dispatches an all-zero bucket
        per executable: zero-direction rays are the renderer's own padding
        convention (forced unoccupied in the occupancy sweep), so that is
        a valid warm-up input. Surfaces that only ever serve one tier
        (render_video) pass ``families=("full",)`` to skip the degraded
        executables. Returns the compile count paid."""
        import jax
        import jax.numpy as jnp

        if families is None:
            families = self._families_for_params()
        t0 = time.perf_counter()
        before = self.tracker.total_compiles()
        if self.aot is not None:
            from ..compile import abstract_like

            params_abs = abstract_like(self.params)
            static_abs = (
                (abstract_like(self.grid), abstract_like(self.bbox))
                if self.use_grid else ()
            )
            chunks_sh = None
            if self._model_parallel:
                # sharded warm-up signatures: the abstract leaves carry
                # the SAME shardings runtime placement uses (set_params /
                # the fleet placer), so the AOT-compiled layout is the
                # one requests hit — zero steady-state recompiles with
                # sharding on, same bar as the replicated path
                from jax.sharding import NamedSharding, PartitionSpec as P

                from ..parallel.sharding import tree_shardings

                params_abs = jax.tree.map(
                    lambda a, s: jax.ShapeDtypeStruct(
                        a.shape, a.dtype, sharding=s
                    ),
                    params_abs, tree_shardings(params_abs, self.mesh),
                )
                rep = NamedSharding(self.mesh, P())
                static_abs = tuple(
                    jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=rep)
                    for a in static_abs
                )
                chunks_sh = self._chunks_sharding
            names = {}
            for bucket in self.buckets:
                chunks_abs = jax.ShapeDtypeStruct(
                    (bucket // self.chunk, self.chunk, 6), jnp.float32,
                    sharding=chunks_sh,
                )
                for family in families:
                    name = self._fn_name(bucket, family)
                    names[(bucket, family)] = name
                    self.aot.register(
                        name, self._build_fn(bucket, family),
                        (params_abs, chunks_abs) + static_abs,
                        serialize=True,
                    )
            self.aot.compile_all(wait=True)
            for key, name in names.items():
                pre = self.aot.take(name)
                if pre is not None:
                    # a failed build stays lazy: _get_fn rebuilds on demand
                    self._fns[key] = self.tracker.wrap(name, pre)
            self.warm_source = self.aot.warm_source()
        else:
            zeros = {
                b: np.zeros((b, 6), np.float32) for b in self.buckets
            }
            for bucket in self.buckets:
                for family in families:
                    # block so the compile lands now, not on request one —
                    # without pulling every warm-up buffer to host the way
                    # np.asarray would (graftlint R1 finding, fixed)
                    jax.block_until_ready(
                        self._dispatch(zeros[bucket], bucket, family)
                    )
            self.warm_source = "compiled"
        self.warmup_compiles += self.tracker.total_compiles() - before
        self.warmup_wall_s += time.perf_counter() - t0
        return self.warmup_compiles

    def set_params(self, params) -> None:
        """Install real checkpoint weights — engine_from_cfg calls this
        AFTER warm-up, so a disk-cache-hit restart is serving-ready before
        the model finishes loading. Under a model-parallel mesh the
        weights land directly in their TP-rule shards (one placement; the
        executables' in_shardings then match without any reshard)."""
        import jax

        if self._model_parallel:
            from ..parallel.sharding import tree_shardings

            self.params = jax.device_put(
                params, tree_shardings(params, self.mesh)
            )
        else:
            self.params = jax.device_put(params)

    def place_scene_tree(self, tree):
        """Place a scene's ``(params, grid, bbox)`` host tree on the
        serving mesh: params by the TP partition rules, grid/bbox
        replicated. The fleet residency manager calls this (installed by
        :meth:`attach_fleet`) so admitted scenes land in the SAME layout
        the warmed executables were compiled for. Without a
        model-parallel mesh this is a plain ``device_put`` — the
        single-device fleet path is bitwise-unchanged."""
        import jax

        if not self._model_parallel:
            return jax.tree.map(jax.device_put, tree)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.sharding import tree_shardings

        params, grid, bbox = tree
        rep = NamedSharding(self.mesh, P())
        params = jax.device_put(params, tree_shardings(params, self.mesh))
        grid = None if grid is None else jax.device_put(grid, rep)
        bbox = None if bbox is None else jax.device_put(bbox, rep)
        return (params, grid, bbox)

    def scene_shard_nbytes(self, tree) -> int:
        """Per-device peak bytes ``tree`` will occupy once placed by
        :meth:`place_scene_tree` — the figure HBM admission checks.
        Derived from the partition specs (no placement happens here)."""
        import jax

        if not self._model_parallel:
            return sum(
                leaf.nbytes for leaf in jax.tree.leaves(tree)
                if hasattr(leaf, "nbytes")
            )
        from ..parallel.sharding import tree_shard_nbytes

        params, grid, bbox = tree
        replicated = sum(
            leaf.nbytes for leaf in jax.tree.leaves((grid, bbox))
            if hasattr(leaf, "nbytes")
        )
        return tree_shard_nbytes(params, self.mesh) + replicated

    @property
    def param_shards(self) -> int:
        """How many ways scene params split across devices (1 =
        replicated). Reported in stats/heartbeats so the placement
        planner can budget-pack with per-shard bytes."""
        if not self._model_parallel:
            return 1
        from ..scale.mesh_dispatch import model_size

        return model_size(self.mesh)

    # -- multi-scene residency (fleet/) --------------------------------------

    def attach_fleet(self, residency, default_scene: str = "default") -> None:
        """Install a :class:`~nerf_replication_tpu.fleet.ResidencyManager`.

        Every admitted scene is validated against the engine's warmed
        signatures FIRST (param-tree structure, grid shape, baked
        near/far) — a scene that would force a per-scene compile is
        rejected at load, so the zero-steady-state-recompile invariant
        holds across arbitrary scene churn."""
        residency.validate = self._check_scene_compat
        # sharded placement: scenes land by the engine's partition rules
        # and admission budgets against per-shard (not replicated) bytes;
        # on a mesh-less engine both hooks reduce to the classic behavior
        residency.placer = self.place_scene_tree
        residency.shard_nbytes = self.scene_shard_nbytes
        residency.param_shards = self.param_shards
        self.fleet = residency
        self.default_scene = str(default_scene)

    def _is_default_scene(self, scene_id) -> bool:
        return scene_id is None or scene_id == self.default_scene

    def resident_scenes(self) -> list[str]:
        """Scene ids served for free right now: the fleet's HBM-resident
        set plus any host-RAM staged copies (re-promotion is a
        device_put, no disk walk). The router's scene-affinity signal —
        routing a request here is an argument swap; routing it to a
        replica without the scene pays a cold load."""
        if self.fleet is None:
            return []
        ids = list(self.fleet.resident_ids())
        staged = getattr(self.fleet, "staged_ids", None)
        if staged is not None:
            ids.extend(s for s in staged() if s not in ids)
        return ids

    def require_scene(self, scene_id) -> None:
        """Synchronous existence check (submission edge: 404 before a
        bad scene id ever occupies queue capacity)."""
        if self._is_default_scene(scene_id):
            return
        if self.fleet is None:
            raise UnknownSceneError(
                scene_id, f"scene {scene_id!r} requested but multi-scene "
                          "serving is not configured (fleet.manifest / "
                          "fleet.scan_dir)")
        self.fleet.registry.get(scene_id)

    def prefetch_scene(self, scene_id) -> bool:
        """Kick a background host->device load so the first batch for a
        new scene overlaps its transfer with current work (no-op when
        resident, loading, default, or fleet-less)."""
        if self.fleet is None or self._is_default_scene(scene_id):
            return False
        return self.fleet.prefetch(scene_id)

    @contextmanager
    def scene_lease(self, scene_id):
        """Pin ``scene_id`` for a render block, yielding its SceneData
        (None = the engine's own checkpoint). The pin guarantees the
        residency manager cannot evict the scene mid-batch."""
        if self._is_default_scene(scene_id):
            yield None
            return
        self.require_scene(scene_id)
        with self.fleet.lease(scene_id) as data:
            yield data

    def _check_scene_compat(self, data) -> None:
        """Reject scenes the warmed executables cannot serve as-is."""
        import jax

        sid = data.scene_id
        if (data.grid is not None) != self.use_grid:
            raise SceneCompatError(
                sid, f"scene {sid!r}: grid presence ({data.grid is not None}) "
                     f"does not match the engine's path (use_grid="
                     f"{self.use_grid})")
        if abs(data.near - self.near) > 1e-6 or abs(data.far - self.far) > 1e-6:
            # near/far are baked into the executables as constants — a
            # scene with different bounds needs its own engine family
            raise SceneCompatError(
                sid, f"scene {sid!r}: bounds ({data.near}, {data.far}) differ "
                     f"from the baked ({self.near}, {self.far})")
        if jax.tree.structure(data.params) != jax.tree.structure(self.params):
            raise SceneCompatError(
                sid, f"scene {sid!r}: param tree structure differs from the "
                     "engine's network")
        eng_leaves = jax.tree.leaves(self.params)
        for ours, theirs in zip(eng_leaves, jax.tree.leaves(data.params)):
            if (tuple(ours.shape) != tuple(theirs.shape)
                    or str(ours.dtype) != str(theirs.dtype)):
                raise SceneCompatError(
                    sid, f"scene {sid!r}: param leaf {theirs.shape}/"
                         f"{theirs.dtype} vs engine {ours.shape}/{ours.dtype}")
        if self.use_grid and (
            tuple(data.grid.shape) != tuple(self.grid.shape)
            or str(data.grid.dtype) != str(self.grid.dtype)
        ):
            raise SceneCompatError(
                sid, f"scene {sid!r}: grid {data.grid.shape}/{data.grid.dtype}"
                     f" vs engine {self.grid.shape}/{self.grid.dtype}")

    # -- rendering -----------------------------------------------------------

    def _dispatch(self, rays_b: np.ndarray, bucket: int, family: str,
                  scene=None) -> dict:
        """One executable call on exactly ``bucket`` rays (already padded).

        ``scene`` (a pinned SceneData) swaps the runtime arguments —
        params/grid/bbox — under the SAME executable: scene switching is
        an argument change, never a compile."""
        import jax

        # the dispatch span covers HOST time only — reshape, h2d copy,
        # executable enqueue; the device's async compute lands in the
        # caller's "serve.device" span at the np.asarray sync point
        with get_tracer().span("serve.dispatch", stage="dispatch",
                               family=family, bucket=int(bucket)):
            # chaos hook: injected dispatch failures exercise the
            # batcher's circuit breaker / degradation path without
            # touching executables
            fault_point("serve.dispatch")
            chunks = rays_b.reshape(bucket // self.chunk, self.chunk,
                                    rays_b.shape[-1])
            # the request rays' host->device copy is the one INTENDED
            # transfer of the serving path; explicit device_put keeps the
            # whole request stream clean under jax.transfer_guard /
            # analysis.sanitizer(). Under a serving mesh the chunks land
            # directly in their data-axis shards — one placement, no
            # post-hoc reshard inside the executable.
            chunks = (
                jax.device_put(chunks) if self._chunks_sharding is None
                else jax.device_put(chunks, self._chunks_sharding)
            )
            fn = self._get_fn(bucket, family)
            params = self.params if scene is None else scene.params
            if self.use_grid:
                grid = self.grid if scene is None else scene.grid
                bbox = self.bbox if scene is None else scene.bbox
                return fn(params, chunks, grid, bbox)
            return fn(params, chunks)

    def _render_bucket(self, rays: np.ndarray, bucket: int,
                       family: str, scene=None) -> dict:
        n = rays.shape[0]
        rays_b = np.pad(rays, ((0, bucket - n), (0, 0)))
        out = dict(self._dispatch(rays_b, bucket, family, scene))
        # the device span wraps the np.asarray pulls below: the first pull
        # blocks until the async dispatch finishes, so its duration IS the
        # device-compute wait — the queue/dispatch/device split the span
        # taxonomy exists for
        with get_tracer().span("serve.device", stage="device",
                               bucket=int(bucket)):
            # traversal diagnostics are PER-CHUNK scalars ([n_chunks]
            # under the lax.map), not per-ray maps — fold them into the
            # serving counters before the per-ray reshape below would
            # garble them
            if "march_candidates" in out:
                cand = np.asarray(out.pop("march_candidates"))  # graftlint: ok(host-sync)
                self.march_chunks += cand.size
                self.march_candidates += float(cand.sum())
                self.march_samples_out += float(
                    np.sum(np.asarray(out.pop("march_samples_out")))  # graftlint: ok(host-sync)
                )
                self.march_coarse_occ_sum += float(
                    np.sum(np.asarray(out.pop("march_coarse_occ")))  # graftlint: ok(host-sync)
                )
                self.march_overflow_sum += float(
                    np.sum(np.asarray(out.pop("overflow_frac")))  # graftlint: ok(host-sync)
                )
            out = {
                # intentional device pull: outputs ARE the response payload
                k: np.asarray(v).reshape((-1,) + v.shape[2:])[:n]  # graftlint: ok(host-sync)
                for k, v in out.items()
            }
        trunc = out.pop("truncated", None)
        if trunc is not None:
            self.n_truncated += int(np.sum(trunc))
        return out

    def bucket_for(self, n_rays: int) -> int:
        """Smallest bucket holding ``n_rays`` (largest for oversize tails —
        callers split)."""
        for b in self.buckets:
            if n_rays <= b:
                return b
        return self.buckets[-1]

    def render_flat(self, rays, family: str = "full",
                    scene=None) -> tuple[dict, dict]:
        """Render a flat [N, C] ray array through the bucketed executables.

        Oversize requests stream through repeated largest-bucket calls; the
        tail lands in the smallest bucket that holds it. ``scene`` (a
        pinned SceneData from :meth:`scene_lease`) renders a fleet scene
        through the same executables. Returns ``(outputs, info)`` —
        outputs are host numpy [N, ...] arrays, info reports the
        padded-ray accounting the occupancy telemetry needs.
        """
        if family == "proposal" and not self.has_proposal:
            # coarse+fine checkpoint: the proposal family's shed step is
            # served from the reduced_k executable — an already-warm
            # family, never a new compile. Remapped HERE so every caller
            # (render_request, the micro-batcher's drain) degrades alike.
            family = "reduced_k"
        # host-side input normalization (requests arrive as numpy/lists)
        rays = np.asarray(rays, np.float32)  # graftlint: ok(host-sync)
        if rays.ndim != 2:
            raise ValueError(f"rays must be [N, C], got shape {rays.shape}")
        n = rays.shape[0]
        largest = self.buckets[-1]
        pieces, used = [], []
        i = 0
        while n - i > largest:
            pieces.append(self._render_bucket(rays[i:i + largest], largest,
                                              family, scene))
            used.append(largest)
            i += largest
        bucket = self.bucket_for(n - i)
        pieces.append(self._render_bucket(rays[i:], bucket, family, scene))
        used.append(bucket)

        out = pieces[0] if len(pieces) == 1 else {
            k: np.concatenate([p[k] for p in pieces], axis=0)
            for k in pieces[0]
        }
        bucket_rays = int(sum(used))
        self.n_rays_rendered += n
        self.n_pad_rays += bucket_rays - n
        info = {
            "n_rays": n,
            "bucket_rays": bucket_rays,
            "buckets": used,
            "occupancy": n / bucket_rays if bucket_rays else 0.0,
        }
        return out, info

    # graftlint: hot
    def render_request(self, rays, near, far, tier: str = "full",
                       emit: bool = True, scene=None) -> dict:
        """Render one request at ``tier``; bounds must match the baked ones.

        ``half_res`` renders every 2nd ray and nearest-neighbor expands the
        outputs back to the request length, so callers always get [N, ...]
        arrays regardless of tier. ``scene`` names a registry scene (None
        = the engine's own checkpoint); the lease pins it for the render.
        The served tier rides in the returned dict under ``"tier"``."""
        check_baked_bounds(self.near, self.far, near, far,
                           surface="serve engine")
        family, stride = TIER_IMPL[tier]
        # host-side input normalization (requests arrive as numpy/lists)
        rays = np.asarray(rays, np.float32)  # graftlint: ok(host-sync)
        n = rays.shape[0]
        t0 = time.perf_counter()
        with self.scene_lease(scene) as scene_data:
            out, info = self.render_flat(rays[::stride], family, scene_data)
        if stride > 1:
            out = {
                k: np.repeat(v, stride, axis=0)[:n] for k, v in out.items()
            }
        latency = time.perf_counter() - t0
        self.n_requests += 1
        if emit:
            fields = {} if self._is_default_scene(scene) \
                else {"scene": str(scene)}
            # graftlint: ok(emit-hot: per-request completion record, post-sync)
            get_emitter().emit(
                "serve_request",
                latency_s=latency,
                n_rays=n,
                tier=tier,
                status="ok",
                n_buckets=len(info["buckets"]),
                bucket_rays=info["bucket_rays"],
                **fields,
            )
        out["tier"] = tier
        return out

    # graftlint: hot
    def render_view(self, c2w, H: int, W: int, focal: float,
                    tier: str = "full", via=None,
                    scene=None) -> tuple[np.ndarray, dict]:
        """Pose -> uint8 [H, W, 3] image through the pose LRU cache.

        ``via(rays, near, far) -> out dict`` overrides the render path —
        the HTTP entrypoint passes the micro-batcher's submitting closure
        so concurrent views coalesce; default is a direct engine render at
        ``tier``. ``scene`` selects the per-scene pose cache and render
        target (a view is a pure function of pose AND scene, so caches
        never alias across scenes)."""
        if self._is_default_scene(scene):
            cache, scene = self.cache, None
        else:
            self.require_scene(scene)
            cache = self.fleet.pose_cache(scene)
        key = cache.key(c2w, H, W, focal)
        t0 = time.perf_counter()
        cached = cache.get(key)
        if cached is not None:
            image, served_tier = cached
            fields = {} if scene is None else {"scene": str(scene)}
            # graftlint: ok(emit-hot: cache-hit record, no device work at all)
            get_emitter().emit(
                "serve_request",
                latency_s=time.perf_counter() - t0,
                n_rays=H * W,
                tier=served_tier,
                status="ok",
                cache_hit=True,
                **fields,
            )
            return image, {"tier": served_tier, "cache_hit": True}

        from ..datasets.rays import get_rays_np

        # pose arrives as host data (HTTP json / python lists)
        rays_o, rays_d = get_rays_np(H, W, float(focal), np.asarray(c2w))  # graftlint: ok(host-sync)
        rays = np.concatenate([rays_o, rays_d], -1).reshape(-1, 6)
        if via is not None:
            out = via(rays, self.near, self.far)
        else:
            out = self.render_request(rays, self.near, self.far, tier=tier,
                                      emit=True, scene=scene)
        served_tier = out.get("tier", tier)
        rgb_key = "rgb_map_f" if "rgb_map_f" in out else "rgb_map_c"
        # image assembly IS the response; render_flat already scattered to host
        rgb = np.clip(np.asarray(out[rgb_key]).reshape(H, W, 3), 0.0, 1.0)  # graftlint: ok(host-sync)
        image = (rgb * 255).astype(np.uint8)
        cache.put(key, (image, served_tier))
        return image, {"tier": served_tier, "cache_hit": False}

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        march = None
        if self.march_chunks:
            march = {
                "chunks": self.march_chunks,
                "candidates_per_chunk": self.march_candidates / self.march_chunks,
                "samples_out_per_chunk": self.march_samples_out / self.march_chunks,
                "sweep_efficiency": (
                    self.march_samples_out / max(self.march_candidates, 1.0)
                ),
                "coarse_occ_mean": self.march_coarse_occ_sum / self.march_chunks,
                "overflow_mean": self.march_overflow_sum / self.march_chunks,
            }
        return {
            "march": march,
            # the learned-sampling story per family: fine-MLP evals/ray is
            # the cost knob the proposal resampler exists to cut, and the
            # per-tier budgets make degraded traffic's quality/cost trade
            # inspectable from GET /stats
            "sampling": {
                "mode": self.eval_options.sampling.mode,
                "has_proposal": self.has_proposal,
                "fine_evals_per_ray": {
                    f: self._family_eval_options(f).fine_evals_per_ray
                    for f in self._families_for_params()
                },
            },
            "buckets": list(self.buckets),
            "chunk": self.chunk,
            "use_grid": self.use_grid,
            "near": self.near,
            "far": self.far,
            "n_requests": self.n_requests,
            "n_rays_rendered": self.n_rays_rendered,
            "n_pad_rays": self.n_pad_rays,
            "n_truncated": self.n_truncated,
            "compiles": self.tracker.counts(),
            "total_compiles": self.tracker.total_compiles(),
            "warmup_compiles": self.warmup_compiles,
            # where the warm-up executables came from: "disk" is the
            # zero-build restart (every executable deserialized from the
            # artifact store), "compiled" means at least one was built
            "warm_source": self.warm_source,
            "warmup_wall_s": round(self.warmup_wall_s, 3),
            # mesh-sharded dispatch (scale/): None = single-device path
            "mesh": None if self.mesh is None else {
                "devices": int(self.mesh.size),
                "axes": dict(self.mesh.shape),
                "model_parallel": self._model_parallel,
                "param_shards": self.param_shards,
            },
            "cache": self.cache.stats(),
            # multi-scene residency (None = single-tenant serving)
            "fleet": None if self.fleet is None else self.fleet.stats(),
        }


def engine_from_cfg(cfg, cfg_file: str | None = None) -> RenderEngine:
    """Boot a serving engine from a trained experiment's config.

    Warm-up runs BEFORE checkpoint I/O: the engine is constructed on
    abstract params (``jax.eval_shape`` of the init — shapes only, no
    compute), registers its executables with the AOT registry, and warms
    from the serialized-artifact store when possible, so a cache-hit
    restart never blocks on model loading. The real weights install via
    ``set_params`` afterwards. Near/far baked from the test dataset; the
    occupancy grid loaded when ``task_arg.accelerated_renderer`` is set
    and a baked artifact exists (missing grid falls back to the chunked
    volume path, matching the one-shot surfaces)."""
    import jax

    from ..compile import registry_from_cfg
    from ..datasets import make_dataset
    from ..models import init_params_for, make_network
    from ..renderer.occupancy import default_grid_path, load_occupancy_pyramid
    from ..train.checkpoint import load_network

    network = make_network(cfg)
    test_ds = make_dataset(cfg, "test")
    grid = bbox = None
    if bool(cfg.task_arg.get("accelerated_renderer", False)):
        import os

        path = default_grid_path(cfg_file or "config")
        if os.path.exists(path):
            # versioned pyramid artifact; legacy flat grids upgrade on
            # load. Executables consume the FINE level and derive the
            # coarse level in-graph (renderer/occupancy.coarse_from_grid)
            # so the serve signatures stay (params, chunks, grid, bbox).
            try:
                levels, bbox = load_occupancy_pyramid(path)
                grid = levels[0]
            except OSError as exc:
                # truncated/corrupt artifact: serve correct pixels through
                # the chunked volume path rather than marching garbage
                print(f"occupancy grid unusable ({exc}); "
                      "serving through the chunked volume path")
        else:
            print(f"occupancy grid not found at {path}; "
                  "serving through the chunked volume path")
    # same key stream as load_trained_network: the param-tree STRUCTURE
    # must match the trainer's, and under AOT only the structure is needed
    # to warm — eval_shape traces the init without running it
    init = init_params_for(cfg)
    init_key = jax.random.PRNGKey(int(cfg.get("seed", 0)))
    tracker = CompileTracker()
    aot = registry_from_cfg(cfg, tracker=tracker)
    # serving mesh (scale: block): shard each executable's chunk axis
    # over the data-parallel mesh. None on a single device unless forced.
    from ..scale.mesh_dispatch import mesh_from_scale_cfg

    mesh = mesh_from_scale_cfg(cfg)
    if mesh is not None:
        print(f"serving mesh: {dict(mesh.shape)} over {mesh.size} device(s)")
    if aot is not None:
        try:
            params = jax.eval_shape(lambda k: init(network, k), init_key)
        # graftlint: ok(swallow: the fallback IS the handling — untraceable inits pay the real compute)
        except Exception:
            params = init(network, init_key)  # exotic init: pay the compute
    else:
        params = init(network, init_key)
    engine = RenderEngine(
        cfg, network, params, near=test_ds.near, far=test_ds.far,
        grid=grid, bbox=bbox, tracker=tracker, aot=aot, mesh=mesh,
    )
    # checkpoint I/O only now — a disk-warm engine is already serving-ready.
    # materialize the init for real (load_network hands the template back
    # unchanged when there is no checkpoint — it must hold init weights,
    # not placeholder zeros)
    leaves = jax.tree.leaves(params)
    if any(isinstance(a, jax.ShapeDtypeStruct) for a in leaves):
        params = init(network, init_key)
    loaded, epoch = load_network(
        cfg.trained_model_dir, params, epoch=int(cfg.test.get("epoch", -1))
    )
    engine.set_params(loaded)
    print(f"loaded network from {cfg.trained_model_dir} (epoch {epoch})")
    # camera defaults for pose-only requests (the HTTP surface)
    engine.default_camera = {
        "H": int(test_ds.H), "W": int(test_ds.W), "focal": float(test_ds.focal),
    }
    # multi-scene residency: attaches only when the fleet: block names a
    # manifest/scan_dir — default config keeps single-tenant behavior
    from ..fleet import fleet_from_cfg

    residency = fleet_from_cfg(cfg, engine)
    if residency is not None:
        print(f"fleet: {len(residency.registry)} scenes registered, "
              f"budget {residency.budget_bytes / (1 << 20):.0f} MB")
    return engine
