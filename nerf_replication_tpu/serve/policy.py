"""Graceful degradation under queue-depth backpressure.

Serving the NerfAcc lesson in reverse: once occupancy-grid sampling makes
per-ray FLOPs cheap, the knob that matters under overload is HOW MUCH work
a request is allowed to cost, not whether it runs. Instead of letting a
backlog push requests past their deadline (timeout = 100% quality loss for
the affected user), the policy trades quality for latency in deterministic
steps, and every response records the tier it was served at so degraded
traffic is measurable, never silent.

Tier ladder (cheapest executable family in parentheses — the last two
tiers share one, so degrading never compiles anything new):

==========  =================  =============================================
tier        executable family  meaning
==========  =================  =============================================
full        full               eval-budget march, fine network, f32
bf16        bf16               full march budget, fine network, bf16
                               COMPUTE (matmul chain) with f32 compositing —
                               the mildest shed step: quality loss is a
                               rounding-level PSNR delta, and on TPU the
                               halved MXU word size makes it cheaper than
                               full, not just equal
proposal    proposal           learned-sampler fine pass at HALF the fine
                               budget (renderer/sampling.py) — checkpoints
                               trained with ``sampling.mode: proposal``
                               carry the proposal net, and its histogram
                               concentrates a reduced budget where the
                               density is, so this sheds compute with less
                               PSNR loss than a uniform-march cut. For
                               coarse+fine checkpoints (no proposal branch)
                               the engine serves this tier from the
                               reduced_k family — never a new executable
reduced_k   reduced_k          half the max_samples MLP budget per ray
coarse      coarse             coarse network + reduced budget
half_res    coarse             coarse, every 2nd ray rendered, output
                               nearest-neighbor expanded back
==========  =================  =============================================
"""

from __future__ import annotations

from dataclasses import dataclass

# degradation order; index 0 is the undegraded tier
TIER_NAMES: tuple[str, ...] = (
    "full", "bf16", "proposal", "reduced_k", "coarse", "half_res"
)

# tier -> (executable family, ray stride applied OUTSIDE the executable)
TIER_IMPL: dict[str, tuple[str, int]] = {
    "full": ("full", 1),
    "bf16": ("bf16", 1),
    "proposal": ("proposal", 1),
    "reduced_k": ("reduced_k", 1),
    "coarse": ("coarse", 1),
    "half_res": ("coarse", 2),
}

# the executable families the engine pre-warms per bucket; "proposal" is
# warmed only when the loaded checkpoint carries the proposal branch
# (engine._families_for_params), else its tier falls back to reduced_k
FAMILIES: tuple[str, ...] = ("full", "bf16", "proposal", "reduced_k", "coarse")


@dataclass(frozen=True)
class DegradationPolicy:
    """Deterministic queue-depth -> tier mapping.

    ``thresholds[i]`` is the queue depth (requests still waiting when a
    batch is cut) at which tier ``i+1`` activates; depths below
    ``thresholds[0]`` serve at full quality. Monotonic by construction:
    the tier index is the count of thresholds the depth has reached.
    """

    thresholds: tuple[int, ...] = (4, 8, 16, 32)

    def __post_init__(self):
        if list(self.thresholds) != sorted(self.thresholds):
            raise ValueError(
                f"shed_queue_depths must be ascending, got {self.thresholds}"
            )
        if len(self.thresholds) > len(TIER_NAMES) - 1:
            raise ValueError(
                f"at most {len(TIER_NAMES) - 1} shed thresholds (one per "
                f"degraded tier), got {len(self.thresholds)}"
            )

    @classmethod
    def from_cfg(cls, cfg) -> "DegradationPolicy":
        s = cfg.get("serve", {})
        return cls(
            thresholds=tuple(
                int(d) for d in s.get("shed_queue_depths", (4, 8, 16, 32))
            )
        )

    def tier_for(self, queue_depth: int) -> str:
        i = sum(queue_depth >= t for t in self.thresholds)
        return TIER_NAMES[min(i, len(TIER_NAMES) - 1)]
