"""Pose->image LRU cache for repeated-view traffic.

Interactive viewers and embeddings of the same scene hammer a small set of
camera poses (the VDB-traversal paper's observation: real inspection
traffic is bursty around landmark views). A rendered NeRF view is a pure
function of (pose, intrinsics, scene), so repeated-view requests can skip
the march entirely. Keys quantize the camera-to-world matrix to
``decimals`` decimal places — close-enough poses (sub-voxel jitter from a
client's float serialization) collapse onto one entry, while genuinely new
views never alias at sane decimals.

Values are whatever the engine rendered (uint8 images + the tier they were
served at), so a cache hit faithfully replays the recorded tier rather
than masquerading as full quality.
"""

from __future__ import annotations

from collections import OrderedDict
from threading import Lock

import numpy as np


class PoseCache:
    """Thread-safe LRU keyed on quantized (c2w, H, W, focal).

    ``capacity <= 0`` disables caching (get always misses, put is a no-op)
    so call sites never branch on configuration.
    """

    def __init__(self, capacity: int = 64, decimals: int = 3):
        self.capacity = int(capacity)
        self.decimals = int(decimals)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._entries: OrderedDict = OrderedDict()
        self._lock = Lock()

    def key(self, c2w, H: int, W: int, focal: float) -> bytes:
        """Quantized lookup key: pose rounded to ``decimals``, intrinsics
        appended (two resolutions of one pose are distinct views)."""
        pose = np.round(
            np.asarray(c2w, np.float64)[:3, :4], self.decimals
        )
        # +0.0 normalizes -0.0 so a pose that rounds to zero from either
        # side produces one key
        head = (pose + 0.0).astype(np.float32).tobytes()
        meta = np.asarray(
            [float(H), float(W), round(float(focal), self.decimals)],
            np.float32,
        ).tobytes()
        return head + meta

    def get(self, key: bytes):
        """Cached value or None; a hit refreshes recency."""
        with self._lock:
            value = self._entries.get(key)
            if value is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return value

    def put(self, key: bytes, value) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1

    def __len__(self) -> int:
        return len(self._entries)

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "entries": len(self._entries),
            "capacity": self.capacity,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "hit_rate": (self.hits / total) if total else 0.0,
        }
