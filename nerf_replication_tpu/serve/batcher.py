"""Request micro-batching: coalesce pending ray batches across requests.

Per-request dispatch wastes the engine's buckets — a 640-ray request in a
4096-ray bucket is 84% padding. The micro-batcher holds a request queue
and cuts a batch when EITHER edge fires: total pending rays reach
``max_batch_rays``, or the oldest request has waited ``max_delay_s``
(the classic max-batch/max-delay deadline pair). The batch concatenates
whole requests, renders through the engine's bucketed executables in one
flat call, and scatters the output slices back per request.

Backpressure is handled by degradation, not queueing to death: the tier
for each batch comes from ``DegradationPolicy.tier_for(queue_depth)``
measured when the batch is cut — a deep backlog serves cheaper tiers
(serve/policy.py) and emits ``serve_shed`` telemetry instead of letting
requests age into timeouts. Requests that DO exceed their deadline while
queued fail fast with :class:`ServeTimeoutError` before any compute is
spent on them.

Determinism for tests: construct with ``start=False`` and an injectable
``clock``, enqueue with ``submit``, and drive batches synchronously with
``pump()`` — the same code path the worker thread runs.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import nullcontext
from dataclasses import dataclass, field

import numpy as np

from ..fleet.errors import SceneError
from ..fleet.qos import TenantQuotaError
from ..obs import get_emitter
from ..obs.metrics import get_metrics
from ..obs.trace import current_ctx, get_tracer
from ..renderer.gate import check_baked_bounds
from ..resil import (
    BreakerOpenError,
    CircuitBreaker,
    dump_flight,
    fault_point,
    report,
)
from .policy import TIER_IMPL, TIER_NAMES, DegradationPolicy


class ServeTimeoutError(TimeoutError):
    """The request exceeded its deadline while queued (never rendered)."""


class ServeFuture:
    """Completion handle for one submitted request."""

    def __init__(self, n_rays: int):
        self.n_rays = n_rays
        self._event = threading.Event()
        self._result: dict | None = None
        self._error: BaseException | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def set_result(self, result: dict) -> None:
        self._result = result
        self._event.set()

    def set_exception(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    def result(self, timeout: float | None = None) -> dict:
        if not self._event.wait(timeout):
            raise ServeTimeoutError(
                f"no result within {timeout}s (request still queued?)"
            )
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result


@dataclass
class _Pending:
    rays: np.ndarray
    future: ServeFuture
    t_enqueued: float
    scene: str | None = None
    tenant: str | None = None
    # trace context captured on the submitting (HTTP) thread — the queue
    # entry is how a request's identity crosses into the worker thread.
    # t_trace is the enqueue time on the TRACER's clock (the batcher's
    # own clock is separately injectable), so queue-wait spans share a
    # timebase with every other span of the trace.
    ctx: object | None = None
    t_trace: float = 0.0
    n_rays: int = field(init=False)

    def __post_init__(self):
        self.n_rays = int(self.rays.shape[0])


class MicroBatcher:
    """Deadline-coalescing request queue in front of a RenderEngine."""

    def __init__(self, engine, policy: DegradationPolicy | None = None,
                 clock=time.monotonic, start: bool = True,
                 breaker: CircuitBreaker | None = None, qos=None):
        self.engine = engine
        self.options = engine.options
        self.policy = policy or DegradationPolicy(
            thresholds=engine.options.shed_queue_depths
        )
        self.clock = clock
        self.breaker = breaker or CircuitBreaker(clock=clock)
        # per-tenant QoS (fleet/qos.py QosController, duck-typed): when
        # attached, submissions meter through tenant token buckets and
        # batch cuts drain tenant queues by weight (None = FIFO classic)
        self.qos = qos
        # weighted-fair virtual time per tenant ("" = tenant-less): a
        # popped request advances its tenant by rays/weight, so assembly
        # order is start-time fair regardless of arrival order
        self._vtime: dict[str, float] = {}
        self._queue: deque[_Pending] = deque()
        # the condition guards the queue/stop handshake ONLY; the
        # counters below are worker-thread owned after start (read-only
        # elsewhere) and deliberately not part of the critical section
        # graftlint: guards(_queue, _stop, _vtime, _inflight)
        self._cond = threading.Condition()
        self._stop = False
        # counters (worker-thread owned after start; read-only elsewhere)
        self.n_batches = 0
        self.n_shed = 0
        self.n_timeouts = 0
        self.n_completed = 0
        self.n_dispatch_errors = 0
        self.n_scene_errors = 0
        self.n_quota_denied = 0
        self.worker_restarts = 0
        self._inflight: list[_Pending] = []
        self._worker_dead = False
        self._last_dispatch_t: float | None = None
        self._thread: threading.Thread | None = None
        self._started = start
        if start:
            self._spawn_worker()

    def _spawn_worker(self) -> None:
        self._worker_dead = False
        self._thread = threading.Thread(
            target=self._worker_main, name="serve-batcher", daemon=True
        )
        self._thread.start()

    def _worker_main(self) -> None:
        try:
            self._worker()
        except BaseException:
            # flag FIRST: a client unblocked by _fail_inflight may resubmit
            # before this thread finishes unwinding (is_alive() still True)
            self._worker_dead = True
            # the worker is dying (kill fault, unexpected error): fail the
            # in-flight batch on the way down — like a real process death
            # severing client connections — so callers get an immediate
            # error instead of blocking out their full request timeout.
            # Deliberately re-raised: recovery is the watchdog's job.
            self._fail_inflight()
            raise

    def ensure_worker(self) -> bool:
        """Watchdog: restart a dead worker thread (a crash that escaped
        the batch-level handler — e.g. a kill fault) so the queue keeps
        draining. Cheap (one is_alive check); runs on every submit and on
        health probes. Returns whether a worker is running."""
        if not self._started or self._stop:
            return self._thread is not None and self._thread.is_alive()
        t = self._thread
        if t is None or not t.is_alive() or self._worker_dead:
            self.worker_restarts += 1
            report("serve.flush", "crash",
                   detail=f"worker dead; restart #{self.worker_restarts}")
            dump_flight(
                "watchdog_crash",
                detail=f"serve worker dead; restart #{self.worker_restarts}",
            )
            # belt-and-braces: normally the dying worker already failed
            # its own in-flight batch (_worker_main)
            self._fail_inflight()
            self._spawn_worker()
        return True

    def _fail_inflight(self) -> None:
        with self._cond:
            stranded = [p for p in self._inflight if not p.future.done()]
            self._inflight = []
        for p in stranded:
            p.future.set_exception(RuntimeError(
                "serve worker crashed mid-batch; request lost"
            ))

    # -- submission -----------------------------------------------------------

    def submit(self, rays, near, far, scene: str | None = None,
               tenant: str | None = None, ctx=None) -> ServeFuture:
        """Enqueue a [N, C] ray request; returns a future.

        ``ctx`` (a :class:`~..obs.trace.SpanContext`) explicitly parents
        the request's spans when the submitter is NOT on the traced
        thread — an in-process replica relaying a routed request passes
        the router's ctx here; default None captures the calling
        thread's current span as before.

        Bounds are validated HERE (BakedBoundsError raises to the caller
        synchronously) so a bad request never occupies queue capacity,
        and an unknown ``scene`` raises :class:`UnknownSceneError` (404)
        the same way. A known non-resident scene kicks an async prefetch
        immediately, overlapping its host->device transfer with whatever
        batch is currently rendering. With the circuit breaker open,
        submission fast-fails with :class:`BreakerOpenError` (503 +
        Retry-After at the HTTP edge) instead of queueing work onto a
        known-bad dispatch path.

        With a QoS controller attached, ``tenant`` meters through that
        tenant's token bucket first (TenantQuotaError -> 429) and its
        scoped breaker (a tenant whose batches keep failing fast-fails
        alone — the engine-level breaker stays closed for everyone
        else)."""
        tenant = None if tenant is None else str(tenant)
        if self.qos is not None:
            tb = self.qos.breaker(tenant)
            if not tb.allow():
                raise BreakerOpenError(tb.retry_after_s())
        if not self.breaker.allow():
            raise BreakerOpenError(self.breaker.retry_after_s())
        if self.qos is not None:
            try:
                self.qos.admit(tenant)
            except TenantQuotaError:     # 429 at the HTTP edge
                self.n_quota_denied += 1
                raise
        self.ensure_worker()
        check_baked_bounds(self.engine.near, self.engine.far, near, far,
                           surface="serve micro-batcher")
        # scene=None short-circuits before any fleet-era engine method so
        # duck-typed engines without multi-scene support still batch.
        if scene is None or self.engine._is_default_scene(scene):
            scene = None
        else:
            self.engine.require_scene(scene)   # 404 before queueing
            self.engine.prefetch_scene(scene)  # overlap h2d with current work
        rays = np.asarray(rays, np.float32)
        if rays.ndim != 2 or rays.shape[0] == 0:
            raise ValueError(
                f"rays must be a non-empty [N, C] array, got {rays.shape}"
            )
        trs = get_tracer()
        pending = _Pending(rays, ServeFuture(rays.shape[0]), self.clock(),
                           scene=scene, tenant=tenant,
                           ctx=ctx if ctx is not None else current_ctx(),
                           t_trace=trs.now())
        with self._cond:
            if self._stop:
                raise RuntimeError("batcher is closed")
            self._queue.append(pending)
            depth = len(self._queue)
            self._cond.notify_all()
        get_metrics().gauge("serve_queue_depth", depth)
        return pending.future

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def close(self, drain: bool = True) -> None:
        """Stop the worker; with ``drain`` the queue renders first,
        otherwise queued futures fail with ServeTimeoutError."""
        with self._cond:
            self._stop = True
            if not drain:
                while self._queue:
                    p = self._queue.popleft()
                    p.future.set_exception(
                        ServeTimeoutError("batcher closed before render")
                    )
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None

    # -- batching core --------------------------------------------------------

    def _cut_batch(self) -> tuple[list[_Pending], int] | None:
        """Block until a batch edge fires; pop and return (batch, depth
        left behind). None only on close with an empty queue.

        A batch is cut for ONE scene — the queue head's — because the
        engine dispatches one (params, grid, bbox) set per flat call.
        Requests for other scenes stay queued in arrival order, so a
        mixed-tenant stream coalesces per-scene instead of fragmenting
        into single-request batches."""
        with self._cond:
            while not self._queue and not self._stop:
                self._cond.wait()
            if not self._queue:
                return None
            max_rays = self.options.max_batch_rays
            while not self._stop:
                head_scene = self._queue[0].scene
                total = sum(p.n_rays for p in self._queue
                            if p.scene == head_scene)
                if total >= max_rays:
                    break  # max-batch edge (for the head scene)
                remaining = self.options.max_delay_s - (
                    self.clock() - self._queue[0].t_enqueued
                )
                if remaining <= 0:
                    break  # max-delay edge
                self._cond.wait(timeout=remaining)
            if self.qos is not None:
                batch = self._fair_pop(max_rays)
                return batch, len(self._queue)
            # pop whole head-scene requests up to the ray budget (always
            # >= 1, so an oversize single request still renders — the
            # engine splits it); other scenes and over-budget stragglers
            # keep their relative order
            scene = self._queue[0].scene
            batch: list[_Pending] = []
            kept: list[_Pending] = []
            total = 0
            budget_full = False
            for p in self._queue:
                if p.scene != scene or budget_full:
                    kept.append(p)
                elif not batch or total + p.n_rays <= max_rays:
                    batch.append(p)
                    total += p.n_rays
                else:
                    budget_full = True
                    kept.append(p)
            self._queue.clear()
            self._queue.extend(kept)
            return batch, len(self._queue)

    def _fair_pop(self, max_rays: int) -> list[_Pending]:
        """Weighted fair batch assembly (QoS mode; caller holds the lock).

        The batch's scene is the one wanted by the *least-served*
        backlogged tenant (lowest virtual time), NOT the queue head — a
        flooding tenant's backlog cannot push a quiet tenant's requests
        behind it. The ray budget then fills tenant-by-tenant in virtual-
        time order (within a tenant: arrival order, same scene only), and
        every popped request advances its tenant's clock by
        ``rays / weight`` — a hot tenant saturates its weighted share of
        batch capacity and no more while anyone else is waiting."""
        by_tenant: dict[str, list[_Pending]] = {}
        for p in self._queue:
            by_tenant.setdefault(p.tenant or "", []).append(p)
        # a tenant (re)joining the backlog starts at the active floor:
        # idle time banks no credit, so a burst after silence still
        # shares the batch fairly
        floor = min((self._vtime[t] for t in by_tenant if t in self._vtime),
                    default=0.0)
        for t in by_tenant:
            self._vtime[t] = max(self._vtime.get(t, floor), floor)
        order = sorted(by_tenant, key=lambda t: self._vtime[t])
        scene = by_tenant[order[0]][0].scene
        batch: list[_Pending] = []
        total = 0
        for t in order:
            weight = self.qos.weight(t or None)
            for p in by_tenant[t]:
                if p.scene != scene:
                    continue
                if batch and total + p.n_rays > max_rays:
                    break
                batch.append(p)
                total += p.n_rays
                self._vtime[t] += p.n_rays / weight
            if total >= max_rays:
                break
        picked = set(map(id, batch))
        kept = [p for p in self._queue if id(p) not in picked]
        self._queue.clear()
        self._queue.extend(kept)
        return batch

    def pump(self) -> int:
        """Cut and render one batch synchronously (the test/manual-drive
        surface; the worker thread is a loop of exactly this). Returns the
        number of requests completed (0 when queue empty and closed)."""
        cut = self._cut_batch()
        if cut is None:
            return 0
        batch, depth = cut
        return self._render_batch(batch, depth)

    def _worker(self) -> None:
        while True:
            with self._cond:
                if self._stop and not self._queue:
                    return
            drained = self.pump() == 0
            with self._cond:
                if drained and self._stop:
                    return

    # graftlint: hot
    def _render_batch(self, batch: list[_Pending], queue_depth: int) -> int:
        emitter = get_emitter()
        trs = get_tracer()
        mx = get_metrics()
        now = self.clock()
        t_cut = trs.now()  # queue wait ends here, on the tracer's clock

        # fail queued-past-deadline requests before spending compute
        live: list[_Pending] = []
        for p in batch:
            waited = now - p.t_enqueued
            if waited > self.options.request_timeout_s:
                self.n_timeouts += 1
                p.future.set_exception(ServeTimeoutError(
                    f"request waited {waited:.3f}s in queue "
                    f"(timeout {self.options.request_timeout_s}s)"
                ))
                # graftlint: ok(emit-hot: timeout fail-fast path, not per-ray work)
                emitter.emit(
                    "serve_request", latency_s=waited, n_rays=p.n_rays,
                    tier="none", status="timeout", queue_s=waited,
                )
                trs.record("serve.queue", start_s=p.t_trace, end_s=t_cut,
                           parent=p.ctx, stage="queue", n_rays=p.n_rays,
                           status="timeout")
                # graftlint: ok(emit-hot: timeout fail-fast path, not per-ray work)
                mx.counter("serve_requests_total", status="timeout",
                           tier="none")
                # graftlint: ok(emit-hot: timeout fail-fast path, not per-ray work)
                mx.observe("serve_request_latency_seconds", waited,
                           trace_id=(p.ctx.trace_id if p.ctx is not None
                                     else None),
                           tier="none")
            else:
                live.append(p)
        if not live:
            return 0

        # close every live request's queue-wait span at the cut: the
        # HTTP-thread context captured at submit makes it a child of the
        # request's root span even though this runs on the worker thread
        for p in live:
            trs.record("serve.queue", start_s=p.t_trace, end_s=t_cut,
                       parent=p.ctx, stage="queue", n_rays=p.n_rays,
                       **({} if p.tenant is None else {"tenant": p.tenant}))

        # tenant attribution: a fair-popped batch is usually single-tenant
        # (vtime ordering groups a tenant's run); when it is, its breaker
        # and telemetry rows carry the tenant so a bad tenant's failures
        # stay scoped to it
        tenants = {p.tenant for p in live}
        batch_tenant = next(iter(tenants)) if len(tenants) == 1 else None
        tenant_breaker = (self.qos.breaker(batch_tenant)
                          if self.qos is not None and batch_tenant is not None
                          else None)
        tenant_fields = ({} if batch_tenant is None
                         else {"tenant": batch_tenant})

        # failure degrades through the SAME ladder load does: consecutive
        # dispatch failures (pre-open breaker pressure) push the tier pick
        # further down — cheaper executables, never a new compile. A
        # tenant whose own breaker is stressed degrades at least as far.
        tier = self.policy.tier_for(queue_depth)
        steps = self.breaker.degrade_steps()
        if tenant_breaker is not None:
            steps = max(steps, tenant_breaker.degrade_steps())
        if steps:
            i = TIER_NAMES.index(tier)
            tier = TIER_NAMES[min(i + steps, len(TIER_NAMES) - 1)]
        family, stride = TIER_IMPL[tier]
        if tier != "full":
            self.n_shed += 1
            # graftlint: ok(emit-hot: batch-cadence shed record, host-side)
            emitter.emit(
                "serve_shed", tier=tier, queue_depth=queue_depth,
                n_requests=len(live),
                n_rays=sum(p.n_rays for p in live),
                **tenant_fields,
            )
            # graftlint: ok(emit-hot: batch-cadence counter bump, lock-cheap)
            mx.counter("serve_sheds_total", tier=tier)

        # assemble: per-request tier striding, one flat engine call
        segments = []
        offset = 0
        for p in live:
            strided = p.rays[::stride]
            segments.append((offset, strided.shape[0]))
            offset += strided.shape[0]
        flat = (
            live[0].rays[::stride] if len(live) == 1
            else np.concatenate([p.rays[::stride] for p in live], axis=0)
        )

        scene = live[0].scene
        scene_fields = {} if scene is None else {"scene": str(scene)}
        t0 = self.clock()
        # deliberately no try/finally around _inflight: a kill must LEAVE
        # it populated so the watchdog can fail the stranded futures
        with self._cond:
            self._inflight = live
        try:
            # the batch span runs on the worker thread but is parented to
            # the FIRST coalesced request's trace (a batch has one
            # timeline, many riders; per-rider attribution comes from the
            # queue/scatter spans). Becoming this thread's current span
            # also nests the acquire/dispatch/device spans underneath.
            with trs.span("serve.batch", parent=(live[0].ctx), tier=tier,
                          n_requests=len(live), n_rays=int(flat.shape[0]),
                          queue_depth=queue_depth, **scene_fields):
                # the lease pins the scene's residency for the whole
                # render — the manager cannot evict it under an in-flight
                # batch. The default scene (None) takes no lease and the
                # legacy two-arg render_flat call, so pre-fleet engine
                # doubles keep working.
                with (nullcontext() if scene is None
                      else self.engine.scene_lease(scene)) as scene_data:
                    # chaos hook: the flush-level fault point (a kill here
                    # is a BaseException — it escapes this handler, dies
                    # with the worker thread, and the watchdog restarts it)
                    fault_point("serve.flush")
                    out, info = (
                        self.engine.render_flat(flat, family)
                        if scene_data is None
                        else self.engine.render_flat(flat, family,
                                                     scene_data)
                    )
        except SceneError as err:
            # scene-scoped failure (torn checkpoint, residency overload):
            # fail THIS scene's requests only and leave the breaker alone —
            # other scenes' dispatch path is healthy and must keep serving
            self.n_scene_errors += 1
            self._last_dispatch_t = self.clock()
            for p in live:
                p.future.set_exception(err)
                # graftlint: ok(emit-hot: scene-failure path, not steady-state)
                get_emitter().emit(
                    "serve_request",
                    latency_s=self.clock() - p.t_enqueued,
                    n_rays=p.n_rays, tier=tier, status="scene_error",
                    queue_s=t0 - p.t_enqueued, **scene_fields,
                    **({} if p.tenant is None else {"tenant": p.tenant}),
                )
                # graftlint: ok(emit-hot: scene-failure path, not steady-state)
                mx.counter("serve_requests_total", status="scene_error",
                           tier=tier)
            dump_flight("scene_error",
                        detail=f"scene={scene} {type(err).__name__}: "
                               f"{err}"[:200])
            with self._cond:
                self._inflight = []
            return 0
        except Exception as err:  # scatter the failure; don't kill the loop
            self.n_dispatch_errors += 1
            self._last_dispatch_t = self.clock()
            # a single-tenant batch charges THAT tenant's breaker only —
            # its floods of bad requests open its own circuit (429/503 for
            # it alone) while the engine-level breaker stays closed for
            # everyone else. Mixed/tenant-less batches charge the global
            # breaker as before.
            if tenant_breaker is not None:
                tenant_breaker.record_failure()
            else:
                self.breaker.record_failure()
            detail = f"{type(err).__name__}: {err}"
            for p in live:
                p.future.set_exception(err)
                # graftlint: ok(emit-hot: dispatch-failure path, not steady-state)
                get_emitter().emit(
                    "serve_request",
                    latency_s=self.clock() - p.t_enqueued,
                    n_rays=p.n_rays, tier=tier, status="error",
                    queue_s=t0 - p.t_enqueued, **scene_fields,
                    **({} if p.tenant is None else {"tenant": p.tenant}),
                )
                # graftlint: ok(emit-hot: dispatch-failure path, not steady-state)
                mx.counter("serve_requests_total", status="error", tier=tier)
            report("serve.dispatch", "error", detail=detail[:200])
            with self._cond:
                self._inflight = []
            return 0
        render_s = self.clock() - t0
        self._last_dispatch_t = self.clock()
        self.breaker.record_success()
        if tenant_breaker is not None:
            tenant_breaker.record_success()

        self.n_batches += 1
        # graftlint: ok(emit-hot: one row per coalesced batch, post-sync)
        emitter.emit(
            "serve_batch",
            n_requests=len(live),
            n_rays=int(flat.shape[0]),
            occupancy=float(info["occupancy"]),
            tier=tier,
            render_s=float(render_s),
            queue_depth=queue_depth,
            bucket_rays=int(info["bucket_rays"]),
            **scene_fields,
            **tenant_fields,
        )

        t_done = self.clock()
        for p, (start, length) in zip(live, segments):
            t_sc = trs.now()
            sliced = {k: v[start:start + length] for k, v in out.items()}
            if stride > 1:
                sliced = {
                    k: np.repeat(v, stride, axis=0)[:p.n_rays]
                    for k, v in sliced.items()
                }
            sliced["tier"] = tier
            self.n_completed += 1
            self.engine.n_requests += 1
            latency_s = t_done - p.t_enqueued
            # graftlint: ok(emit-hot: per-request completion record, post-sync host slicing)
            emitter.emit(
                "serve_request",
                latency_s=latency_s,
                n_rays=p.n_rays,
                tier=tier,
                status="ok",
                queue_s=t0 - p.t_enqueued,
                **scene_fields,
                **({} if p.tenant is None else {"tenant": p.tenant}),
            )
            trs.record("serve.scatter", start_s=t_sc, parent=p.ctx,
                       stage="scatter", n_rays=p.n_rays, tier=tier)
            t_labels = {} if p.tenant is None else {"tenant": p.tenant}
            # graftlint: ok(emit-hot: per-request counter+histogram, lock-cheap post-sync)
            mx.counter("serve_requests_total", status="ok", tier=tier,
                       **t_labels)
            # the request's trace_id rides the bucket as an exemplar:
            # scale_decision evidence joins from aggregate to trace here
            # graftlint: ok(emit-hot: per-request counter+histogram, lock-cheap post-sync)
            mx.observe("serve_request_latency_seconds", latency_s,
                       trace_id=(p.ctx.trace_id if p.ctx is not None
                                 else None),
                       tier=tier, **t_labels)
            p.future.set_result(sliced)
        # graftlint: ok(emit-hot: one gauge store per batch)
        mx.gauge("serve_queue_depth", queue_depth)
        with self._cond:
            self._inflight = []
        return len(live)

    def stats(self) -> dict:
        out = {
            "queue_depth": self.queue_depth(),
            "n_batches": self.n_batches,
            "n_completed": self.n_completed,
            "n_shed": self.n_shed,
            "n_timeouts": self.n_timeouts,
            "n_dispatch_errors": self.n_dispatch_errors,
            "n_scene_errors": self.n_scene_errors,
            "n_quota_denied": self.n_quota_denied,
            "worker_restarts": self.worker_restarts,
            "breaker": self.breaker.snapshot(),
        }
        if self.qos is not None:
            with self._cond:
                depths: dict[str, int] = {}
                for p in self._queue:
                    key = p.tenant or ""
                    depths[key] = depths.get(key, 0) + 1
            out["tenant_queue_depth"] = depths
            out["qos"] = self.qos.stats()
        return out

    def last_dispatch_age_s(self) -> float | None:
        """Seconds since the last dispatch attempt (None before the
        first) — the liveness number /healthz reports."""
        if self._last_dispatch_t is None:
            return None
        return max(0.0, self.clock() - self._last_dispatch_t)

    def health(self) -> dict:
        """The /healthz payload: queue depth, last-dispatch age, breaker
        state, worker liveness. ``ok`` is the headline bit — False when
        the breaker is open or the worker is dead and unrestartable."""
        worker_alive = self.ensure_worker() if self._started else True
        breaker = self.breaker.snapshot()
        age = self.last_dispatch_age_s()
        return {
            "ok": bool(worker_alive and breaker["state"] != "open"),
            "queue_depth": self.queue_depth(),
            "last_dispatch_age_s": None if age is None else round(age, 3),
            "breaker": breaker,
            "worker_alive": bool(worker_alive),
            "worker_restarts": self.worker_restarts,
        }
