"""Batched render-serving engine (docs/serving.md).

The subsystem that turns the one-shot batch renderer into the serving
layer the ROADMAP's north star asks for: a checkpoint-resident
:class:`RenderEngine` with pre-warmed shape-bucketed executables (zero
retraces across arbitrary request shapes), a deadline-coalescing
:class:`MicroBatcher` that amortizes dispatch across concurrent requests,
a deterministic :class:`DegradationPolicy` that sheds load by serving
cheaper tiers instead of timing out, and a quantized-pose
:class:`PoseCache` for repeated-view traffic. Entry points: ``serve.py``
(HTTP) and ``scripts/serve_bench.py`` (closed/open-loop load generator).
"""

from .batcher import MicroBatcher, ServeFuture, ServeTimeoutError
from .cache import PoseCache
from .engine import RenderEngine, ServeOptions, engine_from_cfg
from .policy import FAMILIES, TIER_IMPL, TIER_NAMES, DegradationPolicy

__all__ = [
    "FAMILIES",
    "TIER_IMPL",
    "TIER_NAMES",
    "DegradationPolicy",
    "MicroBatcher",
    "PoseCache",
    "RenderEngine",
    "ServeFuture",
    "ServeOptions",
    "ServeTimeoutError",
    "engine_from_cfg",
]
