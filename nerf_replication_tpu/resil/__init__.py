"""resil/: fault injection, retry, checksums, circuit breaking, and
training preemption/rollback — failure as a first-class, injectable,
telemetry-visible input (docs/robustness.md).

Import surface is deliberately flat: call sites touch one module, and
nothing here imports jax — every primitive is host-side, so the chaos
machinery itself can never cause a retrace.
"""

from .breaker import BreakerOpenError, CircuitBreaker
from .checksum import (
    SIDECAR_SUFFIX,
    file_sha256,
    tree_sha256,
    verify_checksum,
    verify_tree_checksum,
    write_checksum,
    write_tree_checksum,
)
from .faults import (
    FAULT_KINDS,
    FAULT_POINTS,
    FaultPlan,
    FaultSpec,
    SimulatedKill,
    active,
    fault_point,
    injecting,
    install,
    report,
    truncate_file,
    uninstall,
)
from .flight import (
    FlightRecorder,
    dump_flight,
    get_flight_recorder,
    install_flight_recorder,
    note_flight,
    uninstall_flight_recorder,
    validate_flight_dump,
)
from .guard import DivergenceError, PreemptionGuard, check_finite
from .retry import RETRY_ATTEMPTS, retry_params, with_retry

__all__ = [
    "BreakerOpenError",
    "CircuitBreaker",
    "DivergenceError",
    "FlightRecorder",
    "FAULT_KINDS",
    "FAULT_POINTS",
    "FaultPlan",
    "FaultSpec",
    "PreemptionGuard",
    "RETRY_ATTEMPTS",
    "SIDECAR_SUFFIX",
    "SimulatedKill",
    "active",
    "check_finite",
    "dump_flight",
    "fault_point",
    "file_sha256",
    "get_flight_recorder",
    "injecting",
    "install",
    "install_flight_recorder",
    "note_flight",
    "report",
    "retry_params",
    "uninstall_flight_recorder",
    "validate_flight_dump",
    "tree_sha256",
    "truncate_file",
    "uninstall",
    "verify_checksum",
    "verify_tree_checksum",
    "with_retry",
    "write_checksum",
    "write_tree_checksum",
]
