"""Crash flight recorder: the last N spans, dumped at the moment of death.

``telemetry.jsonl`` records everything but answers slowly; when a chaos
run (or production) hits a breaker-open, a watchdog-detected worker
crash, a torn-scene ``SceneError``, or SIGTERM, the question is always
the same: *what was the failing request's timeline?* This module keeps a
bounded in-memory ring of the most recent finished spans (fed as a
tracer sink — see ``obs/trace.py``) plus a smaller ring of fault-point
events, and on any trigger writes one ``flight_<reason>.json`` snapshot
of both — a self-contained post-mortem next to the run's telemetry.

Dumps are atomic (tmp + rename) and deterministic under an injected
clock: ring contents are exactly the span rows in finish order, ids come
from the tracer's counter, and the only wall-clock field is the dump's
own ``t``. Same failure schedule → byte-identical dump.

Like everything in resil/, this is host-side pure Python — no jax
import. The obs→resil dependency points the safe way: ``install`` pulls
the tracer in lazily and registers itself as a sink; obs never imports
resil.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque

_REASON_RE = re.compile(r"[^A-Za-z0-9_.-]+")

FLIGHT_VERSION = 1


class FlightRecorder:
    """Bounded span+event rings with an atomic JSON dump.

    ``capacity`` bounds the span ring (the event ring is fixed small —
    fault hits are rare next to spans). ``clock`` stamps events and the
    dump header; tests inject a fake for deterministic output.
    """

    EVENT_CAPACITY = 64

    def __init__(self, out_dir: str, capacity: int = 256, clock=time.time):
        self.out_dir = str(out_dir)
        self.capacity = max(1, int(capacity))
        self.clock = clock
        self._spans: deque = deque(maxlen=self.capacity)
        self._events: deque = deque(maxlen=self.EVENT_CAPACITY)
        self._lock = threading.Lock()
        self._dumps = 0

    # -- feeds ---------------------------------------------------------------

    def record(self, span_row: dict) -> None:
        """Tracer sink: ring one finished span row."""
        with self._lock:
            self._spans.append(span_row)

    def note(self, **event) -> None:
        """Ring one non-span event (fault-point hit, breaker detail) —
        the annotations that let a dump *name* the injected fault."""
        event.setdefault("t", self.clock())
        with self._lock:
            self._events.append(event)

    # -- dump ----------------------------------------------------------------

    def dump(self, reason: str, detail: str | None = None) -> str:
        """Write ``flight_<reason>.json`` atomically; returns the path.
        A repeat trigger with the same reason overwrites (the newest
        occurrence is the one a post-mortem wants)."""
        reason_slug = _REASON_RE.sub("_", str(reason)) or "unknown"
        with self._lock:
            payload = {
                "v": FLIGHT_VERSION,
                "reason": str(reason),
                "t": self.clock(),
                "detail": detail,
                "spans": list(self._spans),
                "events": list(self._events),
            }
            self._dumps += 1
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, f"flight_{reason_slug}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=True, default=str)
            fh.write("\n")
            fh.flush()
            try:
                os.fsync(fh.fileno())
            except OSError:
                pass
        os.replace(tmp, path)
        _fire_dump_listeners(str(reason), path, detail or "")
        return path

    def stats(self) -> dict:
        with self._lock:
            return {"spans": len(self._spans), "events": len(self._events),
                    "capacity": self.capacity, "dumps": self._dumps}


# -- dump listeners ----------------------------------------------------------
# In-process consumers notified after a flight dump lands on disk — the
# incident correlator (obs/incidents.py) opens an incident from here. The
# wiring direction matters: obs never imports resil, so the caller
# (serve.py, chaos_run) registers ``mgr.on_flight_dump`` with us.

_dump_listeners: list = []


def add_dump_listener(fn) -> None:
    """Subscribe ``fn(reason, path, detail)`` to every flight dump."""
    if fn not in _dump_listeners:
        _dump_listeners.append(fn)


def remove_dump_listener(fn) -> None:
    try:
        _dump_listeners.remove(fn)
    except ValueError:
        pass


def _fire_dump_listeners(reason: str, path: str, detail: str) -> None:
    for fn in list(_dump_listeners):
        try:
            fn(reason, path, detail)
        # graftlint: ok(swallow: a broken listener must never turn a crash dump into a second crash; it is dropped)
        except Exception:
            remove_dump_listener(fn)


# one recorder per process; None = flight recording disabled (the default
# outside serve.py / chaos_run — training runs don't pay the ring)
_recorder: FlightRecorder | None = None


def install_flight_recorder(recorder: FlightRecorder) -> FlightRecorder:
    """Activate ``recorder`` process-wide and subscribe it to the tracer
    so every finished span lands in the ring."""
    global _recorder
    from ..obs.trace import get_tracer

    if _recorder is not None:
        uninstall_flight_recorder()
    _recorder = recorder
    get_tracer().add_sink(recorder.record)
    return recorder


def uninstall_flight_recorder() -> None:
    global _recorder
    if _recorder is None:
        return
    from ..obs.trace import get_tracer

    get_tracer().remove_sink(_recorder.record)
    _recorder = None


def get_flight_recorder() -> FlightRecorder | None:
    return _recorder


def dump_flight(reason: str, detail: str | None = None) -> str | None:
    """Trigger a dump on the active recorder (no-op when none is
    installed, so fault paths call this unconditionally)."""
    rec = _recorder
    if rec is None:
        return None
    try:
        return rec.dump(reason, detail)
    # graftlint: ok(swallow: the recorder must never turn a crash dump into a second crash)
    except Exception:
        return None


def note_flight(**event) -> None:
    """Annotate the active recorder's event ring (no-op when none)."""
    rec = _recorder
    if rec is not None:
        rec.note(**event)


def validate_flight_dump(payload) -> list[str]:
    """Structural errors for one flight_<reason>.json payload (empty list
    = valid) — the shape scripts/check_telemetry_schema.py enforces."""
    if not isinstance(payload, dict):
        return [f"dump is {type(payload).__name__}, not an object"]
    errors = []
    if payload.get("v") != FLIGHT_VERSION:
        errors.append(f"missing/unknown flight version {payload.get('v')!r}")
    if not isinstance(payload.get("reason"), str) or not payload.get("reason"):
        errors.append("missing/empty 'reason'")
    if not isinstance(payload.get("t"), (int, float)):
        errors.append("missing/non-numeric 't'")
    spans = payload.get("spans")
    if not isinstance(spans, list):
        errors.append("'spans' is not a list")
        spans = []
    for i, row in enumerate(spans):
        if not isinstance(row, dict):
            errors.append(f"spans[{i}] is not an object")
            continue
        for field in ("trace_id", "span_id", "name"):
            if not isinstance(row.get(field), str):
                errors.append(f"spans[{i}]: missing/non-str {field!r}")
        for field in ("start_s", "dur_s"):
            if not isinstance(row.get(field), (int, float)):
                errors.append(f"spans[{i}]: missing/non-numeric {field!r}")
    events = payload.get("events")
    if not isinstance(events, list):
        errors.append("'events' is not a list")
    else:
        for i, ev in enumerate(events):
            if not isinstance(ev, dict):
                errors.append(f"events[{i}] is not an object")
    return errors
