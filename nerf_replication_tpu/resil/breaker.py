"""Circuit breaker for the serve dispatch path.

The shed-tier ladder (serve/policy.py) handles *load*; this handles
*failure*. Repeated dispatch exceptions first push requests down the same
degradation ladder (cheaper executables are both faster AND exercise less
of the failing surface), and once ``threshold`` consecutive dispatches
have failed the breaker opens: submissions fast-fail with
:class:`BreakerOpenError` (HTTP 503 + Retry-After at serve.py) instead of
queueing work that will die anyway. After ``cooldown_s`` the breaker goes
half-open — one batch probes the dispatch path — and a success closes it.

Every state transition emits one ``breaker`` telemetry row. The breaker
never touches executables or caches, so a recovery is compile-free by
construction (the chaos suite asserts it via CompileTracker).
"""

from __future__ import annotations

import threading
import time

from ..obs.emit import get_emitter
from ..obs.metrics import get_metrics
from .flight import dump_flight


class BreakerOpenError(RuntimeError):
    """Fast-fail: the dispatch path is known-bad; retry after cooldown."""

    def __init__(self, retry_after_s: float):
        self.retry_after_s = max(0.0, float(retry_after_s))
        super().__init__(
            f"circuit breaker open; retry after {self.retry_after_s:.1f}s"
        )


class CircuitBreaker:
    """closed → (consecutive failures ≥ threshold) open → (cooldown)
    half_open → success closes / failure re-opens."""

    def __init__(self, threshold: int = 5, cooldown_s: float = 5.0,
                 clock=time.monotonic, point: str = "serve.dispatch"):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self.point = point
        self._lock = threading.Lock()
        self._state = "closed"
        self._opened_at: float | None = None
        self._consecutive = 0
        self._failures = 0
        self._opens = 0
        # transition side effects (telemetry row + flight dump) queued
        # under the lock, performed after it is released — the emitter
        # writes a file and dump_flight walks the whole recorder ring;
        # neither belongs inside the breaker's critical section
        self._pending: list[tuple[str, int, int, float]] = []

    @classmethod
    def from_cfg(cls, cfg, clock=time.monotonic,
                 point: str = "serve.dispatch") -> "CircuitBreaker":
        """Breaker with thresholds from the ``resil:`` config block."""
        r = cfg.get("resil", {}) if cfg is not None else {}
        return cls(
            threshold=int(r.get("breaker_threshold", 5)),
            cooldown_s=float(r.get("breaker_cooldown_s", 5.0)),
            clock=clock,
            point=point,
        )

    # -- state ---------------------------------------------------------------

    def _tick(self) -> str:
        """Advance open → half_open when the cooldown has elapsed.
        Callers hold the lock."""
        if (self._state == "open" and self._opened_at is not None
                and self.clock() - self._opened_at >= self.cooldown_s):
            self._transition("half_open")
        return self._state

    def _transition(self, state: str) -> None:
        """Mutate state and queue the side effects; callers hold the lock
        and ``_flush()`` after releasing it."""
        self._state = state
        self._pending.append((state, self._failures, self._consecutive,
                              self._retry_after_locked()))

    def _flush(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
        for state, failures, consecutive, retry_after in pending:
            get_emitter().emit(
                "breaker", state=state, point=self.point,
                failures=failures, consecutive=consecutive,
                retry_after_s=retry_after,
            )
            get_metrics().counter(
                "serve_breaker_transitions_total", state=state)
            if state == "open":
                # post-mortem snapshot at the moment the dispatch path was
                # declared dead; the recorder has its own lock, never ours
                dump_flight(
                    "breaker_open",
                    detail=f"point={self.point} failures={failures} "
                           f"consecutive={consecutive}",
                )

    @property
    def state(self) -> str:
        with self._lock:
            state = self._tick()
        self._flush()
        return state

    def allow(self) -> bool:
        """May a new request enter? half_open allows (the probe)."""
        with self._lock:
            allowed = self._tick() != "open"
        self._flush()
        return allowed

    def _retry_after_locked(self) -> float:
        if self._state != "open" or self._opened_at is None:
            return 0.0
        return max(0.0, self.cooldown_s - (self.clock() - self._opened_at))

    def retry_after_s(self) -> float:
        with self._lock:
            return self._retry_after_locked()

    # -- outcomes ------------------------------------------------------------

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._consecutive += 1
            state = self._tick()
            if state == "half_open" or (
                state == "closed" and self._consecutive >= self.threshold
            ):
                self._opened_at = self.clock()
                self._opens += 1
                self._transition("open")
        self._flush()

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            if self._tick() != "closed":
                self._opened_at = None
                self._transition("closed")
        self._flush()

    # -- degradation coupling ------------------------------------------------

    def degrade_steps(self) -> int:
        """Extra shed-ladder steps from consecutive dispatch failures —
        the pre-open pressure valve the batcher folds into its tier pick."""
        with self._lock:
            return self._consecutive

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "state": self._tick(),
                "failures": self._failures,
                "consecutive": self._consecutive,
                "opens": self._opens,
                "retry_after_s": round(self._retry_after_locked(), 3),
            }
        self._flush()
        return snap
