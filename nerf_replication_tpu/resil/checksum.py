"""Sidecar checksums for on-disk artifacts (.aot executables, occupancy
.npz): a truncated or bit-flipped artifact must degrade to lazy-jit /
rebuild, never load garbage into a serving replica.

A ``<file>.sha256`` sidecar carries the hex digest; the sidecar is
written atomically AFTER the artifact (tmp + ``os.replace``), so a crash
between the two leaves an artifact without a sidecar — which verifies as
"unknown" (None), not as valid. Verification is opt-out cheap: one
streamed read at load time, host-only.
"""

from __future__ import annotations

import hashlib
import os

SIDECAR_SUFFIX = ".sha256"


def file_sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            block = fh.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def write_checksum(path: str) -> str:
    """Write ``path``'s digest sidecar atomically; the digest."""
    digest = file_sha256(path)
    sidecar = path + SIDECAR_SUFFIX
    tmp = f"{sidecar}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(digest + "\n")
    os.replace(tmp, sidecar)
    return digest


def _iter_tree_files(root: str):
    """Digest-relevant files under ``root``: sorted walk, sidecars and
    sidecar tmp files excluded (they describe the tree, they aren't it)."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for fname in sorted(filenames):
            if SIDECAR_SUFFIX in fname:
                continue
            yield os.path.join(dirpath, fname)


def tree_sha256(root: str, chunk: int = 1 << 20) -> str:
    """Digest of a directory tree: every file's root-relative path and
    content, in sorted order — the dir-level analogue of
    :func:`file_sha256` for artifacts that are directories (orbax scene
    checkpoints), where any torn member file must flip the digest."""
    h = hashlib.sha256()
    for path in _iter_tree_files(root):
        h.update(os.path.relpath(path, root).encode("utf-8") + b"\0")
        with open(path, "rb") as fh:
            while True:
                block = fh.read(chunk)
                if not block:
                    break
                h.update(block)
        h.update(b"\0")
    return h.hexdigest()


def write_tree_checksum(root: str) -> str:
    """Write a dir-tree digest sidecar (``<root>/tree.sha256``, atomic);
    the digest. Living INSIDE the tree, the sidecar travels with the
    checkpoint when a scene store is copied or scanned."""
    digest = tree_sha256(root)
    sidecar = os.path.join(root, "tree" + SIDECAR_SUFFIX)
    tmp = f"{sidecar}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(digest + "\n")
    os.replace(tmp, sidecar)
    return digest


def verify_tree_checksum(root: str) -> bool | None:
    """True = tree digest matches, False = mismatch (torn/corrupt scene
    checkpoint), None = unknown (no sidecar / unreadable)."""
    sidecar = os.path.join(root, "tree" + SIDECAR_SUFFIX)
    try:
        with open(sidecar, encoding="utf-8") as fh:
            expected = fh.read().strip()
    except OSError:
        return None
    if not expected:
        return None
    try:
        return tree_sha256(root) == expected
    except OSError:
        return None


def verify_checksum(path: str) -> bool | None:
    """True = digest matches, False = mismatch (torn/corrupt artifact),
    None = unknown (no sidecar, or either file unreadable — the caller's
    ordinary missing-file path handles it)."""
    sidecar = path + SIDECAR_SUFFIX
    try:
        with open(sidecar, encoding="utf-8") as fh:
            expected = fh.read().strip()
    except OSError:
        return None
    if not expected:
        return None
    try:
        return file_sha256(path) == expected
    except OSError:
        return None
