"""Sidecar checksums for on-disk artifacts (.aot executables, occupancy
.npz): a truncated or bit-flipped artifact must degrade to lazy-jit /
rebuild, never load garbage into a serving replica.

A ``<file>.sha256`` sidecar carries the hex digest; the sidecar is
written atomically AFTER the artifact (tmp + ``os.replace``), so a crash
between the two leaves an artifact without a sidecar — which verifies as
"unknown" (None), not as valid. Verification is opt-out cheap: one
streamed read at load time, host-only.
"""

from __future__ import annotations

import hashlib
import os

SIDECAR_SUFFIX = ".sha256"


def file_sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            block = fh.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def write_checksum(path: str) -> str:
    """Write ``path``'s digest sidecar atomically; the digest."""
    digest = file_sha256(path)
    sidecar = path + SIDECAR_SUFFIX
    tmp = f"{sidecar}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(digest + "\n")
    os.replace(tmp, sidecar)
    return digest


def verify_checksum(path: str) -> bool | None:
    """True = digest matches, False = mismatch (torn/corrupt artifact),
    None = unknown (no sidecar, or either file unreadable — the caller's
    ordinary missing-file path handles it)."""
    sidecar = path + SIDECAR_SUFFIX
    try:
        with open(sidecar, encoding="utf-8") as fh:
            expected = fh.read().strip()
    except OSError:
        return None
    if not expected:
        return None
    try:
        return file_sha256(path) == expected
    except OSError:
        return None
