"""Deterministic fault injection: failure as a first-class input.

Every reactive fix so far (the donated-buffer corruption, torn
bundle/sidecar pairs, the silent AOT-serialization skip) started as a
fault nobody could reproduce on demand. This module inverts that: the
library is instrumented with NAMED fault points, and a seeded
:class:`FaultPlan` decides — deterministically — which hits of which
point inject which fault. The same plan + seed always produces the same
failure schedule, so a chaos test is as reproducible as a unit test.

Fault kinds:

==========  ===============================================================
kind        behavior at the fault point
==========  ===============================================================
io_error    raise ``OSError`` (the retry/degrade paths must absorb it)
truncate    truncate the file at ``path`` on disk (torn-artifact simulation)
latency     ``sleep(delay_s)`` then continue (slow disk / network stall)
nan_loss    no side effect — the call site reads the returned spec and
            poisons its already-fetched loss scalar (train.loss only)
kill        raise :class:`SimulatedKill` (a ``BaseException``): the hard
            stop that ``except Exception`` recovery code must NOT absorb
==========  ===============================================================

Injection is host-side only — no fault point lives inside a jitted body,
so a chaos run compiles exactly the executables a clean run does (the
zero-steady-state-recompile invariant the chaos suite asserts).

Detected (not injected) faults — checksum mismatches, torn checkpoint
dirs, crashed worker threads — ride the same ``fault`` telemetry kind via
:func:`report` with ``injected: false``, so ``tlm_report`` summarizes
chaos and the wild identically.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

from ..obs.emit import get_emitter
from .flight import note_flight

# The named fault points the library is instrumented with. A FaultSpec
# naming anything else is rejected at construction, so a chaos plan can
# never silently target nothing. (docs/robustness.md catalogs each.)
FAULT_POINTS: tuple[str, ...] = (
    "checkpoint.save",          # train/checkpoint.py: before the bundle write
    "checkpoint.save.sidecar",  # between bundle and sidecars (torn-dir window)
    "checkpoint.load",          # train/checkpoint.py: before the restore
    "artifact.save",            # compile/artifacts.py: before the .aot write
    "artifact.load",            # compile/artifacts.py: before the .aot read
    "occupancy.load",           # renderer/occupancy.py: before the .npz read
    "serve.dispatch",           # serve/engine.py: per-bucket dispatch
    "serve.flush",              # serve/batcher.py: worker batch flush
    "train.loss",               # train loop's fetched loss scalar (nan_loss)
    "fleet.load",               # fleet/residency.py: before a scene load
    "fleet.publish",            # fleet/publish.py: before a hot-update gate
)

FAULT_KINDS: tuple[str, ...] = (
    "io_error", "truncate", "latency", "nan_loss", "kill"
)


class SimulatedKill(BaseException):
    """kill-at-point: the process "dies" here. Deliberately a
    ``BaseException`` — recovery code catching ``Exception`` must not
    absorb a kill, exactly like a real SIGKILL."""


@dataclass(frozen=True)
class FaultSpec:
    """One injection rule: at ``point``, after letting ``after`` hits
    through, inject ``kind`` on up to ``times`` hits (None = every hit),
    each hit firing with probability ``prob`` (drawn from the plan's
    seeded stream)."""

    point: str
    kind: str
    after: int = 0
    times: int | None = 1
    prob: float = 1.0
    delay_s: float = 0.05

    def __post_init__(self):
        if self.point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {self.point!r} (known: "
                f"{', '.join(FAULT_POINTS)})"
            )
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (known: "
                f"{', '.join(FAULT_KINDS)})"
            )


class FaultPlan:
    """A seeded, deterministic schedule of fault injections.

    Thread-safe: hit counting and the probability stream sit under one
    lock, so a given single-threaded call sequence always injects the
    same faults (the serve worker adds interleaving, but each test drives
    the batcher synchronously via ``pump()`` where determinism matters).
    """

    def __init__(self, specs=(), seed: int = 0):
        self.specs: list[FaultSpec] = list(specs)
        self.seed = seed
        self._rng = random.Random(seed)
        self._hits: dict[str, int] = {}
        self._fired: dict[int, int] = {}
        self._lock = threading.Lock()

    def add(self, point: str, kind: str, **kw) -> "FaultPlan":
        """Append one rule (chainable): ``plan.add("artifact.load",
        "io_error", times=2)``."""
        self.specs.append(FaultSpec(point, kind, **kw))
        return self

    def hit(self, point: str) -> FaultSpec | None:
        """Record one arrival at ``point``; the spec to inject, if any."""
        with self._lock:
            n = self._hits.get(point, 0)
            self._hits[point] = n + 1
            for i, spec in enumerate(self.specs):
                if spec.point != point or n < spec.after:
                    continue
                fired = self._fired.get(i, 0)
                if spec.times is not None and fired >= spec.times:
                    continue
                if spec.prob < 1.0 and self._rng.random() >= spec.prob:
                    continue
                self._fired[i] = fired + 1
                return spec
        return None

    def counts(self) -> dict[str, int]:
        """Total arrivals per point (injected or not)."""
        with self._lock:
            return dict(self._hits)

    def injected(self) -> int:
        """Total injections performed so far."""
        with self._lock:
            return sum(self._fired.values())


# one active plan per process — None means every fault point is free
_active_plan: FaultPlan | None = None


def install(plan: FaultPlan) -> FaultPlan:
    global _active_plan
    _active_plan = plan
    return plan


def uninstall() -> None:
    global _active_plan
    _active_plan = None


def active() -> FaultPlan | None:
    return _active_plan


@contextmanager
def injecting(plan: FaultPlan):
    """``with injecting(plan): ...`` — install for the block, always
    uninstall (even across a SimulatedKill)."""
    install(plan)
    try:
        yield plan
    finally:
        uninstall()


def fault_point(point: str, path: str | None = None,
                step: int | None = None) -> FaultSpec | None:
    """The library-side hook: a no-op (one global read) when no plan is
    installed. Side-effect faults (io_error/latency/truncate/kill) act
    before returning; value faults (nan_loss) return the spec for the
    call site to apply. Every injection emits one ``fault`` row."""
    plan = _active_plan
    if plan is None:
        return None
    spec = plan.hit(point)
    if spec is None:
        return None
    fields: dict = {"injected": True, "hit": plan.counts().get(point, 0)}
    if path is not None:
        fields["path"] = str(path)
    if step is not None:
        fields["step"] = int(step)
    if spec.kind == "latency":
        fields["delay_s"] = spec.delay_s
    get_emitter().emit("fault", point=point, fault=spec.kind, **fields)
    # same row into the flight recorder's event ring, so a post-mortem
    # dump names the injected fault next to the span timeline
    note_flight(point=point, fault=spec.kind, **fields)
    if spec.kind == "latency":
        time.sleep(spec.delay_s)
    elif spec.kind == "truncate":
        if path is not None:
            truncate_file(path)
    elif spec.kind == "io_error":
        raise OSError(f"injected fault at {point}"
                      + (f" ({path})" if path else ""))
    elif spec.kind == "kill":
        raise SimulatedKill(point)
    return spec


def truncate_file(path: str, frac: float = 0.5) -> None:
    """Tear a file on disk: keep the leading ``frac`` of its bytes.

    A directory path (a scene checkpoint, an orbax bundle) tears its
    largest file — deterministic, and the most likely victim of a real
    torn write — so ``truncate`` faults compose with dir-level artifacts
    and their tree checksums."""
    try:
        import os

        if os.path.isdir(path):
            files = sorted(
                (os.path.getsize(os.path.join(d, f)),
                 os.path.join(d, f))
                for d, _dirs, fnames in os.walk(path) for f in fnames
                if not f.endswith(".sha256")
            )
            if not files:
                return
            path = files[-1][1]
        size = os.path.getsize(path)
        with open(path, "r+b") as fh:
            fh.truncate(max(0, int(size * frac)))
    except OSError:
        pass  # a missing file is already as torn as it gets


def report(point: str, fault: str, *, path: str | None = None,
           detail: str | None = None, step: int | None = None) -> None:
    """Record a DETECTED fault (``injected: false``): checksum mismatch,
    torn checkpoint dir, crashed worker — same telemetry kind as chaos
    injections, so report/diff treat them uniformly."""
    fields: dict = {"injected": False}
    if path is not None:
        fields["path"] = str(path)
    if detail is not None:
        fields["detail"] = str(detail)
    if step is not None:
        fields["step"] = int(step)
    get_emitter().emit("fault", point=point, fault=fault, **fields)
    note_flight(point=point, fault=fault, **fields)
