"""Bounded exponential-backoff retry for every load path.

A transient ``OSError`` on a checkpoint/artifact/grid read should cost a
few retries, not a cold restart — but an unbounded retry loop turns a
hard failure into a hang, which is worse. So: a hard attempt cap, a
capped exponential backoff, and one ``retry`` telemetry row per decision
(status ``retry`` | ``ok`` | ``exhausted``) so recovery is measurable.
``tlm_report`` counts ``exhausted`` rows as unrecovered faults and
``--diff`` flags a run that grew them.
"""

from __future__ import annotations

import time

from ..obs.emit import get_emitter

# module defaults; the `resil:` config block overrides where a cfg is in
# scope (trainer resume), deep load paths use these as-is
RETRY_ATTEMPTS = 3
RETRY_BASE_S = 0.05
RETRY_MAX_S = 2.0


def with_retry(fn, *, point: str, attempts: int = RETRY_ATTEMPTS,
               base_s: float = RETRY_BASE_S, max_s: float = RETRY_MAX_S,
               retry_on: tuple = (OSError,), sleep=time.sleep):
    """Call ``fn()`` with up to ``attempts`` tries. Exceptions outside
    ``retry_on`` (including SimulatedKill, a BaseException) propagate
    immediately; the final failure re-raises after an ``exhausted`` row."""
    attempts = max(1, int(attempts))
    t0 = time.perf_counter()
    for attempt in range(1, attempts + 1):
        try:
            out = fn()
        except retry_on as err:
            detail = f"{type(err).__name__}: {err}"
            if attempt >= attempts:
                get_emitter().emit(
                    "retry", point=point, attempt=attempt,
                    status="exhausted", error=detail,
                    wall_s=time.perf_counter() - t0,
                )
                raise
            backoff = min(max_s, base_s * (2 ** (attempt - 1)))
            get_emitter().emit(
                "retry", point=point, attempt=attempt, status="retry",
                error=detail, backoff_s=backoff,
            )
            sleep(backoff)
        else:
            if attempt > 1:  # recovered: close the loop in telemetry
                get_emitter().emit(
                    "retry", point=point, attempt=attempt, status="ok",
                    wall_s=time.perf_counter() - t0,
                )
            return out


def retry_params(cfg) -> dict:
    """The ``resil:`` config block's retry knobs as ``with_retry`` kwargs."""
    r = cfg.get("resil", {})
    return {
        "attempts": int(r.get("retry_attempts", RETRY_ATTEMPTS)),
        "base_s": float(r.get("retry_base_s", RETRY_BASE_S)),
        "max_s": float(r.get("retry_max_s", RETRY_MAX_S)),
    }
