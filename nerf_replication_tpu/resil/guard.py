"""Training robustness: finite-loss guard and SIGTERM preemption.

Both guards are host-side and free of extra device syncs:

* :func:`check_finite` inspects the loss scalar the logging path has
  ALREADY fetched (train/trainer.py blocks on stats every ``log_every``
  steps regardless) — a NaN/Inf raises :class:`DivergenceError`, which
  the fit loop answers by rolling back to the last good checkpoint.
* :class:`PreemptionGuard` turns SIGTERM into a flag the step loop polls
  at its existing host-sync points; the loop flushes one atomic
  checkpoint (bundle + phase sidecar, PR 5's warm-start machinery) and
  exits, so the resumed run re-enters bitwise.
"""

from __future__ import annotations

import math
import signal
import threading

from .faults import fault_point, report
from .flight import dump_flight


class DivergenceError(RuntimeError):
    """The fetched loss went non-finite: roll back, don't checkpoint."""

    def __init__(self, step: int, value: float):
        self.step = int(step)
        self.value = float(value)
        super().__init__(f"non-finite loss {value!r} at step {step}")


def check_finite(stats_host: dict, step: int) -> dict:
    """Finite guard over already-fetched host stats. Applies an active
    ``train.loss`` nan_loss fault first (chaos), then raises
    :class:`DivergenceError` on a non-finite loss. Returns the (possibly
    poisoned) stats so the caller logs what the guard actually saw."""
    spec = fault_point("train.loss", step=step)
    if spec is not None and spec.kind == "nan_loss":
        stats_host = dict(stats_host)
        stats_host["loss"] = float("nan")
    loss = stats_host.get("loss")
    if loss is not None and not math.isfinite(float(loss)):
        if spec is None:  # detected in the wild, not injected
            report("train.loss", "nan_loss", step=step,
                   detail=f"loss={loss!r}")
        raise DivergenceError(step, float(loss))
    return stats_host


class PreemptionGuard:
    """SIGTERM → a polled flag; the loop owns the flush.

    The handler body only sets an event (signal-safe); the training loop
    notices at its next host-sync point, saves ``latest/`` with the phase
    sidecar, and stops cleanly. ``install()`` returns None off the main
    thread (signal.signal would raise) — callers treat that as disabled.
    """

    def __init__(self):
        self._event = threading.Event()
        self._prev = None
        self._installed = False

    @classmethod
    def install(cls) -> "PreemptionGuard | None":
        guard = cls()
        try:
            guard._prev = signal.signal(signal.SIGTERM, guard._on_signal)
        except ValueError:  # not the main thread: no signal delivery here
            return None
        guard._installed = True
        return guard

    def _on_signal(self, signum, frame):
        self._event.set()
        try:
            dump_flight("sigterm", detail=f"signum={signum}")
        # graftlint: ok(swallow: a signal handler must never raise)
        except Exception:
            pass

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def clear(self) -> None:
        self._event.clear()

    def uninstall(self) -> None:
        if self._installed:
            signal.signal(signal.SIGTERM, self._prev or signal.SIG_DFL)
            self._installed = False
