"""Optimizer + LR-schedule factories on optax.

Parity with the reference's `make_optimizer` (src/train/optimizer.py:5-28:
adam/radam/sgd with weight decay) and `make_lr_scheduler`/`set_lr_scheduler`
(src/train/scheduler.py:9-30; src/utils/optimizer/lr_scheduler.py:7-79):

* ``exponential``: lr·gamma^(epoch/decay_epochs) — the reference's continuous
  per-epoch decay (lr_scheduler.py:68-79), expressed here per *step* as
  gamma^(step/(decay_epochs·ep_iter)) so the jitted step needs no epoch state.
* ``multi_step`` / ``warmup_multi_step``: piecewise-constant decay at epoch
  milestones (+ linear warmup).
* gradient clipping **by value** at 40, applied before the optimizer update
  (trainer.py:61's `clip_grad_value_(·, 40)`).

The whole update is one optax chain, so it lives inside the jitted train step.
"""

from __future__ import annotations

import optax

GRAD_CLIP_VALUE = 40.0


def make_lr_schedule(cfg) -> optax.Schedule:
    sched = cfg.train.scheduler
    base_lr = float(cfg.train.lr)
    ep_iter = max(int(cfg.get("ep_iter", -1)), 1)
    stype = sched.get("type", "multi_step")

    if stype == "exponential":
        gamma = float(sched.gamma)
        decay_steps = float(sched.decay_epochs) * ep_iter

        def schedule(step):
            return base_lr * gamma ** (step / decay_steps)

        return schedule

    if stype in ("multi_step", "warmup_multi_step"):
        gamma = float(sched.gamma)
        milestones = [int(m) * ep_iter for m in sched.milestones]
        boundaries = {m: gamma for m in milestones}
        base = optax.piecewise_constant_schedule(base_lr, boundaries)
        if stype == "warmup_multi_step":
            warmup_steps = int(sched.get("warmup_epochs", 1)) * ep_iter
            warmup_factor = float(sched.get("warmup_factor", 1.0 / 3))
            warm = optax.linear_schedule(
                base_lr * warmup_factor, base_lr, warmup_steps
            )
            return optax.join_schedules([warm, base], [warmup_steps])
        return base

    raise NotImplementedError(f"scheduler type {stype!r}")


def make_optimizer(cfg) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """Returns (tx, schedule); schedule is exposed for logging the current lr."""
    schedule = make_lr_schedule(cfg)
    name = cfg.train.get("optim", "adam")
    wd = float(cfg.train.get("weight_decay", 0.0))
    eps = float(cfg.train.get("eps", 1e-8))

    if name == "adam":
        opt = (
            optax.adamw(schedule, eps=eps, weight_decay=wd)
            if wd > 0
            else optax.adam(schedule, eps=eps)
        )
    elif name == "radam":
        opt = optax.radam(schedule, eps=eps)
        if wd > 0:
            opt = optax.chain(optax.add_decayed_weights(wd), opt)
    elif name == "sgd":
        opt = optax.sgd(schedule, momentum=0.9)
    else:
        raise NotImplementedError(f"optimizer {name!r}")

    tx = optax.chain(optax.clip(GRAD_CLIP_VALUE), opt)
    return tx, schedule
