"""Trainer: the jitted hot loop, epoch cadence, validation, checkpointing.

Parity with the reference trainer/train-entry (src/train/trainers/trainer.py:
11-130, train.py:31-98) redesigned for TPU (SURVEY.md §7):

* The whole per-step pipeline — random ray draw from the device-resident ray
  bank, stratified sampling, coarse+fine MLP sweeps, compositing, MSE, grads,
  value-clip(40), adam update — is ONE jitted function. The reference pays
  ~0.2 s/iter of Python/DataLoader overhead for this (BASELINE.md); here the
  hot loop never touches the host.
* RNG: a base key folded with (step, process_index) per step — deterministic,
  resumable, and distinct across data-parallel processes.
* Precrop warm-up (precrop_iters/precrop_frac — configured but dead in the
  reference, SURVEY.md §2.5) is honored via a restricted index pool for the
  first N steps (a second compiled variant of the same step function).
* Validation renders whole test images through the chunked eval path and
  feeds the evaluator (trainer.py:98-130).
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax.training.train_state import TrainState

from ..datasets.sampling import sample_step_key
from ..utils.platform import donation_argnums
from ..obs import (
    CompileTracker,
    ProfileWindow,
    annotate,
    get_emitter,
    init_run,
    sample_memory,
)
from ..resil import DivergenceError, PreemptionGuard, check_finite, report
from .checkpoint import (
    has_checkpoint,
    load_model,
    load_pretrain,
    save_model_with_retry,
    save_trained_config,
)
from .step_core import sampled_grad_step, scan_k_steps
from .optim import make_optimizer
from .recorder import Recorder


def make_train_state(cfg, network, key) -> tuple[TrainState, "optax.Schedule"]:
    from ..models import init_params_for

    params = init_params_for(cfg)(network, key)
    tx, schedule = make_optimizer(cfg)
    state = TrainState.create(
        apply_fn=network.apply, params=params["params"], tx=tx
    )
    return state, schedule


class Trainer:
    def __init__(self, cfg, network, loss, evaluator=None, mesh=None):
        self.cfg = cfg
        self.network = network
        self.loss = loss  # NeRFLoss: (params, batch, key, train) -> (out, loss, stats)
        self.evaluator = evaluator
        # a live device mesh routes every step through the shard_map DP
        # builder (parallel/step.py) — the reference turns DDP on inside its
        # train entry (train.py:116-120, trainer.py:17-22), so the mesh is a
        # Trainer-level mode here, not a separate driver
        self.mesh = mesh
        # img_fit names the batch knob N_pixels (lego_view0.yaml:14)
        self.n_rays = int(
            cfg.task_arg.get("N_rays", cfg.task_arg.get("N_pixels", 1024))
        )
        # the task plugin (loss module) declares whether it uses ray bounds:
        # bound-free tasks set ray_bounds = (near, far) dummies; ray-marching
        # tasks leave it unset and a missing task_arg.near fails loudly here
        bounds = getattr(loss, "ray_bounds", None)
        if bounds is not None and "near" not in cfg.task_arg:
            self.near, self.far = float(bounds[0]), float(bounds[1])
        else:
            self.near = float(cfg.task_arg.near)
            self.far = float(cfg.task_arg.far)
        self.precrop_iters = int(cfg.task_arg.get("precrop_iters", 0))
        self.ep_iter = int(cfg.get("ep_iter", 500))
        # scan_steps > 1 runs K optimizer steps inside ONE jitted lax.scan:
        # the flagship step is latency-bound at small batches (~40 sequential
        # small matmuls/step — PERF.md), and scanning removes K-1 host
        # dispatches and lets XLA pipeline across step boundaries. Numerics
        # are step-for-step identical to K single calls: the per-step key is
        # derived from state.step, which apply_gradients advances inside the
        # scan exactly as it does outside (tested).
        self.scan_steps = max(1, int(cfg.task_arg.get("scan_steps", 1)))
        # microbatch gradient accumulation (HBM lever for past-roofline
        # batches — step_core.sampled_grad_step)
        self.grad_accum = max(1, int(cfg.task_arg.get("grad_accum", 1)))
        self.process_index = jax.process_index()
        self._step_fn = None
        self._step_fn_pool = None
        self._multi_step_fns: dict[int, object] = {}
        self._val_render = None
        # observability: compile/retrace counting on every built step fn
        # and the config-driven profiler window (train.profile) — both
        # no-ops unless a run emitter / profile config is active
        self.tracker = CompileTracker()
        self.profile = ProfileWindow.from_cfg(cfg)
        # AOT compile registry (compile/registry): fit() installs one so
        # step executables build on host threads during setup instead of on
        # first dispatch; None (unit tests, aot: false) keeps the lazy path
        self.aot = None
        # resilience (resil/guard.py, docs/robustness.md): the finite-loss
        # guard rides the stats the logging path already fetched (no extra
        # host sync); fit() installs the SIGTERM guard polled below
        self.finite_guard = bool(cfg.get("resil", {}).get("finite_guard", True))
        self.preempt = None

    def epoch_iters(self, bank_size: int) -> int:
        """Steps per epoch. ep_iter=-1 (the reference's 'no resampling'
        sentinel, make_dataset.py:64-65) means one natural pass over the ray
        bank at N_rays per step."""
        if self.ep_iter > 0:
            return self.ep_iter
        return max(1, bank_size // self.n_rays)

    def _uses_tp(self) -> bool:
        from ..parallel.mesh import MODEL_AXIS

        return self.mesh is not None and self.mesh.shape[MODEL_AXIS] > 1

    def _build_sharded_step(self, k_steps: int = 1, with_pool: bool = False):
        """One routing ladder for every mesh variant: model_axis > 1 goes
        through the GSPMD builder (the shard_map DP body would replicate
        the model axis), pure DP through the explicit-collective builder."""
        grad_accum = self.grad_accum
        if self._uses_tp():
            from ..parallel.step import build_gspmd_step

            if with_pool:
                raise NotImplementedError(
                    "precrop warm-up is not supported with "
                    "parallel.model_axis > 1 — set task_arg.precrop_iters 0 "
                    "or train pure-DP"
                )
            return build_gspmd_step(
                self.mesh, self.loss, self.n_rays, self.near, self.far,
                k_steps=k_steps, grad_accum=grad_accum,
            )
        from ..parallel.step import build_dp_step

        return build_dp_step(
            self.mesh, self.loss, self.n_rays, self.near, self.far,
            k_steps=k_steps, with_pool=with_pool, grad_accum=grad_accum,
        )

    # -- jitted step construction ------------------------------------------
    def _build_step(self, with_pool: bool):
        if self.mesh is not None:
            return self._build_sharded_step(with_pool=with_pool)
        n_rays = self.n_rays
        process_index = self.process_index
        near, far, loss = self.near, self.far, self.loss
        grad_accum = self.grad_accum

        # donate the state: params + adam moments update in place instead of
        # allocating fresh buffers every step (the sharded builders already
        # donate; the single-chip flagship path must too)
        @partial(jax.jit, donate_argnums=donation_argnums(0))
        def step_fn(state, bank_rays, bank_rgbs, base_key, *pool):
            key = sample_step_key(base_key, state.step, process_index)
            k_sample, k_render = jax.random.split(key)
            grads, stats = sampled_grad_step(
                loss, state.params, bank_rays, bank_rgbs, n_rays, near, far,
                k_sample, k_render, index_pool=pool[0] if pool else None,
                grad_accum=grad_accum, step=state.step,
            )
            new_state = state.apply_gradients(grads=grads)
            return new_state, stats

        return step_fn

    def _build_multi_step(self, k_steps: int):
        if self.mesh is not None:
            return self._build_sharded_step(k_steps=k_steps)
        n_rays = self.n_rays
        process_index = self.process_index
        near, far, loss = self.near, self.far, self.loss
        grad_accum = self.grad_accum

        @partial(jax.jit, donate_argnums=donation_argnums(0))
        def multi_step_fn(state, bank_rays, bank_rgbs, base_key):
            def body(st):
                key = sample_step_key(base_key, st.step, process_index)
                k_sample, k_render = jax.random.split(key)
                grads, stats = sampled_grad_step(
                    loss, st.params, bank_rays, bank_rgbs, n_rays, near,
                    far, k_sample, k_render, grad_accum=grad_accum,
                    step=st.step,
                )
                return st.apply_gradients(grads=grads), stats

            return scan_k_steps(body, state, k_steps)

        return multi_step_fn

    def multi_step(self, state, bank_rays, bank_rgbs, base_key, k_steps=None):
        """Run ``k_steps`` optimizer steps in one device dispatch (lax.scan).

        The precrop index-pool variant is excluded on purpose: precrop lasts
        a few hundred steps at most and burst boundaries would straddle the
        precrop→full transition; train_epoch single-steps until the pool
        retires, then switches to bursts."""
        k = int(k_steps if k_steps is not None else self.scan_steps)
        if k <= 1:
            return self.step(state, bank_rays, bank_rgbs, base_key)
        fn = self._multi_step_fns.get(k)
        if fn is None:
            name = f"train_step_k{k}"
            pre = self.aot.take(name) if self.aot is not None else None
            fn = self._multi_step_fns[k] = self.tracker.wrap(
                name, pre if pre is not None else self._build_multi_step(k)
            )
        return fn(state, bank_rays, bank_rgbs, base_key)

    def step(self, state, bank_rays, bank_rgbs, base_key, index_pool=None):
        """One optimization step; dispatches to the precrop or full variant."""
        if index_pool is not None:
            if self._step_fn_pool is None:
                pre = (self.aot.take("train_step_pool")
                       if self.aot is not None else None)
                self._step_fn_pool = self.tracker.wrap(
                    "train_step_pool",
                    pre if pre is not None else self._build_step(with_pool=True),
                )
            return self._step_fn_pool(
                state, bank_rays, bank_rgbs, base_key, index_pool
            )
        if self._step_fn is None:
            pre = self.aot.take("train_step") if self.aot is not None else None
            self._step_fn = self.tracker.wrap(
                "train_step",
                pre if pre is not None else self._build_step(with_pool=False),
            )
        return self._step_fn(state, bank_rays, bank_rgbs, base_key)

    # -- AOT registration ----------------------------------------------------
    def aot_register_steps(self, state, bank, base_key, pool=None) -> None:
        """Register every step executable this run will dispatch with the
        AOT registry and kick their builds off on host threads
        (``compile_all(wait=False)``) — the caller overlaps them with the
        rest of setup (test-dataset load, pool placement), and the first
        optimizer step picks up a finished executable via ``take`` instead
        of paying its build inside the timed hot loop.

        Shapes come from the exact objects the loop will pass (post
        sharding/device_put), so the lowered signature — including layout
        — always matches the dispatch."""
        if self.aot is None:
            return
        from ..compile import abstract_like

        sig = abstract_like((state, bank[0], bank[1], base_key))
        if pool is not None and self.precrop_iters > 0:
            self.aot.register(
                "train_step_pool", self._build_step(with_pool=True),
                sig + (abstract_like(pool),),
            )
        if self.scan_steps > 1:
            self.aot.register(
                f"train_step_k{self.scan_steps}",
                self._build_multi_step(self.scan_steps), sig,
            )
            # the epoch-end clamped tail dispatches its own smaller burst
            # (train_epoch) — precompile it too instead of paying the one
            # "extra small executable" at the first epoch boundary
            tail = self.epoch_iters(int(bank[0].shape[0])) % self.scan_steps
            if tail == 1:
                self.aot.register(
                    "train_step", self._build_step(with_pool=False), sig
                )
            elif tail > 1:
                self.aot.register(
                    f"train_step_k{tail}", self._build_multi_step(tail), sig
                )
        else:
            self.aot.register(
                "train_step", self._build_step(with_pool=False), sig
            )
        self.aot.compile_all(wait=False)

    # -- epoch loops ---------------------------------------------------------
    # graftlint: hot
    def train_epoch(
        self, state, epoch: int, bank, base_key, recorder: Recorder,
        schedule, index_pool=None, log=print,
    ):
        bank_rays, bank_rgbs, pool = bank[0], bank[1], index_pool
        max_iter = self.epoch_iters(int(bank_rays.shape[0]))
        end = time.time()
        log_interval = int(self.cfg.get("log_interval", 20))
        emitter = get_emitter()
        stats = None
        # track the step on the host: int(state.step) would block on the
        # in-flight device step and serialize async dispatch
        host_step = int(state.step)
        it = 0
        while it < max_iter:
            # the profiler window opens BEFORE the burst that first
            # overlaps it, so the windowed steps' dispatches are on-trace
            self.profile.tick(host_step)
            data_time = time.time() - end
            use_pool = pool is not None and host_step < self.precrop_iters
            t_dispatch = time.perf_counter()
            with annotate("train/step_dispatch"):
                if use_pool or self.scan_steps <= 1:
                    k = 1
                    state, stats = self.step(
                        state, bank_rays, bank_rgbs, base_key,
                        index_pool=pool if use_pool else None,
                    )
                else:
                    # burst of K steps in one dispatch; clamp at the epoch
                    # end (the clamped tail compiles one extra small
                    # executable)
                    k = min(self.scan_steps, max_iter - it)
                    state, stats = self.multi_step(
                        state, bank_rays, bank_rgbs, base_key, k
                    )
            dispatch_s = time.perf_counter() - t_dispatch
            host_step += k
            # log when a burst crosses a log_interval boundary (k=1 ⇒ the
            # reference cadence, trainer.py:79)
            should_log = (
                it == 0
                or (it + k - 1) // log_interval > (it - 1) // log_interval
                or it + k >= max_iter
            )
            block_s = None
            if should_log:
                # host sync only at the logging cadence — timed, so the
                # step row splits host dispatch cost from device wait
                # (latency-bound vs compute-bound regressions)
                t_block = time.perf_counter()
                jax.block_until_ready(stats)
                block_s = time.perf_counter() - t_block
                stats_host = {kk: float(v) for kk, v in stats.items()}
                if self.finite_guard:
                    try:
                        stats_host = check_finite(stats_host, host_step)
                    except DivergenceError as err:
                        # attach the live (NaN-poisoned but valid-buffered)
                        # state: fit's rollback needs a restore template
                        # whose buffers were never donated away
                        err.state = state
                        raise
                recorder.update_loss_stats(stats_host)
            recorder.step = host_step
            # per-step time so the console line stays comparable across
            # scan_steps settings (and with the reference's batch: column)
            recorder.batch_time.update((time.time() - end) / k)
            recorder.data_time.update(data_time)
            end = time.time()
            if should_log:
                lr = float(schedule(host_step))
                mem = _device_mem_mb()
                log(recorder.console_line(
                    epoch, min(it + k - 1, max_iter - 1), max_iter, lr, mem
                ))
                recorder.record("train")
                # graftlint: ok(emit-hot: inside the should_log gate — one row per logging cadence, post block_until_ready)
                emitter.emit(
                    "step",
                    step=host_step,
                    epoch=epoch,
                    k=k,
                    step_time_s=recorder.batch_time.median,
                    step_time_avg_s=recorder.batch_time.avg,
                    data_time_s=recorder.data_time.avg,
                    dispatch_s=dispatch_s / k,
                    block_s=block_s / k,
                    lr=lr,
                    max_mem_mb=mem,
                    stats=stats_host,
                )
            it += k
            if self.preempt is not None and self.preempt.triggered:
                # SIGTERM landed: stop at this burst boundary; fit flushes
                # one atomic latest/ checkpoint and exits
                break
        self.profile.tick(host_step)
        return state, stats

    def val(self, state, epoch: int, test_dataset, recorder: Recorder | None = None,
            max_images: int | None = None, log=print):
        """Epoch-boundary validation (trainer.py:98-130): render whole test
        images and run the evaluator per image. Renders go through the shared
        gate (renderer/gate.py): chunked single-device by default, sequence-
        parallel over the mesh's data axis under ``eval.sharded: true`` — on
        a pod, in-training validation must not render 800² images on the
        chief chip alone."""
        # cache keyed on the dataset: the sharded gate bakes the dataset's
        # near/far jit-static, so a different test set needs a fresh gate
        if self._val_render is None or self._val_render[0] is not test_dataset:
            from ..renderer.gate import full_image_render_fn

            self._val_render = (
                test_dataset,
                full_image_render_fn(
                    self.cfg, self.network, self.loss.renderer, test_dataset,
                    use_grid=False,
                ),
            )
        params = {"params": state.params}
        n = len(test_dataset)
        if max_images is not None:
            n = min(n, max_images)
        with annotate("train/validation"):
            for i in range(n):
                batch = test_dataset.image_batch(i)
                out = self._val_render[1](
                    params,
                    {
                        "rays": jnp.asarray(batch["rays"]),
                        "near": batch["near"],
                        "far": batch["far"],
                    },
                )
                out = {k: np.asarray(v) for k, v in out.items()}
                if self.evaluator is not None:
                    self.evaluator.evaluate(out, batch)
        result = {}
        if self.evaluator is not None:
            result = self.evaluator.summarize()
            if recorder is not None and result:
                recorder.record("val", step=epoch, stats=result)
            if result:
                log(f"val epoch {epoch}: " + "  ".join(
                    f"{k}: {v:.4f}" for k, v in result.items()
                ))
        # one sample row per validation pass: the fine-eval budget is the
        # quantity the learned sampler exists to cut, so it is tracked at
        # the same cadence as quality (tlm_report --diff gates on it)
        renderer = getattr(self.loss, "renderer", None)
        if renderer is not None and hasattr(renderer, "sampling_stats"):
            ss = renderer.sampling_stats()
            row = {
                "mode": ss["mode"],
                "fine_evals_per_ray": ss["fine_evals_per_ray_eval"],
                "n_proposal": ss["n_proposal"],
                "n_fine": ss["n_fine"],
                "surface": "val",
                "step": int(state.step),
            }
            if "psnr" in result:
                row["psnr"] = float(result["psnr"])
            get_emitter().emit("sample", **row)
        return result


def _device_mem_mb() -> float | None:
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "peak_bytes_in_use" in stats:
            return stats["peak_bytes_in_use"] / 2**20
    # graftlint: ok(swallow: best-effort HBM probe for the progress line; None hides the field)
    except Exception:
        pass
    return None


def fit(cfg, network=None, log=print):
    """Full training entry (parity: train.py:31-98): build everything from
    cfg, resume if available, run the epoch loop with save/eval cadence."""
    from ..compile import registry_from_cfg
    from ..datasets import make_dataset
    from ..evaluators import make_evaluator
    from ..parallel.collectives import barrier
    from ..parallel.mesh import is_chief, multihost_init
    from ..registry import load_attr
    from ..utils.setup import configure_runtime
    from .recorder import make_recorder

    if bool(cfg.task_arg.get("ngp_training", False)):
        # occupancy-accelerated training has its own state (live grid EMA)
        # and march; same entry contract, separate epoch loop (ngp.py)
        from .ngp import fit_ngp

        return fit_ngp(cfg, network=network, log=log)

    # multi-host runtime first (parity: NCCL process-group init,
    # reference train.py:116-120)
    multihost_init(cfg)
    configure_runtime(cfg)

    if network is None:
        from ..models import make_network

        network = make_network(cfg)

    loss_factory = load_attr(cfg.loss_module, "make_loss", "NetworkWrapper")
    loss = loss_factory(cfg, network)
    evaluator = None if cfg.get("skip_eval", False) else make_evaluator(cfg)

    # distribution is ON by default when more than one chip is visible —
    # the reference's entry point behaves the same way (its launcher wraps
    # every train.py run in DDP, train.py:116-120). Opting out of the mesh
    # entirely takes parallel.data_axis: 1 AND model_axis: 1 (the default);
    # a TP-only topology (data_axis 1, model_axis > 1) still builds one.
    par = cfg.get("parallel", {})
    data_axis = int(par.get("data_axis", -1))
    model_axis = int(par.get("model_axis", 1))
    mesh = None
    if jax.device_count() > 1 and (data_axis != 1 or model_axis > 1):
        from ..parallel.mesh import make_mesh_from_cfg

        mesh = make_mesh_from_cfg(cfg)
        log(f"training over mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}")
        if model_axis > 1 and int(cfg.task_arg.get("precrop_iters", 0)) > 0:
            # fail BEFORE datasets load and the bank/state get sharded —
            # the same contradiction would otherwise only surface at step 1
            raise NotImplementedError(
                "precrop warm-up is not supported with "
                "parallel.model_axis > 1 — set task_arg.precrop_iters 0 "
                "or train pure-DP"
            )

    trainer = Trainer(cfg, network, loss, evaluator, mesh=mesh)
    recorder = make_recorder(cfg)
    # telemetry opens AFTER the recorder (a fresh run wipes record_dir —
    # the stream must not be orphaned by that wipe)
    emitter = init_run(cfg, component="train")

    seed = int(cfg.get("seed", 0))
    key = jax.random.PRNGKey(seed)
    k_init, base_key = jax.random.split(key)
    state, schedule = make_train_state(cfg, network, k_init)

    begin_epoch = 0
    if cfg.get("resume", True):
        state, begin_epoch, rec_state = load_model(cfg.trained_model_dir, state)
        if rec_state:
            recorder.load_state_dict(rec_state)
    if begin_epoch == 0 and cfg.get("pretrain", ""):
        params, ok = load_pretrain(cfg.pretrain, {"params": state.params})
        if ok:
            state = state.replace(params=params["params"])

    if is_chief():
        save_trained_config(cfg)

    train_ds = make_dataset(cfg, "train")
    pool = None
    frac = float(cfg.task_arg.get("precrop_frac", 0.5))
    if mesh is not None:
        from ..parallel.sharding import shard_bank, shard_index_pool

        # globally permute the bank before sharding: contiguous slices
        # would give each shard only a few images' rows (and could starve
        # a shard of precrop rays entirely); a fixed host-side shuffle
        # makes every shard a uniform sample of the whole scene
        bank_rays, bank_rgbs = train_ds.ray_bank()
        perm = np.random.default_rng(seed).permutation(bank_rays.shape[0])
        bank = shard_bank(bank_rays[perm], bank_rgbs[perm], mesh)
        if trainer.precrop_iters > 0:
            inv = np.empty_like(perm)
            inv[perm] = np.arange(perm.size)
            pool_perm = inv[np.asarray(train_ds.precrop_index_pool(frac))]
            # shard_bank truncates to a divisible size; drop pool members
            # whose permuted position fell past the truncation
            n_bank = int(bank[0].shape[0])
            pool = shard_index_pool(
                pool_perm[pool_perm < n_bank], n_bank, mesh
            )
        if trainer._uses_tp():
            from ..parallel.step import shard_train_state

            state = shard_train_state(state, mesh)
        else:
            from jax.sharding import NamedSharding, PartitionSpec

            # the shard_map DP step returns a mesh-replicated state;
            # placing the initial state the same way makes step 1 match
            # the steady-state layout, so one executable serves the run
            state = jax.device_put(state, NamedSharding(mesh, PartitionSpec()))
    else:
        bank = tuple(jax.device_put(a) for a in train_ds.ray_bank())
        if trainer.precrop_iters > 0:
            pool = jax.device_put(train_ds.precrop_index_pool(frac))

    # AOT: register and start compiling every step executable now, on host
    # threads, so the builds overlap the test-dataset load below and the
    # first optimizer step dispatches a finished executable
    # (docs/compilation.md)
    trainer.aot = registry_from_cfg(cfg, tracker=trainer.tracker)
    trainer.aot_register_steps(state, bank, base_key, pool=pool)
    test_ds = make_dataset(cfg, "test")

    epochs = int(cfg.train.epoch)
    save_ep = int(cfg.get("save_ep", 40))
    save_latest_ep = int(cfg.get("save_latest_ep", 10))
    eval_ep = int(cfg.get("eval_ep", 10))

    # resilience (docs/robustness.md): a non-finite loss rolls back to the
    # last good checkpoint (bounded), SIGTERM flushes latest/ and exits
    rcfg = cfg.get("resil", {})
    max_rollbacks = int(rcfg.get("max_rollbacks", 2))
    guard = (PreemptionGuard.install()
             if bool(rcfg.get("preempt_sigterm", True)) else None)
    trainer.preempt = guard
    rollbacks = 0

    t_fit_start = time.time()
    try:
        epoch = begin_epoch
        while epoch < epochs:
            recorder.epoch = epoch
            t_epoch = time.time()
            step_before = int(state.step)
            try:
                state, _ = trainer.train_epoch(
                    state, epoch, bank, base_key, recorder, schedule,
                    index_pool=pool, log=log,
                )
            except DivergenceError as err:
                rollbacks += 1
                template = getattr(err, "state", state)
                if rollbacks > max_rollbacks or not has_checkpoint(
                    cfg.trained_model_dir
                ):
                    raise  # nothing to roll back to, or the budget is spent
                report("train.loss", "rollback", step=err.step,
                       detail=f"rollback {rollbacks}/{max_rollbacks}")
                log(f"non-finite loss at step {err.step}: rolling back to "
                    f"the last good checkpoint ({rollbacks}/{max_rollbacks})")
                state, epoch, rec_state = load_model(
                    cfg.trained_model_dir, template
                )
                if rec_state:
                    recorder.load_state_dict(rec_state)
                continue
            # epoch cadence telemetry: throughput + HBM creep + liveness
            step_after = int(state.step)
            wall = time.time() - t_epoch
            emitter.emit(
                "epoch", epoch=epoch, steps=step_after - step_before,
                wall_s=wall,
                steps_per_sec=(step_after - step_before) / max(wall, 1e-9),
            )
            sample_memory(step=step_after, epoch=epoch)
            emitter.emit(
                "heartbeat", wall_s=time.time() - t_fit_start,
                step=step_after, epoch=epoch,
            )
            chief = is_chief()
            saving = (
                (epoch + 1) % save_ep == 0
                or (epoch + 1) % save_latest_ep == 0
            )
            if saving:
                # bracket chief-only saves with barriers so a non-chief
                # process (or a shared-FS reader resuming from `latest`)
                # can never observe a half-written bundle
                barrier("pre_save")
                if chief and (epoch + 1) % save_ep == 0:
                    save_model_with_retry(cfg, cfg.trained_model_dir, state,
                                          epoch, recorder.state_dict(),
                                          latest=False, log=log)
                if chief and (epoch + 1) % save_latest_ep == 0:
                    save_model_with_retry(cfg, cfg.trained_model_dir, state,
                                          epoch, recorder.state_dict(),
                                          latest=True, log=log)
                barrier("post_save")
            # chief-only: validation renders/writes artifacts on one process
            # (the reference runs val on rank 0 only, train.py:84-85)
            if chief and (epoch + 1) % eval_ep == 0 and evaluator is not None:
                trainer.val(state, epoch, test_ds, recorder, log=log)
            if guard is not None and guard.triggered:
                # preemption: one atomic latest/ flush (same bracket as the
                # cadence saves), then a clean exit — the resumed run
                # restores this exact state bitwise
                barrier("pre_save")
                if chief:
                    save_model_with_retry(cfg, cfg.trained_model_dir, state,
                                          epoch, recorder.state_dict(),
                                          latest=True, log=log)
                barrier("post_save")
                log("SIGTERM: latest checkpoint flushed; exiting")
                break
            epoch += 1
    finally:
        if guard is not None:
            guard.uninstall()
        # a window still open at exit (crash mid-capture) must be closed
        # or the xplane file is unreadable
        trainer.profile.stop()
        emitter.close()
    return state


def make_trainer(cfg, network) -> Trainer:
    """Reference-style factory (make_trainer.py:5-14): wraps the network in
    the configured loss module and returns the Trainer."""
    from ..evaluators import make_evaluator
    from ..registry import load_attr

    loss_factory = load_attr(cfg.loss_module, "make_loss", "NetworkWrapper")
    loss = loss_factory(cfg, network)
    evaluator = None if cfg.get("skip_eval", False) else make_evaluator(cfg)
    return Trainer(cfg, network, loss, evaluator)
