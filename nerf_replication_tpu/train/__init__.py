from .loss import NeRFLoss, make_loss
from .optim import make_lr_schedule, make_optimizer
from .recorder import Recorder, SmoothedValue, make_recorder
from .trainer import Trainer, fit, make_train_state, make_trainer

__all__ = [
    "NeRFLoss",
    "Recorder",
    "SmoothedValue",
    "Trainer",
    "fit",
    "make_loss",
    "make_lr_schedule",
    "make_optimizer",
    "make_recorder",
    "make_train_state",
    "make_trainer",
]
