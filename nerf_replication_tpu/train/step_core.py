"""The shared train-step core: sample a ray batch from the device-resident
bank, render it through the loss module, and return gradients + stats.

Single-chip (train/trainer.py), shard_map DP, and GSPMD dp×tp steps
(parallel/step.py) all wrap this one function — parallelism only changes
where the RNG key is decorrelated and which collectives/constraints surround
the call, never the step semantics (reference contract: trainer.py:55-62).
"""

from __future__ import annotations

import jax

from ..datasets.sampling import sample_rays


def scan_k_steps(one_step, state, k_steps: int):
    """Run ``one_step(state) -> (state, stats)`` K times inside one
    ``lax.scan`` dispatch, returning the LAST step's stats (same
    observability as K sequential calls — per-step traces inside a burst
    are not observable). The single scan-burst idiom shared by the
    single-chip, shard_map-DP, and GSPMD step builders."""
    if k_steps == 1:
        return one_step(state)
    state, stats_seq = jax.lax.scan(
        lambda st, _: one_step(st), state, None, length=k_steps
    )
    return state, jax.tree_util.tree_map(lambda x: x[-1], stats_seq)


def sampled_grad_step(
    loss,
    params,
    bank_rays,
    bank_rgbs,
    n_rays: int,
    near: float,
    far: float,
    k_sample,
    k_render,
    index_pool=None,
    grad_accum: int = 1,
    step=None,
):
    """Draw ``n_rays`` from the bank and compute (grads, stats) of the loss.

    ``grad_accum > 1`` splits the draw into A microbatches evaluated
    sequentially inside one ``lax.scan`` and averages their gradients —
    numerically the mean-loss gradient of the full batch, with activation
    memory bounded by one microbatch. This is how batches past the HBM
    roofline run on one chip: the 65,536-ray flagship step needs a 24 GB
    activation stack as a single batch (PERF.md round 4) but fits as
    4 x 16,384.
    """
    if grad_accum <= 1:
        return _one_grad(loss, params, bank_rays, bank_rgbs, n_rays, near,
                         far, k_sample, k_render, index_pool, step)
    if n_rays % grad_accum != 0:
        raise ValueError(
            f"n_rays={n_rays} must be divisible by "
            f"task_arg.grad_accum={grad_accum}"
        )
    import jax.numpy as jnp

    n_micro = n_rays // grad_accum

    def body(carry, keys):
        ks, kr = keys
        grads, stats = _one_grad(
            loss, params, bank_rays, bank_rgbs, n_micro, near, far, ks, kr,
            index_pool, step,
        )
        carry = jax.tree_util.tree_map(lambda a, b: a + b, carry, grads)
        return carry, stats

    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    ks = jax.random.split(k_sample, grad_accum)
    kr = jax.random.split(k_render, grad_accum)
    gsum, stats_seq = jax.lax.scan(body, zeros, (ks, kr))
    grads = jax.tree_util.tree_map(lambda g: g / grad_accum, gsum)
    # mean stats over microbatches (they are per-microbatch means already)
    stats = jax.tree_util.tree_map(lambda x: x.mean(axis=0), stats_seq)
    return grads, fix_accum_psnr(stats)


def fix_accum_psnr(stats: dict) -> dict:
    """Recompute psnr from the microbatch-averaged mse.

    psnr is nonlinear in mse: the mean of per-microbatch psnrs is not the
    psnr of the full-batch mean loss, so logged metrics would shift with
    grad_accum even though the gradient is exact (round-4 advisor
    finding). Every accumulating step builder (here and the GSPMD path in
    parallel/step.py) routes its averaged stats through this. The mse
    source mirrors each loss module's own psnr choice: the NeRF loss uses
    loss_f (falling back to loss_c without hierarchical sampling,
    loss.py), img_fit uses its sole 'loss'."""
    if "psnr" in stats:
        from .loss import mse_to_psnr

        base = next(
            (stats[k] for k in ("loss_f", "loss_c", "loss") if k in stats),
            None,
        )
        if base is not None:
            stats = dict(stats)
            stats["psnr"] = mse_to_psnr(base)
    return stats


def _one_grad(loss, params, bank_rays, bank_rgbs, n_rays, near, far,
              k_sample, k_render, index_pool, step=None):
    # named scopes land in the compiled op names, so the xplane trace a
    # profiler window captures (obs/profiling.py) attributes device time
    # to the bank draw vs the render+grad sweep
    with jax.named_scope("bank_draw"):
        rays, rgbs = sample_rays(
            k_sample, bank_rays, bank_rgbs, n_rays, index_pool=index_pool
        )

    # traced scalar, not a python int: the proposal sampler's anneal
    # schedule (renderer/sampling.py) reads it per step without retracing
    batch = {"rays": rays, "rgbs": rgbs, "near": near, "far": far}
    if step is not None:
        batch["step"] = step

    def loss_fn(p):
        _, l, stats = loss({"params": p}, batch, key=k_render, train=True)
        return l, stats

    with jax.named_scope("render_grad"):
        (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    return grads, stats
