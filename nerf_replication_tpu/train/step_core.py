"""The shared train-step core: sample a ray batch from the device-resident
bank, render it through the loss module, and return gradients + stats.

Single-chip (train/trainer.py), shard_map DP, and GSPMD dp×tp steps
(parallel/step.py) all wrap this one function — parallelism only changes
where the RNG key is decorrelated and which collectives/constraints surround
the call, never the step semantics (reference contract: trainer.py:55-62).
"""

from __future__ import annotations

import jax

from ..datasets.sampling import sample_rays


def sampled_grad_step(
    loss,
    params,
    bank_rays,
    bank_rgbs,
    n_rays: int,
    near: float,
    far: float,
    k_sample,
    k_render,
    index_pool=None,
):
    """Draw ``n_rays`` from the bank and compute (grads, stats) of the loss."""
    rays, rgbs = sample_rays(
        k_sample, bank_rays, bank_rgbs, n_rays, index_pool=index_pool
    )

    def loss_fn(p):
        _, l, stats = loss(
            {"params": p},
            {"rays": rays, "rgbs": rgbs, "near": near, "far": far},
            key=k_render,
            train=True,
        )
        return l, stats

    (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    return grads, stats
