"""Metrics recorder: windowed smoothing + TensorBoard + console lines.

Parity with the reference's `Recorder`/`SmoothedValue` (src/train/recorder.py:
10-138): median/avg/global-avg over a sliding window, scalar and image
TensorBoard logging, process-0 guard on every method, checkpointable state,
and log-dir wiping when starting fresh. The console line format mirrors the
reference trainer's (trainer.py:79-92: eta / epoch / step / losses / lr /
data+batch time / max-mem) so log-parsing tooling (plot_loss) works on both.
"""

from __future__ import annotations

import os
import shutil
from collections import defaultdict, deque

import numpy as np

from ..parallel.mesh import is_chief as _is_chief


class SmoothedValue:
    """Track a window of values with median/avg plus a global average
    (recorder.py:10-37)."""

    def __init__(self, window_size: int = 20):
        self.deque = deque(maxlen=window_size)
        self.total = 0.0
        self.count = 0

    def update(self, value: float):
        v = float(value)
        self.deque.append(v)
        self.count += 1
        self.total += v

    @property
    def median(self) -> float:
        return float(np.median(self.deque)) if self.deque else 0.0

    @property
    def avg(self) -> float:
        return float(np.mean(self.deque)) if self.deque else 0.0

    @property
    def global_avg(self) -> float:
        return self.total / max(self.count, 1)

    def __str__(self):
        return f"{self.median:.4f} ({self.global_avg:.4f})"

    # -- checkpointable state -----------------------------------------------
    # total/count feed global_avg, which drives the eta: column — without
    # them a resumed run's eta restarts from zero (reference bug preserved
    # until PR 1; see Recorder.state_dict).
    def state_dict(self) -> dict:
        return {
            "total": self.total,
            "count": self.count,
            "window": [float(v) for v in self.deque],
        }

    def load_state_dict(self, state: dict):
        self.total = float(state.get("total", 0.0))
        self.count = int(state.get("count", 0))
        self.deque.clear()
        for v in state.get("window", []):
            self.deque.append(float(v))


class Recorder:
    def __init__(self, cfg, window_size: int = 20):
        self.chief = _is_chief()
        self.record_dir = cfg.record_dir
        self.step = 0
        self.epoch = 0
        self.loss_stats = defaultdict(lambda: SmoothedValue(window_size))
        self.batch_time = SmoothedValue(window_size)
        self.data_time = SmoothedValue(window_size)
        self._writer = None

        if not self.chief:
            return
        if not cfg.get("resume", True) and os.path.exists(self.record_dir):
            shutil.rmtree(self.record_dir, ignore_errors=True)  # recorder.py:56-57
        os.makedirs(self.record_dir, exist_ok=True)

    @property
    def writer(self):
        if self._writer is None and self.chief:
            from tensorboardX import SummaryWriter

            self._writer = SummaryWriter(log_dir=self.record_dir)
        return self._writer

    def update_loss_stats(self, stats: dict):
        if not self.chief:
            return
        for k, v in stats.items():
            self.loss_stats[k].update(float(v))

    def record(self, prefix: str, step: int | None = None, stats: dict | None = None,
               images: dict | None = None):
        """Write window-median scalars (recorder.py:89-107) and images."""
        if not self.chief:
            return
        step = self.step if step is None else step
        pattern = prefix + "/{}"
        if stats is None:
            for k, sv in self.loss_stats.items():
                self.writer.add_scalar(pattern.format(k), sv.median, step)
        else:
            for k, v in stats.items():
                v = v.median if isinstance(v, SmoothedValue) else float(v)
                self.writer.add_scalar(pattern.format(k), v, step)
        if images:
            for k, img in images.items():
                # HWC float [0,1] → CHW
                arr = np.asarray(img)
                if arr.ndim == 3 and arr.shape[-1] in (1, 3, 4):
                    arr = np.transpose(arr, (2, 0, 1))
                self.writer.add_image(pattern.format(k), arr, step)
        # telemetry: non-train records are eval-cadence metric summaries
        # (val/ngp val/test) — one typed row each; train-cadence rows are
        # emitted by the trainer's epoch loop with timing detail the
        # recorder doesn't have. TensorBoard/console output above is
        # byte-identical with or without an active emitter.
        if stats is not None and prefix != "train":
            from ..obs import get_emitter

            get_emitter().emit(
                "eval",
                prefix=prefix,
                step=int(step),
                metrics={
                    k: float(v.median if isinstance(v, SmoothedValue) else v)
                    for k, v in stats.items()
                },
            )

    # -- checkpointable state (recorder.py:109-119) -------------------------
    def state_dict(self) -> dict:
        # "smoothed" also persists the SmoothedValue totals/counts so a
        # resumed run's eta: and global averages continue instead of
        # resetting to zero (checkpoint.py stores it in a sidecar JSON —
        # the orbax bundle keeps its fixed {step, epoch} schema)
        return {
            "step": self.step,
            "epoch": self.epoch,
            "smoothed": {
                "batch_time": self.batch_time.state_dict(),
                "data_time": self.data_time.state_dict(),
                "loss_stats": {
                    k: sv.state_dict() for k, sv in self.loss_stats.items()
                },
            },
        }

    def load_state_dict(self, state: dict):
        self.step = int(state.get("step", 0))
        self.epoch = int(state.get("epoch", 0))
        smoothed = state.get("smoothed") or {}
        if "batch_time" in smoothed:
            self.batch_time.load_state_dict(smoothed["batch_time"])
        if "data_time" in smoothed:
            self.data_time.load_state_dict(smoothed["data_time"])
        for k, sv_state in (smoothed.get("loss_stats") or {}).items():
            self.loss_stats[k].load_state_dict(sv_state)

    # -- console ------------------------------------------------------------
    def console_line(self, epoch: int, it: int, max_iter: int, lr: float,
                     max_mem_mb: float | None = None) -> str:
        eta_sec = self.batch_time.global_avg * (max_iter - it)
        h, rem = divmod(int(eta_sec), 3600)
        m, s = divmod(rem, 60)
        parts = [
            f"eta: {h}:{m:02d}:{s:02d}",
            f"epoch: {epoch}",
            f"step: {self.step}",
            *[f"{k}: {v}" for k, v in self.loss_stats.items()],
            f"lr: {lr:.6f}",
            f"data: {self.data_time.avg:.4f}",
            f"batch: {self.batch_time.avg:.4f}",
        ]
        if max_mem_mb is not None:
            parts.append(f"max_mem: {max_mem_mb:.0f}")
        return "  ".join(parts)


def make_recorder(cfg) -> Recorder:
    return Recorder(cfg, window_size=20)
