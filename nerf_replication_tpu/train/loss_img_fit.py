"""img_fit loss module — the ``loss_module`` plugin the reference's config
names but does not ship (``src.train.losses.img_fit`` is absent from the
reference tree, SURVEY.md §2.1 "Broken as shipped").

Same callable contract as the NeRF loss: ``(params, batch, key, train) →
(output, loss, stats)``; the generic trainer's batch carries uv in the
"rays" slot and target rgb in "rgbs".
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .loss import mse, mse_to_psnr


class ImgFitRenderer:
    """Chunked full-image apply with the Renderer.render_chunked interface
    (so Trainer.val works unchanged). One jitted callable — jit's own
    shape-keyed cache handles per-shape retracing."""

    def __init__(self, cfg, network):
        self.network = network
        self.chunk_size = int(cfg.task_arg.get("chunk_size", 16384))
        self._apply = self._build_apply()

    def _build_apply(self):
        """The jitted chunked apply — a named builder so AOT registration
        (aot_register) can route it through compile/AOTRegistry."""
        network = self.network
        return jax.jit(
            lambda params, uv_p: jax.lax.map(
                lambda c: network.apply(params, c), uv_p
            )
        )

    def aot_register(self, registry, params, n_rays: int,
                     serialize: bool = False) -> str:
        """Register the chunked apply for ``n_rays``-pixel eval images with
        a compile/AOTRegistry; ``registry.take(name)`` after compile_all
        yields the precompiled executable (assignable to ``_apply``)."""
        from ..compile.registry import abstract_like

        chunk = min(self.chunk_size, n_rays)
        n_chunks = -(-n_rays // chunk)
        name = f"img_fit_apply_{n_chunks}x{chunk}"
        registry.register(
            name,
            self._build_apply(),
            (abstract_like(params),
             jax.ShapeDtypeStruct((n_chunks, chunk, 2), jnp.float32)),
            serialize=serialize,
        )
        return name

    def render_chunked(self, params, batch: dict) -> dict:
        uv = jnp.asarray(batch["rays"])
        n = uv.shape[0]
        chunk = min(self.chunk_size, n)
        n_chunks = -(-n // chunk)
        pad = n_chunks * chunk - n
        uv_p = jnp.pad(uv, ((0, pad), (0, 0))).reshape(n_chunks, chunk, 2)
        rgb = self._apply(params, uv_p).reshape(-1, 3)[:n]
        return {"rgb": rgb, "rgb_map_f": rgb}


class ImgFitLoss:
    # bound-free task: near/far are unused dummies (Trainer contract)
    ray_bounds = (0.0, 1.0)

    def __init__(self, cfg, network):
        self.network = network
        self.renderer = ImgFitRenderer(cfg, network)

    def __call__(self, params, batch, key=None, train: bool = True):
        uv = batch.get("uv", batch.get("rays"))
        target = batch.get("rgb", batch.get("rgbs"))
        rgb = self.network.apply(params, uv)
        loss = mse(rgb, target)
        stats = {"loss": loss, "psnr": mse_to_psnr(loss)}
        return {"rgb": rgb}, loss, stats


def make_loss(cfg, network) -> ImgFitLoss:
    return ImgFitLoss(cfg, network)


NetworkWrapper = ImgFitLoss
