"""NeRF loss module — the ``loss_module`` plugin for the nerf task.

Parity with the reference's `NetworkWrapper` (src/train/trainers/nerf.py:6-51):
render the batch through the renderer (which lives *inside* the loss module,
nerf.py:10,19), MSE on the coarse map + MSE on the fine map,
``total = loss_c + loss_f``, and a per-batch train PSNR stat.

Functional shape: :class:`NeRFLoss` is callable as
``(params, batch, key, train) -> (output, loss, stats)`` — pure in params and
batch so it can sit directly under ``jax.value_and_grad`` inside a jitted,
shard_mapped train step.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..renderer import make_renderer
from ..renderer.sampling import interlevel_loss


def mse(pred, target):
    return jnp.mean((pred - target) ** 2)


def mse_to_psnr(m):
    """-10·log10(mse) (reference evaluator formula, src/evaluators/nerf.py:23-26)."""
    return -10.0 * jnp.log(m) / jnp.log(10.0)


class NeRFLoss:
    def __init__(self, cfg, network):
        self.renderer = make_renderer(cfg, network)
        self.network = network

    def __call__(self, params, batch, key=None, train: bool = True):
        output = self.renderer.render(params, batch, key=key, train=train)
        target = batch["rgbs"]
        stats = {}
        loss = 0.0
        # proposal sampling mode (renderer/sampling.py) has no coarse
        # render: the photometric loss is fine-only, and the proposal net
        # trains on the interlevel weight-bound loss over the two
        # histograms the renderer returned
        if "rgb_map_c" in output:
            loss_c = mse(output["rgb_map_c"], target)
            stats["loss_c"] = loss_c
            loss = loss + loss_c
        if "rgb_map_f" in output:
            loss_f = mse(output["rgb_map_f"], target)
            stats["loss_f"] = loss_f
            loss = loss + loss_f
            stats["psnr"] = mse_to_psnr(loss_f)
        else:
            stats["psnr"] = mse_to_psnr(stats["loss_c"])
        if "prop_w" in output:
            loss_p = interlevel_loss(
                output["fine_t"], output["fine_w"],
                output["prop_t"], output["prop_w"],
            )
            mult = self.renderer.train_options.sampling.loss_mult
            stats["loss_prop"] = loss_p
            loss = loss + mult * loss_p
        stats["loss"] = loss
        return output, loss, stats


def make_loss(cfg, network) -> NeRFLoss:
    return NeRFLoss(cfg, network)


# reference-style name: the trainer factory looks for NetworkWrapper too
NetworkWrapper = NeRFLoss
