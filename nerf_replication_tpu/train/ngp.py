"""Occupancy-accelerated training — the instant-ngp speed lever, TPU-native.

The reference bakes its occupancy grid ONCE from an already-trained network
and uses it only at eval (occupancy_grid.py, volume_renderer.py:268-358).
Instant-ngp's actual training speed comes from the grid being LIVE during
training: the MLP never evaluates empty space, cutting points/ray from the
dense S-march to the K ≪ S occupied samples. This module is that capability,
designed for XLA rather than translated from the CUDA original
(hashencoder.cu's training loop):

* **One jitted step, uniform executable.** The density grid rides inside the
  train state (:class:`NGPTrainState.grid_ema`); each step (a) marches the
  sampled rays through the SAME static-shape ESS+ERT two-phase march the
  eval path uses (renderer/accelerated.py — differentiable: grads flow to
  the MLP through the compacted [N, K] query), and (b) refreshes the grid
  EMA on a random subsample of cells with a scatter-max. No ``lax.cond``,
  no host round-trips, no retrace: grid maintenance is amortized
  continuously instead of instant-ngp's every-16-steps host-driven update.
* **Warm start = march everything.** ``grid_ema`` initializes above the
  density threshold, so early steps march densely (every cell "occupied")
  and the EMA decay + updates carve out the empty space as the network
  learns — the static-shape equivalent of instant-ngp's warmup. Caveat:
  while the grid is still dense, rays whose S march positions exceed the
  K = ``max_march_samples`` budget truncate their far content — per-step
  stats report ``truncated_frac`` so the warm-up blind spot is visible in
  the trace (it falls toward zero as the grid carves; size K or raise
  ``ngp_density_threshold`` if it persists).
* **One network.** NGP training drives the ``fine`` MLP only (hierarchical
  coarse→fine sampling is what the grid replaces); eval goes through the
  accelerated march with the live grid.

Config keys (all under ``task_arg``): ``ngp_training: true`` switches
scripts/quality_run.py onto this trainer; ``ngp_grid_res`` (64),
``ngp_grid_decay`` (0.95 per ``ngp_grid_update_every``-step window, applied
continuously), ``ngp_grid_update_every`` (16), ``ngp_density_threshold``
(0.01), plus the shared march knobs ``render_step_size`` /
``max_march_samples`` / ``transmittance_threshold``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax.training.train_state import TrainState

from ..datasets.sampling import sample_rays, sample_step_key
from ..renderer.accelerated import MarchOptions, march_rays_accelerated
from .loss import mse, mse_to_psnr
from .optim import make_optimizer


class NGPTrainState(TrainState):
    """TrainState + the live density EMA ([R, R, R] float32)."""

    grid_ema: jax.Array = None


class NGPTrainer:
    """Occupancy-accelerated trainer (one fused jitted step)."""

    def __init__(self, cfg, network):
        ta = cfg.task_arg
        self.cfg = cfg
        self.network = network
        self.n_rays = int(ta.get("N_rays", 1024))
        self.near = float(ta.near)
        self.far = float(ta.far)
        self.bbox = jnp.asarray(cfg.train_dataset.scene_bbox, jnp.float32)
        self.march = MarchOptions.from_cfg(cfg)
        self.grid_res = int(ta.get("ngp_grid_res", 64))
        self.threshold = float(ta.get("ngp_density_threshold", 0.01))
        update_every = int(ta.get("ngp_grid_update_every", 16))
        decay_window = float(ta.get("ngp_grid_decay", 0.95))
        # continuous equivalent of "×decay every `update_every` steps"
        self.decay_step = float(decay_window ** (1.0 / update_every))
        # cells refreshed per step: full-grid coverage every update window
        self.cells_per_step = max(self.grid_res**3 // update_every, 1)
        self.process_index = jax.process_index()
        self._step_fn = None
        self._render_fns: dict = {}

    # -- state ---------------------------------------------------------------
    def make_state(self, key):
        """(state, schedule) with fresh params and the warm-started grid."""
        from ..models import init_params_for

        params = init_params_for(self.cfg)(self.network, key)
        tx, schedule = make_optimizer(self.cfg)
        return self.init_state(params["params"], tx), schedule

    def init_state(self, params, tx) -> NGPTrainState:
        """Grid starts fully occupied (ema above threshold ⇒ dense march)
        so the first steps have gradients everywhere; decay + live updates
        then carve out the empty space."""
        ema0 = jnp.full(
            (self.grid_res,) * 3, 4.0 * self.threshold, jnp.float32
        )
        return NGPTrainState.create(
            apply_fn=self.network.apply, params=params, tx=tx,
            grid_ema=ema0,
        )

    # -- jitted step ---------------------------------------------------------
    def _build_step(self):
        n_rays = self.n_rays
        near, far = self.near, self.far
        bbox, options = self.bbox, self.march
        network = self.network
        res, thr = self.grid_res, self.threshold
        decay, n_cells = self.decay_step, self.cells_per_step
        process_index = self.process_index
        remat = bool(self.cfg.task_arg.get("remat", False))

        def apply_fn_for(params):
            fn = lambda pts, dirs, model: network.apply(  # noqa: E731
                {"params": params}, pts, dirs, model=model
            )
            return jax.checkpoint(fn, static_argnums=(2,)) if remat else fn

        @partial(jax.jit, donate_argnums=(0,))
        def step_fn(state, bank_rays, bank_rgbs, base_key):
            key = sample_step_key(base_key, state.step, process_index)
            k_sample, k_cells, k_jitter = jax.random.split(key, 3)
            rays, rgbs = sample_rays(k_sample, bank_rays, bank_rgbs, n_rays)

            grid = state.grid_ema > thr  # bool [R,R,R], jit-static shape

            def loss_fn(p):
                out = march_rays_accelerated(
                    apply_fn_for(p), rays, near, far, grid, bbox, options
                )
                l = mse(out["rgb_map_f"], rgbs)
                return l, {
                    "loss": l,
                    "psnr": mse_to_psnr(l),
                    "occupancy": jnp.mean(grid.astype(jnp.float32)),
                    # rays losing far content to the K budget (dense-grid
                    # warm-up makes this nonzero; must fall as cells carve)
                    "truncated_frac": jnp.mean(
                        out["truncated"].astype(jnp.float32)
                    ),
                }

            (_, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params
            )
            new_state = state.apply_gradients(grads=grads)

            # grid maintenance: decay everywhere, scatter-max a random cell
            # subsample with the LIVE network's density at a jittered point
            # inside each cell (stop_gradient: maintenance must not backprop)
            idx = jax.random.randint(
                k_cells, (n_cells,), 0, res * res * res
            )
            iz = idx % res
            iy = (idx // res) % res
            ix = idx // (res * res)
            cell = jnp.stack([ix, iy, iz], axis=-1).astype(jnp.float32)
            u = jax.random.uniform(k_jitter, (n_cells, 3))
            lo, hi = bbox[0], bbox[1]
            pts = lo + (cell + u) / res * (hi - lo)
            dirs = jnp.zeros((n_cells, 3), jnp.float32)
            raw = network.apply(
                {"params": jax.lax.stop_gradient(new_state.params)},
                pts[:, None, :], dirs, model="fine",
            )
            sigma = jax.nn.relu(raw[..., 0, 3])
            ema = state.grid_ema.reshape(-1) * decay
            ema = ema.at[idx].max(sigma)
            new_state = new_state.replace(grid_ema=ema.reshape(res, res, res))
            return new_state, stats

        return step_fn

    def step(self, state, bank_rays, bank_rgbs, base_key):
        if self._step_fn is None:
            self._step_fn = self._build_step()
        return self._step_fn(state, bank_rays, bank_rgbs, base_key)

    # -- eval ----------------------------------------------------------------
    def val(self, state, test_dataset, evaluator, max_images=None, log=print):
        """Whole-image validation mirroring Trainer.val: render every test
        image through the live-grid march, feed the evaluator, summarize.
        The single implementation behind quality_run's NGP mode and
        scripts/bench_ngp.py — eval semantics must not fork."""
        import numpy as np

        n = len(test_dataset)
        if max_images is not None:
            n = min(n, max_images)
        for i in range(n):
            batch = test_dataset.image_batch(i)
            out = self.render_image(state, {"rays": batch["rays"]})
            evaluator.evaluate(
                {k: np.asarray(v) for k, v in out.items()}, batch
            )
        result = evaluator.summarize()
        if result:
            log("ngp val: " + "  ".join(
                f"{k}: {v:.4f}" for k, v in result.items()
            ))
        return result

    def render_image(self, state, batch: dict) -> dict:
        """Full-image eval through the accelerated march with the live grid
        (the chunked coarse+fine path is meaningless here: NGP training
        leaves the coarse network untrained by design). Jitted executables
        are cached per (n_chunks, chunk) shape like Renderer's eval paths."""
        from ..renderer.volume import _pad_to_chunks, _unpad_outputs

        grid = state.grid_ema > self.threshold
        rays_p, n, n_chunks, chunk = _pad_to_chunks(
            jnp.asarray(batch["rays"]), self.march.chunk_size
        )

        render = self._render_fns.get((n_chunks, chunk))
        if render is None:
            network, near, far = self.network, self.near, self.far
            bbox, options = self.bbox, self.march

            @jax.jit
            def render(params, rays_p, grid):
                apply_fn = lambda pts, dirs, model: network.apply(  # noqa: E731
                    {"params": params}, pts, dirs, model=model
                )

                def body(chunk_rays):
                    return march_rays_accelerated(
                        apply_fn, chunk_rays, near, far, grid, bbox, options
                    )

                return jax.lax.map(body, rays_p)

            self._render_fns[(n_chunks, chunk)] = render

        out = render(state.params, rays_p, grid)
        out = _unpad_outputs(out, n)
        # surface the K-budget diagnostic like Renderer.render_accelerated
        # does instead of silently dropping far content
        n_trunc = int(np.asarray(jnp.sum(out.pop("truncated"))))
        if n_trunc:
            print(
                f"ngp render_image: {n_trunc} rays exceeded the "
                f"max_march_samples={self.march.max_samples} budget while "
                "still transparent (far contributions truncated)"
            )
        return out


def make_ngp_trainer(cfg, network) -> NGPTrainer:
    return NGPTrainer(cfg, network)
