"""Occupancy-accelerated training — the instant-ngp speed lever, TPU-native.

The reference bakes its occupancy grid ONCE from an already-trained network
and uses it only at eval (occupancy_grid.py, volume_renderer.py:268-358).
Instant-ngp's actual training speed comes from the grid being LIVE during
training: the MLP never evaluates empty space, cutting points/ray from the
dense S-march to the K ≪ S occupied samples. This module is that capability,
designed for XLA rather than translated from the CUDA original
(hashencoder.cu's training loop):

* **One jitted step, uniform executable.** The density grid rides inside the
  train state (:class:`NGPTrainState.grid_ema`); each step (a) marches the
  sampled rays through the SAME static-shape ESS+ERT two-phase march the
  eval path uses (renderer/accelerated.py — differentiable: grads flow to
  the MLP through the compacted [N, K] query), and (b) refreshes the grid
  EMA on a random subsample of cells with a scatter-max. No ``lax.cond``,
  no host round-trips, no retrace: grid maintenance is amortized
  continuously instead of instant-ngp's every-16-steps host-driven update.
* **Warm start = march everything.** ``grid_ema`` initializes above the
  density threshold, so early steps march densely (every cell "occupied")
  and the EMA decay + updates carve out the empty space as the network
  learns — the static-shape equivalent of instant-ngp's warmup. Caveat:
  while the grid is still dense, rays whose S march positions exceed the
  K = ``max_march_samples`` budget truncate their far content — per-step
  stats report ``truncated_frac`` so the warm-up blind spot is visible in
  the trace (it falls toward zero as the grid carves; size K or raise
  ``ngp_density_threshold`` if it persists).
* **One network.** NGP training drives the ``fine`` MLP only (hierarchical
  coarse→fine sampling is what the grid replaces); eval goes through the
  accelerated march with the live grid.

Round 4 (VERDICT r3 #5): the grid now carves from the densities the march
ACTUALLY SAMPLES on training rays (scatter-max of the compacted [N, K]
sigmas into their cells, subsampled to ``ngp_sample_update_cap`` rows) in
addition to the random-cell refresh — visible matter is refreshed every
step it is trained on, so the warm start can sit just above threshold
(``ngp_grid_warm_factor``, default 2.0) and empty space decays below
threshold within ~half an update-decay half-life instead of round 3's
~27 windows. ``fit_ngp`` is the production epoch-loop entry (train.py
routes ``task_arg.ngp_training: true`` here), with scan-burst support.

Config keys (all under ``task_arg``): ``ngp_training: true`` switches
train.py / scripts/quality_run.py onto this trainer; ``ngp_grid_res``
(64), ``ngp_grid_decay`` (0.95 per ``ngp_grid_update_every``-step window,
applied continuously), ``ngp_grid_update_every`` (16),
``ngp_density_threshold`` (0.01), ``ngp_grid_warm_factor`` (2.0),
``ngp_sample_update_cap`` (65536), ``scan_steps``, plus the shared march
knobs ``render_step_size`` / ``max_march_samples`` /
``transmittance_threshold``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax.training.train_state import TrainState

from ..datasets.sampling import sample_rays, sample_step_key
from ..renderer.accelerated import MarchOptions, march_rays_accelerated
from .loss import mse, mse_to_psnr
from .optim import make_optimizer


class NGPTrainState(TrainState):
    """TrainState + the live density EMA ([R, R, R] float32)."""

    grid_ema: jax.Array = None


class NGPTrainer:
    """Occupancy-accelerated trainer (one fused jitted step)."""

    def __init__(self, cfg, network):
        ta = cfg.task_arg
        self.cfg = cfg
        self.network = network
        self.n_rays = int(ta.get("N_rays", 1024))
        self.near = float(ta.near)
        self.far = float(ta.far)
        self.bbox = jnp.asarray(cfg.train_dataset.scene_bbox, jnp.float32)
        self.march = MarchOptions.from_cfg(cfg)
        self.grid_res = int(ta.get("ngp_grid_res", 64))
        self.threshold = float(ta.get("ngp_density_threshold", 0.01))
        update_every = int(ta.get("ngp_grid_update_every", 16))
        decay_window = float(ta.get("ngp_grid_decay", 0.95))
        # continuous equivalent of "×decay every `update_every` steps"
        self.decay_step = float(decay_window ** (1.0 / update_every))
        # cells refreshed per step: full-grid coverage every update window
        self.cells_per_step = max(self.grid_res**3 // update_every, 1)
        # warm start just above threshold: ray-sampled refreshes keep
        # visible matter alive, so empty space only needs
        # log(warm)/log(1/decay) windows to fall through the threshold
        self.warm_factor = float(ta.get("ngp_grid_warm_factor", 2.0))
        self.sample_update_cap = int(ta.get("ngp_sample_update_cap", 65536))
        self.scan_steps = max(1, int(ta.get("scan_steps", 1)))
        self.process_index = jax.process_index()
        self._step_fn = None
        self._multi_step_fns: dict = {}
        self._render_fns: dict = {}

    # -- state ---------------------------------------------------------------
    def make_state(self, key):
        """(state, schedule) with fresh params and the warm-started grid."""
        from ..models import init_params_for

        params = init_params_for(self.cfg)(self.network, key)
        tx, schedule = make_optimizer(self.cfg)
        return self.init_state(params["params"], tx), schedule

    def init_state(self, params, tx) -> NGPTrainState:
        """Grid starts fully occupied (ema above threshold ⇒ dense march)
        so the first steps have gradients everywhere; decay + live updates
        then carve out the empty space. The warm factor sits deliberately
        LOW (just above threshold): training-ray sample refreshes keep real
        matter occupied while empty cells fall through quickly."""
        ema0 = jnp.full(
            (self.grid_res,) * 3, self.warm_factor * self.threshold,
            jnp.float32,
        )
        return NGPTrainState.create(
            apply_fn=self.network.apply, params=params, tx=tx,
            grid_ema=ema0,
        )

    # -- jitted step ---------------------------------------------------------
    def _build_step(self):
        n_rays = self.n_rays
        near, far = self.near, self.far
        bbox, options = self.bbox, self.march
        network = self.network
        res, thr = self.grid_res, self.threshold
        decay, n_cells = self.decay_step, self.cells_per_step
        process_index = self.process_index
        remat = bool(self.cfg.task_arg.get("remat", False))

        def apply_fn_for(params):
            fn = lambda pts, dirs, model: network.apply(  # noqa: E731
                {"params": params}, pts, dirs, model=model
            )
            return jax.checkpoint(fn, static_argnums=(2,)) if remat else fn

        sample_cap = self.sample_update_cap

        def one_step(state, bank_rays, bank_rgbs, base_key):
            key = sample_step_key(base_key, state.step, process_index)
            k_sample, k_cells, k_jitter = jax.random.split(key, 3)
            rays, rgbs = sample_rays(k_sample, bank_rays, bank_rgbs, n_rays)

            grid = state.grid_ema > thr  # bool [R,R,R], jit-static shape

            def loss_fn(p):
                out = march_rays_accelerated(
                    apply_fn_for(p), rays, near, far, grid, bbox, options,
                    return_samples=True,
                )
                l = mse(out["rgb_map_f"], rgbs)
                return l, (out, {
                    "loss": l,
                    "psnr": mse_to_psnr(l),
                    "occupancy": jnp.mean(grid.astype(jnp.float32)),
                    # rays losing far content to the K budget (dense-grid
                    # warm-up makes this nonzero; must fall as cells carve)
                    "truncated_frac": jnp.mean(
                        out["truncated"].astype(jnp.float32)
                    ),
                })

            (_, (out, stats)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
            new_state = state.apply_gradients(grads=grads)

            ema = state.grid_ema.reshape(-1) * decay

            # carve from what training actually SAMPLED: scatter-max the
            # march's compacted sigmas into their cells (stop_gradient'd by
            # the march; subsampled by a static stride to bound the
            # ~23M rows/s scatter cost). Cells with visible matter refresh
            # every step they are trained on — this is what lets the warm
            # start sit just above threshold and empty space carve fast.
            s_flat = out["sample_flat"].reshape(-1)
            s_sigma = (out["sample_sigma"]
                       * out["sample_valid"]).reshape(-1)
            stride = max(1, int(np.ceil(s_flat.shape[0] / sample_cap)))
            if stride > 1:
                s_flat = s_flat[::stride]
                s_sigma = s_sigma[::stride]
            ema = ema.at[s_flat].max(s_sigma)

            # exploration refresh: random cells probed with the LIVE
            # network at a jittered point (matter occluded on training rays
            # must still be discoverable)
            idx = jax.random.randint(
                k_cells, (n_cells,), 0, res * res * res
            )
            iz = idx % res
            iy = (idx // res) % res
            ix = idx // (res * res)
            cell = jnp.stack([ix, iy, iz], axis=-1).astype(jnp.float32)
            u = jax.random.uniform(k_jitter, (n_cells, 3))
            lo, hi = bbox[0], bbox[1]
            pts = lo + (cell + u) / res * (hi - lo)
            dirs = jnp.zeros((n_cells, 3), jnp.float32)
            raw = network.apply(
                {"params": jax.lax.stop_gradient(new_state.params)},
                pts[:, None, :], dirs, model="fine",
            )
            sigma = jax.nn.relu(raw[..., 0, 3])
            ema = ema.at[idx].max(sigma)
            new_state = new_state.replace(grid_ema=ema.reshape(res, res, res))
            return new_state, stats

        return one_step

    def _jit_step(self, k_steps: int):
        from .step_core import scan_k_steps

        one_step = self._build_step()

        @partial(jax.jit, donate_argnums=(0,))
        def step_fn(state, bank_rays, bank_rgbs, base_key):
            return scan_k_steps(
                lambda st: one_step(st, bank_rays, bank_rgbs, base_key),
                state, k_steps,
            )

        return step_fn

    def step(self, state, bank_rays, bank_rgbs, base_key):
        if self._step_fn is None:
            self._step_fn = self._jit_step(1)
        return self._step_fn(state, bank_rays, bank_rgbs, base_key)

    def multi_step(self, state, bank_rays, bank_rgbs, base_key, k_steps=None):
        """K optimizer steps (incl. grid maintenance) in one dispatch."""
        k = int(k_steps if k_steps is not None else self.scan_steps)
        if k <= 1:
            return self.step(state, bank_rays, bank_rgbs, base_key)
        fn = self._multi_step_fns.get(k)
        if fn is None:
            fn = self._multi_step_fns[k] = self._jit_step(k)
        return fn(state, bank_rays, bank_rgbs, base_key)

    # -- eval ----------------------------------------------------------------
    def val(self, state, test_dataset, evaluator, max_images=None, log=print):
        """Whole-image validation mirroring Trainer.val: render every test
        image through the live-grid march, feed the evaluator, summarize.
        The single implementation behind quality_run's NGP mode and
        scripts/bench_ngp.py — eval semantics must not fork."""
        import numpy as np

        n = len(test_dataset)
        if max_images is not None:
            n = min(n, max_images)
        for i in range(n):
            batch = test_dataset.image_batch(i)
            out = self.render_image(state, {"rays": batch["rays"]})
            evaluator.evaluate(
                {k: np.asarray(v) for k, v in out.items()}, batch
            )
        result = evaluator.summarize()
        if result:
            log("ngp val: " + "  ".join(
                f"{k}: {v:.4f}" for k, v in result.items()
            ))
        return result

    def render_image(self, state, batch: dict) -> dict:
        """Full-image eval through the accelerated march with the live grid
        (the chunked coarse+fine path is meaningless here: NGP training
        leaves the coarse network untrained by design). Jitted executables
        are cached per (n_chunks, chunk) shape like Renderer's eval paths."""
        from ..renderer.volume import _pad_to_chunks, _unpad_outputs

        grid = state.grid_ema > self.threshold
        rays_p, n, n_chunks, chunk = _pad_to_chunks(
            jnp.asarray(batch["rays"]), self.march.chunk_size
        )

        render = self._render_fns.get((n_chunks, chunk))
        if render is None:
            network, near, far = self.network, self.near, self.far
            bbox, options = self.bbox, self.march

            @jax.jit
            def render(params, rays_p, grid):
                apply_fn = lambda pts, dirs, model: network.apply(  # noqa: E731
                    {"params": params}, pts, dirs, model=model
                )

                def body(chunk_rays):
                    return march_rays_accelerated(
                        apply_fn, chunk_rays, near, far, grid, bbox, options
                    )

                return jax.lax.map(body, rays_p)

            self._render_fns[(n_chunks, chunk)] = render

        out = render(state.params, rays_p, grid)
        out = _unpad_outputs(out, n)
        # surface the K-budget diagnostic like Renderer.render_accelerated
        # does instead of silently dropping far content
        n_trunc = int(np.asarray(jnp.sum(out.pop("truncated"))))
        if n_trunc:
            print(
                f"ngp render_image: {n_trunc} rays exceeded the "
                f"max_march_samples={self.march.max_samples} budget while "
                "still transparent (far contributions truncated)"
            )
        return out


def make_ngp_trainer(cfg, network) -> NGPTrainer:
    return NGPTrainer(cfg, network)


def fit_ngp(cfg, network=None, log=print):
    """Epoch-loop training entry for ``task_arg.ngp_training: true`` —
    the occupancy-accelerated counterpart of trainer.fit (train.py routes
    here), with the same resume/save/eval cadence contract.

    Multi-device NGP is not wired yet: the live grid EMA needs a pmax
    merge across data shards; refused loudly rather than silently training
    one chip of a pod (set parallel.data_axis: 1 to opt out)."""
    import time

    import jax

    from ..datasets import make_dataset
    from ..evaluators import make_evaluator
    from ..parallel.collectives import barrier
    from ..parallel.mesh import is_chief, multihost_init
    from ..utils.setup import configure_runtime
    from .checkpoint import load_model, save_model, save_trained_config
    from .recorder import make_recorder

    multihost_init(cfg)
    configure_runtime(cfg)
    par = cfg.get("parallel", {})
    if jax.device_count() > 1 and (
        int(par.get("data_axis", -1)) != 1
        or int(par.get("model_axis", 1)) > 1
    ):
        raise NotImplementedError(
            "ngp_training over a device mesh is not wired yet (the live "
            "grid EMA needs a cross-shard pmax); set parallel.data_axis 1 "
            "(and model_axis 1) to train single-device, or use the "
            "hierarchical trainer"
        )

    if network is None:
        from ..models import make_network

        network = make_network(cfg)

    trainer = NGPTrainer(cfg, network)
    evaluator = None if cfg.get("skip_eval", False) else make_evaluator(cfg)
    recorder = make_recorder(cfg)

    seed = int(cfg.get("seed", 0))
    key = jax.random.PRNGKey(seed)
    k_init, base_key = jax.random.split(key)
    state, schedule = trainer.make_state(k_init)

    begin_epoch = 0
    if cfg.get("resume", True):
        state, begin_epoch, rec_state = load_model(
            cfg.trained_model_dir, state
        )
        if rec_state:
            recorder.load_state_dict(rec_state)
    if begin_epoch == 0 and cfg.get("pretrain", ""):
        from .checkpoint import load_pretrain

        params, ok = load_pretrain(cfg.pretrain, {"params": state.params})
        if ok:
            state = state.replace(params=params["params"])
    if is_chief():
        save_trained_config(cfg)

    train_ds = make_dataset(cfg, "train")
    test_ds = make_dataset(cfg, "test")
    bank = tuple(jax.device_put(a) for a in train_ds.ray_bank())

    epochs = int(cfg.train.epoch)
    ep_iter = int(cfg.get("ep_iter", 500))
    if ep_iter <= 0:
        ep_iter = max(1, int(bank[0].shape[0]) // trainer.n_rays)
    save_ep = int(cfg.get("save_ep", 40))
    save_latest_ep = int(cfg.get("save_latest_ep", 10))
    eval_ep = int(cfg.get("eval_ep", 10))
    log_interval = int(cfg.get("log_interval", 20))

    for epoch in range(begin_epoch, epochs):
        recorder.epoch = epoch
        host_step = int(state.step)
        it = 0
        end = time.time()
        while it < ep_iter:
            k = min(trainer.scan_steps, ep_iter - it)
            state, stats = trainer.multi_step(
                state, bank[0], bank[1], base_key, k
            )
            host_step += k
            should_log = (
                it == 0
                or (it + k - 1) // log_interval > (it - 1) // log_interval
                or it + k >= ep_iter
            )
            recorder.step = host_step
            recorder.batch_time.update((time.time() - end) / k)
            recorder.data_time.update(0.0)
            end = time.time()
            if should_log:
                recorder.update_loss_stats(
                    {kk: float(v) for kk, v in stats.items()}
                )
                lr = float(schedule(host_step))
                log(recorder.console_line(
                    epoch, min(it + k - 1, ep_iter - 1), ep_iter, lr, None
                ))
                recorder.record("train")
            it += k
        chief = is_chief()
        saving = (epoch + 1) % save_ep == 0 or (epoch + 1) % save_latest_ep == 0
        if saving:
            barrier("pre_save")
            if chief and (epoch + 1) % save_ep == 0:
                save_model(cfg.trained_model_dir, state, epoch,
                           recorder.state_dict(), latest=False)
            if chief and (epoch + 1) % save_latest_ep == 0:
                save_model(cfg.trained_model_dir, state, epoch,
                           recorder.state_dict(), latest=True)
            barrier("post_save")
        if chief and (epoch + 1) % eval_ep == 0 and evaluator is not None:
            result = trainer.val(state, test_ds, evaluator, log=log)
            if result:
                recorder.record("val", step=epoch, stats=result)
    return state
