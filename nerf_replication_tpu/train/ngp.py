"""Occupancy-accelerated training — the instant-ngp speed lever, TPU-native.

The reference bakes its occupancy grid ONCE from an already-trained network
and uses it only at eval (occupancy_grid.py, volume_renderer.py:268-358).
Instant-ngp's actual training speed comes from the grid being LIVE during
training: the MLP never evaluates empty space, cutting points/ray from the
dense S-march to the K ≪ S occupied samples. This module is that capability,
designed for XLA rather than translated from the CUDA original
(hashencoder.cu's training loop):

* **One jitted step, uniform executable.** The density grid rides inside the
  train state (:class:`NGPTrainState.grid_ema`); each step (a) marches the
  sampled rays through the SAME static-shape ESS+ERT two-phase march the
  eval path uses (renderer/accelerated.py — differentiable: grads flow to
  the MLP through the compacted [N, K] query), and (b) refreshes the grid
  EMA on a random subsample of cells with a scatter-max. No ``lax.cond``,
  no host round-trips, no retrace: grid maintenance is amortized
  continuously instead of instant-ngp's every-16-steps host-driven update.
* **Two-phase warmup, occupancy-gated.** The first phase trains with
  plain stratified volume rendering (no march, no possible truncation)
  while the grid carves from the sampled densities; the step switches to
  the carved-K march executable only once occupancy has actually fallen
  below ``ngp_warmup_exit_occ``. Round 4 measured why both halves are
  load-bearing: marching densely during warmup costs 4× the samples
  (2.3 s/step), and leaving warmup on a step count alone hands training
  to a truncating march whose supervision corrupts the field (28 dB →
  9.5 dB) while the corrupted density keeps the grid dense — a deadlock.
  The march loss also masks truncated rays outright.
* **One network.** NGP training drives the ``fine`` MLP only (hierarchical
  coarse→fine sampling is what the grid replaces); eval goes through the
  accelerated march with the live grid.

Round 4 (VERDICT r3 #5): the grid now carves from the densities the march
ACTUALLY SAMPLES on training rays (scatter-max of the compacted [N, K]
sigmas into their cells, subsampled to ``ngp_sample_update_cap`` rows) in
addition to the random-cell refresh — visible matter is refreshed every
step it is trained on, so the warm start can sit just above threshold
(``ngp_grid_warm_factor``, default 2.0) and empty space decays below
threshold within ~half an update-decay half-life instead of round 3's
~27 windows. ``fit_ngp`` is the production epoch-loop entry (train.py
routes ``task_arg.ngp_training: true`` here), with scan-burst support.

Config keys (all under ``task_arg``): ``ngp_training: true`` switches
train.py / scripts/quality_run.py onto this trainer; ``ngp_grid_res``
(64), ``ngp_grid_decay`` (0.95 per ``ngp_grid_update_every``-step window,
applied continuously), ``ngp_grid_update_every`` (16),
``ngp_density_threshold`` (0.01), ``ngp_grid_warm_factor`` (2.0),
``ngp_sample_update_cap`` (65536), ``scan_steps``, plus the shared march
knobs ``render_step_size`` / ``max_march_samples`` /
``transmittance_threshold``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax.training.train_state import TrainState

from ..datasets.sampling import sample_rays, sample_step_key
from ..obs import (
    CompileTracker,
    ProfileWindow,
    get_emitter,
    init_run,
    sample_memory,
)
from ..renderer.accelerated import MarchOptions, march_rays_accelerated
from ..utils.platform import donation_argnums
from .loss import mse, mse_to_psnr
from .optim import make_optimizer


class NGPTrainState(TrainState):
    """TrainState + the live density EMA ([R, R, R] float32)."""

    grid_ema: jax.Array = None


class NGPTrainer:
    """Occupancy-accelerated trainer (one fused jitted step)."""

    def __init__(self, cfg, network, mesh=None):
        ta = cfg.task_arg
        self.cfg = cfg
        self.network = network
        # a live mesh routes the step through shard_map DP (grads pmean'd,
        # grid EMA pmax-merged) — same Trainer-level mode as trainer.fit
        self.mesh = mesh
        self.n_rays = int(ta.get("N_rays", 1024))
        self.near = float(ta.near)
        self.far = float(ta.far)
        self.bbox = jnp.asarray(cfg.train_dataset.scene_bbox, jnp.float32)
        self.march = MarchOptions.from_cfg(cfg)
        # eval renders pay their march once per image — they get their own
        # (finer/deeper) budget instead of training's throughput-tuned one
        self.eval_march = MarchOptions.eval_from_cfg(cfg)
        # globally-packed sample stream (renderer/packed_march.py): the
        # MLP/encoder run only on OCCUPIED samples compacted across rays —
        # ~2.7x fewer encoder rows at carved occupancy than the per-ray
        # [N, K] compaction, and per-ray budgets become dynamic (a hard
        # ray can take 10x the samples of an easy one). cap_avg is the
        # stream budget in mean samples/ray.
        self.packed_march = bool(ta.get("ngp_packed_march", False))
        self.packed_cap_avg = int(ta.get("ngp_packed_cap_avg", 32))
        # eval stream cap PRESET to what dense-phase evals actually need
        # (1024 per the stage-3c trail — battery stage 3c died rebuilding
        # the eval executable once per escalation). The escalate loop in
        # render_image stays as the safety net and now telemeters each
        # firing, so a full run compiling more than one eval executable is
        # a visible regression, not a silent stall.
        self.packed_cap_avg_eval = int(
            ta.get(
                "ngp_packed_cap_avg_eval", max(1024, 4 * self.packed_cap_avg)
            )
        )
        self._eval_cap_escalations = 0
        # occupancy-derived cap (maybe_derive_eval_cap): once the grid has
        # carved, the stream's real need is ~occupancy x max_samples per
        # ray — deriving the cap from the live grid before the first eval
        # compile replaces the blanket 1024 preset with a scene-sized one.
        # An explicit ngp_packed_cap_avg_eval pins the cap (no derivation).
        self._eval_cap_user_preset = "ngp_packed_cap_avg_eval" in ta
        self._eval_cap_derived = False
        self.grid_res = int(ta.get("ngp_grid_res", 64))
        # density threshold follows the EVAL bake's convention
        # (task_arg.occupancy_grid_threshold, σ=1.0 in the lego family)
        # unless pinned explicitly. Round 4 measured why this matters: at
        # the old default σ=0.01 (alpha 5e-5 per δ=0.005 step — visually
        # nothing) a 31-dB network still reads as 98% "occupied" and the
        # grid never carves; the same network bakes to 5.7% at σ=1.0.
        thr_cfg = ta.get("ngp_density_threshold", None)
        if thr_cfg is None:
            self.threshold = float(ta.get("occupancy_grid_threshold", 1.0))
        else:
            self.threshold = float(thr_cfg)
        update_every = int(ta.get("ngp_grid_update_every", 16))
        decay_window = float(ta.get("ngp_grid_decay", 0.95))
        # continuous equivalent of "×decay every `update_every` steps"
        self.decay_step = float(decay_window ** (1.0 / update_every))
        # cells refreshed per step: full-grid coverage every update window
        self.cells_per_step = max(self.grid_res**3 // update_every, 1)
        # warm start just above threshold: ray-sampled refreshes keep
        # visible matter alive, so empty space only needs
        # log(warm)/log(1/decay) windows to fall through the threshold
        self.warm_factor = float(ta.get("ngp_grid_warm_factor", 2.0))
        self.sample_update_cap = int(ta.get("ngp_sample_update_cap", 65536))
        self.scan_steps = max(1, int(ta.get("scan_steps", 1)))
        # two-phase training: the first N steps march with the FULL
        # position budget (K = n_steps, truncation impossible), so the
        # network learns the whole ray while the grid carves from real
        # training samples; then the step switches to the carved-K
        # executable. Without this, a dense warm grid + static K truncates
        # most rays' far content and learning stalls (round-4 A/B: 1,580
        # steps at truncated_frac 0.92 ended at 12 dB).
        self.warmup_steps = int(ta.get("ngp_warmup_steps", 500))
        # the phase switch is OCCUPANCY-gated, not just step-gated: handing
        # training to the carved march while the grid is still dense feeds
        # it truncated supervision (see loss_fn_march). warmup ends at the
        # LATER of warmup_steps and occupancy < warmup_exit_occ; warm mode
        # can RE-ENGAGE if the grid later re-densifies (a carved march over
        # a dense grid truncates most rays and the masked loss drops them),
        # with ngp_warmup_max capping CUMULATIVE warm steps so a
        # pathological scene cannot warm forever.
        self.warmup_exit_occ = float(ta.get("ngp_warmup_exit_occ", 0.6))
        self.warmup_max = int(ta.get("ngp_warmup_max", 8 * self.warmup_steps))
        # past warmup_max the per-burst occupancy sync is skipped (it costs
        # a ~0.3-0.4 s device→host round trip on this tunnel), but a grid
        # that re-densifies later must still be able to re-engage warm mode
        # — re-sync every N bursts instead of never (round-4 advisor)
        self.occ_resync_bursts = int(ta.get("ngp_occ_resync_bursts", 32))
        # loud diagnostic when the carved march starts dropping rays: the
        # masked loss silently ignores truncated rays, so a grown grid
        # shows up only here
        self.trunc_warn_frac = float(ta.get("ngp_trunc_warn_frac", 0.25))
        self.process_index = jax.process_index()
        self._host_step: int | None = None
        self._last_occ: float = 1.0
        self._bursts: int = 0
        self._warm_steps_total: int = 0
        self._trunc_warned: bool = False
        self._step_fns: dict = {}
        self._render_fns: dict = {}
        # observability: compile/retrace counting per (k, warm) executable
        # and the config-driven profiler window — the NGP loop's phase
        # switches are exactly where silent recompiles hide
        self.tracker = CompileTracker()
        self.profile = ProfileWindow.from_cfg(cfg)
        # AOT compile registry (compile/registry.py): fit_ngp wires one so
        # step/render executables build up front on host threads; None
        # keeps the lazy-jit path (direct NGPTrainer users, unit tests)
        self.aot = None

    # -- state ---------------------------------------------------------------
    def make_state(self, key):
        """(state, schedule) with fresh params and the warm-started grid."""
        from ..models import init_params_for

        params = init_params_for(self.cfg)(self.network, key)
        tx, schedule = make_optimizer(self.cfg)
        return self.init_state(params["params"], tx), schedule

    def init_state(self, params, tx) -> NGPTrainState:
        """Grid starts fully occupied (ema above threshold ⇒ dense march)
        so the first steps have gradients everywhere; decay + live updates
        then carve out the empty space. The warm factor sits deliberately
        LOW (just above threshold): training-ray sample refreshes keep real
        matter occupied while empty cells fall through quickly."""
        ema0 = jnp.full(
            (self.grid_res,) * 3, self.warm_factor * self.threshold,
            jnp.float32,
        )
        return NGPTrainState.create(
            apply_fn=self.network.apply, params=params, tx=tx,
            grid_ema=ema0,
        )

    # -- warm/carve phase persistence ---------------------------------------
    def phase_state(self) -> dict:
        """Host-side phase counters for the checkpoint sidecar
        (train/checkpoint.save_model): what a resumed trainer needs to
        re-enter the EXACT phase — the occupancy-based estimate in
        multi_step only approximates cumulative warm steps."""
        if self._host_step is None:
            return {}
        return {
            "host_step": int(self._host_step),
            "last_occ": float(self._last_occ),
            "warm_steps_total": int(self._warm_steps_total),
            "bursts": int(self._bursts),
            "trunc_warned": bool(self._trunc_warned),
        }

    def restore_phase(self, phase: dict | None,
                      expect_step: int | None = None) -> bool:
        """Adopt persisted phase counters; False (→ the occupancy
        heuristic runs instead) on a missing sidecar or one that doesn't
        match the restored bundle's step (a torn save pair must not pin
        the trainer to a phase the grid isn't in)."""
        if not phase or "warm_steps_total" not in phase:
            return False
        if expect_step is not None and int(phase.get("host_step", -1)) != int(
            expect_step
        ):
            return False
        self._host_step = int(phase["host_step"])
        self._last_occ = float(phase.get("last_occ", 1.0))
        self._warm_steps_total = int(phase["warm_steps_total"])
        self._bursts = int(phase.get("bursts", 0))
        self._trunc_warned = bool(phase.get("trunc_warned", False))
        return True

    # -- AOT registration (compile/registry.py) ------------------------------
    def aot_register_steps(self, state, bank, base_key) -> None:
        """Register both phase variants of the scan-burst executable so
        the carve-phase program compiles concurrently with warm-phase
        training instead of serially at the phase switch (the round-5
        warmup tax). Clamped boundary bursts still build lazily."""
        if self.aot is None:
            return
        from ..compile import abstract_like

        args = abstract_like((state, bank[0], bank[1], base_key))
        k = self.scan_steps
        for warm in (True, False):
            name = f"ngp_step_k{k}_{'warm' if warm else 'march'}"
            self.aot.register(name, self._jit_step(k, warm=warm), args)
        self.aot.compile_all(wait=False)

    def maybe_derive_eval_cap(self, grid) -> bool:
        """Size the packed eval stream cap from the LIVE grid's occupancy
        (once, before the first eval executable compiles): the packed
        march only emits samples in occupied cells, so a carved grid needs
        ~occupancy x max_samples mean samples per ray; 1.5x headroom
        absorbs rays that cross denser-than-average regions. The blanket
        1024 preset stays as the fallback for uncarved grids, and the
        render_image escalation loop remains the safety net when even the
        derived cap overflows. No-op when the user pinned
        ``ngp_packed_cap_avg_eval`` explicitly, when the march is not
        packed, or after the first derivation (a moving cap would rebuild
        the eval executable every time occupancy drifts). Returns whether
        the cap changed."""
        if (not self.packed_march or self._eval_cap_user_preset
                or self._eval_cap_derived):
            return False
        occ = float(jnp.mean(grid))  # one intentional sync, pre-first-eval
        if occ <= 0.0 or occ >= self.warmup_exit_occ:
            # dead or still-dense grid (fresh inits warm-start ABOVE the
            # threshold, occ = 1.0): keep the blanket preset and leave
            # derivation open for the first genuinely carved eval
            return False
        raw = occ * self.eval_march.max_samples * 1.5
        cap = max(64, -(-int(np.ceil(raw)) // 64) * 64)  # round up to x64
        self._eval_cap_derived = True
        if cap == self.packed_cap_avg_eval:
            return False
        cap_old = self.packed_cap_avg_eval
        self.packed_cap_avg_eval = cap
        get_emitter().emit(
            "compile",
            name="ngp_render_eval_cap_derived",
            n_compiles=0,  # a (re)sizing, not a build — builds ride below
            wall_s=0.0,
            cap_old=cap_old,
            cap_new=cap,
        )
        print(
            f"ngp eval cap: occupancy {occ:.1%} x "
            f"{self.eval_march.max_samples} max_samples x 1.5 headroom "
            f"-> packed_cap_avg_eval {cap} (was {cap_old})"
        )
        return True

    def aot_register_render(self, state, n_rays_image: int) -> None:
        """Pre-build the packed/accelerated eval executable for one test
        image's ray count — sized by the live grid's occupancy when it has
        carved (maybe_derive_eval_cap) — so the first val no longer blocks
        on its compile, and a warm process deserializes it."""
        if self.aot is None:
            return
        from ..compile import abstract_like
        from ..renderer.volume import _pad_to_chunks

        self.maybe_derive_eval_cap(state.grid_ema > self.threshold)
        rays = jnp.zeros((int(n_rays_image), 6), jnp.float32)
        rays_p, _, n_chunks, chunk = _pad_to_chunks(
            rays, self.eval_march.chunk_size
        )
        grid_sds = jax.ShapeDtypeStruct((self.grid_res,) * 3, jnp.bool_)
        name = (
            f"ngp_render_{n_chunks}x{chunk}_cap{self.packed_cap_avg_eval}"
        )
        self.aot.register(
            name,
            self._build_render(n_chunks, chunk),
            abstract_like((state.params, rays_p, grid_sds)),
            serialize=True,
        )
        self.aot.compile_all(wait=False)

    # -- jitted step ---------------------------------------------------------
    def _build_step(self, axis_name: str | None = None, warm: bool = False):
        """One-step body. ``axis_name`` set (shard_map DP): per-shard ray
        sampling with a decorrelated key, grads/stats pmean'd, and the
        live grid merged with a cross-shard pmax — a max-merge of EMA
        candidates over a replicated base equals a single chip consuming
        the union of the shards' samples, so the grid stays replicated
        and step-equivalent."""
        n_rays = self.n_rays
        if axis_name is not None:
            n_rays = self.n_rays // self.mesh.shape[axis_name]
        near, far = self.near, self.far
        bbox, options = self.bbox, self.march
        network = self.network
        res, thr = self.grid_res, self.threshold
        decay, n_cells = self.decay_step, self.cells_per_step
        process_index = self.process_index
        remat = bool(self.cfg.task_arg.get("remat", False))

        def apply_fn_for(params):
            fn = lambda pts, dirs, model: network.apply(  # noqa: E731
                {"params": params}, pts, dirs, model=model
            )
            return jax.checkpoint(fn, static_argnums=(2,)) if remat else fn

        sample_cap = self.sample_update_cap
        s_warm = int(self.cfg.task_arg.get("ngp_warmup_samples", 128))
        white_bkgd = options.white_bkgd
        packed, packed_cap = self.packed_march, self.packed_cap_avg

        def one_step(state, bank_rays, bank_rgbs, base_key):
            if axis_name is not None:
                # multi-controller SPMD: the traced program must be
                # identical on every process — decorrelate by the GLOBAL
                # axis_index, never by host-side process_index
                key = sample_step_key(base_key, state.step)
                key = jax.random.fold_in(key, jax.lax.axis_index(axis_name))
            else:
                key = sample_step_key(base_key, state.step, process_index)
            k_sample, k_cells, k_jitter, k_z = jax.random.split(key, 4)
            with jax.named_scope("bank_draw"):
                rays, rgbs = sample_rays(
                    k_sample, bank_rays, bank_rgbs, n_rays
                )

            grid = state.grid_ema > thr  # bool [R,R,R], jit-static shape

            def loss_fn_march(p):
                if packed:
                    from ..renderer.packed_march import march_rays_packed

                    out = march_rays_packed(
                        apply_fn_for(p), rays, near, far, grid, bbox,
                        options, cap_avg=packed_cap, return_samples=True,
                    )
                else:
                    out = march_rays_accelerated(
                        apply_fn_for(p), rays, near, far, grid, bbox,
                        options, return_samples=True,
                    )
                # EXCLUDE truncated rays from the loss: a ray that ran out
                # of K budget rendered only its near content — supervising
                # that against the full ground truth actively corrupts the
                # field (round-4 A/B: training THROUGH truncation erased
                # the warmup's progress, 28 dB -> 9.5 dB)
                w = 1.0 - out["truncated"].astype(jnp.float32)
                per_ray = jnp.mean(
                    (out["rgb_map_f"] - rgbs) ** 2, axis=-1
                )
                l = jnp.sum(per_ray * w) / jnp.maximum(jnp.sum(w), 1.0)
                stats = {
                    "loss": l,
                    "psnr": mse_to_psnr(l),
                    "occupancy": jnp.mean(grid.astype(jnp.float32)),
                    # rays losing far content to the K budget (must stay
                    # near zero once the grid has carved)
                    "truncated_frac": jnp.mean(
                        out["truncated"].astype(jnp.float32)
                    ),
                }
                if packed:
                    # occupied samples dropped by the global stream cap
                    stats["overflow_frac"] = out["overflow_frac"]
                    # coarse-DDA block admission fraction (1.0 when the
                    # march runs flat) — the carved phase's sweep shrink
                    stats["march_coarse_occ"] = out["march_coarse_occ"]
                return l, (out, stats)

            def loss_fn_warm(p):
                # warmup: NO occupancy march — plain stratified volume
                # rendering of the fine network (the K=n_steps dense march
                # costs 4x the samples and all the compaction overhead for
                # the same supervision; measured 2.3 s/step, round 4). The
                # grid still carves from these samples' densities.
                from ..renderer.accelerated import world_to_voxel
                from ..renderer.volume import raw2outputs, stratified_z_vals

                rays_o, rays_d = rays[..., 0:3], rays[..., 3:6]
                z = stratified_z_vals(k_z, near, far, n_rays, s_warm, 1.0)
                pts = rays_o[:, None, :] + rays_d[:, None, :] * z[..., None]
                viewdirs = rays_d / jnp.linalg.norm(
                    rays_d, axis=-1, keepdims=True
                )
                raw = apply_fn_for(p)(pts, viewdirs, "fine")
                rgb_map, _, _, _ = raw2outputs(
                    raw, z, rays_d, white_bkgd=white_bkgd
                )
                l = mse(rgb_map, rgbs)
                pts_sg = jax.lax.stop_gradient(pts)
                vox = world_to_voxel(pts_sg, bbox, res)
                flat = (vox[..., 0] * res + vox[..., 1]) * res + vox[..., 2]
                # out-of-bbox samples would be clamp-scattered into the
                # boundary shell with the young net's spurious density —
                # mask them out (the march path masks via valid=occupied)
                in_bbox = jnp.all(
                    (pts_sg >= bbox[0]) & (pts_sg <= bbox[1]), axis=-1
                ).astype(jnp.float32)
                out = {
                    "sample_flat": flat.astype(jnp.int32),
                    "sample_sigma": jax.lax.stop_gradient(
                        jax.nn.relu(raw[..., 3])
                    ),
                    "sample_valid": in_bbox,
                }
                return l, (out, {
                    "loss": l,
                    "psnr": mse_to_psnr(l),
                    "occupancy": jnp.mean(grid.astype(jnp.float32)),
                    "truncated_frac": jnp.zeros(()),
                })

            loss_fn = loss_fn_warm if warm else loss_fn_march

            (_, (out, stats)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
            if axis_name is not None:
                from ..parallel.collectives import tree_pmean

                grads = tree_pmean(grads, axis_name)
                stats = tree_pmean(stats, axis_name)
            new_state = state.apply_gradients(grads=grads)

            with jax.named_scope("grid_update"):
                ema = state.grid_ema.reshape(-1) * decay

                # carve from what training actually SAMPLED: scatter-max
                # the march's compacted sigmas into their cells
                # (stop_gradient'd by the march; subsampled by a static
                # stride to bound the ~23M rows/s scatter cost). Cells
                # with visible matter refresh every step they are trained
                # on — this is what lets the warm start sit just above
                # threshold and empty space carve fast.
                s_flat = out["sample_flat"].reshape(-1)
                s_sigma = (out["sample_sigma"]
                           * out["sample_valid"]).reshape(-1)
                stride = max(1, int(np.ceil(s_flat.shape[0] / sample_cap)))
                if stride > 1:
                    s_flat = s_flat[::stride]
                    s_sigma = s_sigma[::stride]
                ema = ema.at[s_flat].max(s_sigma)

                # exploration refresh: random cells probed with the LIVE
                # network at a jittered point (matter occluded on training
                # rays must still be discoverable)
                idx = jax.random.randint(
                    k_cells, (n_cells,), 0, res * res * res
                )
                iz = idx % res
                iy = (idx // res) % res
                ix = idx // (res * res)
                cell = jnp.stack([ix, iy, iz], axis=-1).astype(jnp.float32)
                u = jax.random.uniform(k_jitter, (n_cells, 3))
                lo, hi = bbox[0], bbox[1]
                pts = lo + (cell + u) / res * (hi - lo)
                dirs = jnp.zeros((n_cells, 3), jnp.float32)
                raw = network.apply(
                    {"params": jax.lax.stop_gradient(new_state.params)},
                    pts[:, None, :], dirs, model="fine",
                )
                sigma = jax.nn.relu(raw[..., 0, 3])
                ema = ema.at[idx].max(sigma)
                if axis_name is not None:
                    # max-merge the shards' EMA candidates (all start from
                    # the same replicated decayed base, so this is exactly
                    # the union of every shard's scatter-max updates)
                    ema = jax.lax.pmax(ema, axis_name)
                new_state = new_state.replace(
                    grid_ema=ema.reshape(res, res, res)
                )
            return new_state, stats

        return one_step

    def _jit_step(self, k_steps: int, warm: bool = False):
        from .step_core import scan_k_steps

        if self.mesh is not None:
            from jax.sharding import PartitionSpec as P

            from ..parallel.compat import shard_map

            from ..parallel.mesh import DATA_AXIS

            n_data = self.mesh.shape[DATA_AXIS]
            if self.n_rays % n_data != 0:
                raise ValueError(
                    f"N_rays={self.n_rays} must be divisible by the data "
                    f"axis ({n_data}) — a silent round-down would train a "
                    "different effective batch than configured"
                )
            one_step = self._build_step(axis_name=DATA_AXIS, warm=warm)

            def body(state, bank_rays, bank_rgbs, base_key):
                return scan_k_steps(
                    lambda st: one_step(st, bank_rays, bank_rgbs, base_key),
                    state, k_steps,
                )

            smap = shard_map(
                body,
                mesh=self.mesh,
                in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P()),
                out_specs=(P(), P()),
                check_vma=False,
            )
            return jax.jit(smap, donate_argnums=donation_argnums(0))

        one_step = self._build_step(warm=warm)

        @partial(jax.jit, donate_argnums=donation_argnums(0))
        def step_fn(state, bank_rays, bank_rgbs, base_key):
            return scan_k_steps(
                lambda st: one_step(st, bank_rays, bank_rgbs, base_key),
                state, k_steps,
            )

        return step_fn

    def step(self, state, bank_rays, bank_rgbs, base_key):
        return self.multi_step(state, bank_rays, bank_rgbs, base_key, 1)

    def multi_step(self, state, bank_rays, bank_rgbs, base_key, k_steps=None):
        """K optimizer steps (incl. grid maintenance) in one dispatch,
        routed through the warmup (full-budget) executable until
        ``ngp_warmup_steps`` optimizer steps have run; a burst never
        straddles the phase switch."""
        k = int(k_steps if k_steps is not None else self.scan_steps)
        k = max(k, 1)
        if self._host_step is None:
            # one host sync at (re)start; resume-safe — including the
            # occupancy gate, which must reflect the RESTORED grid (a
            # resumed carved run must not replay a warm burst)
            self._host_step = int(state.step)
            self._last_occ = float(
                jnp.mean((state.grid_ema > self.threshold).astype(
                    jnp.float32
                ))
            )
            # estimate warm steps already consumed so the cumulative cap
            # survives restarts (only a host counter otherwise — a
            # kill/resume loop must not grant a fresh warmup_max each
            # time). Resumed dense ⇒ every prior step was warm; resumed
            # carved ⇒ only the mandatory warmup phase was.
            est = (
                self._host_step
                if self._last_occ > self.warmup_exit_occ
                else min(self._host_step, self.warmup_steps)
            )
            self._warm_steps_total = min(est, self.warmup_max)
        # warm when still inside the mandatory warmup OR the grid is dense
        # (incl. a LATE re-densification — the carved march over a dense
        # grid truncates most rays and the masked loss drops them), capped
        # by cumulative warm steps so a pathological scene cannot warm
        # forever.
        # the cumulative cap bounds only the occupancy EXTENSION — the
        # mandatory step-gated warmup always runs (it is already bounded
        # by warmup_steps, and a warmup_max configured below warmup_steps
        # must not cancel it)
        warm = self._host_step < self.warmup_steps or (
            self._last_occ > self.warmup_exit_occ
            and self._warm_steps_total < self.warmup_max
        )
        if warm and self._host_step < self.warmup_steps:
            k = min(k, self.warmup_steps - self._host_step)
        fn = self._step_fns.get((k, warm))
        if fn is None:
            name = f"ngp_step_k{k}_{'warm' if warm else 'march'}"
            pre = self.aot.take(name) if self.aot is not None else None
            fn = self._step_fns[(k, warm)] = self.tracker.wrap(
                name, pre if pre is not None else self._jit_step(k, warm=warm)
            )
        self._host_step += k
        if warm:
            self._warm_steps_total += k
        self.last_burst_steps = k  # callers account actual steps run
        self.last_burst_warm = warm
        state, stats = fn(state, bank_rays, bank_rgbs, base_key)
        self._bursts += 1
        if (
            warm
            or self._host_step < self.warmup_max
            or (
                self.occ_resync_bursts > 0
                and self._bursts % self.occ_resync_bursts == 0
            )
        ):
            # the occupancy gate is live (it can re-engage warm if the
            # grid re-densifies): one scalar sync per burst during warmup,
            # then every `ngp_occ_resync_bursts` bursts (0 = never) —
            # skipping most syncs lets step loops pipeline dispatches (a
            # ~0.3-0.4 s tunnel round trip each), while a late
            # re-densified grid is still noticed within N bursts.
            self._last_occ = float(stats["occupancy"])
            if not warm and not self._trunc_warned:
                tf = float(stats.get("truncated_frac", 0.0))
                if tf > self.trunc_warn_frac:
                    self._trunc_warned = True
                    knob = (
                        "ngp_packed_cap_avg"
                        if self.packed_march
                        else "max_march_samples"
                    )
                    print(
                        f"ngp: truncated_frac {tf:.2f} exceeds "
                        f"{self.trunc_warn_frac} after warmup — the march "
                        "budget is dropping far content and those rays "
                        f"are masked out of the loss (raise {knob} or "
                        "check the grid threshold)"
                    )
        return state, stats

    # -- eval ----------------------------------------------------------------
    def val(self, state, test_dataset, evaluator, max_images=None, log=print):
        """Whole-image validation mirroring Trainer.val: render every test
        image through the live-grid march, feed the evaluator, summarize.
        The single implementation behind quality_run's NGP mode and
        scripts/bench_ngp.py — eval semantics must not fork."""
        import numpy as np

        n = len(test_dataset)
        if max_images is not None:
            n = min(n, max_images)
        for i in range(n):
            batch = test_dataset.image_batch(i)
            out = self.render_image(state, {"rays": batch["rays"]})
            evaluator.evaluate(
                {k: np.asarray(v) for k, v in out.items()}, batch
            )
        result = evaluator.summarize()
        if result:
            log("ngp val: " + "  ".join(
                f"{k}: {v:.4f}" for k, v in result.items()
            ))
        return result

    def _build_render(self, n_chunks: int, chunk: int):
        """The jitted full-image eval executable for one padded shape at
        the CURRENT eval cap (closed over jit-static) — shared by the
        lazy path below and the AOT registration above."""
        network, near, far = self.network, self.near, self.far
        bbox, options = self.bbox, self.eval_march
        packed, cap_eval = self.packed_march, self.packed_cap_avg_eval
        if options.march_fused == "full":
            # the mega-kernel's in-kernel encode is frequency-family only
            # (ops/fused_march.py) — the hash encoder is a learnable Flax
            # module that cannot run inside the fused body. Refuse at
            # build time instead of silently downgrading the A/B label.
            raise ValueError(
                "march_fused='full' is unsupported on the NGP (hashgrid) "
                "eval path — use march_fused='gather' (fused DDA + gather; "
                "the MLP stays outside, so any encoder family rides it)"
            )

        @jax.jit
        def render(params, rays_p, grid):
            apply_fn = lambda pts, dirs, model: network.apply(  # noqa: E731
                {"params": params}, pts, dirs, model=model
            )

            def body(chunk_rays):
                if options.march_fused == "gather":
                    from ..ops.fused_march import march_rays_fused

                    return march_rays_fused(
                        apply_fn, chunk_rays, near, far, grid, bbox,
                        options,
                    )
                if packed:
                    from ..renderer.packed_march import march_rays_packed

                    out = march_rays_packed(
                        apply_fn, chunk_rays, near, far, grid, bbox,
                        options, cap_avg=cap_eval,
                    )
                    return out
                return march_rays_accelerated(
                    apply_fn, chunk_rays, near, far, grid, bbox, options
                )

            return jax.lax.map(body, rays_p)

        return render

    def render_image(self, state, batch: dict) -> dict:
        """Full-image eval through the accelerated march with the live grid
        (the chunked coarse+fine path is meaningless here: NGP training
        leaves the coarse network untrained by design). Jitted executables
        are cached per (n_chunks, chunk) shape like Renderer's eval paths."""
        from ..renderer.volume import _pad_to_chunks, _unpad_outputs

        grid = state.grid_ema > self.threshold
        # first eval on a carved grid: size the stream cap from occupancy
        # BEFORE the executable cache key below bakes the preset in
        self.maybe_derive_eval_cap(grid)
        rays_p, n, n_chunks, chunk = _pad_to_chunks(
            jnp.asarray(batch["rays"]), self.eval_march.chunk_size
        )

        def _render_fn():
            # cap is part of the key: escalation below must recompile
            key = (n_chunks, chunk, self.packed_cap_avg_eval)
            render = self._render_fns.get(key)
            if render is not None:
                return render
            if self.aot is not None:
                # pre-built (or deserialized) by aot_register_render
                name = (
                    f"ngp_render_{n_chunks}x{chunk}"
                    f"_cap{self.packed_cap_avg_eval}"
                )
                pre = self.aot.take(name)
                if pre is not None:
                    self._render_fns[key] = pre
                    return pre
            render = self._build_render(n_chunks, chunk)
            self._render_fns[key] = render
            return render

        # a dense-phase grid can overflow the packed stream cap (dropped
        # far samples → silently understated eval PSNR): escalate the cap
        # and re-render, bounded; the raised cap persists on the trainer
        # so later evals start right. Each escalation rebuilds the eval
        # executable — telemetered as a `compile` row (cap_old/cap_new)
        # so tlm_report --diff flags a run whose preset cap is too low.
        for attempt in range(4):
            out = _render_fn()(state.params, rays_p, grid)
            overflow = out.pop("overflow_frac", None)
            max_of = (
                float(np.asarray(jnp.max(overflow)))
                if overflow is not None else 0.0
            )
            if max_of <= 0.0 or attempt == 3:
                break  # clean, or out of escalations (warned below)
            # the outgrown executable can never be hit again (the cap
            # only grows) — drop it so it doesn't pin device memory
            self._render_fns.pop(
                (n_chunks, chunk, self.packed_cap_avg_eval), None
            )
            cap_old = self.packed_cap_avg_eval
            self.packed_cap_avg_eval *= 2
            self._eval_cap_escalations += 1
            get_emitter().emit(
                "compile",
                name="ngp_render_eval_cap",
                n_compiles=self._eval_cap_escalations,
                wall_s=0.0,  # the rebuild lands on the re-render below
                cap_old=cap_old,
                cap_new=self.packed_cap_avg_eval,
            )
            print(
                f"ngp render_image: packed stream overflow "
                f"{max_of:.1%} — escalating ngp_packed_cap_avg_eval to "
                f"{self.packed_cap_avg_eval} and re-rendering"
            )
        out = _unpad_outputs(out, n)
        # traversal telemetry ([n_chunks] vectors from the packed march —
        # popped BEFORE callers treat remaining keys as per-ray maps): one
        # "march" row per eval image feeds tlm_report's sweep-efficiency
        # summary and --diff regression gate
        if "march_candidates" in out:
            cand = float(np.asarray(jnp.sum(out.pop("march_candidates"))))
            samp = float(np.asarray(jnp.sum(out.pop("march_samples_out"))))
            c_occ = float(np.asarray(jnp.mean(out.pop("march_coarse_occ"))))
            get_emitter().emit(
                "march",
                surface="ngp_eval",
                mode=(
                    "fused" if self.eval_march.march_fused != "off"
                    else "hierarchical" if self.eval_march.coarse_block > 0
                    else "packed"
                ),
                candidates_in=cand,
                samples_out=samp,
                coarse_occ=c_occ,
                overflow_frac=max_of,
                n_rays=n,
            )
        # surface the budget diagnostics like Renderer.render_accelerated
        # does instead of silently dropping far content — citing the knob
        # that actually bounds the active march mode
        n_trunc = int(np.asarray(jnp.sum(out.pop("truncated"))))
        if n_trunc:
            budget = (
                f"ngp_packed_cap_avg_eval={self.packed_cap_avg_eval}"
                if self.packed_march
                else f"eval K={self.eval_march.max_samples}"
            )
            print(
                f"ngp render_image: {n_trunc} rays exceeded the march "
                f"budget ({budget}) while still transparent (far "
                "contributions truncated)"
            )
        if overflow is not None:
            max_of = float(np.asarray(jnp.max(overflow)))
            if max_of > 0:
                print(
                    f"ngp render_image: packed stream overflow up to "
                    f"{max_of:.1%} of occupied samples per chunk — raise "
                    "ngp_packed_cap_avg_eval"
                )
        return out


def make_ngp_trainer(cfg, network) -> NGPTrainer:
    return NGPTrainer(cfg, network)


def _ngp_epoch_steps(trainer, state, bank, base_key, recorder, schedule,
                     emitter, epoch, ep_iter, log_interval, host_step, *,
                     finite_guard=True, guard=None, log=print):
    """One epoch's burst loop (fit_ngp's hot inner loop, factored out so
    the epoch driver can wrap it in divergence-rollback handling).
    Returns (state, host_step); stops early at a burst boundary when the
    SIGTERM guard has triggered (fit_ngp then flushes latest/)."""
    import time

    from ..resil import DivergenceError, check_finite

    it = 0
    end = time.time()
    while it < ep_iter:
        trainer.profile.tick(host_step)
        k = min(trainer.scan_steps, ep_iter - it)
        t_dispatch = time.perf_counter()
        state, stats = trainer.multi_step(
            state, bank[0], bank[1], base_key, k
        )
        dispatch_s = time.perf_counter() - t_dispatch
        # multi_step may clamp a burst at the warmup boundary — account
        # the steps that actually ran, or epochs undertrain silently
        k = trainer.last_burst_steps
        host_step += k
        should_log = (
            it == 0
            or (it + k - 1) // log_interval > (it - 1) // log_interval
            or it + k >= ep_iter
        )
        recorder.step = host_step
        recorder.batch_time.update((time.time() - end) / k)
        recorder.data_time.update(0.0)
        end = time.time()
        if should_log:
            t_block = time.perf_counter()
            jax.block_until_ready(stats)
            block_s = time.perf_counter() - t_block
            stats_host = {kk: float(v) for kk, v in stats.items()}
            if finite_guard:
                try:
                    stats_host = check_finite(stats_host, host_step)
                except DivergenceError as err:
                    # attach the live (NaN-poisoned but valid-buffered)
                    # state: the rollback needs a restore template whose
                    # buffers were never donated away
                    err.state = state
                    raise
            recorder.update_loss_stats(stats_host)
            lr = float(schedule(host_step))
            log(recorder.console_line(
                epoch, min(it + k - 1, ep_iter - 1), ep_iter, lr,
                None,
            ))
            recorder.record("train")
            emitter.emit(
                "step",
                step=host_step,
                epoch=epoch,
                k=k,
                step_time_s=recorder.batch_time.median,
                step_time_avg_s=recorder.batch_time.avg,
                data_time_s=recorder.data_time.avg,
                dispatch_s=dispatch_s / k,
                block_s=block_s / k,
                lr=lr,
                stats=stats_host,
            )
        it += k
        if guard is not None and guard.triggered:
            # SIGTERM landed: stop at this burst boundary
            break
    trainer.profile.tick(host_step)
    return state, host_step


def fit_ngp(cfg, network=None, log=print):
    """Epoch-loop training entry for ``task_arg.ngp_training: true`` —
    the occupancy-accelerated counterpart of trainer.fit (train.py routes
    here), with the same resume/save/eval cadence contract.

    Multi-device: data parallelism is wired (shard_map over the data axis;
    grads/stats pmean'd, the live grid EMA pmax-merged across shards —
    see ``NGPTrainer._build_step``; tested in test_ngp.py). Model/tensor
    parallelism is NOT: the occupancy march has no tensor-parallel seam
    yet, so ``parallel.model_axis > 1`` is refused loudly below."""
    import time

    import jax

    from ..datasets import make_dataset
    from ..evaluators import make_evaluator
    from ..parallel.collectives import barrier
    from ..compile import registry_from_cfg
    from ..parallel.mesh import is_chief, multihost_init
    from ..resil import DivergenceError, PreemptionGuard, check_finite, report
    from ..utils.setup import configure_runtime
    from .checkpoint import (
        has_checkpoint,
        load_model,
        load_phase_state,
        save_model_with_retry,
        save_trained_config,
    )
    from .recorder import make_recorder

    multihost_init(cfg)
    configure_runtime(cfg)
    par = cfg.get("parallel", {})
    if int(par.get("model_axis", 1)) > 1:
        raise NotImplementedError(
            "ngp_training supports data parallelism only (the occupancy "
            "march has no tensor-parallel seam yet) — set "
            "parallel.model_axis 1"
        )
    mesh = None
    if jax.device_count() > 1 and int(par.get("data_axis", -1)) != 1:
        from ..parallel.mesh import make_mesh_from_cfg

        mesh = make_mesh_from_cfg(cfg)
        log(f"ngp training over mesh "
            f"{dict(zip(mesh.axis_names, mesh.devices.shape))}")

    if network is None:
        from ..models import make_network

        network = make_network(cfg)

    trainer = NGPTrainer(cfg, network, mesh=mesh)
    trainer.aot = registry_from_cfg(cfg, tracker=trainer.tracker)
    evaluator = None if cfg.get("skip_eval", False) else make_evaluator(cfg)
    recorder = make_recorder(cfg)
    # telemetry opens AFTER the recorder (a fresh run wipes record_dir —
    # the stream must not be orphaned by that wipe)
    emitter = init_run(cfg, component="train_ngp")

    seed = int(cfg.get("seed", 0))
    key = jax.random.PRNGKey(seed)
    k_init, base_key = jax.random.split(key)
    state, schedule = trainer.make_state(k_init)

    begin_epoch = 0
    if cfg.get("resume", True):
        state, begin_epoch, rec_state = load_model(
            cfg.trained_model_dir, state
        )
        if rec_state:
            recorder.load_state_dict(rec_state)
        # warm-start: adopt the persisted warm/carve phase counters so the
        # resumed run re-enters the carved phase directly (falls back to
        # the occupancy estimate in multi_step when absent/mismatched)
        trainer.restore_phase(
            load_phase_state(cfg.trained_model_dir),
            expect_step=int(state.step),
        )
    if begin_epoch == 0 and cfg.get("pretrain", ""):
        from .checkpoint import load_pretrain

        params, ok = load_pretrain(cfg.pretrain, {"params": state.params})
        if ok:
            state = state.replace(params=params["params"])
    if is_chief():
        save_trained_config(cfg)

    train_ds = make_dataset(cfg, "train")
    if mesh is not None:
        from ..parallel.sharding import shard_bank

        # globally permute before sharding so every shard is a uniform
        # sample of the whole scene (same rationale as trainer.fit)
        bank_rays, bank_rgbs = train_ds.ray_bank()
        perm = np.random.default_rng(seed).permutation(bank_rays.shape[0])
        bank = shard_bank(bank_rays[perm], bank_rgbs[perm], mesh)
        # the shard_map step returns a mesh-replicated state; placing the
        # initial state the same way makes step 1 match the steady-state
        # layout, so ONE executable (lazy or AOT) serves the whole run
        from jax.sharding import NamedSharding, PartitionSpec

        state = jax.device_put(state, NamedSharding(mesh, PartitionSpec()))
    else:
        bank = tuple(jax.device_put(a) for a in train_ds.ray_bank())
    # AOT: both phase variants of the burst executable start compiling on
    # host threads NOW, overlapping the test-dataset load below and the
    # first warm bursts — the carve-phase program no longer compiles
    # serially at the phase switch (the round-5 warmup tax)
    trainer.aot_register_steps(state, bank, base_key)
    test_ds = make_dataset(cfg, "test")
    trainer.aot_register_render(state, int(test_ds.H) * int(test_ds.W))

    epochs = int(cfg.train.epoch)
    ep_iter = int(cfg.get("ep_iter", 500))
    if ep_iter <= 0:
        ep_iter = max(1, int(bank[0].shape[0]) // trainer.n_rays)
    save_ep = int(cfg.get("save_ep", 40))
    save_latest_ep = int(cfg.get("save_latest_ep", 10))
    eval_ep = int(cfg.get("eval_ep", 10))
    log_interval = int(cfg.get("log_interval", 20))

    # resilience (docs/robustness.md): finite-loss guard on the fetched
    # stats, bounded divergence rollback, SIGTERM -> latest/ flush + exit
    rcfg = cfg.get("resil", {})
    finite_guard = bool(rcfg.get("finite_guard", True))
    max_rollbacks = int(rcfg.get("max_rollbacks", 2))
    guard = (PreemptionGuard.install()
             if bool(rcfg.get("preempt_sigterm", True)) else None)
    rollbacks = 0

    t_fit_start = time.time()
    try:
        epoch = begin_epoch
        while epoch < epochs:
            recorder.epoch = epoch
            host_step = int(state.step)
            step_before = host_step
            t_epoch = time.time()
            try:
                state, host_step = _ngp_epoch_steps(
                    trainer, state, bank, base_key, recorder, schedule,
                    emitter, epoch, ep_iter, log_interval, host_step,
                    finite_guard=finite_guard, guard=guard, log=log,
                )
            except DivergenceError as err:
                rollbacks += 1
                template = getattr(err, "state", state)
                if rollbacks > max_rollbacks or not has_checkpoint(
                    cfg.trained_model_dir
                ):
                    raise  # nothing to roll back to, or the budget is spent
                report("train.loss", "rollback", step=err.step,
                       detail=f"rollback {rollbacks}/{max_rollbacks}")
                log(f"non-finite loss at step {err.step}: rolling back to "
                    f"the last good checkpoint ({rollbacks}/{max_rollbacks})")
                state, epoch, rec_state = load_model(
                    cfg.trained_model_dir, template
                )
                if rec_state:
                    recorder.load_state_dict(rec_state)
                # re-sync the warm/carve phase to the RESTORED state (the
                # diverged run's host counters are stale)
                trainer._host_step = None
                trainer.restore_phase(
                    load_phase_state(cfg.trained_model_dir),
                    expect_step=int(state.step),
                )
                continue
            wall = time.time() - t_epoch
            emitter.emit(
                "epoch", epoch=epoch, steps=host_step - step_before,
                wall_s=wall,
                steps_per_sec=(host_step - step_before) / max(wall, 1e-9),
            )
            sample_memory(step=host_step, epoch=epoch)
            emitter.emit(
                "heartbeat", wall_s=time.time() - t_fit_start,
                step=host_step, epoch=epoch,
            )
            chief = is_chief()
            saving = (
                (epoch + 1) % save_ep == 0
                or (epoch + 1) % save_latest_ep == 0
            )
            if saving:
                barrier("pre_save")
                if chief and (epoch + 1) % save_ep == 0:
                    save_model_with_retry(
                        cfg, cfg.trained_model_dir, state, epoch,
                        recorder.state_dict(), latest=False, log=log,
                        phase_state=trainer.phase_state())
                if chief and (epoch + 1) % save_latest_ep == 0:
                    save_model_with_retry(
                        cfg, cfg.trained_model_dir, state, epoch,
                        recorder.state_dict(), latest=True, log=log,
                        phase_state=trainer.phase_state())
                barrier("post_save")
            if chief and (epoch + 1) % eval_ep == 0 and evaluator is not None:
                result = trainer.val(state, test_ds, evaluator, log=log)
                if result:
                    recorder.record("val", step=epoch, stats=result)
            if guard is not None and guard.triggered:
                # preemption: one atomic latest/ flush carrying the phase
                # sidecar, then a clean exit — the resumed run restores
                # this state bitwise and re-enters the exact phase
                barrier("pre_save")
                if chief:
                    save_model_with_retry(
                        cfg, cfg.trained_model_dir, state, epoch,
                        recorder.state_dict(), latest=True, log=log,
                        phase_state=trainer.phase_state())
                barrier("post_save")
                log("SIGTERM: latest checkpoint flushed; exiting")
                break
            epoch += 1
    finally:
        if guard is not None:
            guard.uninstall()
        trainer.profile.stop()
        emitter.close()
    return state
