"""Checkpoint I/O on Orbax with the reference's retention semantics.

Parity with `src/utils/net_utils.py:288-457`: bundle {params, opt_state, step,
epoch, recorder} per save; ``latest`` updated every ``save_latest_ep`` epochs;
numbered epoch checkpoints every ``save_ep`` epochs with rolling retention of
the most recent 5 (net_utils.py:337-343); full resume restores the bundle and
begin-epoch; weights-only load with epoch selection for eval
(net_utils.py:346-379); ``pretrain`` warm-start loading params only.
"""

from __future__ import annotations

import os
import re
import shutil

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..resil import fault_point, report, retry_params, with_retry

KEEP_EPOCHS = 5  # net_utils.py:337-343


def _abs(path: str) -> str:
    return os.path.abspath(path)


# -- param-tree key surgery (net_utils.py:382-415) ---------------------------
# The reference ships flat state-dict key remappers so checkpoints trained
# under a different module nesting (a wrapper prefix, a renamed branch) can
# still be loaded. Same capability on pytrees: operate on "/"-joined paths.

def _flatten(params):
    from flax.traverse_util import flatten_dict

    return flatten_dict(params, sep="/")


def _unflatten(flat):
    from flax.traverse_util import unflatten_dict

    return unflatten_dict(flat, sep="/")


def remove_param_prefix(params, prefix: str):
    """Strip ``prefix`` from every matching "/"-joined param path
    (net_utils.py:382-389)."""
    flat = _flatten(params)
    return _unflatten({
        (k[len(prefix):] if k.startswith(prefix) else k): v
        for k, v in flat.items()
    })


def add_param_prefix(params, prefix: str):
    """Prepend ``prefix`` to every param path (net_utils.py:392-396)."""
    return _unflatten({prefix + k: v for k, v in _flatten(params).items()})


def replace_param_prefix(params, orig_prefix: str, prefix: str):
    """Rewrite ``orig_prefix`` → ``prefix`` on matching param paths
    (net_utils.py:399-406)."""
    flat = _flatten(params)
    return _unflatten({
        (prefix + k[len(orig_prefix):] if k.startswith(orig_prefix) else k): v
        for k, v in flat.items()
    })


def remove_param_layers(params, layers):
    """Drop every param whose path starts with one of ``layers``
    (net_utils.py:409-415) — e.g. heads excluded from a warm start."""
    flat = _flatten(params)
    return _unflatten({
        k: v for k, v in flat.items()
        if not any(k.startswith(layer) for layer in layers)
    })


def _bundle(state, epoch: int, recorder_state: dict | None):
    rs = recorder_state or {}
    bundle = {
        "params": state.params,
        "opt_state": state.opt_state,
        "step": np.asarray(state.step),
        "epoch": np.asarray(epoch),
        # fixed schema so save/restore templates always structure-match
        "recorder": {
            "step": np.asarray(int(rs.get("step", 0))),
            "epoch": np.asarray(int(rs.get("epoch", 0))),
        },
    }
    # NGP warm-start: the live occupancy grid is STATE (a resumed run that
    # re-warms it from scratch re-pays 100+ s of grid discovery and
    # re-enters the warm phase — docs/compilation.md). Save and restore
    # both derive their template from the caller's state object, so the
    # schema stays matched per state type: legacy TrainStates never see
    # the key, NGPTrainStates always do.
    grid = getattr(state, "grid_ema", None)
    if grid is not None:
        bundle["grid_ema"] = grid
    return bundle


def _recorder_sidecar(model_dir: str, name: str) -> str:
    return os.path.join(model_dir, f"{name}_recorder.json")


def _phase_sidecar(model_dir: str, name: str) -> str:
    return os.path.join(model_dir, f"{name}_phase.json")


def save_model(model_dir: str, state, epoch: int, recorder_state=None,
               latest: bool = False, phase_state=None) -> str:
    """Save a checkpoint bundle; prune numbered checkpoints to KEEP_EPOCHS.

    ``phase_state``: the NGP trainer's host-side warm/carve phase counters
    (``NGPTrainer.phase_state()``) — a small JSON sidecar like the
    recorder's, so a resumed run re-enters the exact phase it left instead
    of re-estimating it from occupancy."""
    import json

    # The NGP step executables donate their input state: the dispatch in
    # flight writes its output IN PLACE into the aliased buffers. A save
    # issued before that dispatch lands can snapshot a torn bundle (stale
    # step alongside half-written grid rows), so force the sync here —
    # saving is a host round-trip anyway.
    state = jax.block_until_ready(state)

    os.makedirs(model_dir, exist_ok=True)
    name = "latest" if latest else str(epoch)
    path = _abs(os.path.join(model_dir, name))
    # fault point sits BEFORE the rmtree: a kill here leaves the previous
    # checkpoint intact (the atomicity a preempted save must preserve)
    fault_point("checkpoint.save", path=path)
    if os.path.exists(path):
        shutil.rmtree(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, _bundle(state, epoch, recorder_state))
    ckptr.wait_until_finished()
    # the torn-dir window: a kill between the bundle landing and the
    # sidecars leaves a loadable bundle with stale/absent sidecars
    fault_point("checkpoint.save.sidecar", path=path)

    # full recorder state (incl. variable-key SmoothedValue trees, which
    # the fixed-schema orbax bundle can't structure-match) rides in a
    # sidecar JSON, written atomically AFTER the bundle so a crash can
    # only leave a loadable bundle with a stale/absent sidecar
    if recorder_state:
        sidecar = _recorder_sidecar(model_dir, name)
        tmp = sidecar + ".tmp"
        with open(tmp, "w") as f:
            json.dump(recorder_state, f)
        os.replace(tmp, sidecar)
    if phase_state:
        sidecar = _phase_sidecar(model_dir, name)
        tmp = sidecar + ".tmp"
        with open(tmp, "w") as f:
            json.dump(phase_state, f)
        os.replace(tmp, sidecar)

    if not latest:
        numbered = sorted(
            (int(d) for d in os.listdir(model_dir) if re.fullmatch(r"\d+", d))
        )
        for old in numbered[:-KEEP_EPOCHS]:
            shutil.rmtree(os.path.join(model_dir, str(old)), ignore_errors=True)
            for sidecar in (_recorder_sidecar(model_dir, str(old)),
                            _phase_sidecar(model_dir, str(old))):
                if os.path.exists(sidecar):
                    os.remove(sidecar)
    return path


def _available_epochs(model_dir: str) -> list[int]:
    if not os.path.isdir(model_dir):
        return []
    return sorted(
        int(d) for d in os.listdir(model_dir) if re.fullmatch(r"\d+", d)
    )


def save_model_with_retry(cfg, model_dir: str, state, epoch: int,
                          recorder_state=None, *, log=print, **kw) -> bool:
    """``save_model`` under the bounded retry ladder (``resil:`` knobs).

    An exhausted ladder is logged and ABSORBED: losing one cadence save
    must not kill a healthy run — the next cadence saves again, and a
    resume falls back to the previous epoch. The ``retry`` telemetry rows
    (status ``exhausted``) still record the loss for ``tlm_report``."""
    try:
        with_retry(
            lambda: save_model(model_dir, state, epoch, recorder_state,
                               **kw),
            point="checkpoint.save",
            **retry_params(cfg),
        )
        return True
    except OSError as exc:
        log(f"warning: checkpoint save (epoch {epoch}) failed after "
            f"retries: {exc} — training continues")
        return False


def has_checkpoint(model_dir: str) -> bool:
    """Anything resumable on disk? The divergence-rollback path must not
    "restore" from an empty dir — ``load_model`` would hand back its
    template (the poisoned live state) unchanged."""
    return bool(
        os.path.isdir(os.path.join(model_dir, "latest"))
        or _available_epochs(model_dir)
    )


def _restore_bundle(target: str, template: dict, ckptr):
    try:
        return ckptr.restore(_abs(target), target=template)
    except Exception:
        if "grid_ema" not in template:
            raise
        # legacy NGP checkpoint (saved before the grid rode the bundle):
        # restore what it has; the grid keeps the caller's warm start
        legacy = dict(template)
        legacy.pop("grid_ema")
        return ckptr.restore(_abs(target), target=legacy)


def load_model(model_dir: str, state, epoch: int = -1):
    """Full resume (net_utils.py:288-320). Returns (state, begin_epoch,
    recorder_state) or (state, 0, None) when nothing to resume.

    Resilience: transient read errors retry with backoff, and a torn
    ``latest/`` (a save killed mid-write) falls back to the newest
    numbered epoch — each fallback is reported as a detected ``fault``
    row. An explicitly pinned epoch gets no fallback: the caller asked
    for exactly that checkpoint."""
    candidates: list[str] = []
    if os.path.isdir(os.path.join(model_dir, "latest")) and epoch == -1:
        candidates.append(os.path.join(model_dir, "latest"))
    epochs = _available_epochs(model_dir)
    if epochs:
        pick = epoch if epoch != -1 and epoch in epochs else epochs[-1]
        candidates.append(os.path.join(model_dir, str(pick)))
        if epoch == -1:  # older epochs, newest first, as last resorts
            candidates += [
                os.path.join(model_dir, str(e))
                for e in reversed(epochs)
                if e != pick
            ]
    if not candidates:
        return state, 0, None

    ckptr = ocp.StandardCheckpointer()
    template = _bundle(state, 0, {})
    restored, target = None, None
    for i, cand in enumerate(candidates):
        def _attempt(cand=cand):
            fault_point("checkpoint.load", path=cand)
            return _restore_bundle(cand, template, ckptr)

        try:
            restored = with_retry(_attempt, point="checkpoint.load")
            target = cand
            break
        except Exception as exc:
            if i + 1 >= len(candidates):
                raise
            report(
                "checkpoint.load", "torn", path=cand,
                detail=f"{type(exc).__name__}: falling back to "
                       f"{os.path.basename(candidates[i + 1])}",
            )
    new_state = state.replace(
        params=restored["params"],
        opt_state=restored["opt_state"],
        step=int(restored["step"]),
    )
    if "grid_ema" in restored:
        # NGP: the live occupancy grid resumes with the params (warm-start
        # — see _bundle); only present when the caller's state carries it
        new_state = new_state.replace(grid_ema=restored["grid_ema"])
    recorder = {k: int(v) for k, v in restored["recorder"].items()}
    # the sidecar carries the full recorder state (SmoothedValue
    # totals/counts); merge it over the bundle's fixed {step, epoch}
    sidecar = _recorder_sidecar(model_dir, os.path.basename(target))
    if os.path.exists(sidecar):
        import json

        try:
            with open(sidecar) as f:
                recorder = {**recorder, **json.load(f)}
        except (OSError, ValueError):
            pass  # stale/torn sidecar: resume with step/epoch only
    return new_state, int(restored["epoch"]) + 1, recorder


def load_phase_state(model_dir: str, epoch: int = -1) -> dict | None:
    """The NGP phase sidecar matching what ``load_model`` would resume
    (``latest`` unless a numbered epoch is pinned), or None — a missing or
    torn sidecar degrades to the trainer's occupancy-based estimate."""
    if os.path.isdir(os.path.join(model_dir, "latest")) and epoch == -1:
        name = "latest"
    else:
        epochs = _available_epochs(model_dir)
        if not epochs:
            return None
        name = str(epoch if epoch != -1 and epoch in epochs else epochs[-1])
    sidecar = _phase_sidecar(model_dir, name)
    if not os.path.exists(sidecar):
        return None
    import json

    try:
        with open(sidecar) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def load_network(model_dir: str, params, epoch: int = -1):
    """Weights-only load with epoch selection (net_utils.py:346-379).
    Returns (params, loaded_epoch) — params unchanged if no checkpoint."""
    target, picked = None, -1
    epochs = _available_epochs(model_dir)
    if epoch == -1:
        if os.path.isdir(os.path.join(model_dir, "latest")):
            target, picked = os.path.join(model_dir, "latest"), -1
        elif epochs:
            target, picked = os.path.join(model_dir, str(epochs[-1])), epochs[-1]
    elif epochs and epoch in epochs:
        target, picked = os.path.join(model_dir, str(epoch)), epoch
    if target is None:
        return params, -1

    # accept either the raw param tree or the {"params": ...} wrapper
    wrapped = isinstance(params, dict) and set(params.keys()) == {"params"}
    inner = params["params"] if wrapped else params
    # partial restore against the caller's template: only the "params" item
    # of the bundle is read (opt_state/step/recorder are skipped), and each
    # leaf restores with the template's dtype/shape/sharding — topology-safe
    # on sharded multi-host restores and free of the orbax "sharding info
    # not provided" warning that blind PyTreeCheckpointer.restore emits
    template = {"params": inner}
    ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())

    def _restore():
        fault_point("checkpoint.load", path=target)
        return ckptr.restore(
            _abs(target),
            args=ocp.args.PyTreeRestore(
                item=template,
                transforms={},
                restore_args=ocp.checkpoint_utils.construct_restore_args(
                    template
                ),
            ),
        )

    restored = with_retry(_restore, point="checkpoint.load")
    loaded = jax.tree.map(
        lambda t, r: np.asarray(r).astype(t.dtype).reshape(t.shape),
        inner,
        restored["params"],
    )
    return ({"params": loaded} if wrapped else loaded), picked


def save_pretrain(pretrain_dir: str, params):
    os.makedirs(pretrain_dir, exist_ok=True)
    path = _abs(os.path.join(pretrain_dir, "pretrain"))
    if os.path.exists(path):
        shutil.rmtree(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, {"params": params})
    ckptr.wait_until_finished()


def load_pretrain(pretrain_dir: str, params):
    """Warm-start params only (net_utils.py:429-450)."""
    path = os.path.join(pretrain_dir, "pretrain")
    if not os.path.isdir(path):
        return params, False
    ckptr = ocp.StandardCheckpointer()
    restored = with_retry(
        lambda: ckptr.restore(_abs(path), target={"params": params}),
        point="checkpoint.load",
    )
    return restored["params"], True


def save_trained_config(cfg):
    """Provenance snapshot: merged YAML + command line (net_utils.py:418-426)."""
    import sys

    if not os.environ.get("JAX_DISABLE_SAVE_CONFIG"):
        os.makedirs(cfg.trained_config_dir, exist_ok=True)
        with open(os.path.join(cfg.trained_config_dir, "train_config.yaml"), "w") as f:
            f.write("# cmd: " + " ".join(sys.argv) + "\n")
            f.write(cfg.dump())
