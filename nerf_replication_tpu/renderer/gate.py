"""Unified whole-image render gate: single-device or sequence-parallel.

One factory used by every full-image surface — the eval CLIs (run.py),
in-training validation (train/trainer.py:Trainer.val), and the video
renderer — so ``eval.sharded: true`` behaves identically everywhere
(VERDICT r2 #5: validation on a pod must not render 800² images on the
chief chip alone when the sequence-parallel path exists).

Single-device (or ``eval.sharded`` unset): the renderer's own chunked path,
which honors per-batch near/far. Sharded on a multi-device runtime: the ray
axis of each image is sharded over the mesh's data axis (sequence
parallelism — parallel/sequence.py) with in-shard chunking for memory;
near/far are baked jit-static, so per-batch bounds are checked against the
baked ones instead of silently rendering the wrong depth range.
"""

from __future__ import annotations

import numpy as np


class BakedBoundsError(ValueError):
    """A render surface with jit-static (baked) near/far received a request
    carrying different bounds.

    Raised instead of a bare ValueError so callers holding a baked
    executable set — the sharded gate, the serve engine's bucketed
    executables — surface ONE unambiguous error naming both sides, rather
    than a comparison buried mid-traceback."""


def check_baked_bounds(baked_near, baked_far, near, far,
                       surface: str = "eval.sharded render gate") -> None:
    """Reject a near/far pair that differs from the baked ones.

    Both sides are coerced through float32 before comparing: batches carry
    np.float32 values, so e.g. near=0.1 (not exactly f32-representable)
    would otherwise mismatch on every image. ``surface`` names the baked
    executable set in the error so a serving stack with several of them
    (gate, engine buckets) points at the right one."""
    bn, bf = float(np.float32(baked_near)), float(np.float32(baked_far))
    rn, rf = float(np.float32(near)), float(np.float32(far))
    if bn != rn or bf != rf:
        raise BakedBoundsError(
            f"{surface}: baked bounds near={bn:g} far={bf:g} do not match "
            f"the requested bounds near={rn:g} far={rf:g} — rebuild the "
            "render surface for the new bounds, or fix the batch"
        )


def _annotated(render):
    """Host-side profiler scope around every whole-image render, so eval
    time is attributable on an xplane trace captured during validation."""
    from ..obs import annotate

    def wrapped(params, batch):
        with annotate("render/full_image"):
            return render(params, batch)

    return wrapped


def full_image_render_fn(cfg, network, renderer, test_ds, use_grid=False):
    """Return ``render(params, batch) -> out`` for whole test images.

    ``use_grid`` selects the occupancy-accelerated ESS+ERT march (a grid
    must already be loaded on the renderer).
    """
    import jax

    sharded = (
        bool(cfg.get("eval", {}).get("sharded", False))
        and jax.device_count() > 1
    )
    if not sharded:
        if use_grid:
            return _annotated(renderer.render_accelerated)
        return _annotated(
            lambda params, batch: renderer.render_chunked(params, batch)
        )

    import jax.numpy as jnp

    from ..parallel.mesh import make_mesh_from_cfg
    from ..parallel.sequence import (
        build_sequence_parallel_march,
        build_sequence_parallel_renderer,
    )

    # the sharded builders bake near/far as jit-static march bounds
    near, far = float(test_ds.near), float(test_ds.far)

    def check_bounds(batch):
        # the single-device paths honor per-batch bounds; the sharded
        # executables can't — reject a mismatch instead of silently
        # rendering at the wrong depth range
        check_baked_bounds(near, far, batch["near"], batch["far"])

    mesh = make_mesh_from_cfg(cfg)
    if use_grid:
        march = build_sequence_parallel_march(
            mesh, network, renderer.march_options, near=near, far=far,
            chunk_size=renderer.march_options.chunk_size,
        )

        def render(params, batch):
            check_bounds(batch)
            out = march(params, jnp.asarray(batch["rays"]),
                        renderer.occupancy_grid, renderer.grid_bbox)
            renderer.accumulate_truncated(out.pop("n_truncated"))
            return out

        return _annotated(render)

    # reuse the renderer's own eval options — a second from_cfg would be
    # a divergence point if Renderer ever adjusts them
    options = renderer.eval_options
    sp = build_sequence_parallel_renderer(
        mesh, network, options, near=near, far=far,
        chunk_size=options.chunk_size,
    )

    def render(params, batch):
        check_bounds(batch)
        return sp(params, jnp.asarray(batch["rays"]))

    return _annotated(render)
