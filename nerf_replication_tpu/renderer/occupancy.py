"""Occupancy-grid baking, I/O, and lookup.

Capability parity with the reference's grid subsystem (occupancy_grid.py:15-82,
volume_renderer.py:249-265): sample an R³ voxel grid of the scene bbox at
2×2×2 sub-positions per voxel, query the coarse network's density, and mark a
voxel occupied when ANY sub-sample's σ exceeds the threshold.

TPU-native differences: the density sweep is a single jitted `lax.map` over
fixed-size voxel batches (no host↔device loop over 4096-point batches like
occupancy_grid.py:48-61), and the artifact is a compressed .npz carrying the
grid together with its bbox/threshold provenance.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..resil import fault_point, report, verify_checksum, with_retry
from ..resil import write_checksum as _write_checksum

SUBSAMPLES = (2, 2, 2)  # occupancy_grid.py:28


def voxel_sample_points(bbox: np.ndarray, resolution: int) -> np.ndarray:
    """[R³, n_sub, 3] world-space sample positions: each voxel's base corner
    plus a sub-grid spanning the voxel (occupancy_grid.py:30-41)."""
    lo, hi = np.asarray(bbox[0], np.float32), np.asarray(bbox[1], np.float32)
    voxel_size = (hi - lo) / resolution
    axes = [np.linspace(0.0, 1.0, s) * voxel_size[d] for d, s in enumerate(SUBSAMPLES)]
    sub = np.stack(np.meshgrid(*axes, indexing="ij"), -1).reshape(-1, 3)

    ranges = [np.arange(resolution)] * 3
    grid_idx = np.stack(np.meshgrid(*ranges, indexing="ij"), -1).astype(np.float32)
    base = lo + grid_idx * voxel_size  # [R,R,R,3]
    pts = base.reshape(-1, 1, 3) + sub[None, :, :]
    return pts.astype(np.float32)


def bake_occupancy_grid(params, network, cfg) -> np.ndarray:
    """bool [R,R,R]: any sub-sample density over the threshold
    (occupancy_grid.py:65-70). Densities come from the COARSE network with
    zero viewdirs, as in the reference (occupancy_grid.py:57-59)."""
    ta = cfg.task_arg
    resolution = int(ta.occupancy_grid_res)
    threshold = float(ta.occupancy_grid_threshold)
    batch = int(ta.get("occupancy_grid_batch_size", 4096))
    bbox = np.asarray(cfg.train_dataset.scene_bbox, np.float32)

    pts = voxel_sample_points(bbox, resolution)  # [V, n_sub, 3]
    n_voxels, n_sub = pts.shape[0], pts.shape[1]
    n_batches = -(-n_voxels // batch)
    pad = n_batches * batch - n_voxels
    pts_p = np.pad(pts, ((0, pad), (0, 0), (0, 0))).reshape(
        n_batches, batch, n_sub, 3
    )

    # one-shot offline bake: traced once per bake invocation and thrown
    # away — an AOT registry entry would outlive the only call it serves
    @jax.jit  # graftlint: ok(aot: one-shot bake, no steady-state dispatch)
    def sweep(params, pts_p):
        def body(p):
            dirs = jnp.zeros((p.shape[0], 3), jnp.float32)
            raw = network.apply(params, p, dirs, model="coarse")
            return jnp.any(jax.nn.relu(raw[..., 3]) > threshold, axis=-1)

        return jax.lax.map(body, pts_p)

    # audited (graftlint R1): the single designed sync of a ONE-SHOT bake —
    # the whole sweep runs as one jitted lax.map and this pull lands the
    # finished grid; nothing per-step ever re-enters this path
    occupied = np.asarray(sweep(params, jnp.asarray(pts_p)))  # graftlint: ok(host-sync)
    occupied = occupied.reshape(-1)[:n_voxels]
    return occupied.reshape(resolution, resolution, resolution)


def default_grid_path(cfg_file: str) -> str:
    """logs/<config_name>/occupancy_grid.npz — the reference's artifact layout
    (occupancy_grid.py:72-75), with .npz instead of .pt."""
    name = os.path.splitext(os.path.basename(cfg_file))[0]
    return os.path.join("logs", name, "occupancy_grid.npz")


# ---------------------------------------------------------------------------
# Mip pyramid: coarse levels are max-pool (any-) reductions of the fine bool
# grid. The hierarchical packed march (packed_march.py) tests each sample's
# PARENT coarse cell (fine voxel index // factor) before admitting it to the
# fine sweep + global sort, so a coarse level must be a strict superset of
# the fine grid: fine-occupied ⇒ coarse-occupied, which the any-reduce
# guarantees. Resolution not divisible by the factor pads with False (the
# pad lies past the +bbox face and is never a parent of an in-range voxel).
# ---------------------------------------------------------------------------

PYRAMID_VERSION = 1
# reduction factor of each coarse level relative to the FINE grid; the
# traversal marches the coarsest (last) level, the intermediate level exists
# for stats/debug and cheap future re-tuning of the traversal factor
PYRAMID_FACTORS = (2, 4)


def _reduce_any(grid, factor: int, xp):
    """Max-pool (any-) reduce a bool [R,R,R] grid by ``factor`` per axis;
    ``xp`` is numpy (host bake) or jax.numpy (in-graph derivation)."""
    r = grid.shape[0]
    rp = -(-r // factor) * factor
    if rp != r:
        grid = xp.pad(grid, [(0, rp - r)] * 3)
    rc = rp // factor
    g = grid.reshape(rc, factor, rc, factor, rc, factor)
    return xp.any(g, axis=(1, 3, 5))


def coarse_from_grid(grid: jax.Array, factor: int) -> jax.Array:
    """Traced any-reduce used INSIDE march executables.

    Deriving the coarse level in-graph (an R³ bool reduce, trivial next to
    the sweep it gates) keeps every executable signature at
    ``(params, rays, grid, bbox)`` — serve buckets, AOT registrations, and
    the NGP step donate the SAME fine grid they always did, and the live
    NGP grid (re-carved every maintenance step) gets a coarse level that
    can never go stale. Provably identical to the baked artifact levels:
    both run ``_reduce_any`` with the same factor."""
    return _reduce_any(grid, factor, jnp)


def build_pyramid(grid: np.ndarray) -> list[np.ndarray]:
    """Host-side ``[fine, coarse@2, coarse@4]`` mip stack of a bool grid."""
    grid = np.asarray(grid, bool)
    return [grid] + [_reduce_any(grid, f, np) for f in PYRAMID_FACTORS]


def save_occupancy_grid(path: str, grid: np.ndarray, bbox, threshold: float) -> str:
    """Write the VERSIONED pyramid artifact: the fine grid plus its baked
    coarse levels. ``grid``/``bbox``/``threshold`` keys keep the legacy
    layout so pre-pyramid readers (check_grid.py, load_occupancy_grid)
    work unchanged."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    levels = build_pyramid(grid)
    np.savez_compressed(
        path,
        grid=levels[0],
        bbox=np.asarray(bbox, np.float32),
        threshold=np.float32(threshold),
        pyramid_version=np.int32(PYRAMID_VERSION),
        pyramid_factors=np.asarray(PYRAMID_FACTORS, np.int32),
        **{f"level_{i}": lv for i, lv in enumerate(levels[1:], start=1)},
    )
    _write_checksum(path)
    return path


def load_occupancy_grid(path: str):
    """(grid bool [R,R,R], bbox [2,3]) or raises FileNotFoundError."""
    with np.load(path) as z:
        return np.asarray(z["grid"], bool), np.asarray(z["bbox"], np.float32)


def load_occupancy_pyramid(path: str):
    """(levels ``[fine, coarse@2, coarse@4]``, bbox [2,3]).

    Legacy flat-grid ``.npz`` files (no ``pyramid_version`` key) upgrade
    transparently: the pyramid is rebuilt on load from the fine grid. A
    version/factor mismatch (artifact baked by a different pyramid layout)
    also rebuilds rather than trusting stale coarse levels — the fine grid
    is always the source of truth.

    Resilience: transient read errors retry with backoff; a checksum
    mismatch or an unparseable archive (truncated ``.npz``) raises
    ``OSError`` after a detected-fault row, so callers rebuild or fall
    back to the chunked path instead of consuming garbage."""
    if verify_checksum(path) is False:
        report("occupancy.load", "checksum", path=path)
        raise OSError(f"corrupt occupancy artifact (checksum mismatch): {path}")

    def _read():
        fault_point("occupancy.load", path=path)
        with np.load(path) as z:
            grid = np.asarray(z["grid"], bool)
            bbox = np.asarray(z["bbox"], np.float32)
            baked_ok = (
                "pyramid_version" in z
                and int(z["pyramid_version"]) == PYRAMID_VERSION
                and tuple(np.asarray(z["pyramid_factors"]).tolist())
                == PYRAMID_FACTORS
            )
            if baked_ok:
                levels = [grid] + [
                    np.asarray(z[f"level_{i}"], bool)
                    for i in range(1, len(PYRAMID_FACTORS) + 1)
                ]
            else:
                levels = build_pyramid(grid)
        return levels, bbox

    try:
        return with_retry(_read, point="occupancy.load")
    except OSError:
        raise
    except Exception as exc:  # torn zip member / bad header / missing key
        report("occupancy.load", "torn", path=path,
               detail=f"{type(exc).__name__}")
        raise OSError(
            f"corrupt occupancy artifact: {path} ({type(exc).__name__})"
        ) from exc


def pyramid_stats(levels: list[np.ndarray]) -> dict:
    """Per-level occupancy fractions — the headline traversal quantity
    (candidate stream shrinks with the COARSEST level's occupancy)."""
    return {
        f"level_{i}_occ": float(lv.mean()) for i, lv in enumerate(levels)
    }


def occupancy_stats(grid: np.ndarray) -> dict:
    """Sanity-check stats (parity: check_grid.py:20-31)."""
    assert grid.dtype == np.bool_, f"grid dtype must be bool, got {grid.dtype}"
    assert grid.ndim == 3, f"grid must be 3-D, got shape {grid.shape}"
    total = grid.size
    occupied = int(grid.sum())
    return {
        "shape": tuple(grid.shape),
        "occupied": occupied,
        "total": total,
        "occupancy_pct": 100.0 * occupied / total,
    }


def world_to_voxel(pts: jax.Array, bbox: jax.Array, resolution: int) -> jax.Array:
    """World points → integer voxel indices, clamped into the grid (the
    reference clamps to the bbox before indexing, volume_renderer.py:261-265,
    so out-of-bounds points land in boundary voxels).

    Deliberate divergence: the reference scales by ``resolution - 1``
    (volume_renderer.py:264) while the bake lays voxels out on a stride of
    ``extent / resolution`` (occupancy_grid.py:25) — a mismatch that shifts
    lookups down by up to one voxel near the +bbox face. We index with the
    bake's own layout: ``floor(u · resolution)`` clamped into range."""
    lo, hi = bbox[0], bbox[1]
    normalized = (jnp.clip(pts, lo, hi) - lo) / (hi - lo)
    return jnp.clip(
        jnp.floor(normalized * resolution).astype(jnp.int32), 0, resolution - 1
    )
