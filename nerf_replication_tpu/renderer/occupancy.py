"""Occupancy-grid baking, I/O, and lookup.

Capability parity with the reference's grid subsystem (occupancy_grid.py:15-82,
volume_renderer.py:249-265): sample an R³ voxel grid of the scene bbox at
2×2×2 sub-positions per voxel, query the coarse network's density, and mark a
voxel occupied when ANY sub-sample's σ exceeds the threshold.

TPU-native differences: the density sweep is a single jitted `lax.map` over
fixed-size voxel batches (no host↔device loop over 4096-point batches like
occupancy_grid.py:48-61), and the artifact is a compressed .npz carrying the
grid together with its bbox/threshold provenance.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

SUBSAMPLES = (2, 2, 2)  # occupancy_grid.py:28


def voxel_sample_points(bbox: np.ndarray, resolution: int) -> np.ndarray:
    """[R³, n_sub, 3] world-space sample positions: each voxel's base corner
    plus a sub-grid spanning the voxel (occupancy_grid.py:30-41)."""
    lo, hi = np.asarray(bbox[0], np.float32), np.asarray(bbox[1], np.float32)
    voxel_size = (hi - lo) / resolution
    axes = [np.linspace(0.0, 1.0, s) * voxel_size[d] for d, s in enumerate(SUBSAMPLES)]
    sub = np.stack(np.meshgrid(*axes, indexing="ij"), -1).reshape(-1, 3)

    ranges = [np.arange(resolution)] * 3
    grid_idx = np.stack(np.meshgrid(*ranges, indexing="ij"), -1).astype(np.float32)
    base = lo + grid_idx * voxel_size  # [R,R,R,3]
    pts = base.reshape(-1, 1, 3) + sub[None, :, :]
    return pts.astype(np.float32)


def bake_occupancy_grid(params, network, cfg) -> np.ndarray:
    """bool [R,R,R]: any sub-sample density over the threshold
    (occupancy_grid.py:65-70). Densities come from the COARSE network with
    zero viewdirs, as in the reference (occupancy_grid.py:57-59)."""
    ta = cfg.task_arg
    resolution = int(ta.occupancy_grid_res)
    threshold = float(ta.occupancy_grid_threshold)
    batch = int(ta.get("occupancy_grid_batch_size", 4096))
    bbox = np.asarray(cfg.train_dataset.scene_bbox, np.float32)

    pts = voxel_sample_points(bbox, resolution)  # [V, n_sub, 3]
    n_voxels, n_sub = pts.shape[0], pts.shape[1]
    n_batches = -(-n_voxels // batch)
    pad = n_batches * batch - n_voxels
    pts_p = np.pad(pts, ((0, pad), (0, 0), (0, 0))).reshape(
        n_batches, batch, n_sub, 3
    )

    @jax.jit
    def sweep(params, pts_p):
        def body(p):
            dirs = jnp.zeros((p.shape[0], 3), jnp.float32)
            raw = network.apply(params, p, dirs, model="coarse")
            return jnp.any(jax.nn.relu(raw[..., 3]) > threshold, axis=-1)

        return jax.lax.map(body, pts_p)

    # audited (graftlint R1): the single designed sync of a ONE-SHOT bake —
    # the whole sweep runs as one jitted lax.map and this pull lands the
    # finished grid; nothing per-step ever re-enters this path
    occupied = np.asarray(sweep(params, jnp.asarray(pts_p)))  # graftlint: ok(host-sync)
    occupied = occupied.reshape(-1)[:n_voxels]
    return occupied.reshape(resolution, resolution, resolution)


def default_grid_path(cfg_file: str) -> str:
    """logs/<config_name>/occupancy_grid.npz — the reference's artifact layout
    (occupancy_grid.py:72-75), with .npz instead of .pt."""
    name = os.path.splitext(os.path.basename(cfg_file))[0]
    return os.path.join("logs", name, "occupancy_grid.npz")


def save_occupancy_grid(path: str, grid: np.ndarray, bbox, threshold: float) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez_compressed(
        path,
        grid=np.asarray(grid, bool),
        bbox=np.asarray(bbox, np.float32),
        threshold=np.float32(threshold),
    )
    return path


def load_occupancy_grid(path: str):
    """(grid bool [R,R,R], bbox [2,3]) or raises FileNotFoundError."""
    with np.load(path) as z:
        return np.asarray(z["grid"], bool), np.asarray(z["bbox"], np.float32)


def occupancy_stats(grid: np.ndarray) -> dict:
    """Sanity-check stats (parity: check_grid.py:20-31)."""
    assert grid.dtype == np.bool_, f"grid dtype must be bool, got {grid.dtype}"
    assert grid.ndim == 3, f"grid must be 3-D, got shape {grid.shape}"
    total = grid.size
    occupied = int(grid.sum())
    return {
        "shape": tuple(grid.shape),
        "occupied": occupied,
        "total": total,
        "occupancy_pct": 100.0 * occupied / total,
    }


def world_to_voxel(pts: jax.Array, bbox: jax.Array, resolution: int) -> jax.Array:
    """World points → integer voxel indices, clamped into the grid (the
    reference clamps to the bbox before indexing, volume_renderer.py:261-265,
    so out-of-bounds points land in boundary voxels).

    Deliberate divergence: the reference scales by ``resolution - 1``
    (volume_renderer.py:264) while the bake lays voxels out on a stride of
    ``extent / resolution`` (occupancy_grid.py:25) — a mismatch that shifts
    lookups down by up to one voxel near the +bbox face. We index with the
    bake's own layout: ``floor(u · resolution)`` clamped into range."""
    lo, hi = bbox[0], bbox[1]
    normalized = (jnp.clip(pts, lo, hi) - lo) / (hi - lo)
    return jnp.clip(
        jnp.floor(normalized * resolution).astype(jnp.int32), 0, resolution - 1
    )
