"""Volume renderer — the hot path, as pure jittable functions.

Capability parity with the reference's `src/models/nerf/renderer/
volume_renderer.py:8-247` (stratified sampling + perturb, `raw2outputs`
alpha compositing, `sample_pdf` inverse-CDF hierarchical sampling, coarse+fine
merge-and-sort), redesigned for XLA:

* No Python chunking loop in training — a 1024-ray × 256-sample batch is one
  fused graph of MXU matmuls. Full-image eval uses `lax.map` over fixed-size
  ray chunks (volume_renderer.py:160's memory capping, compiler-friendly).
* RNG is explicit: stratified jitter, density noise, and PDF draws each fold
  their own stream off the caller's key (SURVEY.md §7 "RNG discipline").
* Gradients do not flow through the hierarchical sample positions
  (`z_samples.detach()` → `lax.stop_gradient`, volume_renderer.py:216).

The math matches the reference formulas exactly (golden tests in
tests/test_renderer.py): dists scaled by ‖rays_d‖, sigmoid(rgb),
relu(sigma+noise), alpha = 1-exp(-σ·δ), transmittance via cumprod with the
1e-10 guard, white-background compositing, and the 1e-5/denominator guards in
the inverse CDF.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from .sampling import SamplingOptions, proposal_render_rays


@dataclass(frozen=True)
class RenderOptions:
    """Jit-static rendering configuration (frozen ⇒ hashable for jit)."""

    n_samples: int = 64
    n_importance: int = 128
    perturb: float = 1.0
    raw_noise_std: float = 0.0
    white_bkgd: bool = True
    lindisp: bool = False
    use_viewdirs: bool = True
    chunk_size: int = 8192
    remat: bool = False  # rematerialize MLP activations in backward (HBM↓)
    # learned sampling (cfg.sampling, renderer/sampling.py): mode
    # "proposal" replaces the coarse pass with the proposal-net resampler
    sampling: SamplingOptions = field(default_factory=SamplingOptions)

    @classmethod
    def from_cfg(cls, cfg, train: bool = True) -> "RenderOptions":
        ta = cfg.task_arg
        perturb = float(ta.get("perturb", 1.0))
        if not train:
            # the reference applies train-time perturb at eval unless
            # overridden (SURVEY.md §2.5) — we default eval to deterministic.
            perturb = float(ta.get("test_perturb", 0.0))
        return cls(
            n_samples=int(ta.N_samples),
            n_importance=int(ta.get("N_importance", 0)),
            perturb=perturb,
            raw_noise_std=float(ta.get("raw_noise_std", 0.0)),
            white_bkgd=bool(ta.get("white_bkgd", True)),
            lindisp=bool(ta.get("lindisp", False)),
            use_viewdirs=bool(ta.get("use_viewdirs", True)),
            chunk_size=int(ta.get("chunk_size", 8192)),
            remat=bool(ta.get("remat", False)) and train,
            sampling=SamplingOptions.from_cfg(cfg, train=train),
        )

    @property
    def fine_evals_per_ray(self) -> int:
        """Fine-MLP evaluations per ray this configuration costs — the
        number the proposal resampler exists to cut (BENCH_SAMPLING's
        headline column). Coarse+fine evaluates the fine network on the
        MERGED S_c + S_f sorted set (render_rays); proposal mode on the
        S_f resampled points alone."""
        if self.sampling.mode == "proposal":
            return self.sampling.n_fine
        if self.n_importance > 0:
            return self.n_samples + self.n_importance
        return 0  # coarse-only: the fine MLP never runs


def stratified_z_vals(
    key: jax.Array | None,
    near,
    far,
    n_rays: int,
    n_samples: int,
    perturb: float,
    lindisp: bool = False,
) -> jax.Array:
    """[n_rays, n_samples] depths: linspace in depth (or disparity) with
    per-bin uniform jitter when perturb > 0 (volume_renderer.py:168-181)."""
    t = jnp.linspace(0.0, 1.0, n_samples, dtype=jnp.float32)
    near = jnp.asarray(near, jnp.float32)
    far = jnp.asarray(far, jnp.float32)
    if lindisp:
        z = 1.0 / (1.0 / near * (1.0 - t) + 1.0 / far * t)
    else:
        z = near * (1.0 - t) + far * t
    z_vals = jnp.broadcast_to(z, (n_rays, n_samples))
    if perturb > 0.0 and key is not None:
        # perturb is a gate, not a scale: any positive value jitters across
        # the full bin (volume_renderer.py:175-181 semantics).
        mids = 0.5 * (z_vals[..., 1:] + z_vals[..., :-1])
        upper = jnp.concatenate([mids, z_vals[..., -1:]], -1)
        lower = jnp.concatenate([z_vals[..., :1], mids], -1)
        t_rand = jax.random.uniform(key, z_vals.shape, dtype=jnp.float32)
        z_vals = lower + (upper - lower) * t_rand
    return z_vals


def raw2outputs(
    raw: jax.Array,
    z_vals: jax.Array,
    rays_d: jax.Array,
    key: jax.Array | None = None,
    raw_noise_std: float = 0.0,
    white_bkgd: bool = False,
):
    """Alpha compositing (volume_renderer.py:20-80).

    raw [..., S, 4], z_vals [..., S], rays_d [..., 3] →
    (rgb_map [..., 3], depth_map [...], acc_map [...], weights [..., S]).
    """
    dists = z_vals[..., 1:] - z_vals[..., :-1]
    dists = jnp.concatenate(
        [dists, jnp.full_like(dists[..., :1], 1e10)], axis=-1
    )
    dists = dists * jnp.linalg.norm(rays_d[..., None, :], axis=-1)

    rgb = jax.nn.sigmoid(raw[..., :3])
    sigma_raw = raw[..., 3]
    if raw_noise_std > 0.0 and key is not None:
        sigma_raw = sigma_raw + (
            jax.random.normal(key, sigma_raw.shape, jnp.float32) * raw_noise_std
        )
    sigma = jax.nn.relu(sigma_raw)

    alpha = 1.0 - jnp.exp(-sigma * dists)
    trans = jnp.cumprod(
        jnp.concatenate(
            [jnp.ones_like(alpha[..., :1]), 1.0 - alpha + 1e-10], axis=-1
        ),
        axis=-1,
    )[..., :-1]
    weights = alpha * trans

    rgb_map = jnp.sum(weights[..., None] * rgb, axis=-2)
    depth_map = jnp.sum(weights * z_vals, axis=-1)
    acc_map = jnp.sum(weights, axis=-1)
    if white_bkgd:
        rgb_map = rgb_map + (1.0 - acc_map[..., None])
    return rgb_map, depth_map, acc_map, weights


def sample_pdf(
    key: jax.Array | None,
    bins: jax.Array,
    weights: jax.Array,
    n_samples: int,
    det: bool = False,
) -> jax.Array:
    """Inverse-CDF importance sampling (volume_renderer.py:82-134).

    bins [..., B], weights [..., B-1] → samples [..., n_samples]."""
    weights = weights + 1e-5
    pdf = weights / jnp.sum(weights, axis=-1, keepdims=True)
    cdf = jnp.cumsum(pdf, axis=-1)
    cdf = jnp.concatenate([jnp.zeros_like(cdf[..., :1]), cdf], axis=-1)

    if det or key is None:
        u = jnp.linspace(0.0, 1.0, n_samples, dtype=jnp.float32)
        u = jnp.broadcast_to(u, cdf.shape[:-1] + (n_samples,))
    else:
        u = jax.random.uniform(
            key, cdf.shape[:-1] + (n_samples,), dtype=jnp.float32
        )

    # batched right-bisect: for row-wise sorted cdf, count entries <= u.
    # A broadcast compare + sum ([..., n_samples, B] bools) lowers to pure
    # vector ops on TPU; vmapped searchsorted would become a log2(B)-step
    # loop of gathers. B is ~64, so the O(n·B) compare is tiny next to the
    # MLP sweeps it sits between.
    inds = jnp.sum(
        (cdf[..., None, :] <= u[..., :, None]).astype(jnp.int32), axis=-1
    )
    below = jnp.maximum(inds - 1, 0)
    above = jnp.minimum(inds, cdf.shape[-1] - 1)

    cdf_below = jnp.take_along_axis(cdf, below, axis=-1)
    cdf_above = jnp.take_along_axis(cdf, above, axis=-1)
    bins_below = jnp.take_along_axis(bins, jnp.minimum(below, bins.shape[-1] - 1), -1)
    bins_above = jnp.take_along_axis(bins, jnp.minimum(above, bins.shape[-1] - 1), -1)

    denom = cdf_above - cdf_below
    denom = jnp.where(denom < 1e-5, 1.0, denom)
    t = (u - cdf_below) / denom
    return bins_below + t * (bins_above - bins_below)


def render_rays(
    apply_fn,
    rays: jax.Array,
    near,
    far,
    key: jax.Array | None,
    options: RenderOptions,
    step: jax.Array | None = None,
) -> dict:
    """Render a [N, 6] (or [N, 7] time-conditioned) ray batch through
    coarse (+fine) networks.

    ``apply_fn(pts, viewdirs, model)`` is the bound network (params already
    closed over); returns the reference's output dict keys
    (`rgb_map_c/f`, `depth_map_c/f`, `acc_map_c/f`).

    ``options.sampling.mode == "proposal"`` routes the proposal-network
    resampler (renderer/sampling.py) instead of the coarse pass — a
    trace-time static, so each mode is its own fused executable. ``step``
    (a traced scalar from the train state; None at eval) drives the
    proposal PDF anneal and is ignored by the coarse+fine path.

    A 7th ray column (the per-frame latent/time index — light-stage and
    dynamic-scene datasets) is broadcast onto every sample point as a 4th
    point coordinate, so ``xyz_encoder`` receives the ``(x, y, z, t)`` the
    dynamic encoder family (models/encoding/dynamic.py) consumes. Static
    3-D encoders must be paired with 6-column rays — the extra coordinate
    is a shape-static trace-time property, never a runtime branch.

    Under model-parallel serving (``scale.mesh_shape`` with M > 1,
    scale/mesh_dispatch.py) ``apply_fn`` closes over a params tree
    sharded by parallel/sharding.py's partition rules. This body must
    stay placement-agnostic: XLA inserts the model-axis collectives
    inside ``apply_fn``, and everything downstream of the raw network
    outputs (weights, compositing) sees replicated activations — so the
    serve path reuses these exact bodies sharded, and any future edit
    that branches on concrete array placement here would break them."""
    if options.sampling.mode == "proposal":
        return proposal_render_rays(
            apply_fn, rays, near, far, key, options, step=step
        )
    rays_o, rays_d = rays[..., 0:3], rays[..., 3:6]
    t_col = rays[..., 6:7] if rays.shape[-1] > 6 else None
    n_rays = rays.shape[0]

    def _with_t(pts):
        if t_col is None:
            return pts
        t = jnp.broadcast_to(t_col[..., None, :], pts.shape[:-1] + (1,))
        return jnp.concatenate([pts, t], axis=-1)

    if options.remat:
        # trade FLOPs for HBM: recompute the MLP sweep during backward so
        # the 256-wide activations of ~N·256 points are never stored —
        # the batch-size ceiling moves from activations to the ray batch
        apply_fn = jax.checkpoint(apply_fn, static_argnums=(2,))

    if key is not None:
        k_strat, k_noise_c, k_pdf, k_noise_f = jax.random.split(key, 4)
    else:
        k_strat = k_noise_c = k_pdf = k_noise_f = None

    z_vals = stratified_z_vals(
        k_strat, near, far, n_rays, options.n_samples, options.perturb,
        options.lindisp,
    )
    pts = rays_o[..., None, :] + rays_d[..., None, :] * z_vals[..., :, None]
    viewdirs = rays_d / jnp.linalg.norm(rays_d, axis=-1, keepdims=True)

    raw_c = apply_fn(_with_t(pts), viewdirs, "coarse")
    rgb_c, depth_c, acc_c, weights_c = raw2outputs(
        raw_c, z_vals, rays_d, k_noise_c, options.raw_noise_std,
        options.white_bkgd,
    )
    out = {"rgb_map_c": rgb_c, "depth_map_c": depth_c, "acc_map_c": acc_c}

    if options.n_importance > 0:
        z_mid = 0.5 * (z_vals[..., 1:] + z_vals[..., :-1])
        z_samples = sample_pdf(
            k_pdf,
            z_mid,
            weights_c[..., 1:-1],
            options.n_importance,
            det=(options.perturb == 0.0),
        )
        z_samples = jax.lax.stop_gradient(z_samples)
        z_vals_f = jnp.sort(
            jnp.concatenate([z_vals, z_samples], axis=-1), axis=-1
        )
        pts_f = (
            rays_o[..., None, :] + rays_d[..., None, :] * z_vals_f[..., :, None]
        )
        raw_f = apply_fn(_with_t(pts_f), viewdirs, "fine")
        rgb_f, depth_f, acc_f, _ = raw2outputs(
            raw_f, z_vals_f, rays_d, k_noise_f, options.raw_noise_std,
            options.white_bkgd,
        )
        out.update(
            {"rgb_map_f": rgb_f, "depth_map_f": depth_f, "acc_map_f": acc_f}
        )
    return out


def _pad_to_chunks(rays: jax.Array, chunk_size: int):
    """[N, C] → ([n_chunks, chunk, C], n, n_chunks, chunk) with zero-padding
    (C = 6, or 7 with the time column)."""
    n = rays.shape[0]
    chunk = min(chunk_size, n)
    n_chunks = -(-n // chunk)
    pad = n_chunks * chunk - n
    return (
        jnp.pad(rays, ((0, pad), (0, 0))).reshape(n_chunks, chunk, rays.shape[-1]),
        n,
        n_chunks,
        chunk,
    )


def _unpad_outputs(out: dict, n: int) -> dict:
    """Flatten chunked outputs back to [N, ...] (non-ray scalars pass through)."""
    return {
        k: v.reshape((-1,) + v.shape[2:])[:n] if v.ndim >= 2 else v
        for k, v in out.items()
    }


class Renderer:
    """Config-bound renderer (parity: reference `Renderer` +
    `make_renderer(cfg, network)`, make_renderer.py:4-8).

    Holds the network module and static options; methods take params
    explicitly so they stay pure and jit/vmap/shard_map-compatible.
    """

    def __init__(self, cfg, network):
        self.network = network
        self.train_options = RenderOptions.from_cfg(cfg, train=True)
        self.eval_options = RenderOptions.from_cfg(cfg, train=False)
        # jitted chunked-render executables, keyed by (n_chunks, chunk) so
        # repeated validation images reuse one compilation
        self._chunked_fns: dict = {}
        # occupancy-accelerated state (reference volume_renderer.py:249-259)
        from .accelerated import MarchOptions

        # the Renderer's accelerated path only serves EVAL (run.py,
        # render_video.py) — it takes the eval-specific march budget
        self.march_options = MarchOptions.eval_from_cfg(cfg)
        # stream cap for the packed (hierarchical / clip_bbox) march
        self.packed_cap = int(
            cfg.task_arg.get(
                "packed_cap_avg_eval", self.march_options.max_samples
            )
        )
        self.occupancy_grid = None
        self.grid_bbox = None
        self._march_fns: dict = {}
        self._march_fns_cap = 8
        self._n_truncated = jnp.zeros((), jnp.int32)
        # last traversal diagnostics from the packed march, kept ON DEVICE
        # (no sync on the render path); telemetry surfaces pull them.
        # Rebuilt (not mutated) each marched render and CLEARED by chunked
        # renders, with a monotone "sweep" stamp — a consumer can neither
        # read a previous sweep's numbers after a chunked render nor
        # mistake one sweep's stats for another's
        self.last_march_stats: dict = {}
        self._march_sweep = 0
        # AOT bookkeeping: registry entry name -> local executable-cache key
        self._aot_names: dict = {}
        # fused Pallas MLP trunk (ops/fused_mlp.py): weights + activations
        # VMEM-resident per tile, backward recomputes in VMEM — the lever
        # against the flagship's 48.8 GB/step activation traffic (PERF.md
        # f3). Opt-in; unsupported families are refused at build time.
        self._fused_apply = None
        if bool(cfg.network.nerf.get("fused_trunk", False)):
            from ..ops.fused_mlp import make_fused_apply

            self._fused_apply = make_fused_apply(network, cfg)

    def _apply_fn(self, params):
        if self._fused_apply is not None:
            fused = self._fused_apply
            return lambda pts, viewdirs, model: fused(
                params, pts, viewdirs, model
            )
        return lambda pts, viewdirs, model: self.network.apply(
            params, pts, viewdirs, model=model
        )

    def render(self, params, batch: dict, key=None, train: bool = True) -> dict:
        """Render a batch dict {rays [N,6], near, far} (reference render()).

        An optional ``batch["step"]`` (the traced train-state step the
        step builders thread through) drives the proposal-sampling anneal;
        absent means fully-sharp resampling."""
        options = self.train_options if train else self.eval_options
        return render_rays(
            self._apply_fn(params),
            batch["rays"],
            batch["near"],
            batch["far"],
            key,
            options,
            step=batch.get("step"),
        )

    def sampling_stats(self) -> dict:
        """Static sampling ledger for telemetry surfaces (the trainer's
        ``sample`` rows, serve ``GET /stats``): the mode and the
        fine-MLP evaluations per ray each path costs."""
        s = self.eval_options.sampling
        return {
            "mode": s.mode,
            "fine_evals_per_ray_train": self.train_options.fine_evals_per_ray,
            "fine_evals_per_ray_eval": self.eval_options.fine_evals_per_ray,
            "n_proposal": s.n_proposal if s.mode == "proposal" else 0,
            "n_fine": s.n_fine if s.mode == "proposal" else 0,
        }

    def _build_chunked_fn(self, n_chunks: int):
        """Jitted chunked-eval executable for a fixed chunk count. Named
        builder so AOT registration (aot_register_eval) can route it
        through compile/AOTRegistry instead of first-dispatch tracing."""
        options = self.eval_options
        network = self.network
        fused = self._fused_apply

        @jax.jit
        def fn(params, rays_p, near, far, key):
            if fused is not None:
                apply_fn = lambda pts, vd, model: fused(  # noqa: E731
                    params, pts, vd, model
                )
            else:
                apply_fn = lambda pts, vd, model: network.apply(  # noqa: E731
                    params, pts, vd, model=model
                )

            def body(idx_and_rays):
                idx, rays_chunk = idx_and_rays
                # distinct stream per chunk, else every chunk repeats the
                # same jitter/noise draws → chunk-periodic stripes
                ck = None if key is None else jax.random.fold_in(key, idx)
                return render_rays(
                    apply_fn, rays_chunk, near, far, ck, options
                )

            return jax.lax.map(body, (jnp.arange(n_chunks), rays_p))

        return fn

    def render_chunked(self, params, batch: dict, key=None) -> dict:
        """Full-image eval: `lax.map` over fixed-size chunks with padding —
        the XLA idiom for the reference's python chunk loop
        (volume_renderer.py:160). The jitted executable is cached per
        (n_chunks, chunk) shape, so validation doesn't re-trace per image."""
        # a chunked render performs no occupancy march: drop the previous
        # sweep's diagnostics so GET /stats and the telemetry "march" row
        # can never attribute stale numbers to this render
        self.last_march_stats = {}

        rays_p, n, n_chunks, chunk = _pad_to_chunks(
            batch["rays"], self.eval_options.chunk_size
        )

        fn = self._chunked_fns.get((n_chunks, chunk))
        if fn is None:
            fn = self._build_chunked_fn(n_chunks)
            self._chunked_fns[(n_chunks, chunk)] = fn

        out = fn(params, rays_p, batch["near"], batch["far"], key)
        return _unpad_outputs(out, n)

    # -- occupancy-accelerated path (ESS + ERT) -----------------------------
    def load_occupancy_grid(self, grid_path: str) -> bool:
        """Load a baked grid; missing file → slow-mode fallback, matching the
        reference (volume_renderer.py:249-259). Returns True when loaded.

        Reads the versioned pyramid artifact (legacy flat ``.npz`` grids are
        upgraded on load). Only the FINE level is held — the coarse DDA
        level is derived in-graph (occupancy.coarse_from_grid) inside each
        executable, so the march signature stays (params, rays, grid, bbox)
        and the coarse level can never go stale against the fine grid."""
        import os

        from .occupancy import load_occupancy_pyramid

        if not os.path.exists(grid_path):
            print(f"Occupancy grid file not found: {grid_path}, run in slow mode.")
            return False
        try:
            levels, bbox = load_occupancy_pyramid(grid_path)
        except OSError as exc:
            # truncated/corrupt artifact: the chunked (slow-mode) path is
            # always correct — never march a garbage grid
            print(f"Occupancy grid unusable ({exc}), run in slow mode.")
            return False
        self.occupancy_grid = jnp.asarray(levels[0])
        self.grid_bbox = jnp.asarray(bbox)
        return True

    def _build_march_fn(self, near: float, far: float):
        """Jitted occupancy-march executable for fixed bounds/options.

        Routing mirrors serve/engine.py exactly (full-tier parity by
        construction): ``march_fused`` (ops/fused_march.py — "full" is the
        whole-march mega-kernel, "gather" the fused DDA+gather front end)
        wins; a proposal-mode sampler feeds the packed composite through
        ``march_rays_proposal_packed``; otherwise ``coarse_block > 0``
        (hierarchical coarse-DDA) or ``clip_bbox`` (per-ray quadrature)
        take the globally-packed march, and the plain per-ray two-phase
        march runs last. Named builder so AOT registration
        (aot_register_eval) can route it through compile/AOTRegistry."""
        network = self.network
        options = self.march_options
        fused = self._fused_apply
        packed = options.coarse_block > 0 or options.clip_bbox

        def _apply(params):
            if fused is not None:
                def apply_fn(pts, vd, model, valid=None):
                    if model == "proposal":
                        # the density-only sampler branch is NOT the NeRF
                        # trunk — the fused kernel's weight chain does not
                        # apply to it
                        return network.apply(params, pts, vd, model=model)
                    if valid is not None:
                        return fused(params, pts, vd, model, valid=valid)
                    return fused(params, pts, vd, model)

                # forward the Pallas trunk's masked entry point so the
                # packed march can stream its occupancy bits into the kernel
                apply_fn.supports_valid_mask = getattr(
                    fused, "supports_valid_mask", False
                )
            else:
                apply_fn = lambda pts, vd, model, valid=None: network.apply(  # noqa: E731
                    params, pts, vd, model=model
                )
            return apply_fn

        if options.march_fused == "full":
            # stage (b) mega-kernel: DDA + sampling + frequency encoding +
            # MLP + compositing in one block-fused program. The family
            # gate (fused_spec_for) refuses unsupported networks at BUILD
            # time, so a hashgrid config fails here, not mid-render.
            from ..ops.fused_march import march_rays_fused_full
            from ..ops.fused_mlp import fused_spec_for

            spec = fused_spec_for(network)
            xyz_enc, dir_enc = network.xyz_encoder, network.dir_encoder

            @jax.jit
            def fn(params, rays_p, grid, bbox):
                branch = params["params"]["fine"]
                return jax.lax.map(
                    lambda rc: march_rays_fused_full(
                        spec, xyz_enc, dir_enc, branch, rc, near, far,
                        grid, bbox, options,
                    ),
                    rays_p,
                )

            return fn

        if options.march_fused == "gather":
            # stage (a): fused DDA + fine gather, MLP + compositing outside
            # — any encoder family (hashgrid included) rides this one
            from ..ops.fused_march import march_rays_fused

            @jax.jit
            def fn(params, rays_p, grid, bbox):
                apply_fn = _apply(params)
                return jax.lax.map(
                    lambda rc: march_rays_fused(
                        apply_fn, rc, near, far, grid, bbox, options
                    ),
                    rays_p,
                )

            return fn

        if self.eval_options.sampling.mode == "proposal":
            # learned-sampler checkpoint on a grid engine: the resampler
            # is the admission structure and the grid culls its output —
            # proposal-mode eval inherits the packed-stream speedup
            # instead of riding the dense chunked render
            from .packed_march import march_rays_proposal_packed

            sampling = self.eval_options.sampling
            lindisp = bool(self.eval_options.lindisp)
            cap = self.packed_cap

            @jax.jit
            def fn(params, rays_p, grid, bbox):
                apply_fn = _apply(params)
                return jax.lax.map(
                    lambda rc: march_rays_proposal_packed(
                        apply_fn, rc, near, far, grid, bbox, options,
                        sampling, cap_avg=cap, lindisp=lindisp,
                    ),
                    rays_p,
                )

            return fn

        if packed:
            from .packed_march import march_rays_packed

            cap = self.packed_cap

            @jax.jit
            def fn(params, rays_p, grid, bbox):
                apply_fn = _apply(params)
                return jax.lax.map(
                    lambda rc: march_rays_packed(
                        apply_fn, rc, near, far, grid, bbox, options,
                        cap_avg=cap,
                    ),
                    rays_p,
                )

            return fn

        from .accelerated import march_rays_accelerated

        @jax.jit
        def fn(params, rays_p, grid, bbox):
            apply_fn = _apply(params)
            return jax.lax.map(
                lambda rc: march_rays_accelerated(
                    apply_fn, rc, near, far, grid, bbox, options
                ),
                rays_p,
            )

        return fn

    def render_accelerated(self, params, batch: dict) -> dict:
        """Full-image ESS+ERT render; falls back to the vanilla chunked path
        when no grid is loaded (volume_renderer.py:269-271)."""
        if self.occupancy_grid is None:
            return self.render_chunked(params, batch)

        rays_p, n, n_chunks, chunk = _pad_to_chunks(
            batch["rays"], self.march_options.chunk_size
        )

        # near/far ARE jit-static here — they set the march-step count, a
        # static shape — so they belong in the cache key; the LRU cap keeps
        # per-frame-varying bounds from growing the executable cache
        # without bound
        near, far = float(batch["near"]), float(batch["far"])
        # march_options is in the key (frozen dataclass, hashable) so a
        # caller adjusting the budget between renders — e.g. the offline
        # video stage doubling max_samples — can never hit a stale
        # executable built under the old options
        cache_key = (n_chunks, chunk, near, far, self.march_options)
        fn = self._march_fns.get(cache_key)
        if fn is None:
            fn = self._build_march_fn(near, far)
            while len(self._march_fns) >= self._march_fns_cap:
                self._march_fns.pop(next(iter(self._march_fns)))
            self._march_fns[cache_key] = fn
        else:
            self._march_fns[cache_key] = self._march_fns.pop(cache_key)  # LRU

        out = _unpad_outputs(
            fn(params, rays_p, self.occupancy_grid, self.grid_bbox), n
        )
        # the packed march also reports per-chunk traversal diagnostics —
        # [n_chunks] vectors, NOT per-ray — park them on device for
        # telemetry surfaces (train/ngp.py render_image emits "march" rows).
        # A FRESH dict with a monotone sweep stamp replaces the previous
        # one wholesale: a path that reports fewer keys (or none) can
        # never leave another sweep's values readable beside its own
        stats: dict = {}
        for k in (
            "march_candidates", "march_samples_out", "march_coarse_occ",
            "overflow_frac",
        ):
            if k in out:
                stats[k] = out.pop(k)
        self._march_sweep += 1
        stats["sweep"] = self._march_sweep
        self.last_march_stats = stats
        # accumulate the truncation diagnostic ON DEVICE — a host sync here
        # would serialize per-image dispatch (ADVICE r1); callers read it
        # once per eval via report_truncation(). Summed after unpadding, so
        # padding rows never count.
        self._n_truncated = self._n_truncated + jnp.sum(out.pop("truncated"))
        return out

    # -- AOT registration ---------------------------------------------------
    def aot_register_eval(
        self, registry, params, n_rays: int, near: float, far: float,
        serialize: bool = False,
    ) -> list[str]:
        """Register the renderer's eval executables with a
        compile/AOTRegistry so their builds happen during warm-up
        (concurrently, optionally serialized to the artifact store)
        instead of on the first validation image. The chunked entry is
        lowered for deterministic eval (key=None — run.py's eval
        contract); the march entry is registered only once a grid is
        loaded. Call :meth:`aot_install` after ``compile_all()`` to adopt
        the precompiled executables. Returns the registered names."""
        from ..compile.registry import abstract_like

        near, far = float(near), float(far)
        p_abs = abstract_like(params)
        names: list[str] = []

        chunk = min(self.eval_options.chunk_size, n_rays)
        n_chunks = -(-n_rays // chunk)
        rays_abs = jax.ShapeDtypeStruct((n_chunks, chunk, 6), jnp.float32)
        name = f"eval_chunked_{n_chunks}x{chunk}"
        registry.register(
            name,
            self._build_chunked_fn(n_chunks),
            (p_abs, rays_abs, near, far, None),
            serialize=serialize,
        )
        self._aot_names[name] = ("chunked", (n_chunks, chunk))
        names.append(name)

        if self.occupancy_grid is not None:
            chunk_m = min(self.march_options.chunk_size, n_rays)
            n_chunks_m = -(-n_rays // chunk_m)
            rays_m = jax.ShapeDtypeStruct(
                (n_chunks_m, chunk_m, 6), jnp.float32
            )
            mname = f"eval_march_{n_chunks_m}x{chunk_m}"
            registry.register(
                mname,
                self._build_march_fn(near, far),
                (
                    p_abs, rays_m, abstract_like(self.occupancy_grid),
                    abstract_like(self.grid_bbox),
                ),
                serialize=serialize,
            )
            self._aot_names[mname] = (
                "march", (n_chunks_m, chunk_m, near, far, self.march_options)
            )
            names.append(mname)
        return names

    def aot_install(self, registry) -> int:
        """Adopt every successfully precompiled eval executable into the
        local caches (failed builds keep the lazy-jit path). Returns the
        number installed."""
        installed = 0
        for name, (kind, key) in self._aot_names.items():
            fn = registry.take(name)
            if fn is None:
                continue
            if kind == "chunked":
                self._chunked_fns[key] = fn
            else:
                self._march_fns[key] = fn
            installed += 1
        return installed

    def accumulate_truncated(self, flags_or_count) -> None:
        """Fold an external path's truncation diagnostic (per-ray flags or a
        count) into the on-device accumulator read by report_truncation()."""
        self._n_truncated = self._n_truncated + jnp.sum(flags_or_count)

    def report_truncation(self, log=print) -> int:
        """One host sync: total rays (since last call) that exhausted the
        max_march_samples budget while still transparent."""
        n_truncated = int(self._n_truncated)
        self._n_truncated = jnp.zeros((), jnp.int32)
        if n_truncated:
            log(
                f"render_accelerated: {n_truncated} rays exceeded the "
                f"max_march_samples={self.march_options.max_samples} budget "
                f"while still transparent (far contributions truncated)"
            )
        return n_truncated


def make_renderer(cfg, network) -> Renderer:
    return Renderer(cfg, network)
