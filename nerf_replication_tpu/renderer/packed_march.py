"""Globally-packed occupancy march — samples compacted ACROSS rays.

The two-phase march in ``accelerated.py`` compacts each ray's occupied
positions into a fixed per-ray budget ``[N, K]`` and runs the MLP on every
slot — including the padding of rays with fewer than K occupied samples.
At carved occupancy (~5%, mean ~19 occupied samples/ray at S=400) that
wastes ~70% of the encoder gathers and MLP points, and the per-ray K cap
truncates exactly the hard rays that need more samples (the round-4 NGP
trail's quality ceiling).

This module is the TPU-native version of the sample-packing design the
CUDA originals use (the reference's CUDA marcher compacts alive rays per
step, volume_renderer.py:298-324; instant-ngp/nerfacc pack samples into a
flat stream): ONE static-size stream of M = N × cap_avg samples shared by
the whole batch. Per-ray sample counts become fully dynamic — a hard ray
may take 200 samples while its neighbors take 3 — with static shapes
end to end:

1. **Occupancy sweep** (same as accelerated.py): ``occupied [N, S]`` in
   one bool gather, no MLP.
2. **Global compaction, one sort**: sort key ``(~occupied)·N·S + idx``
   over the flattened ``[N·S]`` positions floats every occupied sample to
   the front IN (ray, t) ORDER (idx = ray·S + s is already lexicographic).
   Take the first M payload indices — a static-shape alive-list. The sort
   runs at the chip's 240-330M rows/s (BENCH_PRIMITIVES.jsonl) — ~6 ms at
   4096×400 — and replaces per-ray argsort + per-ray padding.
3. **One batched query over [M]** points (gathers of ray rows at
   98-160M rows/s), then segmented compositing in log space:
   ``1 − α = exp(−σδ)`` makes the transmittance cumprod EXACTLY
   ``exp(−cumsum(σδ))``, so per-ray transmittance is an exclusive cumsum
   minus its value at the ray's segment start — cumsum at 420M rows/s
   plus one [N]-row gather. No scatter in the forward; the backward of
   the final per-ray ``segment_sum`` is a gather.

Truncation semantics change from per-ray to GLOBAL: a ray is truncated
only when the whole stream overflows M (reported per ray, like
accelerated.py's ``truncated``). With cap_avg ≈ 1.5× the mean occupied
count the overflow frac is ~0 after the grid carves.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .accelerated import MarchOptions, occupancy_sweep
from .occupancy import PYRAMID_FACTORS, coarse_from_grid, world_to_voxel


def _ray_bbox_spans(rays_o, rays_d, bbox, near, far):
    """Per-ray [t0, t1] of the bbox intersection, clipped to [near, far].

    Slab method; rays missing the bbox (or with a degenerate direction
    component and origin outside the slab) come back with t1 == t0."""
    inv = 1.0 / jnp.where(jnp.abs(rays_d) < 1e-12, 1e-12, rays_d)
    t_lo = (bbox[0] - rays_o) * inv
    t_hi = (bbox[1] - rays_o) * inv
    tmin = jnp.max(jnp.minimum(t_lo, t_hi), axis=-1)
    tmax = jnp.min(jnp.maximum(t_lo, t_hi), axis=-1)
    t0 = jnp.clip(tmin, near, far)
    t1 = jnp.clip(tmax, near, far)
    return t0, jnp.maximum(t1, t0)


def hierarchical_caps(n_steps: int, options: MarchOptions) -> tuple[int, int]:
    """Static (S_c coarse blocks per ray, K_c kept-interval budget).

    K_c defaults to ceil(S_c / 4): a 4× reduction of the candidate stream
    entering the fine sweep + global sort. The DDA static-shape contract
    (docs/traversal.md): every executable sees exactly N·K_c·coarse_block
    candidate rows regardless of scene content; rays crossing more than
    K_c occupied coarse blocks are CLIPPED and report ``truncated``."""
    r = options.coarse_block
    s_c = -(-n_steps // r)
    k_c = options.coarse_cap if options.coarse_cap > 0 else max(1, -(-s_c // 4))
    return s_c, min(k_c, s_c)


def _hierarchical_sweep(rays, near, far, grid, bbox, options, spans):
    """Coarse-DDA phase 1: fixed-step march of the COARSE pyramid level
    selects per-ray occupied intervals; only their fine positions get a
    fine-grid lookup and enter the global sort.

    The coarse test is the PARENT cell of each position's fine voxel index
    (``fine_vox // factor``) against the any-reduced pyramid level — a
    strict superset of the fine grid by construction, so admitting exactly
    the positions whose parent is occupied can never drop a fine-occupied
    sample: hierarchical and flat marches composite identically (up to the
    K_c interval clip, which reports ``truncated``). The elementwise
    position→voxel math still runs at every march position (it is what the
    DDA steps on), but the three O(N·S) terms that dominate the flat sweep
    — the fine-grid random gather, the [N·S] global sort, and everything
    downstream — shrink to the N·K_c·r candidate stream.

    Returns ``(flat_cand [N, C] fine voxel ids, occ_cand [N, C] bool,
    s_f [N, C] fine step ids, n_steps, n_blk [N], block_frac scalar,
    k_c)`` with C = K_c · coarse_block.
    """
    import math

    if rays.shape[-1] > 6:
        # same contract as occupancy_sweep: a static geometry bake cannot
        # gate time-conditioned rays
        raise ValueError(
            "the occupancy-accelerated march only supports static [N, 6] "
            f"rays, got {rays.shape[-1]} columns — time-conditioned scenes "
            "must use the chunked volume renderer (accelerated_renderer: "
            "false)"
        )
    rays_o, rays_d = rays[..., 0:3], rays[..., 3:6]
    n_rays = rays.shape[0]
    resolution = grid.shape[0]
    factor = PYRAMID_FACTORS[-1]
    r = options.coarse_block
    n_steps = max(math.ceil((far - near) / options.step_size - 1e-9), 1)
    s_c, k_c = hierarchical_caps(n_steps, options)
    s_pad = s_c * r

    s_idx = jnp.arange(s_pad, dtype=jnp.float32)
    if spans is None:
        ts = near + s_idx * options.step_size
        pts = rays_o[:, None, :] + rays_d[:, None, :] * ts[None, :, None]
    else:
        t0, step_r = spans
        ts = t0[:, None] + s_idx[None, :] * step_r[:, None]  # [N, S_pad]
        pts = rays_o[:, None, :] + rays_d[:, None, :] * ts[..., None]
    vox = world_to_voxel(pts, bbox, resolution)  # [N, S_pad, 3]

    # coarse lookup in INDEX space (parent = fine // factor), not a second
    # world_to_voxel at coarse resolution: when R is not a multiple of the
    # factor the two mappings disagree near the +bbox face, and a mismatch
    # there would break the superset guarantee the parity contract rests on
    coarse = coarse_from_grid(grid, factor)
    rc = coarse.shape[0]
    cvox = vox // factor  # < rc always: vox ≤ R-1 ≤ rc·factor - 1
    cflat = (cvox[..., 0] * rc + cvox[..., 1]) * rc + cvox[..., 2]
    coarse_occ = jnp.take(coarse.reshape(-1), cflat)  # [N, S_pad] bool
    real = jnp.sum(rays_d * rays_d, axis=-1) > 0.0  # padding rays drop out
    in_range = jnp.arange(s_pad) < n_steps
    coarse_occ = coarse_occ & real[:, None] & in_range[None, :]
    if spans is not None:
        coarse_occ = coarse_occ & (spans[1] > 0)[:, None]

    # fixed-step DDA over blocks of r consecutive fine positions: a block
    # is an interval [s·r, (s+1)·r) of march steps, admitted when ANY of
    # its positions sits in an occupied coarse cell
    block_occ = coarse_occ.reshape(n_rays, s_c, r).any(-1)  # [N, S_c]
    n_blk = jnp.sum(block_occ, axis=-1)  # [N]
    block_frac = jnp.mean(block_occ.astype(jnp.float32))

    # static-shape per-ray interval list: stable argsort floats occupied
    # blocks to the front IN MARCH ORDER; keep the first K_c
    border = jnp.argsort(~block_occ, axis=-1, stable=True)[:, :k_c]
    bvalid = jnp.take_along_axis(block_occ, border, axis=-1)  # [N, K_c]

    s_f = border[..., None] * r + jnp.arange(r)  # [N, K_c, r]
    s_f = s_f.reshape(n_rays, k_c * r)
    cand_mask = jnp.broadcast_to(
        bvalid[..., None], (n_rays, k_c, r)
    ).reshape(n_rays, k_c * r) & (s_f < n_steps)

    # fine sweep ONLY at admitted candidates — [N, K_c·r] not [N, S]
    flat_all = (vox[..., 0] * resolution + vox[..., 1]) * resolution + vox[..., 2]
    flat_cand = jnp.take_along_axis(flat_all, s_f, axis=-1)
    occ_cand = jnp.take(grid.reshape(-1), flat_cand) & cand_mask
    return flat_cand, occ_cand, s_f, n_steps, n_blk, block_frac, k_c


def _composite_stream(
    apply_fn,
    rays_o: jax.Array,
    rays_d: jax.Array,
    occupied: jax.Array,
    t_cand: jax.Array,
    dist_cand: jax.Array,
    options: MarchOptions,
    m_cap: int,
    extra_lost: jax.Array | None = None,
    model: str = "fine",
    tau_clip: float | None = None,
) -> tuple[dict, dict]:
    """Phase 2 shared by every packed admission structure: global sort →
    masked MLP over the compacted stream → log-space segmented compositing.

    The admission structure (flat sweep, hierarchical DDA, or the proposal
    resampler) only has to produce per-candidate arrays in per-ray march
    order: ``occupied [N, C]`` bool, ``t_cand [N, C]`` sample depths and
    ``dist_cand [N, C]`` quadrature widths (already ‖d‖-scaled).
    ``extra_lost [N]`` ORs admission-side sample loss (e.g. the coarse
    K_c interval clip) into the truncation flag the stream-overflow test
    alone cannot observe. Returns ``(out, aux)``: the render/telemetry
    dict (minus ``march_coarse_occ``, an admission-side statistic) and the
    stream internals ``{order, valid, sigma}`` for ``return_samples``
    consumers.
    """
    n_rays, n_cand = occupied.shape
    m_cap = min(int(m_cap), n_rays * n_cand)

    # ONE global sort compacts every occupied (ray, t) position to the
    # front of a flat candidate stream in (ray, t) order (candidates are
    # per-ray march-ordered, so idx = ray·C + c is already lexicographic).
    total = n_rays * n_cand
    occ_flat = occupied.reshape(-1)
    idx = jnp.arange(total, dtype=jnp.int32)
    key = jnp.where(occ_flat, idx, total + idx)
    _, order = jax.lax.sort_key_val(key, idx)
    order = order[:m_cap]  # static [M] alive-list
    valid = occ_flat[order]  # [M] bool (False ⇒ stream tail padding)

    ray_id = order // n_cand  # [M] int32, nondecreasing over valid prefix
    t_m = t_cand.reshape(-1)[order]
    dists = dist_cand.reshape(-1)[order]

    o_m = rays_o[ray_id]
    d_m = rays_d[ray_id]
    pts_m = o_m + d_m * t_m[..., None]  # [M, 3]
    viewdirs = rays_d / jnp.linalg.norm(rays_d, axis=-1, keepdims=True)

    # the network contract is [rays, samples, 3] points + [rays, 3] dirs;
    # the packed stream is "M rays of one sample each". Fused-trunk apply
    # fns advertise ``supports_valid_mask``: the per-sample occupancy bit
    # streams INTO the Pallas kernel, which masks invalid rows and skips
    # the matmul chain for all-invalid tiles — the sorted stream puts the
    # valid prefix first, so the padding tail costs ~no MXU work.
    if getattr(apply_fn, "supports_valid_mask", False):
        raw = apply_fn(
            pts_m[:, None, :], viewdirs[ray_id], model,
            valid=valid.astype(jnp.float32),
        )[:, 0, :]
    else:
        raw = apply_fn(pts_m[:, None, :], viewdirs[ray_id], model)[:, 0, :]

    rgb = jax.nn.sigmoid(raw[..., :3])  # [M, 3]
    sigma = jax.nn.relu(raw[..., 3])  # [M]
    # 1 − α = exp(−σδ): transmittance in log space is EXACT, no clamps
    tau = sigma * dists * valid.astype(jnp.float32)  # [M]
    if tau_clip is not None:
        # quadratures with unbounded tail widths (the proposal path's
        # raw2outputs-parity 1e10 tail interval) would push the SHARED
        # stream cumsum to ~1e12 per ray, and every later segment's
        # e − e0 subtraction then cancels catastrophically in float32.
        # τ ≥ ~80 already means α = 1 and T_after < 2e-35 — clamping
        # there is invisible to the composite but keeps the cumsum small
        tau = jnp.minimum(tau, tau_clip)
    c = jnp.cumsum(tau)
    e = c - tau  # exclusive prefix: Σ τ of stream-earlier samples

    # per-ray segment starts: samples are (ray, t)-sorted, so ray r's
    # segment begins at cumsum(n_occ)[r-1], clamped to the stream cap
    n_occ = jnp.sum(occupied, axis=-1)  # [N]
    cum_occ = jnp.cumsum(n_occ)
    seg_start = jnp.minimum(cum_occ - n_occ, m_cap - 1).astype(jnp.int32)
    e0 = e[seg_start]  # [N]; gather — bwd is an [N]-row scatter-add
    trans = jnp.exp(-(e - e0[ray_id]))  # T BEFORE each sample
    alpha = 1.0 - jnp.exp(-tau)
    # ERT: zero weight once transmittance fell below the threshold —
    # identical composited output to the reference's dead-ray kill
    # (volume_renderer.py:340-341), like accelerated.py
    weights = trans * alpha * (trans >= options.transmittance_threshold)

    seg = jnp.where(valid, ray_id, n_rays)  # route padding to a bin we drop
    contrib = jnp.concatenate(
        [weights[:, None] * rgb, weights[:, None], (weights * t_m)[:, None]],
        axis=-1,
    )  # [M, 5]
    sums = jax.ops.segment_sum(
        contrib, seg, num_segments=n_rays + 1, indices_are_sorted=True
    )[:n_rays]
    rgb_map = sums[:, 0:3]
    acc_map = sums[:, 3]
    depth_map = sums[:, 4]
    if options.white_bkgd:
        rgb_map = rgb_map + (1.0 - acc_map[..., None])

    # truncation is GLOBAL here: ray r loses samples only if the stream
    # overflowed before r's segment ended, and matters only while the ray
    # was still transparent at its last kept sample
    kept_end = jnp.minimum(cum_occ, m_cap)
    # some of r's samples fell off the stream (n_occ guard: a ray with NO
    # occupied samples renders pure background correctly and must not be
    # flagged just because earlier rays filled the cap)
    lost = (cum_occ > kept_end) & (n_occ > 0)
    # transmittance after the ray's last KEPT sample = exp(-(c_end - e0)).
    # A ray that kept ZERO samples (its whole segment fell past the cap)
    # is trivially still transparent — computing from the clamped indices
    # would read ANOTHER ray's tau and could silently unflag it.
    kept_n = kept_end - jnp.minimum(cum_occ - n_occ, m_cap)
    c_end = c[jnp.maximum(kept_end - 1, 0)]
    t_after = jnp.where(kept_n > 0, jnp.exp(-(c_end - e0)), 1.0)
    still_alive = t_after >= options.transmittance_threshold
    if extra_lost is not None:
        lost = lost | extra_lost
    n_total_occ = cum_occ[-1]
    out = {
        "rgb_map_f": rgb_map,
        "depth_map_f": depth_map,
        "acc_map_f": acc_map,
        "truncated": lost & still_alive,
        "overflow_frac": (
            jnp.maximum(n_total_occ - m_cap, 0).astype(jnp.float32)
            / jnp.maximum(n_total_occ, 1).astype(jnp.float32)
        ),
        # traversal telemetry (obs/schema.py "march" rows): rows entering
        # the global sort and occupied rows surviving the admission test
        "march_candidates": jnp.float32(total),
        "march_samples_out": n_total_occ.astype(jnp.float32),
    }
    aux = {"order": order, "valid": valid, "sigma": sigma}
    return out, aux


def march_rays_packed(
    apply_fn,
    rays: jax.Array,
    near: float,
    far: float,
    grid: jax.Array,
    bbox: jax.Array,
    options: MarchOptions,
    cap_avg: int = 32,
    return_samples: bool = False,
) -> dict:
    """Render a [N, 6] ray chunk with globally-packed ESS + ERT.

    Output contract matches ``march_rays_accelerated`` (rgb/depth/acc maps,
    per-ray ``truncated``), plus ``overflow_frac`` — the fraction of
    occupied samples dropped by the global M = N × cap_avg cap (0.0 once
    the grid is carved and cap_avg is sized to ~1.5× the occupied mean).
    """
    rays_o, rays_d = rays[..., 0:3], rays[..., 3:6]
    n_rays = rays.shape[0]
    step = options.step_size

    # phase 1: occupancy of every march position — ONE implementation
    # shared with the per-ray march (exact-parity contract). clip_bbox
    # switches the shared sweep to per-ray quadrature: the same static S
    # covers only the ray's bbox span at a finer per-ray step. Padding
    # rays / bbox misses come back fully unoccupied either way.
    # coarse_block > 0 inserts the coarse-DDA stage: the flat [N, S]
    # candidate set shrinks to the [N, K_c·r] positions inside occupied
    # coarse-pyramid cells BEFORE the fine gather and the global sort.
    if options.clip_bbox:
        import math

        n_est = max(math.ceil((far - near) / step - 1e-9), 1)
        t0, t1 = _ray_bbox_spans(rays_o, rays_d, bbox, near, far)
        step_r = (t1 - t0) / n_est  # [N]
        spans = (t0, step_r)
    else:
        t0 = step_r = spans = None
    hierarchical = options.coarse_block > 0
    extra_lost = None
    if hierarchical:
        flat_vox, occupied, s_f, n_steps, n_blk_c, block_frac, k_c = (
            _hierarchical_sweep(rays, near, far, grid, bbox, options, spans)
        )
        s_ff = s_f.astype(jnp.float32)
        if options.clip_bbox:
            t_cand = t0[:, None] + s_ff * step_r[:, None]
        else:
            t_cand = near + s_ff * step
        # the coarse DDA clipped whole intervals off rays crossing more
        # than K_c occupied blocks BEFORE the stream ever saw them — the
        # stream-overflow test alone cannot observe that loss, so a
        # clipped ray must still report truncation, not silently shorten
        extra_lost = n_blk_c > k_c
    else:
        ts, flat_vox, occupied, n_steps = occupancy_sweep(
            rays, near, far, grid, bbox, step, spans=spans
        )
        t_cand = jnp.broadcast_to(ts, occupied.shape)
        block_frac = jnp.float32(1.0)
    d_norm = jnp.linalg.norm(rays_d, axis=-1)
    dist_ray = (step_r if options.clip_bbox else step) * d_norm  # [N]
    dist_cand = jnp.broadcast_to(dist_ray[:, None], occupied.shape)
    m_cap = min(int(n_rays * cap_avg), n_rays * occupied.shape[-1])

    out, aux = _composite_stream(
        apply_fn, rays_o, rays_d, occupied, t_cand, dist_cand, options,
        m_cap, extra_lost=extra_lost,
    )
    # coarse-level admission fraction (1.0 in the flat sweep)
    out["march_coarse_occ"] = block_frac
    if return_samples:
        out["sample_flat"] = jax.lax.stop_gradient(
            occ_to_flat(flat_vox, aux["order"])
        )
        out["sample_sigma"] = jax.lax.stop_gradient(aux["sigma"])
        out["sample_valid"] = aux["valid"].astype(jnp.float32)
    return out


def march_rays_proposal_packed(
    apply_fn,
    rays: jax.Array,
    near: float,
    far: float,
    grid: jax.Array,
    bbox: jax.Array,
    options: MarchOptions,
    sampling,
    cap_avg: int = 32,
    lindisp: bool = False,
) -> dict:
    """Proposal-resampler admission feeding the packed compositing stream.

    The PR 11 proposal pipeline (renderer/sampling.py) still rode the
    chunked renderer: S_p proposal evals + S_f DENSE fine evals per ray.
    Here the resampler replaces the coarse DDA as the packed march's
    admission structure — the deterministic eval quadrature of
    ``proposal_render_rays`` (stratified midpoints → proposal σ →
    histogram → det inverse-CDF resample, sorted) produces the per-ray
    candidate depths, the occupancy grid culls resampled points that
    landed in carved-empty space, and the shared global compaction +
    masked fine MLP + log-space composite run on the survivors only. The
    fine MLP therefore sees the packed stream (M = N·cap_avg rows, valid
    prefix first) instead of a dense [N, S_f] sweep, so the proposal
    serve tier and proposal-mode eval inherit the packed/fused-trunk
    speedup. Quadrature widths carry raw2outputs' 1e10 tail interval, so
    on an all-admitting grid the composite matches the chunked proposal
    path to float tolerance (the log-space cumsum vs the 1e-10-guarded
    cumprod is the only difference).

    Eval-only by design: deterministic resampling (no key), no aux
    histograms, no interlevel loss — training keeps the chunked path.
    """
    if rays.shape[-1] > 6:
        # same contract as occupancy_sweep: a static geometry bake cannot
        # gate time-conditioned rays
        raise ValueError(
            "the packed proposal march only supports static [N, 6] rays, "
            f"got {rays.shape[-1]} columns — time-conditioned scenes must "
            "use the chunked volume renderer"
        )
    from .sampling import resample_pdf, weights_from_sigma
    from .volume import stratified_z_vals

    rays_o, rays_d = rays[..., 0:3], rays[..., 3:6]
    n_rays = rays.shape[0]

    # proposal histogram — the deterministic eval quadrature of
    # proposal_render_rays, keyless (det inverse-CDF at bin centers)
    z_p = stratified_z_vals(
        None, near, far, n_rays, sampling.n_proposal, 0.0, lindisp
    )
    pts_p = rays_o[..., None, :] + rays_d[..., None, :] * z_p[..., :, None]
    viewdirs = rays_d / jnp.linalg.norm(rays_d, axis=-1, keepdims=True)
    raw_p = apply_fn(pts_p, viewdirs, "proposal")
    w_p = weights_from_sigma(raw_p[..., 0], z_p, rays_d)
    z_mid = 0.5 * (z_p[..., 1:] + z_p[..., :-1])
    z_f = resample_pdf(None, z_mid, w_p[..., 1:-1], sampling.n_fine, det=True)
    z_f = jax.lax.stop_gradient(jnp.sort(z_f, axis=-1))  # [N, S_f]

    # admission: the occupancy grid culls resampled points in carved space
    # (a trained proposal puts ~no mass there, so the cull is ~free and
    # the kept set drives the packed stream well under N·S_f)
    resolution = grid.shape[0]
    pts_f = rays_o[..., None, :] + rays_d[..., None, :] * z_f[..., :, None]
    vox = world_to_voxel(pts_f, bbox, resolution)
    flat = (vox[..., 0] * resolution + vox[..., 1]) * resolution + vox[..., 2]
    real = jnp.sum(rays_d * rays_d, axis=-1) > 0.0  # padding rays drop out
    occupied = jnp.take(grid.reshape(-1), flat) & real[:, None]

    # raw2outputs interval widths: diff with the 1e10 tail, ‖d‖-scaled —
    # the log-space composite then equals the chunked cumprod composite
    d_norm = jnp.linalg.norm(rays_d, axis=-1)
    dz = jnp.concatenate(
        [z_f[..., 1:] - z_f[..., :-1], jnp.full_like(z_f[..., :1], 1e10)],
        axis=-1,
    )
    dist_cand = dz * d_norm[:, None]

    m_cap = min(int(n_rays * cap_avg), n_rays * sampling.n_fine)
    out, _ = _composite_stream(
        apply_fn, rays_o, rays_d, occupied, z_f, dist_cand, options, m_cap,
        tau_clip=80.0,
    )
    # admission fraction: resampled points surviving the grid cull (the
    # proposal analog of the coarse DDA's block_frac)
    out["march_coarse_occ"] = jnp.mean(occupied.astype(jnp.float32))
    return out


def occ_to_flat(flat_vox: jax.Array, order: jax.Array) -> jax.Array:
    """Gather the [N, S] flat voxel ids at the packed stream's positions."""
    return flat_vox.reshape(-1)[order].astype(jnp.int32)
