"""Globally-packed occupancy march — samples compacted ACROSS rays.

The two-phase march in ``accelerated.py`` compacts each ray's occupied
positions into a fixed per-ray budget ``[N, K]`` and runs the MLP on every
slot — including the padding of rays with fewer than K occupied samples.
At carved occupancy (~5%, mean ~19 occupied samples/ray at S=400) that
wastes ~70% of the encoder gathers and MLP points, and the per-ray K cap
truncates exactly the hard rays that need more samples (the round-4 NGP
trail's quality ceiling).

This module is the TPU-native version of the sample-packing design the
CUDA originals use (the reference's CUDA marcher compacts alive rays per
step, volume_renderer.py:298-324; instant-ngp/nerfacc pack samples into a
flat stream): ONE static-size stream of M = N × cap_avg samples shared by
the whole batch. Per-ray sample counts become fully dynamic — a hard ray
may take 200 samples while its neighbors take 3 — with static shapes
end to end:

1. **Occupancy sweep** (same as accelerated.py): ``occupied [N, S]`` in
   one bool gather, no MLP.
2. **Global compaction, one sort**: sort key ``(~occupied)·N·S + idx``
   over the flattened ``[N·S]`` positions floats every occupied sample to
   the front IN (ray, t) ORDER (idx = ray·S + s is already lexicographic).
   Take the first M payload indices — a static-shape alive-list. The sort
   runs at the chip's 240-330M rows/s (BENCH_PRIMITIVES.jsonl) — ~6 ms at
   4096×400 — and replaces per-ray argsort + per-ray padding.
3. **One batched query over [M]** points (gathers of ray rows at
   98-160M rows/s), then segmented compositing in log space:
   ``1 − α = exp(−σδ)`` makes the transmittance cumprod EXACTLY
   ``exp(−cumsum(σδ))``, so per-ray transmittance is an exclusive cumsum
   minus its value at the ray's segment start — cumsum at 420M rows/s
   plus one [N]-row gather. No scatter in the forward; the backward of
   the final per-ray ``segment_sum`` is a gather.

Truncation semantics change from per-ray to GLOBAL: a ray is truncated
only when the whole stream overflows M (reported per ray, like
accelerated.py's ``truncated``). With cap_avg ≈ 1.5× the mean occupied
count the overflow frac is ~0 after the grid carves.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .accelerated import MarchOptions, occupancy_sweep


def _ray_bbox_spans(rays_o, rays_d, bbox, near, far):
    """Per-ray [t0, t1] of the bbox intersection, clipped to [near, far].

    Slab method; rays missing the bbox (or with a degenerate direction
    component and origin outside the slab) come back with t1 == t0."""
    inv = 1.0 / jnp.where(jnp.abs(rays_d) < 1e-12, 1e-12, rays_d)
    t_lo = (bbox[0] - rays_o) * inv
    t_hi = (bbox[1] - rays_o) * inv
    tmin = jnp.max(jnp.minimum(t_lo, t_hi), axis=-1)
    tmax = jnp.min(jnp.maximum(t_lo, t_hi), axis=-1)
    t0 = jnp.clip(tmin, near, far)
    t1 = jnp.clip(tmax, near, far)
    return t0, jnp.maximum(t1, t0)


def march_rays_packed(
    apply_fn,
    rays: jax.Array,
    near: float,
    far: float,
    grid: jax.Array,
    bbox: jax.Array,
    options: MarchOptions,
    cap_avg: int = 32,
    return_samples: bool = False,
) -> dict:
    """Render a [N, 6] ray chunk with globally-packed ESS + ERT.

    Output contract matches ``march_rays_accelerated`` (rgb/depth/acc maps,
    per-ray ``truncated``), plus ``overflow_frac`` — the fraction of
    occupied samples dropped by the global M = N × cap_avg cap (0.0 once
    the grid is carved and cap_avg is sized to ~1.5× the occupied mean).
    """
    rays_o, rays_d = rays[..., 0:3], rays[..., 3:6]
    n_rays = rays.shape[0]
    step = options.step_size

    # phase 1: occupancy of every march position — ONE implementation
    # shared with the per-ray march (exact-parity contract). clip_bbox
    # switches the shared sweep to per-ray quadrature: the same static S
    # covers only the ray's bbox span at a finer per-ray step. Padding
    # rays / bbox misses come back fully unoccupied either way.
    if options.clip_bbox:
        import math

        n_est = max(math.ceil((far - near) / step - 1e-9), 1)
        t0, t1 = _ray_bbox_spans(rays_o, rays_d, bbox, near, far)
        step_r = (t1 - t0) / n_est  # [N]
        spans = (t0, step_r)
    else:
        t0 = step_r = spans = None
    _, flat_vox, occupied, n_steps = occupancy_sweep(
        rays, near, far, grid, bbox, step, spans=spans
    )
    m_cap = min(int(n_rays * cap_avg), n_rays * n_steps)

    # phase 2: ONE global sort compacts every occupied (ray, t) position
    # to the front of a flat [N·S] stream in (ray, t) order.
    total = n_rays * n_steps
    occ_flat = occupied.reshape(-1)
    idx = jnp.arange(total, dtype=jnp.int32)
    key = jnp.where(occ_flat, idx, total + idx)
    _, order = jax.lax.sort_key_val(key, idx)
    order = order[:m_cap]  # static [M] alive-list
    valid = occ_flat[order]  # [M] bool (False ⇒ stream tail padding)

    ray_id = order // n_steps  # [M] int32, nondecreasing over valid prefix
    s_id = order % n_steps
    if options.clip_bbox:
        t_m = t0[ray_id] + s_id.astype(jnp.float32) * step_r[ray_id]
        step_m = step_r[ray_id]
    else:
        t_m = near + s_id.astype(jnp.float32) * step
        step_m = step

    o_m = rays_o[ray_id]
    d_m = rays_d[ray_id]
    pts_m = o_m + d_m * t_m[..., None]  # [M, 3]
    viewdirs = rays_d / jnp.linalg.norm(rays_d, axis=-1, keepdims=True)

    # the network contract is [rays, samples, 3] points + [rays, 3] dirs;
    # the packed stream is "M rays of one sample each"
    raw = apply_fn(pts_m[:, None, :], viewdirs[ray_id], "fine")[:, 0, :]

    rgb = jax.nn.sigmoid(raw[..., :3])  # [M, 3]
    sigma = jax.nn.relu(raw[..., 3])  # [M]
    dists = step_m * jnp.linalg.norm(d_m, axis=-1)
    # 1 − α = exp(−σδ): transmittance in log space is EXACT, no clamps
    tau = sigma * dists * valid.astype(jnp.float32)  # [M]
    c = jnp.cumsum(tau)
    e = c - tau  # exclusive prefix: Σ τ of stream-earlier samples

    # per-ray segment starts: samples are (ray, t)-sorted, so ray r's
    # segment begins at cumsum(n_occ)[r-1], clamped to the stream cap
    n_occ = jnp.sum(occupied, axis=-1)  # [N]
    cum_occ = jnp.cumsum(n_occ)
    seg_start = jnp.minimum(cum_occ - n_occ, m_cap - 1).astype(jnp.int32)
    e0 = e[seg_start]  # [N]; gather — bwd is an [N]-row scatter-add
    trans = jnp.exp(-(e - e0[ray_id]))  # T BEFORE each sample
    alpha = 1.0 - jnp.exp(-tau)
    # ERT: zero weight once transmittance fell below the threshold —
    # identical composited output to the reference's dead-ray kill
    # (volume_renderer.py:340-341), like accelerated.py
    weights = trans * alpha * (trans >= options.transmittance_threshold)

    seg = jnp.where(valid, ray_id, n_rays)  # route padding to a bin we drop
    contrib = jnp.concatenate(
        [weights[:, None] * rgb, weights[:, None], (weights * t_m)[:, None]],
        axis=-1,
    )  # [M, 5]
    sums = jax.ops.segment_sum(
        contrib, seg, num_segments=n_rays + 1, indices_are_sorted=True
    )[:n_rays]
    rgb_map = sums[:, 0:3]
    acc_map = sums[:, 3]
    depth_map = sums[:, 4]
    if options.white_bkgd:
        rgb_map = rgb_map + (1.0 - acc_map[..., None])

    # truncation is GLOBAL here: ray r loses samples only if the stream
    # overflowed before r's segment ended, and matters only while the ray
    # was still transparent at its last kept sample
    kept_end = jnp.minimum(cum_occ, m_cap)
    # some of r's samples fell off the stream (n_occ guard: a ray with NO
    # occupied samples renders pure background correctly and must not be
    # flagged just because earlier rays filled the cap)
    lost = (cum_occ > kept_end) & (n_occ > 0)
    # transmittance after the ray's last KEPT sample = exp(-(c_end - e0)).
    # A ray that kept ZERO samples (its whole segment fell past the cap)
    # is trivially still transparent — computing from the clamped indices
    # would read ANOTHER ray's tau and could silently unflag it.
    kept_n = kept_end - jnp.minimum(cum_occ - n_occ, m_cap)
    c_end = c[jnp.maximum(kept_end - 1, 0)]
    t_after = jnp.where(kept_n > 0, jnp.exp(-(c_end - e0)), 1.0)
    still_alive = t_after >= options.transmittance_threshold
    n_total_occ = cum_occ[-1]
    out = {
        "rgb_map_f": rgb_map,
        "depth_map_f": depth_map,
        "acc_map_f": acc_map,
        "truncated": lost & still_alive,
        "overflow_frac": (
            jnp.maximum(n_total_occ - m_cap, 0).astype(jnp.float32)
            / jnp.maximum(n_total_occ, 1).astype(jnp.float32)
        ),
    }
    if return_samples:
        out["sample_flat"] = jax.lax.stop_gradient(
            occ_to_flat(flat_vox, order)
        )
        out["sample_sigma"] = jax.lax.stop_gradient(sigma)
        out["sample_valid"] = valid.astype(jnp.float32)
    return out


def occ_to_flat(flat_vox: jax.Array, order: jax.Array) -> jax.Array:
    """Gather the [N, S] flat voxel ids at the packed stream's positions."""
    return flat_vox.reshape(-1)[order].astype(jnp.int32)
