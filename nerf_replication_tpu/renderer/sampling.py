"""Learned sampling: proposal-network resampling replacing the coarse pass.

Per NerfAcc (arXiv 2305.04966) and NeuSample (arXiv 2111.15552), the
hierarchical coarse pass exists only to produce a per-ray weight histogram
for importance sampling — a job a *much* smaller density-only network does
just as well. This module is the sampling side of that trade:

* :func:`resample_pdf` — piecewise-constant weight PDF → inverse-CDF draw,
  generalizing ``volume.sample_pdf`` with an **annealed** train mode (the
  PDF blends from uniform toward the proposal histogram over
  ``anneal_iters`` steps, so an untrained proposal net cannot starve the
  fine network of coverage) and a deterministic stratified eval mode.
* :func:`proposal_render_rays` — the proposal-mode ray pipeline: S_p
  stratified proposal-MLP evaluations → weight histogram → S_f ≪ S_c+S_f
  resampled fine-network points. The fine MLP runs on S_f points only;
  sample positions carry ``stop_gradient`` so the photometric loss never
  backprops into the proposal (it trains on :func:`interlevel_loss` alone).
* :func:`interlevel_loss` — the mip-NeRF-360-style weight-bound loss:
  the proposal histogram must UPPER-bound the fine weights on every fine
  interval; fine weights are stop-gradient'ed, so the bound pulls proposal
  mass toward where the fine network found content.

Everything here is fully jit-traceable: modes are trace-time statics
(frozen :class:`SamplingOptions`), the anneal factor is a traced scalar
(``step`` rides the batch dict), and the inverse CDF uses the repo's
broadcast-compare right-bisect (volume.py:163-167) rather than a gather
loop.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingOptions:
    """Jit-static sampling configuration (cfg.sampling; docs/sampling.md).

    ``mode`` "coarse_fine" keeps the reference hierarchical pass;
    "proposal" replaces it with the proposal-network resampler. ``aux``
    (train only) returns the two weight histograms the interlevel loss
    consumes alongside the rendered maps."""

    mode: str = "coarse_fine"
    n_proposal: int = 64       # S_p: stratified proposal-MLP samples
    n_fine: int = 32           # S_f: resampled fine-network samples
    anneal_iters: int = 1000   # steps to sharpen the PDF from uniform
    loss_mult: float = 1.0     # interlevel loss weight
    det: bool = False          # deterministic (eval) resampling
    aux: bool = False          # return histograms for the interlevel loss

    @classmethod
    def from_cfg(cls, cfg, train: bool = True) -> "SamplingOptions":
        s = cfg.get("sampling", {})
        return cls(
            mode=str(s.get("mode", "coarse_fine")),
            n_proposal=int(s.get("n_proposal", 64)),
            n_fine=int(s.get("n_fine", 32)),
            anneal_iters=int(s.get("anneal_iters", 1000)),
            loss_mult=float(s.get("loss_mult", 1.0)),
            det=not train,
            aux=bool(train),
        )


def resample_pdf(
    key: jax.Array | None,
    bins: jax.Array,
    weights: jax.Array,
    n_samples: int,
    det: bool = False,
    anneal: jax.Array | float | None = None,
) -> jax.Array:
    """Inverse-CDF draw from a piecewise-constant weight PDF.

    bins [..., B] (sorted), weights [..., B-1] → samples [..., n_samples].
    Generalizes ``volume.sample_pdf`` (same 1e-5 guards, same
    broadcast-compare bisect) with:

    * ``anneal`` in [0, 1]: the PDF is ``a·pdf + (1-a)·uniform`` — a
      traced scalar, so an annealing schedule costs zero retraces. None
      (or 1.0) is the fully-sharp histogram.
    * ``det=True`` (or ``key=None``): deterministic stratified u at bin
      centers ``(i + 0.5)/n`` — with uniform weights the draw IS the
      stratified midpoint rule (the parity property tests pin).
    """
    weights = weights + 1e-5
    pdf = weights / jnp.sum(weights, axis=-1, keepdims=True)
    if anneal is not None:
        a = jnp.asarray(anneal, jnp.float32)
        pdf = a * pdf + (1.0 - a) / pdf.shape[-1]
    cdf = jnp.cumsum(pdf, axis=-1)
    cdf = jnp.concatenate([jnp.zeros_like(cdf[..., :1]), cdf], axis=-1)

    if det or key is None:
        u = (jnp.arange(n_samples, dtype=jnp.float32) + 0.5) / n_samples
        u = jnp.broadcast_to(u, cdf.shape[:-1] + (n_samples,))
    else:
        u = jax.random.uniform(
            key, cdf.shape[:-1] + (n_samples,), dtype=jnp.float32
        )

    # batched right-bisect by broadcast compare + sum (volume.py:163-167):
    # pure vector ops on TPU, and B is small next to the MLP sweeps.
    inds = jnp.sum(
        (cdf[..., None, :] <= u[..., :, None]).astype(jnp.int32), axis=-1
    )
    below = jnp.maximum(inds - 1, 0)
    above = jnp.minimum(inds, cdf.shape[-1] - 1)

    cdf_below = jnp.take_along_axis(cdf, below, axis=-1)
    cdf_above = jnp.take_along_axis(cdf, above, axis=-1)
    bins_below = jnp.take_along_axis(
        bins, jnp.minimum(below, bins.shape[-1] - 1), -1
    )
    bins_above = jnp.take_along_axis(
        bins, jnp.minimum(above, bins.shape[-1] - 1), -1
    )

    denom = cdf_above - cdf_below
    denom = jnp.where(denom < 1e-5, 1.0, denom)
    t = (u - cdf_below) / denom
    return bins_below + t * (bins_above - bins_below)


def weights_from_sigma(
    sigma: jax.Array, z_vals: jax.Array, rays_d: jax.Array
) -> jax.Array:
    """Compositing weights from raw density alone (no color sweep).

    Exactly ``raw2outputs``'s alpha/transmittance math — relu(σ),
    α = 1-exp(-σ·δ·‖d‖), T via cumprod with the 1e-10 guard — minus the
    RGB path the proposal network does not have.
    """
    dists = z_vals[..., 1:] - z_vals[..., :-1]
    dists = jnp.concatenate(
        [dists, jnp.full_like(dists[..., :1], 1e10)], axis=-1
    )
    dists = dists * jnp.linalg.norm(rays_d[..., None, :], axis=-1)
    alpha = 1.0 - jnp.exp(-jax.nn.relu(sigma) * dists)
    trans = jnp.cumprod(
        jnp.concatenate(
            [jnp.ones_like(alpha[..., :1]), 1.0 - alpha + 1e-10], axis=-1
        ),
        axis=-1,
    )[..., :-1]
    return alpha * trans


def edges_from_samples(z: jax.Array) -> jax.Array:
    """Sample positions [..., S] → interval edges [..., S+1] (midpoint
    rule, endpoints clamped to the first/last sample)."""
    mids = 0.5 * (z[..., 1:] + z[..., :-1])
    return jnp.concatenate([z[..., :1], mids, z[..., -1:]], axis=-1)


def _outer_measure(
    t: jax.Array, t_env: jax.Array, w_env: jax.Array
) -> jax.Array:
    """Envelope histogram mass over each query interval.

    t [..., S+1] query edges, (t_env [..., P+1], w_env [..., P]) the
    envelope histogram → [..., S]: for query interval [t_i, t_{i+1}), the
    total envelope mass of every bin OVERLAPPING it (mip-NeRF 360's outer
    measure — an upper bound on the envelope's mass inside the interval).
    Bisects with the broadcast-compare idiom; S and P are sample counts
    (tens), so the [..., S+1, P+1] compare is small next to the MLP sweep.
    """
    cw = jnp.concatenate(
        [jnp.zeros_like(w_env[..., :1]), jnp.cumsum(w_env, axis=-1)], axis=-1
    )
    # idx_lo: last envelope edge <= t; idx_hi: first envelope edge >= t
    p = t_env.shape[-1] - 1
    idx_lo = jnp.maximum(
        jnp.sum(
            (t_env[..., None, :] <= t[..., :, None]).astype(jnp.int32), -1
        ) - 1,
        0,
    )
    idx_hi = jnp.minimum(
        jnp.sum(
            (t_env[..., None, :] < t[..., :, None]).astype(jnp.int32), -1
        ),
        p,
    )
    cw_lo = jnp.take_along_axis(cw, idx_lo, axis=-1)
    cw_hi = jnp.take_along_axis(cw, idx_hi, axis=-1)
    return cw_hi[..., 1:] - cw_lo[..., :-1]


def interlevel_loss(
    t_fine: jax.Array,
    w_fine: jax.Array,
    t_prop: jax.Array,
    w_prop: jax.Array,
    eps: float = 1e-7,
) -> jax.Array:
    """Weight-bound loss supervising the proposal histogram.

    Penalizes fine-interval weight exceeding the proposal's overlapping
    mass: ``mean(Σ max(0, w_f - bound)² / (w_f + eps))``. Fine inputs are
    stop-gradient'ed — the loss trains the PROPOSAL to cover the fine
    distribution, never the reverse (mip-NeRF 360 §5 / NerfAcc's
    transmittance estimator loss). Zero exactly when the proposal
    upper-bounds the fine weights everywhere.
    """
    t_f = jax.lax.stop_gradient(t_fine)
    w_f = jax.lax.stop_gradient(w_fine)
    bound = _outer_measure(t_f, t_prop, w_prop)
    excess = jnp.maximum(0.0, w_f - bound)
    return jnp.mean(jnp.sum(excess ** 2 / (w_f + eps), axis=-1))


def proposal_render_rays(
    apply_fn,
    rays: jax.Array,
    near,
    far,
    key: jax.Array | None,
    options,
    step: jax.Array | None = None,
) -> dict:
    """Proposal-mode ray pipeline (the ``sampling.mode: proposal`` route of
    ``volume.render_rays`` — same apply_fn/ray/output contracts).

    S_p stratified points → proposal density → weight histogram →
    inverse-CDF resample S_f fine-network points. ``step`` (a traced
    scalar from the train state, None at eval) drives the PDF anneal.
    Returns the fine maps under the reference's ``*_map_f`` keys; with
    ``options.sampling.aux`` also the two (edges, weights) histograms the
    interlevel loss consumes (``prop_t``/``prop_w`` keep gradients,
    ``fine_t``/``fine_w`` are stop-gradient'ed).
    """
    from .volume import raw2outputs, stratified_z_vals

    s = options.sampling
    rays_o, rays_d = rays[..., 0:3], rays[..., 3:6]
    t_col = rays[..., 6:7] if rays.shape[-1] > 6 else None
    n_rays = rays.shape[0]

    def _with_t(pts):
        if t_col is None:
            return pts
        t = jnp.broadcast_to(t_col[..., None, :], pts.shape[:-1] + (1,))
        return jnp.concatenate([pts, t], axis=-1)

    if options.remat:
        apply_fn = jax.checkpoint(apply_fn, static_argnums=(2,))

    if key is not None:
        k_strat, k_pdf, k_noise = jax.random.split(key, 3)
    else:
        k_strat = k_pdf = k_noise = None

    z_p = stratified_z_vals(
        k_strat, near, far, n_rays, s.n_proposal, options.perturb,
        options.lindisp,
    )
    pts_p = rays_o[..., None, :] + rays_d[..., None, :] * z_p[..., :, None]
    viewdirs = rays_d / jnp.linalg.norm(rays_d, axis=-1, keepdims=True)

    raw_p = apply_fn(_with_t(pts_p), viewdirs, "proposal")
    w_p = weights_from_sigma(raw_p[..., 0], z_p, rays_d)

    # anneal in [0, 1]: 0 at step 0 (pure uniform — a random proposal net
    # cannot starve the fine network of coverage), 1 from anneal_iters on
    # (pure proposal histogram). None (eval / anneal_iters<=0) is sharp.
    anneal = None
    if step is not None and s.anneal_iters > 0:
        anneal = jnp.clip(
            jnp.asarray(step, jnp.float32) / float(s.anneal_iters), 0.0, 1.0
        )

    z_mid = 0.5 * (z_p[..., 1:] + z_p[..., :-1])
    z_f = resample_pdf(
        k_pdf, z_mid, w_p[..., 1:-1], s.n_fine,
        det=s.det or options.perturb == 0.0, anneal=anneal,
    )
    # sample positions are not a gradient path: the proposal trains on the
    # interlevel loss, the fine network on photometric loss alone
    # (volume_renderer.py:216's detach, same contract as the coarse pass)
    z_f = jax.lax.stop_gradient(jnp.sort(z_f, axis=-1))

    pts_f = rays_o[..., None, :] + rays_d[..., None, :] * z_f[..., :, None]
    raw_f = apply_fn(_with_t(pts_f), viewdirs, "fine")
    rgb_f, depth_f, acc_f, w_f = raw2outputs(
        raw_f, z_f, rays_d, k_noise, options.raw_noise_std,
        options.white_bkgd,
    )
    out = {"rgb_map_f": rgb_f, "depth_map_f": depth_f, "acc_map_f": acc_f}
    if s.aux:
        out["prop_t"] = edges_from_samples(z_p)
        out["prop_w"] = w_p
        out["fine_t"] = jax.lax.stop_gradient(edges_from_samples(z_f))
        out["fine_w"] = jax.lax.stop_gradient(w_f)
    return out
