"""Renderer factory: resolves the ``renderer_module`` plugin key
(parity: src/models/nerf/renderer/make_renderer.py:4-8)."""

from __future__ import annotations

from ..registry import load_attr
from .volume import RenderOptions, Renderer, raw2outputs, render_rays, sample_pdf

__all__ = [
    "RenderOptions",
    "Renderer",
    "make_renderer",
    "raw2outputs",
    "render_rays",
    "sample_pdf",
]


def make_renderer(cfg, network):
    factory = load_attr(cfg.renderer_module, "make_renderer", "Renderer")
    return factory(cfg, network)
