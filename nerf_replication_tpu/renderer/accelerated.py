"""Occupancy-accelerated ray marching (ESS + ERT), redesigned for XLA.

Capability parity with the reference's `render_accelerated`
(volume_renderer.py:268-358): fixed-step march over [near, far], empty-space
skipping via the baked occupancy grid, fine-network queries only where
occupied, incremental transmittance compositing, early ray termination below
a transmittance threshold, white-background compositing.

The CUDA formulation — per-step compaction of alive rays and dynamic-size
network queries (volume_renderer.py:298-324) — is dynamic-shape hostile and
would retrace/recompile every step on TPU. The TPU-native design splits the
march into two static-shape phases (SURVEY.md §7 "Hard parts"):

1. **Occupancy sweep (no MLP)**: all S = ⌈(far−near)/Δ⌉ march positions of a
   ray chunk are classified occupied/empty in one vectorized gather from the
   bool grid — a bandwidth-trivial [N, S] lookup.
2. **Compaction + one batched query**: per ray, the first K occupied march
   positions are compacted front-of-array with a stable argsort on the
   occupancy mask (static [N, K] shapes), the MLP runs ONCE over [N, K]
   points, and compositing applies transmittance masking for ERT: samples
   after transmittance falls below the threshold contribute exactly zero,
   matching the reference's dead-ray semantics without divergence.

Empty-space skipping therefore saves real MLP FLOPs (K ≪ S points queried),
and the whole renderer is one fused XLA program per chunk shape.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp

from .occupancy import world_to_voxel


@dataclass(frozen=True)
class MarchOptions:
    """Jit-static accelerated-march configuration."""

    step_size: float = 0.005
    transmittance_threshold: float = 1e-4
    max_samples: int = 192  # K: MLP-query budget per ray
    white_bkgd: bool = True
    chunk_size: int = 4096
    # packed march only: clip each ray's march span to its scene-bbox
    # intersection, so the SAME static S covers a shorter span at a finer
    # per-ray effective step — equivalently, a config can raise step_size
    # (shrinking the phase-1/sort row counts) at unchanged in-bbox
    # resolution. Changes quadrature positions: off by default.
    clip_bbox: bool = False
    # packed march only: hierarchical coarse-DDA traversal. coarse_block
    # groups the S march positions into blocks of this many consecutive
    # fine steps; a block enters the fine sweep + global sort only when
    # one of its positions' PARENT coarse-pyramid cell is occupied. 0
    # disables (flat sweep — the pre-pyramid behavior). coarse_cap is the
    # static per-ray interval budget K_c (blocks kept per ray); 0 picks
    # ceil(S_c / 4), a 4× candidate-stream reduction at the default.
    coarse_block: int = 0
    coarse_cap: int = 0
    # fused mega-kernel (ops/fused_march.py). "off" keeps the staged
    # sweep→sort→MLP→composite pipeline; "gather" fuses the coarse DDA +
    # fine gather into one per-ray-block kernel emitting a compacted
    # sample stream (encoder-agnostic: the MLP still runs outside);
    # "full" additionally runs the frequency-family fused MLP trunk and
    # the transmittance compositing in-kernel with early ray termination.
    # Both stages require coarse_block > 0 (the DDA IS the hierarchical
    # traversal) and refuse loudly otherwise.
    march_fused: str = "off"
    # rays per fused-kernel program instance (one Pallas grid block owns
    # this many rays' scratch state; chunks are padded up to a multiple)
    fused_block: int = 256

    @classmethod
    def from_cfg(cls, cfg) -> "MarchOptions":
        ta = cfg.task_arg
        raw_fused = ta.get("march_fused", False)
        if isinstance(raw_fused, str):
            if raw_fused not in ("off", "gather", "full"):
                raise ValueError(
                    "task_arg.march_fused must be one of off/gather/full "
                    f"(or a bool; true = gather), got {raw_fused!r}"
                )
            fused = raw_fused
        else:
            fused = "gather" if raw_fused else "off"
        return cls(
            step_size=float(ta.get("render_step_size", 0.005)),
            transmittance_threshold=float(
                ta.get("transmittance_threshold", 1e-4)
            ),
            max_samples=int(ta.get("max_march_samples", 192)),
            white_bkgd=bool(ta.get("white_bkgd", True)),
            chunk_size=int(ta.get("march_chunk_size", 4096)),
            clip_bbox=bool(ta.get("march_clip_bbox", False)),
            coarse_block=int(ta.get("march_coarse_block", 0)),
            coarse_cap=int(ta.get("march_coarse_cap", 0)),
            march_fused=fused,
            fused_block=int(ta.get("march_fused_block", 256)),
        )

    @classmethod
    def eval_from_cfg(cls, cfg) -> "MarchOptions":
        """March options for EVAL renders, decoupled from training's.

        NGP training tunes ``render_step_size`` / ``max_march_samples``
        for per-step throughput (coarse steps, tight K); rendering
        held-out images through that budget caps quality (round-4 trail:
        H=400 topped out at 28.16 dB on the training budget). Eval pays
        its cost once per image, so ``task_arg.eval_render_step_size`` /
        ``task_arg.eval_max_march_samples`` override the shared keys for
        eval executables only (they fall back to the training values when
        unset — the pre-round-5 behavior). Reference seat: the fps-path
        march config in volume_renderer.py:249-358."""
        base = cls.from_cfg(cfg)
        ta = cfg.task_arg
        return replace(
            base,
            step_size=float(
                ta.get("eval_render_step_size", base.step_size)
            ),
            max_samples=int(
                ta.get("eval_max_march_samples", base.max_samples)
            ),
        )


def occupancy_sweep(rays, near, far, grid, bbox, step_size, spans=None):
    """Phase 1 shared by the per-ray and packed marches: classify every
    march position of every ray against the occupancy grid in one
    vectorized gather (no MLP).

    Returns ``(ts, flat_vox [N, S] voxel ids, occupied [N, S] bool,
    n_steps)``. torch.arange(near, far, Δ) semantics set S:
    ceil((far−near)/Δ) positions, far excluded (the epsilon keeps
    exactly-divisible ranges from gaining one). Zero-direction rays
    (chunk/shard PADDING) are forced unoccupied: their positions all
    collapse onto one voxel and would otherwise consume march budget /
    inflate overflow stats.

    ``spans=(t0 [N], step_r [N])`` switches to PER-RAY quadrature (the
    packed march's clip_bbox mode): position s of ray r sits at
    ``t0[r] + s·step_r[r]``, degenerate spans (step_r ≤ 0) are masked
    unoccupied, and ``ts`` comes back as the [N, S] per-ray positions.
    """
    import math

    if rays.shape[-1] > 6:
        # deliberate: an occupancy grid is a STATIC scene-geometry bake —
        # marching time-conditioned (7-column) rays against it would skip
        # space that is empty in one frame but occupied in another. Dynamic
        # scenes render through the chunked volume path (which threads t).
        raise ValueError(
            "the occupancy-accelerated march only supports static [N, 6] "
            f"rays, got {rays.shape[-1]} columns — time-conditioned scenes "
            "must use the chunked volume renderer (accelerated_renderer: "
            "false)"
        )
    rays_o, rays_d = rays[..., 0:3], rays[..., 3:6]
    resolution = grid.shape[0]
    n_steps = max(math.ceil((far - near) / step_size - 1e-9), 1)
    s_idx = jnp.arange(n_steps, dtype=jnp.float32)
    if spans is None:
        ts = near + s_idx * step_size
        pts = rays_o[:, None, :] + rays_d[:, None, :] * ts[None, :, None]
    else:
        t0, step_r = spans
        ts = t0[:, None] + s_idx[None, :] * step_r[:, None]  # [N, S]
        pts = rays_o[:, None, :] + rays_d[:, None, :] * ts[..., None]
    vox = world_to_voxel(pts, bbox, resolution)  # [N, S, 3]
    flat = (vox[..., 0] * resolution + vox[..., 1]) * resolution + vox[..., 2]
    occupied = jnp.take(grid.reshape(-1), flat)  # [N, S] bool
    real = jnp.sum(rays_d * rays_d, axis=-1) > 0.0  # [N]
    occupied = occupied & real[:, None]
    if spans is not None:
        occupied = occupied & (spans[1] > 0)[:, None]
    return ts, flat, occupied, n_steps


def march_rays_accelerated(
    apply_fn,
    rays: jax.Array,
    near: float,
    far: float,
    grid: jax.Array,
    bbox: jax.Array,
    options: MarchOptions,
    return_samples: bool = False,
) -> dict:
    """Render a [N, 6] ray chunk with ESS + ERT. near/far/options are static.

    ``return_samples`` adds the per-sample march internals the NGP trainer's
    live grid maintenance feeds on (train/ngp.py): ``sample_flat`` [N, K]
    int32 flat voxel ids, ``sample_sigma`` [N, K], ``sample_valid`` [N, K]
    bool — gradients stopped (grid maintenance must not backprop)."""
    if options.clip_bbox:
        raise ValueError(
            "march_clip_bbox is implemented only by the packed march — "
            "set task_arg.ngp_packed_march true (the per-ray [N, K] "
            "march would silently run UNCLIPPED at the coarse step, "
            "invalidating any A/B labeled with the clip knob)"
        )
    if options.coarse_block > 0:
        raise ValueError(
            "march_coarse_block (hierarchical coarse-DDA traversal) is "
            "implemented only by the packed march — set "
            "task_arg.ngp_packed_march true (the per-ray [N, K] march "
            "would silently run the FLAT sweep, invalidating any A/B "
            "labeled with the hierarchical knob)"
        )
    if options.march_fused != "off":
        raise ValueError(
            "march_fused is implemented only by the fused mega-kernel "
            "(ops/fused_march.py) — callers must route through "
            "march_rays_fused / march_rays_fused_full, not the per-ray "
            "[N, K] march (which would silently run staged, invalidating "
            "any A/B labeled with the fused knob)"
        )
    rays_o, rays_d = rays[..., 0:3], rays[..., 3:6]
    n_rays = rays.shape[0]
    step = options.step_size
    k = options.max_samples

    # phase 1: occupancy of every march position, one gather, no MLP
    ts, flat, occupied, n_steps = occupancy_sweep(
        rays, near, far, grid, bbox, step
    )

    # phase 2: compact the first K occupied positions per ray.
    # stable argsort on ~occupied floats the True entries to the front in
    # march order — a static-shape replacement for alive-ray compaction.
    order = jnp.argsort(~occupied, axis=-1, stable=True)[:, :k]
    valid = jnp.take_along_axis(occupied, order, axis=-1)  # [N, K]
    t_sel = ts[order]

    pts_sel = rays_o[:, None, :] + rays_d[:, None, :] * t_sel[..., None]
    viewdirs = rays_d / jnp.linalg.norm(rays_d, axis=-1, keepdims=True)
    raw = apply_fn(pts_sel, viewdirs, "fine")  # [N, K, 4]

    rgb = jax.nn.sigmoid(raw[..., :3])
    sigma = jax.nn.relu(raw[..., 3])
    dists = step * jnp.linalg.norm(rays_d, axis=-1, keepdims=True)
    alpha = (1.0 - jnp.exp(-sigma * dists)) * valid

    # transmittance BEFORE each sample; ERT = zero weight once it has fallen
    # below the threshold (the reference kills the ray after the update that
    # crossed it, volume_renderer.py:340-341 — identical composited output)
    trans = jnp.cumprod(
        jnp.concatenate([jnp.ones((n_rays, 1)), 1.0 - alpha], axis=-1),
        axis=-1,
    )[..., :-1]
    weights = trans * alpha * (trans >= options.transmittance_threshold)

    rgb_map = jnp.sum(weights[..., None] * rgb, axis=-2)
    depth_map = jnp.sum(weights * t_sel, axis=-1)
    acc_map = jnp.sum(weights, axis=-1)
    if options.white_bkgd:
        rgb_map = rgb_map + (1.0 - acc_map[..., None])
    # diagnostic: rays whose occupied positions exceeded the K budget while
    # still transparent lose far contributions — surface it instead of
    # silently truncating (still-alive check keeps ERT-finished rays out).
    # Returned PER RAY so chunk/shard padding rows can be sliced off before
    # summing (zero-direction pad rays never composite but can look
    # "still alive over an occupied voxel" and would inflate a scalar count).
    n_occ = jnp.sum(occupied, axis=-1)
    still_alive = trans[:, -1] >= options.transmittance_threshold
    out = {
        "rgb_map_f": rgb_map,
        "depth_map_f": depth_map,
        "acc_map_f": acc_map,
        "truncated": (n_occ > k) & still_alive,
    }
    if return_samples:
        out["sample_flat"] = jax.lax.stop_gradient(
            jnp.take_along_axis(flat, order, axis=-1).astype(jnp.int32)
        )
        out["sample_sigma"] = jax.lax.stop_gradient(sigma)
        out["sample_valid"] = valid
    return out
