"""img_fit evaluator: PSNR + gt|pred side-by-side image + metrics.json.

Parity with the reference's `src/evaluators/img_fit.py:14-40`.
"""

from __future__ import annotations

import json
import os
import shutil

import numpy as np

from ..utils.image import psnr, write_png


class Evaluator:
    def __init__(self, cfg):
        self.result_dir = cfg.result_dir
        if cfg.get("clear_result", False):
            shutil.rmtree(self.result_dir, ignore_errors=True)
        self.psnrs: list[float] = []

    def evaluate(self, output: dict, batch: dict):
        meta = batch.get("meta", {})
        H, W = int(meta.get("H")), int(meta.get("W"))
        key = "rgb" if "rgb" in output else "rgb_map_f"
        pred = np.clip(np.asarray(output[key]).reshape(H, W, 3), 0.0, 1.0)
        gt_arr = batch.get("rgb", batch.get("rgbs"))
        gt = np.asarray(gt_arr).reshape(H, W, 3)
        self.psnrs.append(psnr(pred, gt))
        write_png(
            os.path.join(self.result_dir, "vis", "res.png"),
            np.concatenate([gt, pred], axis=1),  # gt | pred side by side
        )

    def summarize(self) -> dict:
        if not self.psnrs:
            return {}
        result = {"psnr": float(np.mean(self.psnrs))}
        os.makedirs(self.result_dir, exist_ok=True)
        with open(os.path.join(self.result_dir, "metrics.json"), "w") as f:
            json.dump(result, f)
        self.psnrs = []
        return result


def make_evaluator(cfg) -> Evaluator:
    return Evaluator(cfg)
