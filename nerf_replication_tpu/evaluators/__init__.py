"""Evaluator factory: resolves the ``evaluator_module`` plugin key
(parity: src/evaluators/make_evaluator.py:5-16)."""

from __future__ import annotations

from ..registry import load_attr


def make_evaluator(cfg):
    factory = load_attr(cfg.evaluator_module, "make_evaluator", "Evaluator")
    return factory(cfg)
