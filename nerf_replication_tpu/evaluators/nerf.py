"""NeRF evaluator: per-image PSNR/SSIM, pred/gt PNG dumps, summary.json.

Parity with the reference's `Evaluator` (src/evaluators/nerf.py:14-92): a
stateful accumulator whose ``evaluate(output, batch)`` scores one rendered
view (writing ``pred_{i}.png`` / ``gt_{i}.png`` into the result dir) and whose
``summarize()`` persists mean PSNR/SSIM to ``summary.json`` and returns them.
SSIM is computed on float images with data_range=1 (the reference's
uint8/minmax data_range is a quirk we do not replicate, SURVEY.md §2.5).
"""

from __future__ import annotations

import json
import os
import shutil

import numpy as np

from ..utils.image import psnr, ssim, write_png


class Evaluator:
    def __init__(self, cfg):
        self.result_dir = cfg.result_dir
        self.save_images = bool(cfg.get("save_result", True))
        if cfg.get("clear_result", False):
            # wipe stale per-view artifacts from a previous run so the dir
            # holds exactly this evaluation's outputs
            shutil.rmtree(self.result_dir, ignore_errors=True)
        self.psnrs: list[float] = []
        self.ssims: list[float] = []

    def evaluate(self, output: dict, batch: dict):
        meta = batch.get("meta", {})
        H, W = int(meta.get("H")), int(meta.get("W"))
        key = "rgb_map_f" if "rgb_map_f" in output else "rgb_map_c"
        pred = np.clip(np.asarray(output[key]).reshape(H, W, 3), 0.0, 1.0)
        gt = np.asarray(batch["rgbs"]).reshape(H, W, 3)

        self.psnrs.append(psnr(pred, gt))
        self.ssims.append(ssim(pred, gt))

        if self.save_images:
            i = int(batch.get("i", len(self.psnrs) - 1))
            write_png(os.path.join(self.result_dir, f"pred_{i:04d}.png"), pred)
            write_png(os.path.join(self.result_dir, f"gt_{i:04d}.png"), gt)

    def summarize(self) -> dict:
        if not self.psnrs:
            return {}
        result = {
            "psnr": float(np.mean(self.psnrs)),
            "ssim": float(np.mean(self.ssims)),
        }
        os.makedirs(self.result_dir, exist_ok=True)
        with open(os.path.join(self.result_dir, "summary.json"), "w") as f:
            json.dump(
                {**result, "per_image_psnr": self.psnrs,
                 "per_image_ssim": self.ssims}, f, indent=2,
            )
        self.psnrs, self.ssims = [], []
        return result


def make_evaluator(cfg) -> Evaluator:
    return Evaluator(cfg)
