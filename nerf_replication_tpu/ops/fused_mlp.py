"""Fused NeRF-MLP trunk as a Pallas TPU kernel — the HBM-traffic lever.

PERF.md "f3 closure": the flagship train step is bound by 48.8 GB/step of
forward-saved / backward-read activation traffic (~40 layer instances of
[786k, 256]); XLA remat LOSES (recompute goes through HBM again), so the
single-chip headline closed at ~48k rays/s, 73% of HBM peak, 22% MFU.

This kernel attacks the bytes directly, flash-attention-style: the whole
MLP chain runs per TILE of points with weights (~2.4 MB) and activations
resident in VMEM. The forward writes ONLY the [M, 4] raw output; the
backward re-runs the forward per tile in VMEM (recompute never touches
HBM) and accumulates weight gradients across the sequentially-executed
grid. HBM traffic per step drops from ~40 × [M, W] activations to
inputs + outputs + per-tile weight streams — modeled ≥10× less.

Unlike the hash-encoder Pallas attempt (models/encoding/pallas_hash.py —
Mosaic rejects its in-kernel gather, a recorded negative), this kernel is
pure matmul chain + elementwise: the exact op mix Mosaic is built for.

Scope: the original-paper NeRF MLP family (models/nerf/network.py — D
trunk layers of width W, ONE skip re-injection, viewdirs branch W/2,
f32 density/rgb heads; reference src/models/nerf/network.py:9-192).
``make_fused_apply`` builds a drop-in ``apply_fn(params, pts, viewdirs,
model)`` for Renderer._apply_fn when ``network.nerf.fused_trunk`` is on;
configs outside the supported family are refused loudly at build time.

CPU (and any non-TPU backend) runs the same kernels under the Pallas
interpreter — numerically verified against the Flax apply in
tests/test_fused_mlp.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    # trace-time constant: Mosaic lowering only exists on real TPU
    return jax.devices()[0].platform not in ("tpu", "axon")


def _mosaic_kwargs(tile: int) -> dict:
    """Raise the scoped-VMEM (kernel stack) limit above Mosaic's 16 MB
    default for BIG tiles only: the tile-1024 backward's stack is
    17.4 MB (recorded OOM, BENCH_SWEEP_FUSED.jsonl), comfortably inside
    the chip's 128 MB VMEM. Bigger tiles matter because the per-tile
    weight stream (~2.4 MB f32) is the kernel's own HBM term — grid
    steps halve as tiles double. Tiles ≤512 keep the default params so
    the chip-measured headline executable (tile 512, 48.6k rays/s) is
    replayed byte-identically by the driver's bench."""
    if _interpret() or tile <= 512:
        return {}
    from jax.experimental.pallas import tpu as pltpu

    return {
        "compiler_params": pltpu.CompilerParams(
            vmem_limit_bytes=96 * 1024 * 1024
        )
    }


def _pad_cols(a, to):
    c = a.shape[-1]
    if c == to:
        return a
    return jnp.pad(a, ((0, 0),) * (a.ndim - 1) + ((0, to - c),))


def _place_col(a, col, to):
    """Pad [..., 1] to [..., to] with the live column at index ``col``.

    Lets the alpha head write DIRECTLY into the raw layout's column
    (raw = rgb8 + alpha8 — a plain element-wise add) so the kernel never
    does lane-dimension concatenation, which Mosaic handles poorly. The
    VJP of this pad extracts exactly the live column."""
    return jnp.pad(
        a, ((0, 0),) * (a.ndim - 1) + ((col, to - col - a.shape[-1]),)
    )


def _pad_rows(a, to):
    r = a.shape[0]
    if r == to:
        return a
    return jnp.pad(a, ((0, to - r),) + ((0, 0),) * (a.ndim - 1))


def _rup(n, m):
    return ((n + m - 1) // m) * m


class FusedSpec:
    """Static geometry of one fused MLP (shapes after padding)."""

    def __init__(self, D, W, skip, c_in, c_views, compute_dtype):
        if skip is not None and not (0 <= skip < D - 1):
            raise ValueError(
                f"fused_trunk: skip={skip} must feed a later trunk layer "
                f"(D={D}) — a skip at the last layer changes the head width"
            )
        self.D, self.W, self.skip = int(D), int(W), skip
        self.W2 = self.W // 2
        self.c_in, self.c_views = int(c_in), int(c_views)
        self.c_in_pad = _rup(max(self.c_in, 1), 64)
        self.c_views_pad = _rup(max(self.c_views, 1), 32)
        self.compute_dtype = compute_dtype

    # canonical parameter order fed to the kernels (compute-dtype streams
    # for trunk/feature/views, f32 for the alpha/rgb heads; padded):
    #   W0 [c_in_pad, W], b0 [1, W]
    #   per trunk layer i in 1..D-1:
    #       skip+1: Wsx [c_in_pad, W], Wsh [W, W], bs [1, W]
    #       else:   Wi [W, W], bi [1, W]
    #   Wa [W, 8], ba [1, 8]       (alpha head, col 0 live)
    #   Wf [W, W], bf [1, W]       (feature head)
    #   Wvf [W, W2], Wvv [c_views_pad, W2], bv [1, W2]
    #   Wr [W2, 8], br [1, 8]      (rgb head, cols 0..2 live)
    def flatten_params(self, branch: dict) -> list:
        D, W, skip = self.D, self.W, self.skip
        out = []

        # Stream dtype: the trunk/feature/views weights reach the MXU as
        # compute_dtype anyway (the kernels .astype(cd) every operand),
        # so streaming them bf16 halves the kernel's dominant HBM term —
        # the per-tile weight re-read (~2.4 MB f32 × every grid step).
        # The alpha/rgb heads stay f32 to mirror the Flax net's
        # f32-head numerics (models/nerf/network.py:174-186). The VJP
        # of the cast routes the f32 cotangent back exactly.
        sd = jnp.dtype(self.compute_dtype)

        def kb(name, dtype=None):
            dt = sd if dtype is None else dtype
            p = branch[name]
            return jnp.asarray(p["kernel"], dt), jnp.asarray(
                p["bias"], dt
            ).reshape(1, -1)

        k0, b0 = kb("pts_linear_0")
        out += [_pad_rows(k0, self.c_in_pad), b0]
        for i in range(1, D):
            ki, bi = kb(f"pts_linear_{i}")
            if skip is not None and i == skip + 1:
                # SplitDense layout: kernel [c_in + W, W]
                out += [
                    _pad_rows(ki[: self.c_in], self.c_in_pad),
                    ki[self.c_in :],
                    bi,
                ]
            else:
                out += [ki, bi]
        ka, ba = kb("alpha_linear", dtype=jnp.float32)
        # live column at 3: raw layout is [r, g, b, alpha, pad...]
        out += [_place_col(ka, 3, 8), _place_col(ba, 3, 8)]
        kf, bf = kb("feature_linear")
        out += [kf, bf]
        kv, bv = kb("views_linear_0")  # SplitDense [W + c_views, W2]
        out += [
            kv[: self.W],
            _pad_rows(kv[self.W :], self.c_views_pad),
            bv,
        ]
        kr, br = kb("rgb_linear", dtype=jnp.float32)
        out += [_pad_cols(kr, 8), _pad_cols(br, 8)]
        return out

    # (the inverse of flatten_params is free: fused_mlp_raw differentiates
    # THROUGH flatten_params, whose pad/slice VJPs route the flat grads
    # back into the branch dict automatically)

    def n_params(self) -> int:
        D, skip = self.D, self.skip
        n = 2  # W0, b0
        for i in range(1, D):
            n += 3 if (skip is not None and i == skip + 1) else 2
        n += 2 + 2 + 3 + 2  # alpha, feature, views, rgb
        return n


def _forward_tile(spec: FusedSpec, x, v, ws):
    """The whole MLP on one tile; returns (raw8, activations list).

    Mirrors NeRFMLP.__call__ exactly: trunk (+ optional skip via split
    matmuls), f32 alpha head off the trunk, feature → viewdirs branch
    (relu) → f32 rgb head. ``ws`` follows flatten_params order.
    """
    cd = spec.compute_dtype
    it = iter(ws)

    def nxt():
        return next(it)

    acts = []
    h = jnp.dot(
        x.astype(cd), nxt().astype(cd), preferred_element_type=jnp.float32
    ) + nxt()
    h = jax.nn.relu(h)
    acts.append(h)
    for i in range(1, spec.D):
        if spec.skip is not None and i == spec.skip + 1:
            wx, wh, b = nxt(), nxt(), nxt()
            h = (
                jnp.dot(x.astype(cd), wx.astype(cd),
                        preferred_element_type=jnp.float32)
                + jnp.dot(h.astype(cd), wh.astype(cd),
                          preferred_element_type=jnp.float32)
                + b
            )
        else:
            w, b = nxt(), nxt()
            h = jnp.dot(
                h.astype(cd), w.astype(cd),
                preferred_element_type=jnp.float32,
            ) + b
        h = jax.nn.relu(h)
        acts.append(h)
    wa, ba = nxt(), nxt()
    alpha8 = jnp.dot(h, wa, preferred_element_type=jnp.float32) + ba
    wf, bf = nxt(), nxt()
    f = jnp.dot(
        h.astype(cd), wf.astype(cd), preferred_element_type=jnp.float32
    ) + bf
    acts.append(f)
    wvf, wvv, bv = nxt(), nxt(), nxt()
    vh = jax.nn.relu(
        jnp.dot(f.astype(cd), wvf.astype(cd),
                preferred_element_type=jnp.float32)
        + jnp.dot(v.astype(cd), wvv.astype(cd),
                  preferred_element_type=jnp.float32)
        + bv
    )
    acts.append(vh)
    wr, br = nxt(), nxt()
    rgb8 = jnp.dot(vh, wr, preferred_element_type=jnp.float32) + br
    # raw layout [rgb, alpha, pad]: rgb lives in cols 0-2 (wr/br padding),
    # alpha in col 3 (_place_col) — a plain add, no lane concat in-kernel
    raw8 = rgb8 + alpha8
    return raw8, acts


def _backward_tile(spec: FusedSpec, x, v, draw, ws):
    """Recompute forward in VMEM, return (dx, dv, [dW/db...])."""
    cd = spec.compute_dtype
    _, acts = _forward_tile(spec, x, v, ws)
    # name the pieces
    it = iter(ws)
    w0, b0 = next(it), next(it)
    trunk = []
    for i in range(1, spec.D):
        if spec.skip is not None and i == spec.skip + 1:
            trunk.append((next(it), next(it), next(it)))
        else:
            trunk.append((next(it), next(it)))
    wa, ba = next(it), next(it)
    wf, bf = next(it), next(it)
    wvf, wvv, bv = next(it), next(it), next(it)
    wr, br = next(it), next(it)

    h_last = acts[spec.D - 1]
    f, vh = acts[spec.D], acts[spec.D + 1]

    # raw = rgb8 + alpha8 with structurally-disjoint live columns (wr live
    # cols 0-2, wa live col 3), so BOTH heads take the full [T, 8]
    # cotangent: the dead columns of each head's weights zero out the
    # other head's contribution, and the padding VJP outside the kernel
    # slices the dead weight-gradient columns off.
    drgb = draw
    dalpha = draw

    f32 = jnp.float32

    def dotT(a, b):  # a @ b.T
        return jax.lax.dot_general(
            a.astype(f32), b.astype(f32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=f32,
        )

    def Tdot(a, b):  # a.T @ b
        return jax.lax.dot_general(
            a.astype(f32), b.astype(f32),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=f32,
        )

    grads = []
    # rgb head
    dwr = Tdot(vh, drgb)
    dbr = jnp.sum(drgb, axis=0, keepdims=True)
    dvh = dotT(drgb, wr) * (vh > 0)
    # views branch
    dwvf = Tdot(f, dvh)
    dwvv = Tdot(v, dvh)
    dbv = jnp.sum(dvh, axis=0, keepdims=True)
    df = dotT(dvh, wvf)
    dv = dotT(dvh, wvv)
    # feature + alpha heads (both read the last trunk activation)
    dwf = Tdot(h_last, df)
    dbf = jnp.sum(df, axis=0, keepdims=True)
    dwa = Tdot(h_last, dalpha)
    dba = jnp.sum(dalpha, axis=0, keepdims=True)
    dh = dotT(df, wf) + dotT(dalpha, wa)
    # trunk, in reverse
    dx = jnp.zeros_like(x, dtype=f32)
    trunk_grads = []
    for i in range(spec.D - 1, 0, -1):
        a_i = acts[i]
        a_prev = acts[i - 1]
        dz = dh * (a_i > 0)
        if spec.skip is not None and i == spec.skip + 1:
            wx, wh, _ = trunk[i - 1]
            trunk_grads.append([
                Tdot(x, dz), Tdot(a_prev, dz),
                jnp.sum(dz, axis=0, keepdims=True),
            ])
            dx = dx + dotT(dz, wx)
            dh = dotT(dz, wh)
        else:
            w, _ = trunk[i - 1]
            trunk_grads.append([
                Tdot(a_prev, dz), jnp.sum(dz, axis=0, keepdims=True),
            ])
            dh = dotT(dz, w)
    dz0 = dh * (acts[0] > 0)
    dw0 = Tdot(x, dz0)
    db0 = jnp.sum(dz0, axis=0, keepdims=True)
    dx = dx + dotT(dz0, w0)

    grads = [dw0, db0]
    for g in reversed(trunk_grads):
        grads += g
    grads += [dwa, dba, dwf, dbf, dwvf, dwvv, dbv, dwr, dbr]
    return dx, dv, grads


def _fwd_kernel(spec, x_ref, v_ref, *rest):
    ws = rest[:-1]
    out_ref = rest[-1]
    raw8, _ = _forward_tile(
        spec, x_ref[...], v_ref[...], [w[...] for w in ws]
    )
    out_ref[...] = raw8


def _bwd_kernel(spec, x_ref, v_ref, draw_ref, *rest):
    n_p = spec.n_params()
    ws = rest[:n_p]
    dx_ref, dv_ref = rest[n_p], rest[n_p + 1]
    gr = rest[n_p + 2 :]
    dx, dv, grads = _backward_tile(
        spec, x_ref[...], v_ref[...], draw_ref[...], [w[...] for w in ws]
    )
    dx_ref[...] = dx
    dv_ref[...] = dv
    # weight grads accumulate across the SEQUENTIAL TPU grid
    first = pl.program_id(0) == 0
    for ref, g in zip(gr, grads):
        @pl.when(first)
        def _init(ref=ref, g=g):
            ref[...] = g

        @pl.when(jnp.logical_not(first))
        def _acc(ref=ref, g=g):
            ref[...] = ref[...] + g


def _fwd_kernel_masked(spec, x_ref, v_ref, valid_ref, *rest):
    """Forward with the packed march's per-sample occupancy bit streamed
    into the kernel (the fine-level bit-test fused with the matmul chain
    — pure elementwise + matmul, the op mix Mosaic accepts, unlike the
    recorded in-kernel gather negative in models/encoding/pallas_hash.py;
    the raw grid/hash GATHER itself stays outside the kernel).

    The packed stream is sorted valid-first, so whole tail tiles are
    all-invalid: ``pl.when`` skips their matmul chain entirely, making
    the stream's padding cost ~no MXU work."""
    ws = rest[:-1]
    out_ref = rest[-1]
    valid = valid_ref[...]  # [tile, 1] f32 0/1
    any_valid = jnp.sum(valid) > 0.0

    @pl.when(any_valid)
    def _run():
        raw8, _ = _forward_tile(
            spec, x_ref[...], v_ref[...], [w[...] for w in ws]
        )
        out_ref[...] = raw8 * valid

    @pl.when(jnp.logical_not(any_valid))
    def _skip():
        out_ref[...] = jnp.zeros_like(out_ref)


def _bwd_kernel_masked(spec, x_ref, v_ref, valid_ref, draw_ref, *rest):
    n_p = spec.n_params()
    ws = rest[:n_p]
    dx_ref, dv_ref = rest[n_p], rest[n_p + 1]
    gr = rest[n_p + 2 :]
    valid = valid_ref[...]
    any_valid = jnp.sum(valid) > 0.0
    first = pl.program_id(0) == 0

    # zero-init unconditionally on the first grid step: with tile-skip,
    # "first tile" and "first tile that accumulates" need not coincide,
    # and a skipped first tile must not leave the accumulators unwritten
    for ref in gr:
        @pl.when(first)
        def _init(ref=ref):
            ref[...] = jnp.zeros_like(ref)

    @pl.when(any_valid)
    def _run():
        # masking the cotangent masks everything downstream: every dx/dv
        # row and every weight-grad contribution chains linearly from its
        # row's draw, so invalid rows contribute exactly zero
        dx, dv, grads = _backward_tile(
            spec, x_ref[...], v_ref[...], draw_ref[...] * valid,
            [w[...] for w in ws],
        )
        dx_ref[...] = dx
        dv_ref[...] = dv
        for ref, g in zip(gr, grads):
            ref[...] = ref[...] + g

    @pl.when(jnp.logical_not(any_valid))
    def _skip():
        dx_ref[...] = jnp.zeros_like(dx_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _fused_raw(spec, tile, flat_ws, x, v):
    out, _ = _fused_fwd(spec, tile, flat_ws, x, v)
    return out


def _pallas_fwd(spec, tile, flat_ws, x, v):
    m = x.shape[0]
    grid = (m // tile,)
    in_specs = [
        pl.BlockSpec((tile, x.shape[1]), lambda i: (i, 0)),
        pl.BlockSpec((tile, v.shape[1]), lambda i: (i, 0)),
    ] + [
        pl.BlockSpec(w.shape, lambda i, nd=w.ndim: (0,) * nd)
        for w in flat_ws
    ]
    return pl.pallas_call(
        partial(_fwd_kernel, spec),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((tile, 8), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, 8), jnp.float32),
        interpret=_interpret(),
        **_mosaic_kwargs(tile),
    )(x, v, *flat_ws)


def _fused_fwd(spec, tile, flat_ws, x, v):
    out = _pallas_fwd(spec, tile, flat_ws, x, v)
    return out, (flat_ws, x, v)


def _fused_bwd(spec, tile, res, draw):
    flat_ws, x, v = res
    m = x.shape[0]
    grid = (m // tile,)
    in_specs = [
        pl.BlockSpec((tile, x.shape[1]), lambda i: (i, 0)),
        pl.BlockSpec((tile, v.shape[1]), lambda i: (i, 0)),
        pl.BlockSpec((tile, 8), lambda i: (i, 0)),
    ] + [
        pl.BlockSpec(w.shape, lambda i, nd=w.ndim: (0,) * nd)
        for w in flat_ws
    ]
    out_specs = [
        pl.BlockSpec((tile, x.shape[1]), lambda i: (i, 0)),
        pl.BlockSpec((tile, v.shape[1]), lambda i: (i, 0)),
    ] + [
        # full-array blocks revisited every grid step: the accumulation
        # target stays VMEM-resident (sequential grid on TPU)
        pl.BlockSpec(w.shape, lambda i, nd=w.ndim: (0,) * nd)
        for w in flat_ws
    ]
    out_shape = [
        jax.ShapeDtypeStruct((m, x.shape[1]), jnp.float32),
        jax.ShapeDtypeStruct((m, v.shape[1]), jnp.float32),
    ] + [jax.ShapeDtypeStruct(w.shape, jnp.float32) for w in flat_ws]
    outs = pl.pallas_call(
        partial(_bwd_kernel, spec),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
        **_mosaic_kwargs(tile),
    )(x, v, jnp.asarray(draw, jnp.float32), *flat_ws)
    dx, dv = outs[0], outs[1]
    # cotangent dtypes must match the primals: bf16-streamed weights get
    # their dW rounded to bf16 here (the Flax bf16 path rounds its dW the
    # same way); flatten_params' cast-VJP upcasts back to f32 params
    dws = [g.astype(w.dtype) for g, w in zip(outs[2:], flat_ws)]
    return tuple(dws), dx, dv


_fused_raw.defvjp(_fused_fwd, _fused_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _fused_raw_masked(spec, tile, flat_ws, x, v, valid):
    out, _ = _fused_fwd_masked(spec, tile, flat_ws, x, v, valid)
    return out


def _pallas_fwd_masked(spec, tile, flat_ws, x, v, valid):
    m = x.shape[0]
    grid = (m // tile,)
    in_specs = [
        pl.BlockSpec((tile, x.shape[1]), lambda i: (i, 0)),
        pl.BlockSpec((tile, v.shape[1]), lambda i: (i, 0)),
        pl.BlockSpec((tile, 1), lambda i: (i, 0)),
    ] + [
        pl.BlockSpec(w.shape, lambda i, nd=w.ndim: (0,) * nd)
        for w in flat_ws
    ]
    return pl.pallas_call(
        partial(_fwd_kernel_masked, spec),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((tile, 8), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, 8), jnp.float32),
        interpret=_interpret(),
        **_mosaic_kwargs(tile),
    )(x, v, valid, *flat_ws)


def _fused_fwd_masked(spec, tile, flat_ws, x, v, valid):
    out = _pallas_fwd_masked(spec, tile, flat_ws, x, v, valid)
    return out, (flat_ws, x, v, valid)


def _fused_bwd_masked(spec, tile, res, draw):
    flat_ws, x, v, valid = res
    m = x.shape[0]
    grid = (m // tile,)
    in_specs = [
        pl.BlockSpec((tile, x.shape[1]), lambda i: (i, 0)),
        pl.BlockSpec((tile, v.shape[1]), lambda i: (i, 0)),
        pl.BlockSpec((tile, 1), lambda i: (i, 0)),
        pl.BlockSpec((tile, 8), lambda i: (i, 0)),
    ] + [
        pl.BlockSpec(w.shape, lambda i, nd=w.ndim: (0,) * nd)
        for w in flat_ws
    ]
    out_specs = [
        pl.BlockSpec((tile, x.shape[1]), lambda i: (i, 0)),
        pl.BlockSpec((tile, v.shape[1]), lambda i: (i, 0)),
    ] + [
        pl.BlockSpec(w.shape, lambda i, nd=w.ndim: (0,) * nd)
        for w in flat_ws
    ]
    out_shape = [
        jax.ShapeDtypeStruct((m, x.shape[1]), jnp.float32),
        jax.ShapeDtypeStruct((m, v.shape[1]), jnp.float32),
    ] + [jax.ShapeDtypeStruct(w.shape, jnp.float32) for w in flat_ws]
    outs = pl.pallas_call(
        partial(_bwd_kernel_masked, spec),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
        **_mosaic_kwargs(tile),
    )(x, v, valid, jnp.asarray(draw, jnp.float32), *flat_ws)
    dx, dv = outs[0], outs[1]
    dws = [g.astype(w.dtype) for g, w in zip(outs[2:], flat_ws)]
    # the occupancy bit is data-routing, not a differentiable quantity
    return tuple(dws), dx, dv, jnp.zeros_like(valid)


_fused_raw_masked.defvjp(_fused_fwd_masked, _fused_bwd_masked)


def fused_mlp_raw_masked(
    spec: FusedSpec, branch: dict, x_enc, d_enc, valid, tile=512
):
    """``fused_mlp_raw`` with a [M] validity mask streamed into the kernel.

    Rows with ``valid == 0`` return raw 0 and receive zero cotangent; the
    row-pad to the tile multiple is marked invalid, so the padded tail
    tiles (and, for the sorted packed stream, the trailing all-padding
    tiles of the real rows) skip the MLP entirely."""
    m = x_enc.shape[0]
    m_pad = _rup(max(m, 1), tile)
    x = _pad_cols(jnp.asarray(x_enc, jnp.float32), spec.c_in_pad)
    v = _pad_cols(jnp.asarray(d_enc, jnp.float32), spec.c_views_pad)
    x = _pad_rows(x, m_pad)
    v = _pad_rows(v, m_pad)
    val = _pad_rows(
        jnp.asarray(valid, jnp.float32).reshape(-1, 1), m_pad
    )

    flat = spec.flatten_params(branch)

    raw8 = _fused_raw_masked(spec, tile, tuple(flat), x, v, val)
    return raw8[:m, :4]


def fused_mlp_raw(spec: FusedSpec, branch: dict, x_enc, d_enc, tile=512):
    """[M, c_in] encoded points + [M, c_views] encoded dirs → [M, 4] raw.

    Pads M to a tile multiple and the channel dims to the spec's padded
    widths; differentiable in (branch, x_enc, d_enc).
    """
    m = x_enc.shape[0]
    m_pad = _rup(max(m, 1), tile)
    x = _pad_cols(jnp.asarray(x_enc, jnp.float32), spec.c_in_pad)
    v = _pad_cols(jnp.asarray(d_enc, jnp.float32), spec.c_views_pad)
    x = _pad_rows(x, m_pad)
    v = _pad_rows(v, m_pad)

    flat = spec.flatten_params(branch)

    raw8 = _fused_raw(spec, tile, tuple(flat), x, v)
    return raw8[:m, :4]


def fused_spec_for(network) -> FusedSpec:
    """Validate a network is kernel-fusable and return its FusedSpec.

    Shared family gate for every surface that streams the MLP through the
    Pallas tiles (``make_fused_apply`` and the fused ray-march mega-kernel
    in ops/fused_march.py). Refuses unsupported families loudly."""
    import flax.linen as nn

    from ..models.nerf.network import Network

    if not isinstance(network, Network):
        raise ValueError("fused_trunk supports the NeRF Network family")
    if isinstance(network.xyz_encoder, nn.Module) or isinstance(
        network.dir_encoder, nn.Module
    ):
        raise ValueError(
            "fused_trunk requires parameter-free encoders (frequency "
            "family): a learnable encoder (hashgrid) cannot be called "
            "outside the Flax apply and its tables would get no gradients "
            "through the fused branch params"
        )
    if not network.use_viewdirs:
        raise ValueError("fused_trunk requires use_viewdirs (rgb branch)")
    if network.scan_trunk:
        raise ValueError("fused_trunk and scan_trunk are exclusive")
    skips = tuple(network.skips)
    if len(skips) != 1:
        raise ValueError("fused_trunk supports exactly one skip index")
    return FusedSpec(
        D=network.D, W=network.W, skip=skips[0],
        c_in=network.input_ch, c_views=network.input_ch_views,
        compute_dtype=network.compute_dtype,
    )


def make_fused_apply(network, cfg):
    """Drop-in ``apply_fn(params, pts, viewdirs, model)`` running the MLP
    through the fused kernels. Refuses unsupported families loudly."""
    tile = int(cfg.network.nerf.get("fused_tile", 512))
    spec = fused_spec_for(network)

    def apply_fn(params, pts, viewdirs, model, valid=None):
        x_enc = network.xyz_encoder(pts)
        dirs = jnp.broadcast_to(
            viewdirs[..., None, :], pts.shape[:-1] + (viewdirs.shape[-1],)
        )
        d_enc = network.dir_encoder(dirs)
        lead = x_enc.shape[:-1]
        branch = params["params"][model]
        if valid is None:
            raw = fused_mlp_raw(
                spec, branch,
                x_enc.reshape(-1, x_enc.shape[-1]),
                d_enc.reshape(-1, d_enc.shape[-1]),
                tile=tile,
            )
        else:
            raw = fused_mlp_raw_masked(
                spec, branch,
                x_enc.reshape(-1, x_enc.shape[-1]),
                d_enc.reshape(-1, d_enc.shape[-1]),
                jnp.reshape(valid, (-1,)),
                tile=tile,
            )
        return raw.reshape(*lead, 4)

    # the packed march streams its per-sample occupancy bit into the
    # kernel when the apply advertises this flag (packed_march.py)
    apply_fn.supports_valid_mask = True
    return apply_fn
